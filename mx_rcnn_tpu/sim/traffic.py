"""Scenario traces: the demand/failure scripts the gauntlet replays.

A **trace** is a plain-JSON dict — replayable, diffable, fingerprinted:

``{"name", "seed", "hosts", "duration_s",``
``  "rate": [[t, rps], ...],          # piecewise-constant demand``
``  "bucket_weights": [[[h, w], frac], ...],``
``  "events": [{"t", "kind", "host"}, ...],  # host_down|host_flap|drain_host``
``  "overrides": {"section__field": value, ...},  # cfg for BOTH arms``
``  "fingerprint": sha256-of-the-above}``

Arrivals are NOT enumerated in the trace (a 240 s scenario carries
~10^5 requests): the harness draws Poisson arrivals from the kernel's
seeded ``arrivals`` substream against the rate curve, so the same trace
+ seed reproduces the same request sequence exactly.

Generators (each deterministic in (hosts, cfg, seed)):

* ``diurnal``       — a full demand cycle, trough 10% of peak.  No
  faults; the question is pure stability: does hysteresis flap at this
  scale?  (The shipped policy must take ZERO actions here.)
* ``flash_crowd``   — a 2x step spike to ~130% of fleet capacity for
  45 s.  Demand exceeds capacity, so watermark shedding is the CORRECT
  outcome; the deadline is set well above the watermark wait so
  shedding structurally precedes expiry and nothing is lost.
* ``failure_storm`` — 15% of hosts preempted mid-trace (3 of them
  crash-looping flappers judged by the shipped ``RestartPolicy``),
  then a demand ramp to ~92% of ORIGINAL capacity.  The deficit signal
  must re-place capacity on survivors before the ramp; a policy that
  ignores it overloads the survivors until queued requests expire.
* ``rolling_update``— every host drained, darkened and relaunched on a
  stagger, under steady load.  The scheduler must NOT fight the update
  (the operator derates the target by the expected concurrent dip —
  the trace's override encodes that runbook step).
* ``canary_rollout``— a ``"rollout"`` block starts the REAL
  ``RolloutController`` (pull → canary + online paired gate → wave
  rolling swaps → finalize) against the whole fleet under steady load,
  with one host darkened mid-roll so FINALIZE must re-converge a
  relaunched (boot-version) host.  Autoscaling is frozen for the
  rollout window (the runbook step), and the red-team arm damages the
  canary's shadow scores (``rollout__redteam_damage``) so only the
  paired gate can catch it — the required outcome there is refusal +
  auto-rollback, not completion.
"""

from __future__ import annotations

import hashlib
import json
import math
from typing import Dict, List, Tuple

from mx_rcnn_tpu.config import Config

SCENARIOS = ("diurnal", "flash_crowd", "failure_storm",
             "rolling_update", "canary_rollout")


def bucket_weights(cfg: Config) -> List[Tuple[Tuple[int, int], float]]:
    """Demand mix over the configured static buckets: 60/30/10 across
    the first three shapes (normalized over however many exist)."""
    shapes = [tuple(b) for b in cfg.bucket.shapes][:3]
    raw = [0.6, 0.3, 0.1][:len(shapes)]
    total = sum(raw)
    return [(s, w / total) for s, w in zip(shapes, raw)]


def fleet_capacity_rps(cfg: Config, hosts: int) -> float:
    """Boot-fleet service capacity estimate: replicas x batch / the
    demand-weighted mean service time.  An estimate for shaping demand
    curves, not a promise — the simulator measures the truth."""
    weights = bucket_weights(cfg)
    base = min(h * w for h, w in (tuple(b) for b in cfg.bucket.shapes))
    mean_svc_s = sum(w * (cfg.sim.service_ms / 1000.0)
                     * (s[0] * s[1]) / base for s, w in weights)
    replicas = hosts * max(int(cfg.crosshost.agent_replicas), 1)
    return replicas * cfg.serve.batch_size / mean_svc_s


def _finalize(trace: Dict) -> Dict:
    body = json.dumps({k: trace[k] for k in sorted(trace)},
                      sort_keys=True)
    trace["fingerprint"] = hashlib.sha256(body.encode()).hexdigest()
    return trace


def _base(name: str, cfg: Config, hosts: int, seed: int,
          duration_s: float) -> Dict:
    return {
        "name": name, "seed": int(seed), "hosts": int(hosts),
        "duration_s": float(duration_s),
        "bucket_weights": [[list(s), w]
                           for s, w in bucket_weights(cfg)],
        "events": [],
        # every scenario pins the fleet-shape knobs both arms share:
        # explicit target (hosts x agent_replicas), a ceiling that can
        # absorb re-placement, a floor at one replica per host
        "overrides": {
            "crosshost__target_replicas": hosts,
            "crosshost__max_replicas": hosts * 4,
            "crosshost__min_replicas": hosts,
            # padded-batch serving carries ~1-2 dispatch times of
            # standing queue even at healthy utilization (the batch
            # only fills when something waits), so the 2-4-host
            # up_backlog tuning reads permanent overload at fleet
            # scale; the scenarios derate it and let the shed-ratio
            # trigger own the overload judgment
            "crosshost__up_backlog": 50.0,
            # deadline well above the standing padded-batch wait —
            # nothing should expire at baseline
            "serve__default_timeout_ms": 6000.0,
        },
    }


def gen_diurnal(cfg: Config, hosts: int, seed: int) -> Dict:
    T = cfg.sim.duration_s
    cap = fleet_capacity_rps(cfg, hosts)
    tr = _base("diurnal", cfg, hosts, seed, T)
    steps = 24
    tr["rate"] = []
    for i in range(steps):
        t = i * T / steps
        # trough->peak->trough over one trace: 10%..100% of util x cap
        frac = 0.55 - 0.45 * math.cos(2.0 * math.pi * i / steps)
        tr["rate"].append([round(t, 3),
                           round(cap * cfg.sim.util * frac, 3)])
    return _finalize(tr)


def gen_flash_crowd(cfg: Config, hosts: int, seed: int) -> Dict:
    T = cfg.sim.duration_s
    cap = fleet_capacity_rps(cfg, hosts)
    base = cap * cfg.sim.util
    t0, spike_s = round(0.4 * T, 3), 45.0
    tr = _base("flash_crowd", cfg, hosts, seed, T)
    # the spike lands at ~2x base = ~130% of capacity: shedding is the
    # correct answer, and the deadline leaves expiry unreachable below
    # the watermark (watermark wait ~ shed_watermark/batch service
    # cycles << deadline)
    tr["overrides"]["serve__default_timeout_ms"] = 15_000.0
    tr["rate"] = [[0.0, round(base, 3)],
                  [t0, round(2.0 * base, 3)],
                  [round(t0 + spike_s, 3), round(base, 3)]]
    return _finalize(tr)


def gen_failure_storm(cfg: Config, hosts: int, seed: int) -> Dict:
    T = cfg.sim.duration_s
    cap = fleet_capacity_rps(cfg, hosts)
    base = cap * cfg.sim.util
    tr = _base("failure_storm", cfg, hosts, seed, T)
    # the watermark is raised so that under a SUSTAINED survivor
    # overload the queue wait crosses the deadline BEFORE the lane
    # sheds: watermark/batch dispatch cycles x service must exceed the
    # deadline.  A policy that never re-places the preempted capacity
    # therefore LOSES requests (expiry), not just sheds them.
    tr["overrides"]["serve__shed_watermark"] = 96
    # a sweep strands work on several hosts inside one queue lifetime;
    # one extra reroute is the difference between absorbed and lost
    tr["overrides"]["fleet__reroute_retries"] = 2
    killed = max(hosts * 15 // 100, 1)
    flappy = min(3, killed)
    t_kill = 0.25 * T
    for j in range(killed):
        kind = "host_flap" if j < flappy else "host_down"
        # stagger 2.5 s apart: a correlated preemption sweep, not one
        # atomic instant
        tr["events"].append({"t": round(t_kill + 2.5 * j, 3),
                             "kind": kind, "host": hosts - 1 - j})
    # phase 2: demand ramps to ~92% of ORIGINAL capacity — survivable
    # only if the lost capacity was re-placed
    t_ramp0, t_ramp1 = 0.55 * T, 0.75 * T
    tr["rate"] = [[0.0, round(base, 3)]]
    steps = 8
    for i in range(1, steps + 1):
        t = t_ramp0 + (t_ramp1 - t_ramp0) * i / steps
        r = base + (0.92 * cap - base) * i / steps
        tr["rate"].append([round(t, 3), round(r, 3)])
    return _finalize(tr)


def gen_rolling_update(cfg: Config, hosts: int, seed: int) -> Dict:
    T = cfg.sim.duration_s
    cap = fleet_capacity_rps(cfg, hosts)
    tr = _base("rolling_update", cfg, hosts, seed, T)
    # drain->dark->relaunch takes ~relaunch_s + warmup_s; the stagger
    # keeps a handful of hosts dark at once.  The runbook derate: the
    # operator lowers the target by the expected concurrent dip so the
    # deficit signal doesn't fight the planned update.
    dark_s = cfg.sim.relaunch_s + cfg.sim.warmup_s + 2.0
    t0, t1 = 10.0, T - max(dark_s * 2, 30.0)
    stagger = (t1 - t0) / hosts
    concurrent = max(int(math.ceil(dark_s / stagger)), 1)
    tr["overrides"]["crosshost__target_replicas"] = hosts - concurrent - 1
    tr["overrides"]["crosshost__min_replicas"] = hosts - concurrent - 1
    for i in range(hosts):
        tr["events"].append({"t": round(t0 + i * stagger, 3),
                             "kind": "drain_host", "host": i})
    tr["rate"] = [[0.0, round(cap * cfg.sim.util, 3)]]
    return _finalize(tr)


def gen_canary_rollout(cfg: Config, hosts: int, seed: int) -> Dict:
    cap = fleet_capacity_rps(cfg, hosts)
    # wave width: roll ~1/6th of the fleet concurrently (floor 1) —
    # the runbook wave a 100-host operator would pick
    wave = max(1, min(16, hosts // 6))
    per_host = max(int(cfg.crosshost.agent_replicas), 1)
    interval = cfg.sim.scrape_interval_s
    # duration floor from the rollout's own timeline: pull, canary
    # capacity warm + gate samples + bake, the wave-rolled swaps, and
    # a FINALIZE re-convergence of the darkened host — with 50% slack
    t_start = 10.0
    pull_s = cfg.rollout.pull_s + 2 * interval
    # the canary replica warms while the bake clock and the per-tick
    # gate samples run concurrently
    canary_s = (max(cfg.rollout.bake_s,
                    cfg.sim.warmup_s
                    + cfg.rollout.gate_min_pairs * interval)
                + 3 * interval)
    roll_s = (math.ceil(hosts / wave)
              * per_host * (cfg.sim.warmup_s + 2 * interval + 2.0))
    final_s = (cfg.sim.relaunch_s + cfg.sim.warmup_s
               + cfg.rollout.pull_s
               + per_host * (cfg.sim.warmup_s + 3 * interval) + 10.0)
    T = max(cfg.sim.duration_s,
            round((t_start + pull_s + canary_s + roll_s + final_s)
                  * 1.5, 0))
    tr = _base("canary_rollout", cfg, hosts, seed, T)
    tr["rate"] = [[0.0, round(cap * cfg.sim.util, 3)]]
    # runbook: freeze autoscaling during the rollout window (both the
    # deficit and the idle-drain signals) — the rollout's deliberate
    # +1-replica overshoot and one-dark-host dip are planned states,
    # not capacity incidents
    tr["overrides"]["crosshost__for_samples"] = 100_000
    tr["overrides"]["crosshost__idle_samples"] = 100_000
    tr["overrides"]["rollout__wave"] = wave
    # the canary lane must not exceed the canary arm's capacity share:
    # `wave` hosts hold ONE canary replica each while the gate bakes,
    # so a fraction above wave/(hosts*per_host) would overload them
    # into watermark sheds and page the canary p99 rule on a perfectly
    # healthy model (the runbook sizing step)
    tr["overrides"]["rollout__canary_fraction"] = round(
        min(cfg.rollout.canary_fraction,
            wave / float(hosts * per_host)), 4)
    # one gate sample per controller tick: the online gate judges on
    # ~min_pairs virtual seconds instead of min_pairs x sample_every
    tr["overrides"]["rollout__gate_sample_every"] = 1
    # a darkened host relaunches on the boot version well inside the
    # trace; a tighter step bound hands it to FINALIZE quickly
    tr["overrides"]["rollout__step_timeout_s"] = 25.0
    tr["rollout"] = {"version": "v2", "t_start": t_start}
    # darken one early-rolled host mid-roll: it comes back on the boot
    # store and FINALIZE must pull + swap it again (the convergence
    # half of the kill-mid-rollout bar)
    t_dark = round(t_start + pull_s + canary_s + roll_s * 0.3, 3)
    tr["events"].append({"t": t_dark, "kind": "drain_host", "host": 0})
    return _finalize(tr)


GENERATORS = {
    "diurnal": gen_diurnal,
    "flash_crowd": gen_flash_crowd,
    "failure_storm": gen_failure_storm,
    "rolling_update": gen_rolling_update,
    "canary_rollout": gen_canary_rollout,
}


def generate(name: str, cfg: Config, hosts: int, seed: int) -> Dict:
    if name not in GENERATORS:
        raise KeyError(f"unknown scenario {name!r} "
                       f"(have: {', '.join(SCENARIOS)})")
    return GENERATORS[name](cfg, hosts, seed)


def rate_at(trace: Dict, t: float) -> float:
    """Piecewise-constant demand readout (0 past the trace end)."""
    if t >= trace["duration_s"]:
        return 0.0
    r = 0.0
    for t0, rps in trace["rate"]:
        if t >= t0:
            r = rps
        else:
            break
    return float(r)
