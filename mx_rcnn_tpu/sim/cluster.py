"""Simulated hosts / replicas emulating the agent plane's gauge surface.

One :class:`SimHost` stands in for one ``serve/agent.py`` host: it owns
a REAL ``obs.metrics.Registry`` carrying exactly the metric surface the
cross-host backlog feed scrapes — ``agent.replicas_ready``,
``lane.<h>x<w>.depth``, ``serve.submitted/shed/served/expired`` counters
and the ``serve.total_ms`` histogram — so the collector, time-series
store, health rules and scheduler parse simulated hosts through the
same code paths as live ones.  The head keeps its own registry with the
``fleet.*`` counters the router would count.

Request semantics mirror ``serve/fleet.py`` + ``serve/engine.py``:

* routing is batch-aware JSQ via the SHIPPED :func:`~mx_rcnn_tpu.serve.
  fleet.jsq_key` over ready replicas minus the ones a request already
  tried;
* admission sheds at the per-lane watermark (``serve.shed_watermark``);
  a watermark shed on the JSQ-chosen (least-loaded) replica is a
  TERMINAL fleet shed — the whole fleet is saturated, 429 now;
* the engine pads every micro-batch to ``serve.batch_size`` rows, so a
  dispatch costs the bucket's service draw regardless of occupancy;
* expired requests are cancelled BEFORE dispatch, never consuming a
  batch slot; a request in flight when its deadline passes still serves
  (it was already dispatched) — both exactly the engine's contract;
* replica/host death strands queued + in-flight work, which reroutes on
  the fleet deadline budget up to ``fleet.reroute_retries`` times, then
  fails honestly.

Everything is single-threaded on the kernel's virtual clock; host
registries are real (locked) Registry objects only so the real
collector can scrape them.
"""

from __future__ import annotations

from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Tuple

from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.obs.metrics import Registry
from mx_rcnn_tpu.serve.fleet import jsq_key
from mx_rcnn_tpu.serve.rollout import version_label
from mx_rcnn_tpu.sim.kernel import SimKernel

# replica lifecycle (the sim's reduction of serve/fleet.py R_* states)
WARMING, READY, DRAINING, DEAD = "warming", "ready", "draining", "dead"

# request terminals (mirrors serve/queue.py verdicts)
SERVED, SHED, EXPIRED, FAILED = "SERVED", "SHED", "EXPIRED", "FAILED"


class SimRequest:
    __slots__ = ("rid", "bucket", "t_arrive", "deadline", "attempts",
                 "tried", "state", "t_done", "version", "routed")

    def __init__(self, rid: int, bucket: Tuple[int, int],
                 t_arrive: float, deadline: Optional[float]):
        self.rid = rid
        self.bucket = bucket
        self.t_arrive = t_arrive
        self.deadline = deadline     # absolute virtual time, None = never
        self.attempts = 0
        self.tried: set = set()
        self.state: Optional[str] = None
        self.t_done: Optional[float] = None
        # per-version exactly-once accounting: the LAST dispatch
        # target's version owns this request's terminal (mirrors the
        # live router, which counts only requests that reached a
        # replica)
        self.version: Optional[str] = None
        self.routed = False

    def past_deadline(self, now: float) -> bool:
        return self.deadline is not None and now >= self.deadline


class SimReplica:
    __slots__ = ("rid", "host", "state", "lanes", "in_flight",
                 "generation", "version")

    def __init__(self, rid: int, host: "SimHost", state: str = READY,
                 version: Optional[str] = None):
        self.rid = rid               # fleet-unique: the JSQ tiebreak id
        self.host = host
        self.state = state
        self.lanes: Dict[Tuple[int, int], Deque[SimRequest]] = {}
        self.in_flight: List[SimRequest] = []
        self.generation = 0
        # export-store version this replica serves (None = the boot
        # store — the sim analog of Replica.version in serve/fleet.py)
        self.version = version

    def lane_depth(self, bucket: Tuple[int, int]) -> int:
        lane = self.lanes.get(bucket)
        return len(lane) if lane is not None else 0

    def depth(self) -> int:
        return (sum(len(q) for q in self.lanes.values())
                + len(self.in_flight))

    def queued(self) -> int:
        return sum(len(q) for q in self.lanes.values())


class SimHost:
    """One simulated agent host: registry + replicas + up/down state."""

    def __init__(self, index: int, boot_replicas: int,
                 next_rid: Callable[[], int]):
        self.index = index
        self.name = f"agent-{index}"
        self.up = True
        self.generation = 0
        self.registry = Registry()
        self.replicas: List[SimReplica] = [
            SimReplica(next_rid(), self) for _ in range(boot_replicas)]
        # rollout plane: versions this host has pulled, and in-progress
        # pulls (version -> virtual completion time).  Both reset on
        # relaunch — a fresh process re-pulls (the live agent's pull is
        # idempotent/resumable for the same reason)
        self.pulled: set = set()
        self.pulling: Dict[str, float] = {}
        # the version scheduler resizes build (the live agent repoints
        # this when a swap completes, so post-rollout adds stay v2)
        self.default_version: Optional[str] = None

    def ready_replicas(self) -> List[SimReplica]:
        return [r for r in self.replicas if r.state == READY]

    def resolve(self):
        """RegistrySource resolver: down host = None, exactly like a
        dead HttpSource — its gauges vanish from the next sample."""
        if not self.up:
            return None
        return self.registry, {"generation": self.generation}


class SimCluster:
    """The simulated fleet: hosts, routing, service, failure injection.

    All mutation happens inside kernel events (single thread, virtual
    time).  ``log`` is the harness's event sink — entries land in the
    deterministic decision log.
    """

    def __init__(self, kernel: SimKernel, cfg: Config, hosts: int,
                 log: Callable[..., None]):
        self.k = kernel
        self.cfg = cfg
        self.log = log
        self._rid = 0

        def next_rid() -> int:
            self._rid += 1
            return self._rid

        self._next_rid = next_rid
        per_host = max(int(cfg.crosshost.agent_replicas), 1)
        self.hosts: List[SimHost] = [
            SimHost(i, per_host, next_rid) for i in range(int(hosts))]
        self.head = Registry()
        self.buckets: List[Tuple[int, int]] = [
            tuple(b) for b in cfg.bucket.shapes]
        base = min(h * w for h, w in self.buckets)
        self._bucket_scale = {b: (b[0] * b[1]) / base
                              for b in self.buckets}
        self._svc_rng = kernel.rng("service")
        self._rot = 0                # JSQ tie-break rotation counter
        self._req_seq = 0
        # exact terminal accounting for the scorer (the head registry
        # carries the same totals; these stay ints with no scrape lag)
        self.stats = {"submitted": 0, "served": 0, "shed": 0,
                      "expired": 0, "failed": 0, "rerouted": 0}
        self.wait_ms_max = 0.0
        # rollout plane: the deterministic canary lane (mirrors
        # FleetRouter.set_canary's fraction accumulator) and exact
        # per-version terminal counts keyed by version label
        self._canary: Optional[Tuple[str, float]] = None
        self._canary_acc = 0.0
        self.ver_stats: Dict[str, Dict[str, int]] = {}

    # -- gauge surface (called by the harness before every scrape) --------

    def refresh_gauges(self) -> None:
        total_ready = 0
        hosts_up = 0
        for h in self.hosts:
            if not h.up:
                continue
            hosts_up += 1
            ready = len(h.ready_replicas())
            total_ready += ready
            h.registry.set_gauge("agent.replicas_ready", float(ready))
            for b in self.buckets:
                depth = sum(r.lane_depth(b) for r in h.replicas
                            if r.state in (READY, DRAINING))
                h.registry.set_gauge(f"lane.{b[0]}x{b[1]}.depth",
                                     float(depth))
        self.head.set_gauge("fleet.replicas_ready", float(total_ready))
        self.head.set_gauge("fleet.hosts_up", float(hosts_up))

    def ready_count(self) -> int:
        return sum(len(h.ready_replicas()) for h in self.hosts
                   if h.up)

    # -- request path ------------------------------------------------------

    def submit(self, bucket: Tuple[int, int]) -> SimRequest:
        now = self.k.clock.now
        self._req_seq += 1
        deadline_ms = self.cfg.serve.default_timeout_ms
        deadline = (now + deadline_ms / 1000.0) if deadline_ms else None
        req = SimRequest(self._req_seq, bucket, now, deadline)
        self.stats["submitted"] += 1
        self.head.inc("fleet.submitted")
        self._dispatch(req)
        return req

    def _candidates(self, req: SimRequest) -> List[SimReplica]:
        out = []
        for h in self.hosts:
            if not h.up:
                continue
            for r in h.replicas:
                if r.state == READY and r.rid not in req.tried:
                    out.append(r)
        return out

    def _dispatch(self, req: SimRequest) -> None:
        """Mirror of ``FleetRouter._dispatch``: deadline first, then
        JSQ over untried ready replicas, then watermark admission."""
        now = self.k.clock.now
        if req.past_deadline(now):
            self._settle(req, EXPIRED)
            return
        cands = self._candidates(req)
        if not cands:
            self._settle(req, FAILED)
            return
        cands = self._canary_lane(cands)
        batch = self.cfg.serve.batch_size
        self._rot += 1
        rot, n = self._rot, len(cands)
        target = min(cands,
                     key=lambda r: jsq_key(r.lane_depth(req.bucket),
                                           r.depth(), r.rid, rot, n,
                                           batch))
        req.tried.add(target.rid)
        req.attempts += 1
        req.version = target.version
        req.routed = True
        self._ver(version_label(req.version))["dispatched"] += 1
        self.head.inc(f"fleet.ver.{version_label(req.version)}"
                      ".dispatched")
        if target.lane_depth(req.bucket) >= self.cfg.serve.shed_watermark:
            # the least-loaded lane is at its watermark: the fleet is
            # saturated — terminal 429, no retry (fleet.py contract)
            target.host.registry.inc("serve.shed")
            self._settle(req, SHED)
            return
        target.host.registry.inc("serve.submitted")
        target.lanes.setdefault(req.bucket, deque()).append(req)
        self._maybe_start(target)

    def _maybe_start(self, r: SimReplica) -> None:
        if r.in_flight or r.state not in (READY, DRAINING):
            self._maybe_finish_drain(r)
            return
        now = self.k.clock.now
        batch_rows: List[SimRequest] = []
        # oldest waiter first (the engine's max_delay contract: no lane
        # starves behind a hot bucket), bucket order breaking ties
        for bucket in sorted(
                r.lanes,
                key=lambda b: ((r.lanes[b][0].t_arrive, b)
                               if r.lanes[b] else (float("inf"), b))):
            lane = r.lanes[bucket]
            while lane and len(batch_rows) < self.cfg.serve.batch_size:
                req = lane.popleft()
                if req.past_deadline(now):
                    # cancelled before dispatch: dead work never
                    # occupies a batch slot (engine contract)
                    r.host.registry.inc("serve.expired")
                    self._settle(req, EXPIRED)
                    continue
                batch_rows.append(req)
            if batch_rows:
                svc = self._service_s(bucket)
                r.in_flight = batch_rows
                self.k.after(svc, lambda rr=r: self._complete(rr))
                return
        self._maybe_finish_drain(r)

    def _service_s(self, bucket: Tuple[int, int]) -> float:
        sim = self.cfg.sim
        base = (sim.service_ms / 1000.0) * self._bucket_scale[bucket]
        jitter = float(self._svc_rng.lognormal(0.0, sim.service_jitter))
        return base * jitter

    def _complete(self, r: SimReplica) -> None:
        now = self.k.clock.now
        rows, r.in_flight = r.in_flight, []
        if r.state == DEAD:
            return  # the death path already rerouted these rows
        for req in rows:
            wait_ms = (now - req.t_arrive) * 1000.0
            self.wait_ms_max = max(self.wait_ms_max, wait_ms)
            r.host.registry.inc("serve.served")
            r.host.registry.observe("serve.total_ms", wait_ms)
            self.head.observe("fleet.total_ms", wait_ms)
            self._settle(req, SERVED)
        self._maybe_start(r)

    def _retry_or_fail(self, req: SimRequest) -> None:
        """Mirror of ``FleetRouter._retry_or_fail``: expiry outranks the
        death verdict; reroutes never extend the deadline."""
        if req.past_deadline(self.k.clock.now):
            self._settle(req, EXPIRED)
            return
        if req.attempts < 1 + max(self.cfg.fleet.reroute_retries, 0):
            self.stats["rerouted"] += 1
            self.head.inc("fleet.rerouted")
            self._dispatch(req)
            return
        self._settle(req, FAILED)

    def _ver(self, label: str) -> Dict[str, int]:
        return self.ver_stats.setdefault(
            label, {"dispatched": 0, "served": 0, "shed": 0,
                    "expired": 0, "failed": 0})

    def _canary_lane(self, cands: List[SimReplica]) -> List[SimReplica]:
        """Mirror of ``FleetRouter._canary_lane``: a deterministic
        fraction accumulator steers every Nth dispatch to the canary
        version; an empty lane falls back to the full candidate set
        (availability outranks canary purity), counted."""
        if self._canary is None:
            return cands
        version, fraction = self._canary
        self._canary_acc += fraction
        take = self._canary_acc >= 1.0
        if take:
            self._canary_acc -= 1.0
        lane = [r for r in cands if (r.version == version) == take]
        if not lane:
            self.head.inc("fleet.canary_fallback")
            return cands
        return lane

    def set_canary(self, version: Optional[str],
                   fraction: float) -> None:
        if version is None:
            self._canary = None
        else:
            self._canary = (version,
                            max(0.0, min(float(fraction), 1.0)))
        self._canary_acc = 0.0
        self.log("set_canary",
                 version=None if version is None
                 else version_label(version),
                 fraction=round(float(fraction), 4))

    def _settle(self, req: SimRequest, state: str) -> None:
        req.state = state
        req.t_done = self.k.clock.now
        key = state.lower()
        self.stats[key] += 1
        self.head.inc(f"fleet.{key}")
        if req.routed:
            lbl = version_label(req.version)
            self._ver(lbl)[key] += 1
            self.head.inc(f"fleet.ver.{lbl}.{key}")
            if state == SERVED:
                self.head.observe(
                    f"fleet.ver.{lbl}.total_ms",
                    (req.t_done - req.t_arrive) * 1000.0)

    # -- failure / actuation events ---------------------------------------

    def host_down(self, index: int) -> None:
        h = self.hosts[index]
        if not h.up:
            return
        h.up = False
        self.log("host_down", host=h.name)
        stranded: List[SimRequest] = []
        for r in h.replicas:
            r.state = DEAD
            stranded.extend(r.in_flight)
            r.in_flight = []
            for bucket in sorted(r.lanes):
                stranded.extend(r.lanes[bucket])
            r.lanes.clear()
        for req in stranded:
            self._retry_or_fail(req)

    def host_up(self, index: int) -> None:
        """Relaunch: fresh process → fresh registry (counters reset —
        the scheduler's negative-delta clamp exists for exactly this),
        replicas rewarm through the cold-join delay."""
        h = self.hosts[index]
        if h.up:
            return
        h.generation += 1
        h.registry = Registry()
        per_host = max(int(self.cfg.crosshost.agent_replicas), 1)
        h.replicas = [SimReplica(self._next_rid(), h, state=WARMING)
                      for _ in range(per_host)]
        # a fresh process boots from the boot store: pulled versions
        # are gone, replicas come up version-less (the FINALIZE
        # convergence path re-pulls and re-swaps them)
        h.pulled = set()
        h.pulling = {}
        h.default_version = None
        h.up = True
        self.log("host_up", host=h.name, generation=h.generation)
        for r in h.replicas:
            self.k.after(self.cfg.sim.warmup_s,
                         lambda rr=r: self._replica_ready(rr))

    def _replica_ready(self, r: SimReplica) -> None:
        if r.state != WARMING or not r.host.up:
            return
        r.state = READY
        self.log("replica_ready", host=r.host.name, replica=r.rid)
        self._maybe_start(r)

    def resize(self, index: int, delta: int) -> Optional[Dict]:
        """The agent's ``POST /replicas`` semantics: +1 warms a new
        replica, -1 drains one — clamped at one live replica per host
        (a live host always keeps a warm engine)."""
        h = self.hosts[index]
        if not h.up:
            return None
        if delta >= 1:
            r = SimReplica(self._next_rid(), h, state=WARMING,
                           version=h.default_version)
            h.replicas.append(r)
            self.k.after(self.cfg.sim.warmup_s,
                         lambda rr=r: self._replica_ready(rr))
            return {"ok": True, "replicas": len(h.replicas)}
        ready = h.ready_replicas()
        if len(ready) <= 1:
            return {"ok": False, "error": "refusing to drain below one "
                                          "replica"}
        # drain the emptiest ready replica (cheapest to finish out)
        r = min(ready, key=lambda x: (x.depth(), x.rid))
        r.state = DRAINING
        self._maybe_finish_drain(r)
        return {"ok": True, "replicas": len(h.replicas)}

    def _maybe_finish_drain(self, r: SimReplica) -> None:
        if (r.state == DRAINING and not r.in_flight
                and r.queued() == 0):
            r.state = DEAD
            if r in r.host.replicas:
                r.host.replicas.remove(r)
            self.log("replica_drained", host=r.host.name,
                     replica=r.rid)

    def drain_host(self, index: int) -> None:
        """Rolling-update step: stop admissions on every replica, let
        queues finish, go dark, relaunch after ``sim.relaunch_s``."""
        h = self.hosts[index]
        if not h.up:
            return
        self.log("drain_host", host=h.name)
        for r in h.replicas:
            if r.state in (READY, WARMING):
                r.state = DRAINING
                self._maybe_finish_drain(r)
        self._watch_drain(index)

    def _watch_drain(self, index: int) -> None:
        h = self.hosts[index]
        if not h.up:
            return
        if any(r.in_flight or r.queued() for r in h.replicas):
            self.k.after(0.5, lambda: self._watch_drain(index))
            return
        h.up = False
        h.replicas = []
        self.log("host_dark", host=h.name)
        self.k.after(self.cfg.sim.relaunch_s,
                     lambda: self.host_up(index))

    # -- rollout plane (SimRolloutPort verbs) ------------------------------

    def pull_version(self, index: int, version: str) -> Optional[Dict]:
        """The agent's ``/rollout op=pull``: idempotent, takes
        ``rollout.pull_s`` virtual seconds the first time (returning
        None while in flight — the controller's retry/defer machinery
        owns the wait, exactly as live)."""
        h = self.hosts[index]
        if not h.up:
            return None
        if version in h.pulled:
            return {"ok": True, "version": version, "already": True}
        now = self.k.clock.now
        t_done = h.pulling.get(version)
        if t_done is None:
            h.pulling[version] = now + self.cfg.rollout.pull_s
            self.log("pull_start", host=h.name,
                     version=version_label(version))
            return None
        if now < t_done:
            return None
        del h.pulling[version]
        h.pulled.add(version)
        self.log("pulled", host=h.name, version=version_label(version))
        return {"ok": True, "version": version, "already": False}

    def host_versions(self, index: int) -> Optional[Dict[str, int]]:
        """``{version_label: ready_count}`` — the agent's rollout
        status surface; None when the host is down."""
        h = self.hosts[index]
        if not h.up:
            return None
        out: Dict[str, int] = {}
        for r in h.replicas:
            if r.state == READY:
                lbl = version_label(r.version)
                out[lbl] = out.get(lbl, 0) + 1
        return out

    def swap_replica(self, index: int, version: str) -> Optional[Dict]:
        """One pump of the agent's rolling replace toward ``version``.
        An unpulled version answers None (a relaunched host lost its
        pull — the controller defers and FINALIZE re-converges)."""
        h = self.hosts[index]
        if not h.up or version not in h.pulled:
            return None
        return self._pump_host(h, version)

    def rollback_host(self, index: int) -> Optional[Dict]:
        """One pump back toward the boot (version-less) store."""
        h = self.hosts[index]
        if not h.up:
            return None
        return self._pump_host(h, None)

    def _pump_host(self, h: SimHost,
                   version: Optional[str]) -> Dict:
        """The agent's ``_pump_toward`` reduced to sim state: add ONE
        warming target replica (bounded to +1 over the boot count),
        then — once a target replica is ready — gracefully drain ONE
        old replica, repeat.  Never drops below one ready replica;
        draining replicas finish their queues (exactly-once)."""
        per_host = max(int(self.cfg.crosshost.agent_replicas), 1)
        tgt = [r for r in h.replicas
               if r.version == version and r.state in (READY, WARMING)]
        tgt_ready = [r for r in tgt if r.state == READY]
        old = [r for r in h.replicas
               if r.version != version and r.state in (READY, WARMING)]
        added = swapped = None
        if len(tgt) < per_host and len(tgt) + len(old) <= per_host:
            r = SimReplica(self._next_rid(), h, state=WARMING,
                           version=version)
            h.replicas.append(r)
            self.k.after(self.cfg.sim.warmup_s,
                         lambda rr=r: self._replica_ready(rr))
            added = r.rid
            self.log("swap_add", host=h.name, replica=r.rid,
                     version=version_label(version))
        elif old and tgt_ready:
            old_ready = [r for r in old if r.state == READY]
            if old_ready:
                victim = min(old_ready,
                             key=lambda x: (x.depth(), x.rid))
                victim.state = DRAINING
                swapped = victim.rid
                self.log("swap_drain", host=h.name,
                         replica=victim.rid,
                         version=version_label(victim.version))
                self._maybe_finish_drain(victim)
        remaining = sum(1 for r in h.replicas
                        if r.version != version
                        and r.state in (READY, WARMING, DRAINING))
        pending = sum(1 for r in h.replicas
                      if r.version == version and r.state == WARMING)
        if remaining == 0 and pending == 0:
            # swap complete: future scheduler resizes build this
            # version (the live agent repoints manager._build_fn)
            h.default_version = version
        return {"remaining": remaining, "pending": pending,
                "added": added, "swapped": swapped}

    # -- quiescence --------------------------------------------------------

    def pending(self) -> int:
        return sum(r.queued() + len(r.in_flight)
                   for h in self.hosts for r in h.replicas)

    def fail_pending(self) -> int:
        """End-of-settle cleanup: anything still queued after the settle
        budget is honestly lost."""
        n = 0
        for h in self.hosts:
            for r in h.replicas:
                for req in list(r.in_flight):
                    self._settle(req, FAILED)
                    n += 1
                r.in_flight = []
                for bucket in sorted(r.lanes):
                    for req in r.lanes[bucket]:
                        self._settle(req, FAILED)
                        n += 1
                r.lanes.clear()
        return n
