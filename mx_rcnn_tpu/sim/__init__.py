"""Fleet-at-scale simulator: the control plane judged at 100 hosts.

No reference equivalent.  The scheduler, health engine and JSQ router
(PRs 14-15) have only ever been exercised at 2-4 hosts on a 1-core box;
the north star is a fleet.  This package answers "does hysteresis flap
at 100 hosts?" without silicon: a discrete-event virtual-time kernel
(``kernel.py``) drives simulated hosts/replicas that emulate the agent
plane's gauge surface (``cluster.py``), scenario trace generators
(``traffic.py``) shape demand and failures, and the harness
(``control.py``) runs the SHIPPED ``SchedulerPolicy`` / ``HealthEngine``
/ ``jsq_key`` / ``RestartPolicy`` decision code — the real classes, fed
through the real ``Collector`` → ``TimeSeriesStore`` path, on a virtual
clock — while ``score.py`` turns the outcome into lost requests,
SLO-minutes breached and capacity-seconds wasted.

``python -m mx_rcnn_tpu.tools.sim`` is the policy gauntlet driver
(``SIM_r17.json``); ``docs/SIM.md`` is the manual.
"""

from mx_rcnn_tpu.sim.kernel import SimKernel, VirtualClock  # noqa: F401
