"""Bounded network I/O primitives for every wire surface.

No reference equivalent.  PR 15 made bytes arrive from other machines
(``serve/remote.py`` wire frames, ``serve/agent.py`` HTTP bodies,
``obs/collect.py`` metric scrapes, ``serve/scheduler.py`` admin RPCs),
and netlint (``analysis/netlint.py``) now demands that every one of
those reads be *bounded by construction*: a remote peer that claims a
multi-GB Content-Length, streams an unbounded body, or trickles bytes
forever must cost a typed rejection, never an allocation or a wedged
thread.  This module is the one place that discipline lives so the
linter can model it as a single trusted helper:

* :func:`read_limited` — drain an ``http.client``/``urllib`` response
  in chunks with a hard byte cap; crossing the cap raises
  :class:`ResponseTooLarge` (a ``ValueError``, so every existing
  malformed-input catch path already handles it);
* :func:`read_request_body` — the server-side twin for
  ``BaseHTTPRequestHandler``: absent Content-Length is 411, an
  oversized claim is 413 *before a single body byte is read*, a short
  body (peer died mid-send) is 400.  Handlers map
  :class:`BodyError.status` straight onto the reply;
* :func:`check_timeout_ms` — sanitize a peer-supplied timeout before
  it reaches deadline arithmetic: wirefuzz found that an ``inf`` in a
  frame's timeout field reaches ``Condition.wait`` as an
  ``OverflowError`` (a 500 for client bytes) and a ``NaN`` poisons
  every deadline comparison.

Deliberately dependency-free (stdlib only, no package imports) so both
``serve/*`` and ``obs/*`` use it without an import cycle.
"""

from __future__ import annotations

import time
from typing import Dict, Tuple

# default cap for control-plane JSON replies (healthz, resize results,
# metric snapshots): far above any legitimate body, far below harm
DEFAULT_CAP_BYTES = 8 << 20

_CHUNK = 64 << 10


class ResponseTooLarge(ValueError):
    """A response body crossed its byte cap mid-read.  ValueError on
    purpose: every wire consumer already routes ValueError to its typed
    rejection path (400 / failed scrape / RemoteTransportError)."""


class ResponseTooSlow(ValueError):
    """A body read crossed its wall-clock deadline.  Socket timeouts
    bound the gap BETWEEN bytes; a slow-loris peer that trickles one
    byte per tick never trips them, so total-read time needs its own
    bound (wirefuzz's trickle leg pins this)."""


class BodyError(ValueError):
    """A request body that must be refused before it is read.  Carries
    the HTTP status the handler should reply with: 411 (no
    Content-Length — includes chunked transfer, which the stdlib
    handler does not decode), 413 (claimed length over the cap), 408
    (body read past its wall-clock deadline), 400 (unparseable/negative
    length, or a body shorter than its claim)."""

    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = int(status)


def read_limited(resp, max_bytes: int = DEFAULT_CAP_BYTES,
                 what: str = "response body",
                 deadline_s: float = None) -> bytes:
    """Drain ``resp`` (anything with ``.read(n)``) up to ``max_bytes``.

    Chunked on purpose: the cap is enforced DURING the read, so a peer
    streaming more than it claimed (or claiming nothing at all) is cut
    off at the cap plus one chunk, never buffered whole.  With
    ``deadline_s`` set the TOTAL read is also wall-clock bounded: the
    loop prefers ``read1`` (returns whatever is buffered instead of
    blocking until a full chunk arrives) so a trickling peer is cut off
    at the deadline, not at ``cap / bytes-per-tick`` (which is hours).
    """
    max_bytes = int(max_bytes)
    t0 = time.monotonic() if deadline_s else 0.0
    read1 = getattr(resp, "read1", None) if deadline_s else None
    out = bytearray()
    while True:
        chunk = read1(_CHUNK) if read1 is not None else resp.read(_CHUNK)
        if not chunk:
            return bytes(out)
        out += chunk
        if len(out) > max_bytes:
            raise ResponseTooLarge(
                f"{what} exceeded the {max_bytes}-byte cap")
        if deadline_s and time.monotonic() - t0 > deadline_s:
            raise ResponseTooSlow(
                f"{what} read exceeded {deadline_s:g}s "
                f"({len(out)} bytes in)")


# widest timeout any wire peer may request: a week in ms.  Finite but
# huge values (one flipped exponent bit makes 1e38) still overflow
# Condition.wait's C timestamp, so "finite" alone is not enough.
MAX_TIMEOUT_MS = 7 * 86400 * 1000.0


def check_timeout_ms(value, what: str = "timeout_ms"):
    """Validate a wire-supplied timeout: ``None`` passes through (the
    caller's default applies); anything else must be a number in
    ``[0, MAX_TIMEOUT_MS]``.  NaN fails the ``>= 0`` comparison by IEEE
    semantics, so one range check covers NaN, inf and negatives."""
    if value is None:
        return None
    try:
        t = float(value)
    except (TypeError, ValueError):
        raise ValueError(f"{what} must be a number, got {value!r}")
    if not (0.0 <= t <= MAX_TIMEOUT_MS):
        raise ValueError(f"{what} must be in [0, {MAX_TIMEOUT_MS:g}], "
                         f"got {t!r}")
    return t


# trace-context header bound (obs/trace.py TRACE_HEADER): the value is
# a short structured string; anything longer is hostile, not a trace
MAX_TRACE_HEADER = 256


def check_trace_header(value, what: str = "X-MXR-Trace"):
    """Pre-validate a wire-supplied trace-context header before it
    reaches the parser: ``None`` passes through (untraced request —
    the back-compat path), anything else must be a short ascii string.
    Malformation is a typed 400 (BodyError), NEVER a silently dropped
    or zero-filled context — a peer that SENT a context must learn it
    was unusable (the netio rejection contract applied to tracing)."""
    if value is None:
        return None
    if not isinstance(value, str) or len(value) > MAX_TRACE_HEADER:
        raise BodyError(400, f"{what} header missing or over "
                             f"{MAX_TRACE_HEADER} chars")
    try:
        value.encode("ascii")
    except UnicodeEncodeError:
        raise BodyError(400, f"{what} header is not ascii")
    return value


# iovec count per sendmsg call: safely under every platform's IOV_MAX
# (Linux 1024); the loop below re-issues for anything beyond it
_IOV_CHUNK = 64

# response-header byte bound for the raw-socket client: a status line +
# the stdlib server's handful of headers is < 1 KB; anything near this
# cap is not an HTTP response from our agent
MAX_HTTP_HEAD = 16 << 10


def sendmsg_all(sock, bufs) -> int:
    """Vectored ``sendall``: ship a list of buffers (bytes / memoryview /
    anything buffer-protocol) through ``socket.sendmsg`` until every
    byte is out.  The zero-copy half of the wire hot path
    (``serve/remote.py``): a frame goes out as header-bytes +
    memoryview-of-pixels iovecs, so the payload is never concatenated
    into one transient request body.  Handles short writes by re-slicing
    the partially-sent buffer (``sendmsg`` has no ``sendall`` twin) and
    chunks the iovec list under IOV_MAX.  Returns total bytes sent."""
    views = [memoryview(b).cast("B") for b in bufs]
    views = [v for v in views if len(v)]
    total = 0
    while views:
        n = sock.sendmsg(views[:_IOV_CHUNK])
        total += n
        while n > 0:
            if n >= len(views[0]):
                n -= len(views[0])
                views.pop(0)
            else:
                views[0] = views[0][n:]
                n = 0
    return total


def _parse_http_head(head: bytes, what: str) -> Tuple[int, Dict[str, str]]:
    lines = head.split(b"\r\n")
    parts = lines[0].split(None, 2)
    if len(parts) < 2 or not parts[0].startswith(b"HTTP/"):
        raise ValueError(f"{what}: not an HTTP status line: "
                         f"{bytes(lines[0][:80])!r}")
    try:
        status = int(parts[1])
    except ValueError:
        raise ValueError(f"{what}: unparseable status {parts[1]!r}")
    headers: Dict[str, str] = {}
    for ln in lines[1:]:
        k, _, v = ln.partition(b":")
        headers[k.strip().lower().decode("latin-1")] = \
            v.strip().decode("latin-1")
    return status, headers


def read_http_response_into(sock, body: bytearray, max_bytes: int,
                            deadline_s: float = None,
                            what: str = "response"
                            ) -> Tuple[int, int, bool]:
    """Read ONE HTTP/1.1 response off a blocking socket into a caller-
    owned (preallocated, reused) ``body`` buffer — the recv half of the
    wire hot path: no per-response allocation once the buffer has grown
    to the burst's largest reply.

    Bounded by construction: the header read is capped at
    :data:`MAX_HTTP_HEAD`, the body must declare Content-Length (our
    agents always do) and its claim is refused ABOVE ``max_bytes``
    before a body byte lands, and ``deadline_s`` wall-clock bounds the
    total read (socket timeouts only bound the gap between bytes).
    Returns ``(status, body_len, server_wants_close)``; the caller reads
    the body from ``memoryview(body)[:body_len]`` and must consume it
    before the next call.  Protocol violations raise ``ValueError``
    (typed rejection); a peer that vanished raises ``ConnectionError``
    (the stale-keep-alive retry signal)."""
    t0 = time.monotonic() if deadline_s else 0.0
    head = bytearray()
    while True:
        idx = head.find(b"\r\n\r\n")
        if idx >= 0:
            break
        if len(head) > MAX_HTTP_HEAD:
            raise ResponseTooLarge(
                f"{what}: header exceeded the {MAX_HTTP_HEAD}-byte cap")
        if deadline_s and time.monotonic() - t0 > deadline_s:
            raise ResponseTooSlow(
                f"{what}: header read exceeded {deadline_s:g}s")
        chunk = sock.recv(8192)
        if not chunk:
            raise ConnectionError(
                f"{what}: peer closed at {len(head)} header bytes")
        head += chunk
    status, headers = _parse_http_head(bytes(head[:idx]), what)
    leftover = head[idx + 4:]
    claimed = headers.get("content-length")
    if claimed is None:
        raise ValueError(f"{what}: missing Content-Length")
    n = int(claimed)  # ValueError on garbage is the typed rejection
    if n < 0:
        raise ValueError(f"{what}: negative Content-Length {n}")
    if n > int(max_bytes):
        raise ResponseTooLarge(
            f"{what}: body of {n} bytes over the {int(max_bytes)}-byte "
            f"cap")
    if len(leftover) > n:
        raise ValueError(f"{what}: {len(leftover) - n} bytes past the "
                         f"declared body")
    if len(body) < n:
        body.extend(bytes(n - len(body)))
    view = memoryview(body)
    view[:len(leftover)] = leftover
    got = len(leftover)
    while got < n:
        if deadline_s and time.monotonic() - t0 > deadline_s:
            raise ResponseTooSlow(
                f"{what}: body read exceeded {deadline_s:g}s at {got} "
                f"of {n} bytes")
        k = sock.recv_into(view[got:n])
        if not k:
            raise ConnectionError(
                f"{what}: peer closed at {got} of {n} body bytes")
        got += k
    wants_close = headers.get("connection", "").lower() == "close"
    return status, n, wants_close


def read_request_body(handler, max_bytes: int,
                      deadline_s: float = None) -> bytes:
    """Read one HTTP request body off ``handler`` (a
    ``BaseHTTPRequestHandler``) with the 411/413/400 refusal contract.

    The 413 fires off the *claimed* length, before any body byte is
    read — a multi-GB Content-Length costs the peer a rejection, not
    this process an allocation.  ``deadline_s`` wall-clock bounds the
    whole body read (408 past it): the handler's socket timeout only
    bounds the gap between bytes, which a slow-loris sender never
    exceeds.
    """
    claimed = handler.headers.get("Content-Length")
    if claimed is None:
        raise BodyError(411, "Content-Length required "
                             "(chunked bodies are not accepted)")
    try:
        n = int(claimed)
    except ValueError:
        raise BodyError(400, f"unparseable Content-Length {claimed!r}")
    if n < 0:
        raise BodyError(400, f"negative Content-Length {n}")
    if n > int(max_bytes):
        raise BodyError(413, f"body of {n} bytes over the "
                             f"{int(max_bytes)}-byte cap")
    if not deadline_s:
        body = handler.rfile.read(n)
    else:
        t0 = time.monotonic()
        read1 = getattr(handler.rfile, "read1", None)
        out = bytearray()
        while len(out) < n:
            want = min(_CHUNK, n - len(out))
            chunk = (read1(want) if read1 is not None
                     else handler.rfile.read(want))
            if not chunk:
                break
            out += chunk
            if len(out) < n and time.monotonic() - t0 > deadline_s:
                raise BodyError(408, f"body read exceeded "
                                     f"{deadline_s:g}s at {len(out)} "
                                     f"of {n} bytes")
        body = bytes(out)
    if len(body) != n:
        raise BodyError(400, f"body ended at {len(body)} of {n} "
                             f"claimed bytes")
    return body
