"""configlint — config-key static analysis for the 3-level precedence
surface.

The config system (``config.py``) is a frozen-dataclass tree addressed
as ``cfg.<section>.<key>`` everywhere, with CLI overrides spelled
``--set section__key=value``.  Nothing ties an attribute read to the
dataclass: a typo'd read (``cfg.serve.batch_sz``) raises only when the
line executes — possibly rounds later, in a rarely-driven smoke — and a
knob nobody reads anymore silently rides along forever, looking
configurable while doing nothing.  configlint closes both directions:

* **CL101** — a ``cfg.<section>.<key>`` read names a key that does not
  exist in that section's dataclass (typo / removed knob).  Follows the
  common aliasing patterns: ``s = cfg.serve; s.batch_size``,
  ``self.cfg.<section>.<key>``, ``getattr(cfg, "obs", None)``.
* **CL201** — dead key: a field declared in a ``config.py`` dataclass
  that no code in the scanned tree reads.  Reported at the field's
  definition line, so the waiver (with its reason) sits next to the
  knob it documents.

Keys consumed only generically (``dataclasses.fields`` iteration in the
fingerprint, ``--set`` plumbing) do NOT count as reads — a knob that is
only serialized is still dead.  Properties of a section class count as
valid keys (``cfg.network.num_anchors`` is derived, not declared).

Waivers: same protocol as graphlint/threadlint
(``# configlint: disable=CL201 <reason>``); reasonless → CL001, unknown
rule → CL002.

CLI::

    python -m mx_rcnn_tpu.analysis.configlint [paths...] [--json]
        [--show-waived] [--list-rules] [--dump-keys]
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import json
import os
import sys
from typing import Dict, List, Optional, Sequence, Set, Tuple

from mx_rcnn_tpu.analysis.common import (Finding, apply_waivers,
                                         check_paths_exist, iter_py_files,
                                         parse_waivers)

RULES: Dict[str, str] = {
    "CL001": "waiver without a reason (every waiver must say why)",
    "CL002": "waiver names an unknown rule code",
    "CL101": "config key read does not exist in the config.py dataclass",
    "CL201": "dead config key: declared in config.py but never read",
}


def _section_schema() -> Tuple[Dict[str, Set[str]], Dict[str, type]]:
    """``{section: {valid keys}}`` from the live Config dataclasses —
    fields plus properties (derived keys like ``num_anchors``)."""
    from mx_rcnn_tpu.config import Config

    sections: Dict[str, Set[str]] = {}
    classes: Dict[str, type] = {}
    for f in dataclasses.fields(Config):
        cls = f.default_factory if f.default_factory is not \
            dataclasses.MISSING else type(getattr(Config(), f.name))
        keys = {sf.name for sf in dataclasses.fields(cls)}
        keys |= {n for n, v in vars(cls).items()
                 if isinstance(v, property)}
        sections[f.name] = keys
        classes[f.name] = cls
    return sections, classes


def _is_cfg_base(node: ast.AST) -> bool:
    """Heuristic root test: ``cfg`` / ``kcfg`` / anything ``*cfg``, or an
    attribute spelled ``.cfg`` (``self.cfg``)."""
    if isinstance(node, ast.Name):
        return node.id == "config" or node.id.endswith("cfg")
    if isinstance(node, ast.Attribute):
        return node.attr == "cfg" or node.attr.endswith("cfg")
    return False


class _Visitor(ast.NodeVisitor):
    """Per-module scan: section-alias tracking + key-read collection."""

    def __init__(self, path: str, sections: Dict[str, Set[str]],
                 section_classes: Dict[str, str]):
        self.path = path
        self.sections = sections
        self.section_classes = section_classes   # class name -> section
        self.scope_aliases: List[Dict[str, str]] = [{}]  # name -> section
        self.reads: Set[Tuple[str, str]] = set()
        self.findings: List[Finding] = []

    # -- scopes -------------------------------------------------------------

    def visit_FunctionDef(self, node):
        scope = dict(self.scope_aliases[0])
        # a parameter annotated with a section CLASS is that section
        # (``def spec_from_config(qcfg: QuantConfig)``)
        for a in node.args.posonlyargs + node.args.args + \
                node.args.kwonlyargs:
            ann = a.annotation
            name = None
            if isinstance(ann, ast.Name):
                name = ann.id
            elif isinstance(ann, ast.Constant) and isinstance(ann.value,
                                                              str):
                name = ann.value.strip("'\"")
            if name in self.section_classes:
                scope[a.arg] = self.section_classes[name]
        self.scope_aliases.append(scope)
        self.generic_visit(node)
        self.scope_aliases.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def _alias_of(self, value: ast.AST) -> Optional[str]:
        """The section named by an expression, if any: ``cfg.serve``,
        ``getattr(cfg, "serve", ...)``."""
        if isinstance(value, ast.Attribute) and \
                value.attr in self.sections and _is_cfg_base(value.value):
            return value.attr
        if isinstance(value, ast.Call) and \
                isinstance(value.func, ast.Name) and \
                value.func.id == "getattr" and len(value.args) >= 2 and \
                _is_cfg_base(value.args[0]) and \
                isinstance(value.args[1], ast.Constant) and \
                value.args[1].value in self.sections:
            return value.args[1].value
        return None

    def _section_of(self, node: ast.AST) -> Optional[str]:
        """The section an expression denotes: ``cfg.<section>``, a local
        alias, or ``getattr(cfg, "section", ...)``."""
        sec = self._alias_of(node)
        if sec is not None:
            return sec
        if isinstance(node, ast.Name):
            return self.scope_aliases[-1].get(node.id)
        return None

    def visit_Call(self, node: ast.Call) -> None:
        # getattr(<section>, "key", default) is a read of section.key
        # (the defensive-access idiom, e.g. ft/elastic.py topology_path)
        if isinstance(node.func, ast.Name) and node.func.id == "getattr" \
                and len(node.args) >= 2 and \
                isinstance(node.args[1], ast.Constant) and \
                isinstance(node.args[1].value, str):
            sec = self._section_of(node.args[0])
            key = node.args[1].value
            if sec is not None:
                self.reads.add((sec, key))
                # a 2-arg getattr raises like a plain read; 3-arg is
                # defensive and never a typo finding
                if key not in self.sections[sec] and len(node.args) < 3:
                    self.findings.append(Finding(
                        self.path, node.lineno, node.col_offset, "CL101",
                        f"'{sec}.{key}' is not a field of the "
                        f"{sec!r} config section"))
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        sec = self._alias_of(node.value)
        for t in node.targets:
            if isinstance(t, ast.Name):
                if sec is not None:
                    self.scope_aliases[-1][t.id] = sec
                else:
                    self.scope_aliases[-1].pop(t.id, None)
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        sec: Optional[str] = None
        # cfg.<section>.<key>
        if isinstance(node.value, ast.Attribute) and \
                node.value.attr in self.sections and \
                _is_cfg_base(node.value.value):
            sec = node.value.attr
        # <alias>.<key> where alias = cfg.<section>
        elif isinstance(node.value, ast.Name) and \
                node.value.id in self.scope_aliases[-1]:
            sec = self.scope_aliases[-1][node.value.id]
        if sec is not None:
            key = node.attr
            self.reads.add((sec, key))
            if key not in self.sections[sec]:
                self.findings.append(Finding(
                    self.path, node.lineno, node.col_offset, "CL101",
                    f"'{sec}.{key}' is not a field of the "
                    f"{sec!r} config section (typo or removed knob — "
                    "this read raises AttributeError at runtime)"))
        self.generic_visit(node)


def _field_lines(config_path: str, classes: Dict[str, type]
                 ) -> Dict[Tuple[str, str], int]:
    """``{(section, key): line}`` of each field's AnnAssign in
    config.py."""
    with open(config_path, "r", encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=config_path)
    by_clsname = {cls.__name__: sec for sec, cls in classes.items()}
    out: Dict[Tuple[str, str], int] = {}
    for node in tree.body:
        if isinstance(node, ast.ClassDef) and node.name in by_clsname:
            sec = by_clsname[node.name]
            for stmt in node.body:
                if isinstance(stmt, ast.AnnAssign) and \
                        isinstance(stmt.target, ast.Name):
                    out[(sec, stmt.target.id)] = stmt.lineno
    return out


def lint_paths(paths: Sequence[str],
               config_path: Optional[str] = None) -> List[Finding]:
    """CL101 over every ``.py`` under ``paths`` (except config.py
    itself — its generic getattr plumbing is not key usage), then CL201
    for declared-but-never-read keys, reported in config.py."""
    sections, classes = _section_schema()
    from mx_rcnn_tpu import config as _cfgmod

    config_path = config_path or _cfgmod.__file__
    findings: List[Finding] = []
    reads: Set[Tuple[str, str]] = set()
    waivers_by_path: Dict[str, Dict] = {}
    for path in iter_py_files(paths):
        if os.path.abspath(path) == os.path.abspath(config_path):
            continue
        try:
            with open(path, "r", encoding="utf-8") as f:
                source = f.read()
            tree = ast.parse(source, filename=path)
        except (OSError, SyntaxError) as e:
            print(f"configlint: cannot parse {path}: {e}", file=sys.stderr)
            continue
        waivers_by_path[path] = parse_waivers(source, "configlint")
        v = _Visitor(path, sections,
                     {cls.__name__: sec for sec, cls in classes.items()})
        v.visit(tree)
        findings.extend(v.findings)
        reads |= v.reads

    lines = _field_lines(config_path, classes)
    with open(config_path, "r", encoding="utf-8") as f:
        waivers_by_path[config_path] = parse_waivers(f.read(), "configlint")
    for (sec, key), line in sorted(lines.items()):
        if (sec, key) not in reads:
            findings.append(Finding(
                config_path, line, 0, "CL201",
                f"dead config key '{sec}.{key}': declared here but no "
                "code reads it — remove it or waive with the reason it "
                "must stay"))

    by_path: Dict[str, List[Finding]] = {}
    for f in findings:
        by_path.setdefault(f.path, []).append(f)
    out: List[Finding] = []
    for path, w in waivers_by_path.items():
        out.extend(apply_waivers(path, w, by_path.pop(path, []), RULES,
                                 prefix="CL", tool="configlint"))
    for rest in by_path.values():   # paths without any waivers
        out.extend(rest)
    out.sort(key=lambda f: (f.path, f.line, f.col, f.code))
    return out


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="configlint",
        description="config-key static analysis (rules: docs/ANALYSIS.md)")
    p.add_argument("paths", nargs="*", default=["mx_rcnn_tpu"])
    p.add_argument("--json", action="store_true")
    p.add_argument("--show-waived", action="store_true")
    p.add_argument("--list-rules", action="store_true")
    p.add_argument("--dump-keys", action="store_true",
                   help="print the section/key schema and exit")
    args = p.parse_args(argv)
    if args.list_rules:
        for code, desc in sorted(RULES.items()):
            print(f"{code}  {desc}")
        return 0
    if args.dump_keys:
        sections, _ = _section_schema()
        print(json.dumps({s: sorted(k) for s, k in sections.items()},
                         indent=1))
        return 0
    rc = check_paths_exist("configlint", args.paths)
    if rc is not None:
        return rc
    findings = lint_paths(args.paths)
    active = [f for f in findings if f.waived is None]
    waived = [f for f in findings if f.waived is not None]
    shown = findings if args.show_waived else active
    for f in shown:
        if args.json:
            print(json.dumps({"path": f.path, "line": f.line,
                              "col": f.col + 1, "code": f.code,
                              "message": f.message, "waived": f.waived}))
        else:
            print(f.render())
    print(f"configlint: {len(active)} finding(s), {len(waived)} waived",
          file=sys.stderr)
    return 1 if active else 0


if __name__ == "__main__":
    raise SystemExit(main())
