"""crashsim — crash-consistency simulator: persistlint's runtime twin.

The static pass (``analysis/persistlint.py``) reasons about the
tmp → fsync → rename → dir-fsync → manifest-last idiom from the AST;
this module checks what actually matters: that EVERY state a crash can
leave on disk is one the real recovery paths either restore from or
cleanly refuse.  The technique is CrashMonkey's (Mohan et al.,
OSDI '18) brought in-tree: record the workload's persistence
operations, enumerate the crash states the POSIX persistence model
allows, materialize each one, and run the real recovery code against
it — systematic enumeration instead of the kill-at-step-K fault plans
of ``ft/faults.py`` (which sample wall-clock crash points, not
reordering semantics).

Three pieces:

* :class:`CrashRecorder` — an opt-in interposition shim (same
  allocation-site pattern as the lock sanitizer,
  ``analysis/sanitizer.py``): while armed it monkey-patches
  ``builtins.open`` / ``os.replace`` / ``os.rename`` / ``os.fsync`` /
  ``os.open`` / ``os.close`` / ``os.unlink`` and records every
  PACKAGE-ORIGINATED operation under the capture root into an op log —
  ``write`` (cumulative content snapshot, emitted at fsync and close),
  ``fsync`` / ``dirfsync`` (the ordering barriers), ``rename``,
  ``unlink``, and logical ``commit`` markers the workload driver
  plants when a commit call RETURNS (the durability the code just
  promised its caller).  The real syscalls always execute — the shim
  only observes.
* :func:`crash_states` — the enumerator.  For every truncation point
  of the op log it yields the states the persistence model allows:
  operations ordered by a barrier are applied; in-flight data writes
  may persist fully, as a torn prefix, or not at all (per-file prefix
  semantics: later snapshots supersede earlier ones, a tear never
  rewinds past a synced prefix); in-flight directory operations
  (rename/unlink) may individually persist or vanish — including the
  classic torn state where a rename persists without its source's
  un-fsynced data.
* :func:`simulate` — the verdict engine.  Each materialized state is
  handed to the workload's real recovery function; the verdict is
  RECOVER-OR-REFUSE: the state must either restore a byte-identical
  known artifact at least as new as the DURABLE FLOOR (the newest
  ``commit`` marker in the truncated log — what the code had already
  promised) or be cleanly refused — and refusal is only legal while
  nothing has been promised.  A recovered artifact that matches no
  known version, or a refusal after a commit returned, is a
  VIOLATION — the class of bug a removed fsync or dir-fsync plants
  (``tools/crashsim.py`` proves sensitivity by running exactly that
  arm and requiring the violation to be found).

Model honesty (documented limits): content tears at byte granularity
within one write session (prefix of the session's delta); an
in-place-overwrite session that crashes before its first fsync/close
is not modeled (the tree's durable writers never overwrite in place —
persistlint enforces the staging idiom); directory creation is assumed
durable (every workload root pre-exists).  When a crash point's
combination count exceeds the cap the point is truncated
DETERMINISTICALLY and reported in the result (no silent coverage
loss).
"""

from __future__ import annotations

import builtins
import hashlib
import os
import shutil
import sys
from dataclasses import dataclass, field
from itertools import product
from typing import Callable, Dict, Iterator, List, Optional, Sequence, Tuple

_RAW_OPEN = builtins.open
_RAW_OS = {name: getattr(os, name)
           for name in ("replace", "rename", "fsync", "open", "close",
                        "unlink", "remove")}

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _from_package_or_tests(extra: Tuple[str, ...] = ()) -> bool:
    """True when the calling frame (outside this module) lives under
    mx_rcnn_tpu or an explicitly-registered driver file — the
    allocation-site filter the lock sanitizer uses, so pytest/stdlib
    I/O under the capture root never pollutes the log."""
    f = sys._getframe(2)
    while f is not None and f.f_code.co_filename == __file__:
        f = f.f_back
    if f is None:
        return False
    fn = f.f_code.co_filename
    return fn.startswith(_PKG_DIR) or any(fn.startswith(e) for e in extra)


@dataclass
class Op:
    """One logged persistence operation.  ``kind`` ∈ write | fsync |
    dirfsync | rename | unlink | commit."""
    kind: str
    path: str = ""
    dst: str = ""                  # rename target
    data: Optional[bytes] = None   # write: cumulative content snapshot
    ident: str = ""                # commit marker payload

    def brief(self) -> Dict:
        d = {"kind": self.kind}
        if self.path:
            d["path"] = self.path
        if self.dst:
            d["dst"] = self.dst
        if self.data is not None:
            d["bytes"] = len(self.data)
            d["sha256"] = hashlib.sha256(self.data).hexdigest()[:12]
        if self.ident:
            d["ident"] = self.ident
        return d


class _FileProxy:
    """Wraps a real writable file under the capture root: emits a
    cumulative content snapshot into the op log at every fsync (via the
    recorder's fd map) and at close.  All I/O passes through to the
    real file — the content snapshot is read back from disk, so
    position-seeking writers (np.save headers) are captured exactly."""

    def __init__(self, rec: "CrashRecorder", inner, path: str):
        self._rec = rec
        self._inner = inner
        self._path = path
        self._closed = False

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __iter__(self):
        return iter(self._inner)

    def snapshot(self) -> None:
        try:
            self._inner.flush()
        except ValueError:
            pass
        with _RAW_OPEN(self._path, "rb") as f:
            content = f.read()
        self._rec._emit_write(self._path, content)

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._rec._fd_map.pop(self._fd_safe(), None)
            try:
                self.snapshot()
            finally:
                self._inner.close()
        else:
            self._inner.close()

    def _fd_safe(self) -> int:
        try:
            return self._inner.fileno()
        except (OSError, ValueError):
            return -1


class CrashRecorder:
    """Context manager that arms the shim and collects the op log for
    everything package code persists under ``root``.

    ``drop`` simulates REMOVED durability calls for the sensitivity
    arms: ``"fsync"`` omits file-fsync barriers from the log,
    ``"dirfsync"`` omits directory-fsync barriers — the real syscalls
    still run (the workload must behave identically), only the recorded
    ordering guarantees weaken, exactly as if the code never made the
    calls.
    """

    def __init__(self, root: str, drop: Sequence[str] = (),
                 extra_caller_files: Sequence[str] = ()):
        self.root = os.path.abspath(root)
        self.drop = frozenset(drop)
        self.ops: List[Op] = []
        self._armed = False
        self._fd_map: Dict[int, str] = {}   # fd -> path (os.open + proxies)
        self._extra = tuple(os.path.abspath(e) for e in extra_caller_files)

    # -- log helpers --------------------------------------------------------

    def _under_root(self, path) -> bool:
        try:
            p = os.path.abspath(os.fspath(path))
        except TypeError:
            return False
        # the capture root itself counts: the workload dir's own
        # dir-fsync (the rename barrier) happens on exactly that fd
        return p == self.root or p.startswith(self.root + os.sep)

    def _emit_write(self, path: str, content: bytes) -> None:
        # cumulative snapshots: skip only if identical to the file's last
        # snapshot (fsync followed by close with no new bytes)
        for op in reversed(self.ops):
            if op.kind == "write" and op.path == path:
                if op.data == content:
                    return
                break
            if op.kind == "rename" and (op.path == path or op.dst == path):
                break  # new session under a recycled name
        self.ops.append(Op("write", path=path, data=content))

    def mark_commit(self, ident: str) -> None:
        """Plant a logical durability marker: the workload's commit call
        for ``ident`` has RETURNED, so from here on recovery refusing to
        produce ≥ ``ident`` is a violation."""
        self.ops.append(Op("commit", ident=ident))

    # -- patches ------------------------------------------------------------

    def __enter__(self) -> "CrashRecorder":
        assert not self._armed
        self._armed = True
        rec = self

        def p_open(path, mode="r", *a, **kw):
            f = _RAW_OPEN(path, mode, *a, **kw)
            if (any(c in str(mode) for c in "wax")
                    and rec._under_root(path)
                    and _from_package_or_tests(rec._extra)):
                proxy = _FileProxy(rec, f, os.path.abspath(os.fspath(path)))
                fd = proxy._fd_safe()
                if fd >= 0:
                    rec._fd_map[fd] = proxy._path
                return proxy
            return f

        def p_os_open(path, flags, *a, **kw):
            fd = _RAW_OS["open"](path, flags, *a, **kw)
            if rec._under_root(path) and _from_package_or_tests(rec._extra):
                rec._fd_map[fd] = os.path.abspath(os.fspath(path))
            return fd

        def p_os_close(fd):
            rec._fd_map.pop(fd, None)
            return _RAW_OS["close"](fd)

        def p_fsync(fd):
            path = rec._fd_map.get(fd)
            if path is not None:
                if os.path.isdir(path):
                    if "dirfsync" not in rec.drop:
                        rec.ops.append(Op("dirfsync", path=path))
                else:
                    # snapshot the synced content FIRST so the barrier
                    # covers exactly the bytes that were just made durable
                    with _RAW_OPEN(path, "rb") as f:
                        rec._emit_write(path, f.read())
                    if "fsync" not in rec.drop:
                        rec.ops.append(Op("fsync", path=path))
            return _RAW_OS["fsync"](fd)

        def p_replace(src, dst, **kw):
            out = _RAW_OS["replace"](src, dst, **kw)
            if rec._under_root(dst) and _from_package_or_tests(rec._extra):
                rec.ops.append(Op(
                    "rename", path=os.path.abspath(os.fspath(src)),
                    dst=os.path.abspath(os.fspath(dst))))
            return out

        def p_rename(src, dst, **kw):
            out = _RAW_OS["rename"](src, dst, **kw)
            if rec._under_root(dst) and _from_package_or_tests(rec._extra):
                rec.ops.append(Op(
                    "rename", path=os.path.abspath(os.fspath(src)),
                    dst=os.path.abspath(os.fspath(dst))))
            return out

        def p_unlink(path, **kw):
            out = _RAW_OS["unlink"](path, **kw)
            if rec._under_root(path) and _from_package_or_tests(rec._extra):
                rec.ops.append(Op("unlink",
                                  path=os.path.abspath(os.fspath(path))))
            return out

        builtins.open = p_open
        os.open = p_os_open
        os.close = p_os_close
        os.fsync = p_fsync
        os.replace = p_replace
        os.rename = p_rename
        os.unlink = p_unlink
        os.remove = p_unlink
        return self

    def __exit__(self, *exc) -> bool:
        builtins.open = _RAW_OPEN
        for name, fn in _RAW_OS.items():
            setattr(os, name, fn)
        os.remove = _RAW_OS["remove"]
        self._armed = False
        return False

    def journal(self) -> List[Dict]:
        return [op.brief() for op in self.ops]


# --------------------------------------------------------------------------
# crash-state enumeration
# --------------------------------------------------------------------------

@dataclass
class CrashState:
    """One enumerable post-crash disk state: file contents relative to
    the capture root, the crash point, the in-flight decisions that
    produced it, and the durable floor promised by then."""
    point: int
    fs: Dict[str, bytes]
    floor: Optional[str]
    decisions: Tuple = ()

    def key(self) -> str:
        h = hashlib.sha256()
        for rel in sorted(self.fs):
            h.update(rel.encode())
            h.update(b"\0")
            h.update(hashlib.sha256(self.fs[rel]).digest())
            h.update(b"\1")
        return h.hexdigest()


def _torn(prev: bytes, full: bytes) -> Optional[bytes]:
    """A mid-session tear: the synced prefix plus half the un-synced
    delta (byte granularity; never rewinds past the synced prefix)."""
    if full.startswith(prev):
        delta = full[len(prev):]
        if len(delta) >= 2:
            return prev + delta[:len(delta) // 2]
        return None
    if len(full) >= 2:
        return full[:len(full) // 2]
    return None


def crash_states(ops: Sequence[Op], root: str,
                 max_states_per_point: int = 256
                 ) -> Iterator[CrashState]:
    """Yield every enumerable crash state of the log.  A crash point
    whose combination count exceeds the cap is truncated
    DETERMINISTICALLY and signalled by one sentinel state with
    ``decisions == ("CAPPED",)`` (``simulate`` aggregates these into
    ``capped_points`` — capping is reported, never silent).

    Barrier semantics at a crash point ``k`` (the crash lands after ops
    ``0..k-1`` were ISSUED):

    * a ``write`` on file f followed by an ``fsync`` on f before ``k``
      is FORCED (applied in full);
    * trailing writes after f's last fsync are in flight: each may
      persist in full, as a torn prefix of its session delta, or not at
      all — per-file PREFIX semantics (a later cumulative snapshot
      supersedes an earlier one, so the candidate set is every
      intermediate snapshot plus tears between them);
    * a ``rename``/``unlink`` followed by a ``dirfsync`` of its parent
      directory before ``k`` is FORCED; trailing directory ops may
      individually persist or vanish (same-directory reordering is
      allowed — the model errs toward MORE reachable states);
    * a rename that persists moves whatever content its source has in
      the state (possibly nothing — then the target exists EMPTY: the
      dir entry made it, the un-synced data did not: the classic torn
      publish).
    """
    root = os.path.abspath(root)
    n = len(ops)
    for k in range(n + 1):
        prefix = ops[:k]
        floor: Optional[str] = None
        for op in prefix:
            if op.kind == "commit":
                floor = op.ident
        # forced write per file: the last snapshot at or before the
        # file's last fsync (within the prefix)
        last_fsync: Dict[str, int] = {}
        last_dsync: Dict[str, int] = {}
        for i, op in enumerate(prefix):
            if op.kind == "fsync":
                last_fsync[op.path] = i
            elif op.kind == "dirfsync":
                last_dsync[op.path] = i
        # decision slots, in log order
        slots: List[Tuple[int, List]] = []   # (op index, choices)
        for i, op in enumerate(prefix):
            if op.kind == "write":
                forced = last_fsync.get(op.path, -1) > i
                if forced:
                    continue
                prev = _prev_snapshot(prefix, i)
                choices = [("apply", i), ("skip", i)]
                t = _torn(prev, op.data or b"")
                if t is not None:
                    choices.append(("torn", i, t))
                slots.append((i, choices))
            elif op.kind in ("rename", "unlink"):
                d = os.path.dirname(op.dst or op.path)
                forced = last_dsync.get(d, -1) > i
                if not forced:
                    slots.append((i, [("apply", i), ("skip", i)]))
        combos = product(*[c for _, c in slots]) if slots else iter([()])
        emitted = 0
        capped = False
        for combo in combos:
            if emitted >= max_states_per_point:
                capped = True
                break
            decisions = {c[1]: c for c in combo}
            fs = _materialize_fs(prefix, decisions, root)
            yield CrashState(point=k, fs=fs, floor=floor,
                             decisions=tuple(combo))
            emitted += 1
        if capped:
            yield CrashState(point=k, fs={}, floor=floor,
                             decisions=("CAPPED",))


def _prev_snapshot(prefix: Sequence[Op], i: int) -> bytes:
    """The previous content snapshot of prefix[i]'s file within its
    write session (for tear computation)."""
    path = prefix[i].path
    for j in range(i - 1, -1, -1):
        op = prefix[j]
        if op.kind == "write" and op.path == path:
            return op.data or b""
        if op.kind == "rename" and (op.path == path or op.dst == path):
            return b""
    return b""


def _materialize_fs(prefix: Sequence[Op], decisions: Dict[int, Tuple],
                    root: str) -> Dict[str, bytes]:
    fs: Dict[str, bytes] = {}
    for i, op in enumerate(prefix):
        d = decisions.get(i)
        if op.kind == "write":
            if d is None:                     # forced
                fs[_rel(op.path, root)] = op.data or b""
            elif d[0] == "apply":
                fs[_rel(op.path, root)] = op.data or b""
            elif d[0] == "torn":
                fs[_rel(op.path, root)] = d[2]
            # skip: nothing
        elif op.kind == "rename":
            if d is None or d[0] == "apply":
                src, dst = _rel(op.path, root), _rel(op.dst, root)
                # the rename persists: the target gets whatever data the
                # source has — possibly none (torn publish: empty file)
                fs[dst] = fs.pop(src, b"")
        elif op.kind == "unlink":
            if d is None or d[0] == "apply":
                fs.pop(_rel(op.path, root), None)
    return fs


def _rel(path: str, root: str) -> str:
    return os.path.relpath(path, root)


def materialize(state: CrashState, scratch: str) -> str:
    """Write a crash state into ``scratch`` (wiped first)."""
    if os.path.exists(scratch):
        shutil.rmtree(scratch)
    os.makedirs(scratch)
    for rel, content in state.fs.items():
        p = os.path.join(scratch, rel)
        os.makedirs(os.path.dirname(p) or scratch, exist_ok=True)
        with _RAW_OPEN(p, "wb") as f:
            f.write(content)
    return scratch


# --------------------------------------------------------------------------
# verdict engine
# --------------------------------------------------------------------------

def simulate(ops: Sequence[Op], root: str,
             recover: Callable[[str], Tuple[str, str]],
             idents: Sequence[str], scratch: str,
             max_states_per_point: int = 256) -> Dict:
    """Enumerate every crash state of ``ops``, run ``recover`` against
    each, and assert recover-or-refuse.

    ``recover(dir)`` is the workload's REAL recovery path wrapped to a
    verdict: ``("recovered", ident)`` — it restored a byte-validated
    known artifact; ``("refused", why)`` — it cleanly detected the state
    as unusable through its documented refusal surface; ``("corrupt",
    detail)`` — it SERVED something that matches no known artifact (an
    immediate violation).  ``idents`` is the workload's commit order:
    recovering older than the durable floor, or refusing while a floor
    exists, is a violation.

    Returns a report dict: state counts, verdict tallies, the violation
    list (each with crash point + decisions for reproduction), and the
    per-point cap count (capped points are reported, never silent).
    """
    order = {ident: i for i, ident in enumerate(idents)}
    verdict_cache: Dict[str, Tuple[str, str]] = {}
    seen: set = set()
    report: Dict = {
        "ops": len(ops), "crash_points": len(ops) + 1,
        "states_total": 0, "states_unique": 0,
        "recovered": 0, "refused": 0,
        "violations": [], "capped_points": 0,
    }
    for state in crash_states(ops, root, max_states_per_point):
        if state.decisions == ("CAPPED",):
            report["capped_points"] += 1
            continue
        report["states_total"] += 1
        key = state.key()
        if key not in verdict_cache:
            report["states_unique"] += 1
            materialize(state, scratch)
            try:
                verdict_cache[key] = recover(scratch)
            except Exception as e:  # noqa: BLE001 — an untyped crash in a
                # recovery path is itself a verdict: the documented
                # refusal surface did not cover this state (recorded as
                # a violation, never an aborted enumeration)
                verdict_cache[key] = (
                    "corrupt",
                    f"recovery path crashed with an UNTYPED exception: "
                    f"{type(e).__name__}: {e}")
        outcome, detail = verdict_cache[key]
        if outcome == "recovered":
            report["recovered"] += 1
        elif outcome == "refused":
            report["refused"] += 1
        violation = None
        if outcome == "corrupt":
            violation = (f"recovery SERVED an unknown/corrupt artifact: "
                         f"{detail}")
        elif outcome == "recovered":
            if detail not in order:
                violation = f"recovered unknown ident {detail!r}"
            elif state.floor is not None and \
                    order[detail] < order[state.floor]:
                violation = (f"recovered {detail!r} but {state.floor!r} "
                             "was already durably committed — a promised "
                             "artifact was lost")
        elif outcome == "refused" and state.floor is not None:
            violation = (f"recovery refused ({detail}) but "
                         f"{state.floor!r} was already durably "
                         "committed — a promised artifact was lost")
        if violation is not None and (state.point, key) not in seen:
            seen.add((state.point, key))
            report["violations"].append({
                "point": state.point,
                "floor": state.floor,
                "outcome": outcome,
                "detail": str(detail)[:300],
                "decisions": [list(map(str, d))
                              for d in state.decisions],
                "problem": violation,
            })
    report["ok"] = not report["violations"]
    return report
