"""threadlint — AST linter for the concurrency surface (locks, threads,
signal handlers).

Why a second linter: ``graphlint`` guards the jit/graph invariants, but
the concurrency surface grew from zero to ~16 threaded or
signal-handling modules across the serve/ft/obs/data planes, and every
concurrency bug so far (the SIGUSR2 profiler deadlock, the async
snapshot racing the donating train step, ``ReplicaManager.close``
racing an in-flight relaunch) was found only by live-driving the
system.  threadlint catches the same bug classes at lint time; the
runtime twin is the opt-in lock sanitizer (``analysis/sanitizer.py``),
which records REAL acquisition order under the smokes.

Rule families (full catalogue with bad/good examples: docs/ANALYSIS.md):

* TL1xx — lock ordering: a cross-module lock-order graph (which locks
  are acquired while which are held, following calls through the
  cross-module call graph) is built from every ``with <lock>:`` block;
  cycles are potential deadlocks (TL101), and re-acquiring a
  non-reentrant ``Lock`` already held is a guaranteed self-deadlock
  (TL102).  ``--graph`` dumps the graph as JSON.
* TL2xx — shared-state discipline: writes to ``self.<attr>`` / module
  globals from functions reachable from a ``threading.Thread`` target,
  outside any ``with <lock>:`` block, when the same state is also
  touched from non-thread code (TL201); check-then-act on a shared
  container outside the guarding lock (TL202).
* TL3xx — blocking while holding a lock: ``.result()``,
  ``jax.block_until_ready``, ``subprocess``/HTTP, ``time.sleep``,
  ``Queue.get/put`` or ``Event.wait`` without a timeout, thread
  ``join`` — any of these inside a ``with <lock>:`` body stalls every
  other thread that needs the lock (TL301).
* TL4xx — signal-handler safety: a handler registered via
  ``signal.signal`` runs on the main thread at an arbitrary bytecode
  boundary; taking locks, calling into jax, or doing I/O there is the
  exact class that deadlocked the SIGUSR2 profiler (the handler must
  only flip state; a worker thread does the work).  (TL401)
* TL5xx — condition-variable protocol: ``Condition.wait`` outside a
  ``while``-predicate loop misses spurious wakeups and stolen wakeups
  (TL501).

Lock identity: ``self.<attr> = threading.Lock()/RLock()/Condition()``
defines lock node ``Class.attr``; module-level ``X = threading.Lock()``
defines ``module.X``; function-local locks get ``module.func.X``.  An
acquisition through another object (``r._lock``) resolves through local
type inference (parameter annotations, ``x = Class(...)`` assignments,
``for x in <List[Class]-typed>`` iteration); when the attribute name is
defined as a lock by exactly one class in the corpus, that class is
assumed; otherwise the node is ``?.attr`` (ambiguous — visible in the
graph dump, merged conservatively).

Waivers: same protocol as graphlint (``analysis/common.py``) —
``# threadlint: disable=TL201 <reason>`` on the line or the line above;
a reasonless waiver is TL001, an unknown rule TL002.

CLI::

    python -m mx_rcnn_tpu.analysis.threadlint [paths...] [--json]
        [--show-waived] [--list-rules] [--graph]

Exit status 0 iff no unwaived findings (``--graph`` always exits 0 —
it is a reporting mode).
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import sys
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from mx_rcnn_tpu.analysis.common import (Finding, apply_waivers, canonical,
                                         check_paths_exist,
                                         collect_import_aliases,
                                         iter_py_files, parse_waivers)

RULES: Dict[str, str] = {
    "TL001": "waiver without a reason (every waiver must say why)",
    "TL002": "waiver names an unknown rule code",
    "TL101": "lock-order cycle across the lock graph (potential deadlock)",
    "TL102": "non-reentrant Lock acquired while already held "
             "(self-deadlock)",
    "TL201": "unguarded write to shared state reachable from a Thread "
             "target",
    "TL202": "check-then-act on a shared container outside the guarding "
             "lock",
    "TL301": "blocking call while holding a lock",
    "TL401": "non-async-signal-safe work inside a signal handler",
    "TL501": "Condition.wait outside a while-predicate loop",
}

_LOCK_CTORS = {
    "threading.Lock": "Lock",
    "threading.RLock": "RLock",
    "threading.Condition": "Condition",
}
# constructor -> type tag for attribute/local type tracking; types in
# _THREADSAFE_TYPES are internally synchronized, so unguarded method
# calls/writes on them are not shared-state findings
_KNOWN_CTORS = dict(_LOCK_CTORS)
_KNOWN_CTORS.update({
    "threading.Event": "Event",
    "threading.Semaphore": "Semaphore",
    "threading.BoundedSemaphore": "Semaphore",
    "threading.Thread": "Thread",
    "queue.Queue": "Queue",
    "queue.LifoQueue": "Queue",
    "queue.PriorityQueue": "Queue",
    "queue.SimpleQueue": "Queue",
    "collections.deque": "deque",
    "collections.OrderedDict": "dict",
})
_THREADSAFE_TYPES = {"Lock", "RLock", "Condition", "Event", "Semaphore",
                     "Queue", "Thread"}

# container mutators for TL201/TL202 ("set" is deliberately absent —
# Event.set would false-positive on untyped receivers)
_MUTATORS = {"append", "appendleft", "extend", "insert", "add", "update",
             "pop", "popleft", "popitem", "remove", "discard", "clear",
             "setdefault"}

# blocking calls for TL301 (canonical names)
_BLOCKING_CANON = {
    "time.sleep", "jax.block_until_ready",
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output", "subprocess.Popen", "os.system",
    "urllib.request.urlopen", "socket.create_connection",
    "jax.device_get", "jax.device_put",
}
_BLOCKING_CANON_PREFIXES = ("requests.", "http.client.")


# --------------------------------------------------------------------------
# data model
# --------------------------------------------------------------------------

@dataclass
class LockDef:
    node_id: str                 # "Class.attr" / "module.name" / local
    kind: str                    # Lock | RLock | Condition
    path: str
    line: int


@dataclass
class FuncInfo:
    qualname: str                # "Class.method", "func", "outer.inner"
    module: "ModuleInfo"
    node: ast.AST
    cls: Optional[str] = None
    callees: Set[str] = field(default_factory=set)   # resolved keys
    direct_acquires: Set[str] = field(default_factory=set)
    trans_acquires: Set[str] = field(default_factory=set)
    # calls made while lexically holding locks: (held ids, callee key, node)
    calls_under_lock: List[Tuple[Tuple[str, ...], str, ast.AST]] = \
        field(default_factory=list)
    thread_scope: bool = False   # reachable from a threading.Thread target
    handler_scope: bool = False  # reachable from a signal.signal handler


@dataclass
class ClassInfo:
    name: str
    module: "ModuleInfo"
    attr_types: Dict[str, str] = field(default_factory=dict)  # attr -> tag
    lock_attrs: Dict[str, LockDef] = field(default_factory=dict)
    # attr -> set of qualnames (of THIS corpus) touching it, split by
    # write/read for the shared-state rule
    attr_writers: Dict[str, Set[str]] = field(default_factory=dict)
    attr_readers: Dict[str, Set[str]] = field(default_factory=dict)


@dataclass
class ModuleInfo:
    path: str
    name: str                    # module basename (no .py), for display
    uid: str                     # unique key (the path) — two modules
    # may share a basename (serve/fleet.py vs tools/fleet.py), and
    # keying the corpus by basename would silently overwrite entries
    tree: ast.Module
    aliases: Dict[str, str] = field(default_factory=dict)
    waivers: Dict[int, Tuple[Set[str], str]] = field(default_factory=dict)
    classes: Dict[str, ClassInfo] = field(default_factory=dict)
    funcs: Dict[str, FuncInfo] = field(default_factory=dict)  # by qualname
    module_locks: Dict[str, LockDef] = field(default_factory=dict)
    global_writers: Dict[str, Set[str]] = field(default_factory=dict)
    global_readers: Dict[str, Set[str]] = field(default_factory=dict)


@dataclass
class Edge:
    """One observed ordering: ``acquired`` taken while ``held``."""
    held: str
    acquired: str
    path: str
    line: int
    func: str
    via: str = ""                # callee chain note for closure edges


class Corpus:
    """Cross-module index: classes by name, functions by UNIQUE module
    key (two modules may share a basename — serve/fleet.py vs
    tools/fleet.py — so cross-module references resolve through the
    basename index and must be unambiguous to count)."""

    def __init__(self, mods: List[ModuleInfo]):
        self.mods = mods
        self.classes: Dict[str, ClassInfo] = {}
        self.funcs: Dict[str, FuncInfo] = {}        # "<uid>:<qualname>"
        self.lock_attr_owners: Dict[str, List[ClassInfo]] = {}
        self.method_owners: Dict[str, List[str]] = {}  # name -> [keys]
        self.mods_by_tail: Dict[str, List[ModuleInfo]] = {}
        for m in mods:
            self.mods_by_tail.setdefault(m.name, []).append(m)
            for cname, ci in m.classes.items():
                self.classes.setdefault(cname, ci)
                for attr in ci.lock_attrs:
                    self.lock_attr_owners.setdefault(attr, []).append(ci)
            for q, fi in m.funcs.items():
                self.funcs[f"{m.uid}:{q}"] = fi
                if "." in q:
                    mname = q.rsplit(".", 1)[-1]
                    self.method_owners.setdefault(mname, []).append(
                        f"{m.uid}:{q}")

    def resolve(self, key: str) -> Optional[FuncInfo]:
        return self.funcs.get(key)

    def module_func_key(self, mod_tail: str, fname: str) -> Optional[str]:
        """Key of top-level ``fname`` in the module whose basename is
        ``mod_tail`` — None unless exactly one candidate defines it."""
        cands = [m for m in self.mods_by_tail.get(mod_tail, [])
                 if fname in m.funcs]
        if len(cands) == 1:
            return f"{cands[0].uid}:{fname}"
        return None


# --------------------------------------------------------------------------
# pass 1: per-module collection
# --------------------------------------------------------------------------

def _ctor_tag(mod: ModuleInfo, value: ast.AST,
              corpus_classes: Set[str]) -> Optional[str]:
    """Type tag of an assigned value: known ctor tag, a corpus class
    name, or a container literal tag."""
    if isinstance(value, (ast.List, ast.ListComp)):
        return "list"
    if isinstance(value, (ast.Dict, ast.DictComp)):
        return "dict"
    if isinstance(value, (ast.Set, ast.SetComp)):
        return "set"
    calls = [value]
    if isinstance(value, ast.BoolOp):     # e.g. ``policy or RestartPolicy()``
        calls = list(value.values)
    if isinstance(value, ast.IfExp):
        calls = [value.body, value.orelse]
    for v in calls:
        if not isinstance(v, ast.Call):
            continue
        canon = canonical(mod.aliases, v.func) or ""
        if canon in _KNOWN_CTORS:
            return _KNOWN_CTORS[canon]
        leaf = canon.rsplit(".", 1)[-1]
        if leaf in corpus_classes:
            return leaf
    return None


def _elem_tag(mod: ModuleInfo, value: ast.AST,
              corpus_classes: Set[str]) -> Optional[str]:
    """Element type of a list literal/comprehension of constructor calls
    (``[Replica(i) for i in ...]`` -> ``Replica``)."""
    elts: List[ast.AST] = []
    if isinstance(value, ast.ListComp):
        elts = [value.elt]
    elif isinstance(value, ast.List):
        elts = value.elts
    for e in elts:
        if isinstance(e, ast.Call):
            canon = canonical(mod.aliases, e.func) or ""
            leaf = canon.rsplit(".", 1)[-1]
            if leaf in corpus_classes:
                return leaf
    return None


def _ann_class(ann: Optional[ast.AST]) -> Optional[str]:
    """Class name out of an annotation (``Replica``,
    ``Optional[ServingEngine]``, ``List[Replica]`` -> element handled by
    caller)."""
    if ann is None:
        return None
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        try:
            ann = ast.parse(ann.value, mode="eval").body
        except SyntaxError:
            return None
    if isinstance(ann, ast.Name):
        return ann.id
    if isinstance(ann, ast.Attribute):
        return ann.attr
    if isinstance(ann, ast.Subscript):      # Optional[X] / List[X]
        return _ann_class(ann.slice)
    if isinstance(ann, ast.BinOp):          # X | None
        return _ann_class(ann.left)
    return None


def _ann_is_seq(ann: Optional[ast.AST]) -> bool:
    if isinstance(ann, ast.Subscript):
        base = ann.value
        return isinstance(base, ast.Name) and base.id in (
            "List", "Sequence", "Tuple", "Iterable", "list", "tuple")
    return False


class _Scanner(ast.NodeVisitor):
    """Collects classes, functions (incl. nested), lock/attr types."""

    def __init__(self, mod: ModuleInfo, corpus_classes: Set[str]):
        self.mod = mod
        self.corpus_classes = corpus_classes
        self.cls_stack: List[ClassInfo] = []
        self.func_stack: List[FuncInfo] = []

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        ci = ClassInfo(name=node.name, module=self.mod)
        self.mod.classes[node.name] = ci
        self.cls_stack.append(ci)
        self.generic_visit(node)
        self.cls_stack.pop()

    def _enter_func(self, node) -> None:
        if self.func_stack:
            qual = f"{self.func_stack[-1].qualname}.{node.name}"
        elif self.cls_stack:
            qual = f"{self.cls_stack[-1].name}.{node.name}"
        else:
            qual = node.name
        fi = FuncInfo(qualname=qual, module=self.mod, node=node,
                      cls=self.cls_stack[-1].name if self.cls_stack
                      else None)
        self.mod.funcs[qual] = fi
        self.func_stack.append(fi)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._enter_func(node)
        self.generic_visit(node)
        self.func_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Assign(self, node: ast.Assign) -> None:
        self._record_assign(node.targets, node.value, node)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        tgts = [node.target]
        if node.value is not None:
            self._record_assign(tgts, node.value, node)
        # annotated attr with no useful value: take the annotation type
        t = node.target
        if (self.cls_stack and isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name) and t.value.id == "self"):
            cname = _ann_class(node.annotation)
            if cname and t.attr not in self.cls_stack[-1].attr_types:
                if cname in self.corpus_classes or cname in (
                        "Thread", "Queue", "Event"):
                    self.cls_stack[-1].attr_types[t.attr] = cname
        self.generic_visit(node)

    def _record_assign(self, targets, value, node) -> None:
        tag = _ctor_tag(self.mod, value, self.corpus_classes)
        elem = _elem_tag(self.mod, value, self.corpus_classes)
        for t in targets:
            if isinstance(t, ast.Attribute) and \
                    isinstance(t.value, ast.Name) and t.value.id == "self" \
                    and self.cls_stack:
                ci = self.cls_stack[-1]
                if tag is not None:
                    ci.attr_types[t.attr] = tag
                    if tag in _LOCK_CTORS.values():
                        ci.lock_attrs[t.attr] = LockDef(
                            node_id=f"{ci.name}.{t.attr}", kind=tag,
                            path=self.mod.path, line=node.lineno)
                if elem is not None:
                    ci.attr_types.setdefault(t.attr, f"List[{elem}]")
            elif isinstance(t, ast.Name) and not self.func_stack \
                    and not self.cls_stack:
                if tag in _LOCK_CTORS.values():
                    self.mod.module_locks[t.id] = LockDef(
                        node_id=f"{self.mod.name}.{t.id}", kind=tag,
                        path=self.mod.path, line=node.lineno)


def load_module(path: str) -> Optional[ModuleInfo]:
    try:
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
        tree = ast.parse(source, filename=path)
    except (OSError, SyntaxError) as e:
        print(f"threadlint: cannot parse {path}: {e}", file=sys.stderr)
        return None
    name = os.path.basename(path)[:-3]
    mod = ModuleInfo(path=path, name=name, uid=path, tree=tree)
    mod.aliases = collect_import_aliases(tree)
    mod.waivers = parse_waivers(source, "threadlint")
    return mod


# --------------------------------------------------------------------------
# pass 2: per-function analysis (types, locks, calls, writes)
# --------------------------------------------------------------------------

class _FuncAnalysis(ast.NodeVisitor):
    """One function body: local types, with-lock regions, acquisitions,
    calls-under-lock, writes, signal registrations, thread targets."""

    def __init__(self, fi: FuncInfo, corpus: Corpus):
        self.fi = fi
        self.mod = fi.module
        self.corpus = corpus
        self.local_types: Dict[str, str] = {}
        self.local_locks: Dict[str, LockDef] = {}
        self.with_stack: List[str] = []       # lock node ids held lexically
        self.with_kinds: Dict[str, str] = {}  # node id -> kind
        self.while_depth = 0
        self.edges: List[Edge] = []
        self.findings: List[Finding] = []
        # (class, attr, node) for every unguarded attribute write
        self.unguarded_self_writes: List[Tuple[str, str, ast.AST]] = []
        self.unguarded_global_writes: List[Tuple[str, ast.AST]] = []
        self.thread_targets: List[str] = []   # resolved callee keys
        self.handler_targets: List[str] = []
        self.globals_declared: Set[str] = set()
        self._param_types()
        self._nested = {q.rsplit(".", 1)[-1]: q for q, f in
                        self.mod.funcs.items()
                        if q.startswith(fi.qualname + ".")
                        and q.count(".") == fi.qualname.count(".") + 1}

    # -- type plumbing ------------------------------------------------------

    def _param_types(self) -> None:
        node = self.fi.node
        if isinstance(node, (ast.Lambda, ast.Module)):
            return
        for a in node.args.posonlyargs + node.args.args + \
                node.args.kwonlyargs:
            cname = _ann_class(a.annotation)
            if cname and cname in self.corpus.classes:
                if _ann_is_seq(a.annotation):
                    self.local_types[a.arg] = f"List[{cname}]"
                else:
                    self.local_types[a.arg] = cname

    def _type_of(self, node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Name):
            return self.local_types.get(node.id)
        if isinstance(node, ast.Attribute):
            base: Optional[ClassInfo] = None
            if isinstance(node.value, ast.Name):
                if node.value.id == "self" and self.fi.cls:
                    base = self.corpus.classes.get(self.fi.cls)
                else:
                    t = self.local_types.get(node.value.id)
                    base = self.corpus.classes.get(t) if t else None
            elif isinstance(node.value, ast.Attribute):
                t = self._type_of(node.value)
                base = self.corpus.classes.get(t) if t else None
            if base is not None:
                return base.attr_types.get(node.attr)
        return None

    # -- lock resolution ----------------------------------------------------

    def _lock_node(self, expr: ast.AST) -> Optional[LockDef]:
        """Resolve a with-item / receiver expression to a lock node."""
        if isinstance(expr, ast.Name):
            if expr.id in self.local_locks:
                return self.local_locks[expr.id]
            if expr.id in self.mod.module_locks:
                return self.mod.module_locks[expr.id]
            return None
        if isinstance(expr, ast.Attribute):
            attr = expr.attr
            owner: Optional[str] = None
            if isinstance(expr.value, ast.Name) and \
                    expr.value.id == "self" and self.fi.cls:
                owner = self.fi.cls
            else:
                owner = self._type_of(expr.value)
            if owner:
                ci = self.corpus.classes.get(owner)
                if ci is not None and attr in ci.lock_attrs:
                    return ci.lock_attrs[attr]
                if ci is not None:
                    return None   # known type, not a lock attr
            cands = self.corpus.lock_attr_owners.get(attr, [])
            if len(cands) == 1:
                return cands[0].lock_attrs[attr]
            if len(cands) > 1:
                # ambiguous: merged node, visible in the graph dump
                return LockDef(node_id=f"?.{attr}",
                               kind=cands[0].lock_attrs[attr].kind,
                               path=self.mod.path, line=expr.lineno)
        return None

    # -- callee resolution --------------------------------------------------

    def _callee_key(self, func: ast.AST) -> Optional[str]:
        if isinstance(func, ast.Name):
            if func.id in self._nested:
                return f"{self.mod.uid}:{self._nested[func.id]}"
            if func.id in self.mod.funcs:
                return f"{self.mod.uid}:{func.id}"
            alias = self.mod.aliases.get(func.id)
            if alias and "." in alias:
                m, _, f = alias.rpartition(".")
                return self.corpus.module_func_key(m.rsplit(".", 1)[-1], f)
            return None
        if isinstance(func, ast.Attribute):
            mname = func.attr
            owner: Optional[str] = None
            if isinstance(func.value, ast.Name) and \
                    func.value.id == "self" and self.fi.cls:
                owner = self.fi.cls
            else:
                owner = self._type_of(func.value)
            if owner and owner.startswith("List["):
                owner = None
            if owner:
                for key in self.corpus.method_owners.get(mname, []):
                    if key.split(":", 1)[1] == f"{owner}.{mname}":
                        return key
                return None
            # unique method name across the corpus (e.g. ``req._finish``)
            cands = self.corpus.method_owners.get(mname, [])
            if len(cands) == 1:
                return cands[0]
            # module-qualified call through an import alias
            canon = canonical(self.mod.aliases, func) or ""
            if "." in canon:
                m, _, f = canon.rpartition(".")
                return self.corpus.module_func_key(m.rsplit(".", 1)[-1], f)
        return None

    # -- traversal ----------------------------------------------------------

    def run(self) -> None:
        node = self.fi.node
        body = node.body if not isinstance(node, ast.Lambda) else [node.body]
        for stmt in body:
            self._visit_stmt(stmt)
        if not isinstance(node, ast.Module):
            self._index_attr_access()

    def _attr_owner(self, node: ast.Attribute) -> Optional[str]:
        """The class owning an attribute access: ``self.x`` → enclosing
        class; ``r.x`` → r's inferred class."""
        if isinstance(node.value, ast.Name) and node.value.id == "self":
            return self.fi.cls
        t = self._type_of(node.value) if isinstance(
            node.value, (ast.Name, ast.Attribute)) else None
        return t if t in self.corpus.classes else None

    def _index_attr_access(self) -> None:
        """Full-body read/write index over self- and typed-object
        attribute accesses — feeds the shared-state rule's 'who else
        touches this' test (local types are complete by now)."""
        me = f"{self.mod.uid}:{self.fi.qualname}"
        for sub in ast.walk(self.fi.node):
            if not isinstance(sub, ast.Attribute):
                continue
            owner = self._attr_owner(sub)
            if owner is None:
                continue
            ci = self.corpus.classes.get(owner)
            if ci is None:
                continue
            if isinstance(sub.ctx, ast.Load):
                ci.attr_readers.setdefault(sub.attr, set()).add(me)
            else:
                ci.attr_writers.setdefault(sub.attr, set()).add(me)

    def _visit_stmt(self, node: ast.AST) -> None:
        # skip nested function bodies (analyzed as their own FuncInfos)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return
        if isinstance(node, ast.Global):
            self.globals_declared.update(node.names)
        if isinstance(node, ast.With):
            self._visit_with(node)
            return
        if isinstance(node, ast.While):
            self.while_depth += 1
            for sub in ast.iter_child_nodes(node):
                self._visit_stmt(sub)
            self.while_depth -= 1
            return
        if isinstance(node, ast.Assign):
            self._bind_types(node)
            self._propagate_attr_type(node)
            self._check_write(node.targets, node)
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            # annotated assignments are writes too — `self.n: int = 1`
            # must not dodge TL201 just by carrying an annotation
            synth = ast.Assign(targets=[node.target], value=node.value)
            ast.copy_location(synth, node)
            self._bind_types(synth)
            self._propagate_attr_type(synth)
            self._check_write([node.target], node)
        elif isinstance(node, ast.AugAssign):
            self._check_write([node.target], node)
        elif isinstance(node, ast.For):
            self._bind_for(node)
        elif isinstance(node, ast.If):
            self._check_check_then_act(node)
        elif isinstance(node, ast.Call):
            self._visit_call(node)
        for sub in ast.iter_child_nodes(node):
            self._visit_stmt(sub)

    def _visit_with(self, node: ast.With) -> None:
        acquired: List[str] = []
        for item in node.items:
            ld = self._lock_node(item.context_expr)
            if ld is None:
                continue
            self._record_acquire(ld, item.context_expr)
            acquired.append(ld.node_id)
            self.with_stack.append(ld.node_id)
        for stmt in node.body:
            self._visit_stmt(stmt)
        for _ in acquired:
            self.with_stack.pop()

    def _record_acquire(self, ld: LockDef, site: ast.AST) -> None:
        self.fi.direct_acquires.add(ld.node_id)
        self.with_kinds[ld.node_id] = ld.kind
        if ld.node_id in self.with_stack:
            if ld.kind == "Lock":
                self.findings.append(Finding(
                    self.mod.path, site.lineno, site.col_offset, "TL102",
                    f"non-reentrant Lock '{ld.node_id}' acquired while "
                    "already held — guaranteed self-deadlock",
                    self.fi.qualname))
            return  # reentrant re-acquire: no ordering edge
        for held in self.with_stack:
            if held != ld.node_id:
                self.edges.append(Edge(
                    held=held, acquired=ld.node_id, path=self.mod.path,
                    line=site.lineno, func=self.fi.qualname))

    def _bind_types(self, node: ast.Assign) -> None:
        tag = _ctor_tag(self.mod, node.value,
                        set(self.corpus.classes))
        for t in node.targets:
            if not isinstance(t, ast.Name):
                continue
            if tag in _LOCK_CTORS.values():
                self.local_locks[t.id] = LockDef(
                    node_id=f"{self.mod.name}.{self.fi.qualname}.{t.id}",
                    kind=tag, path=self.mod.path, line=node.lineno)
            if tag is not None:
                self.local_types[t.id] = tag

    def _propagate_attr_type(self, node: ast.Assign) -> None:
        """``self.manager = manager`` with an annotated ``manager`` param
        types the attribute (so other-class accessors resolve)."""
        if not self.fi.cls or not isinstance(node.value, ast.Name):
            return
        t = self.local_types.get(node.value.id)
        if t is None:
            return
        ci = self.corpus.classes.get(self.fi.cls)
        if ci is not None:
            for tgt in node.targets:
                if isinstance(tgt, ast.Attribute) and \
                        isinstance(tgt.value, ast.Name) and \
                        tgt.value.id == "self":
                    ci.attr_types.setdefault(tgt.attr, t)

    def _bind_for(self, node: ast.For) -> None:
        if not isinstance(node.target, ast.Name):
            return
        it = node.iter
        t: Optional[str] = None
        if isinstance(it, (ast.Name, ast.Attribute)):
            t = self._type_of(it)
        elif isinstance(it, ast.Call):
            # iteration over a call with a List[C] return annotation
            key = self._callee_key(it.func)
            fi = self.corpus.resolve(key) if key else None
            if fi is not None and not isinstance(fi.node, ast.Lambda):
                ret = getattr(fi.node, "returns", None)
                cname = _ann_class(ret)
                if cname in self.corpus.classes and _ann_is_seq(ret):
                    t = f"List[{cname}]"
            if isinstance(it.func, ast.Name) and it.func.id == "enumerate" \
                    and it.args:
                inner = it.args[0]
                et = self._type_of(inner) if isinstance(
                    inner, (ast.Name, ast.Attribute)) else None
                if et and et.startswith("List["):
                    # ``for j, r in enumerate(reqs)`` — handled below via
                    # tuple targets; single-name target gets the tuple
                    t = et
        if t and t.startswith("List["):
            self.local_types[node.target.id] = t[5:-1]

    # -- writes (TL201) -----------------------------------------------------

    def _self_attr(self, node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and node.value.id == "self":
            return node.attr
        return None

    def _check_write(self, targets: List[ast.AST], node: ast.AST) -> None:
        guarded = bool(self.with_stack)
        for t in targets:
            if isinstance(t, ast.Tuple):
                self._check_write(list(t.elts), node)
                continue
            tgt = t
            if isinstance(tgt, ast.Subscript):
                tgt = tgt.value
            if isinstance(tgt, ast.Attribute):
                owner = self._attr_owner(tgt)
                if owner is not None and not guarded:
                    self.unguarded_self_writes.append(
                        (owner, tgt.attr, node))
            if isinstance(t, ast.Name) and t.id in self.globals_declared:
                self.mod.global_writers.setdefault(t.id, set()).add(
                    f"{self.mod.uid}:{self.fi.qualname}")
                if not guarded:
                    self.unguarded_global_writes.append((t.id, node))

    def _check_check_then_act(self, node: ast.If) -> None:
        """TL202: ``if <reads self.A>`` whose body mutates ``self.A``,
        outside any lock, in a class that owns locks or threads."""
        if self.with_stack or not self.fi.cls:
            return
        ci = self.corpus.classes.get(self.fi.cls)
        if ci is None:
            return
        concurrent = bool(ci.lock_attrs) or any(
            t in ("Thread", "Event", "Queue", "Condition")
            for t in ci.attr_types.values())
        if not concurrent:
            return
        read_attrs = {self._self_attr(sub)
                      for sub in ast.walk(node.test)} - {None}
        if not read_attrs:
            return
        for stmt in node.body:
            for sub in ast.walk(stmt):
                attr = None
                if isinstance(sub, ast.Assign):
                    for t in sub.targets:
                        if isinstance(t, ast.Subscript):
                            attr = self._self_attr(t.value)
                elif isinstance(sub, ast.Call) and \
                        isinstance(sub.func, ast.Attribute) and \
                        sub.func.attr in _MUTATORS:
                    attr = self._self_attr(sub.func.value)
                if attr is not None and attr in read_attrs and \
                        ci.attr_types.get(attr) not in _THREADSAFE_TYPES:
                    self.findings.append(Finding(
                        self.mod.path, node.lineno, node.col_offset,
                        "TL202",
                        f"check-then-act on 'self.{attr}' outside the "
                        "guarding lock — the state can change between "
                        "the test and the mutation", self.fi.qualname))
                    return

    # -- calls --------------------------------------------------------------

    def _visit_call(self, node: ast.Call) -> None:
        canon = canonical(self.mod.aliases, node.func) or ""
        # thread targets / signal handlers
        if canon == "threading.Thread" or canon.endswith(".Thread"):
            for kw in node.keywords:
                if kw.arg == "target":
                    key = self._callee_key(kw.value)
                    if key:
                        self.thread_targets.append(key)
        if canon == "signal.signal" and len(node.args) >= 2:
            key = self._callee_key(node.args[1])
            if key:
                self.handler_targets.append(key)
        # mutator calls are writes for TL201
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in _MUTATORS and \
                isinstance(node.func.value, ast.Attribute):
            recv = node.func.value
            owner = self._attr_owner(recv)
            if owner is not None:
                ci = self.corpus.classes.get(owner)
                if ci is not None:
                    ci.attr_writers.setdefault(recv.attr, set()).add(
                        f"{self.mod.uid}:{self.fi.qualname}")
                    if not self.with_stack and \
                            ci.attr_types.get(recv.attr) not in \
                            _THREADSAFE_TYPES:
                        self.unguarded_self_writes.append(
                            (owner, recv.attr, node))
        # TL501: Condition.wait outside a while loop
        if isinstance(node.func, ast.Attribute) and node.func.attr == "wait":
            ld = self._lock_node(node.func.value)
            if ld is not None and ld.kind == "Condition" and \
                    self.while_depth == 0:
                self.findings.append(Finding(
                    self.mod.path, node.lineno, node.col_offset, "TL501",
                    f"Condition.wait on '{ld.node_id}' outside a while "
                    "loop — spurious/stolen wakeups break the predicate; "
                    "use 'while not <pred>: cond.wait()'",
                    self.fi.qualname))
        # lock-order + blocking checks while lexically holding locks
        if self.with_stack:
            self._check_blocking(node, canon)
            key = self._callee_key(node.func)
            if key is not None:
                self.fi.calls_under_lock.append(
                    (tuple(self.with_stack), key, node))
        # record callee for reachability closures
        key = self._callee_key(node.func)
        if key is not None:
            self.fi.callees.add(key)

    def _check_blocking(self, node: ast.Call, canon: str) -> None:
        held = self.with_stack[-1]
        msg = None
        kwargs = {kw.arg for kw in node.keywords}
        if canon in _BLOCKING_CANON or \
                canon.startswith(_BLOCKING_CANON_PREFIXES):
            msg = f"'{canon}'"
        elif isinstance(node.func, ast.Attribute):
            a = node.func.attr
            recv_t = self._type_of(node.func.value)
            if a == "result":
                msg = "'.result()' (future wait)"
            elif a == "block_until_ready":
                msg = "'.block_until_ready()'"
            elif a == "join" and not node.args and \
                    not isinstance(node.func.value, ast.Constant):
                # 1-positional-arg join is str.join — skipped; a bare
                # or timeout-kwarg join is a thread/queue wait
                msg = "'.join()' (thread/queue wait)"
            elif a in ("get", "put") and recv_t == "Queue" and \
                    "timeout" not in kwargs and \
                    "block" not in kwargs and \
                    len(node.args) <= (0 if a == "get" else 1):
                # only the bare default form is flagged: any block=/
                # timeout= kwarg or positional (block[, timeout]) arg —
                # get(False), get(True, 5), put(item, False) — means the
                # author chose the blocking semantics explicitly
                msg = f"'Queue.{a}()' without a timeout"
            elif a == "wait":
                ld = self._lock_node(node.func.value)
                if ld is not None and ld.kind == "Condition" and \
                        ld.node_id in self.with_stack:
                    msg = None     # the condition protocol itself
                elif (recv_t == "Event" or
                      (ld is not None and ld.kind == "Condition")) and \
                        "timeout" not in kwargs and not node.args:
                    msg = "'.wait()' without a timeout"
        if msg:
            self.findings.append(Finding(
                self.mod.path, node.lineno, node.col_offset, "TL301",
                f"blocking call {msg} while holding '{held}' stalls "
                "every thread that needs the lock", self.fi.qualname))


# --------------------------------------------------------------------------
# pass 3: closures + graph rules
# --------------------------------------------------------------------------

def _fixpoint_scope(corpus: Corpus, roots: List[str], attr: str) -> None:
    """Mark ``attr`` (thread_scope / handler_scope) on roots and their
    transitive callees."""
    work = [k for k in roots if k in corpus.funcs]
    while work:
        key = work.pop()
        fi = corpus.funcs[key]
        if getattr(fi, attr):
            continue
        setattr(fi, attr, True)
        for c in fi.callees:
            if c in corpus.funcs and not getattr(corpus.funcs[c], attr):
                work.append(c)


def _trans_acquires(corpus: Corpus) -> None:
    for fi in corpus.funcs.values():
        fi.trans_acquires = set(fi.direct_acquires)
    changed = True
    while changed:
        changed = False
        for fi in corpus.funcs.values():
            for c in fi.callees:
                callee = corpus.funcs.get(c)
                if callee is None:
                    continue
                add = callee.trans_acquires - fi.trans_acquires
                if add:
                    fi.trans_acquires |= add
                    changed = True


def _closure_edges(corpus: Corpus) -> List[Edge]:
    """Ordering edges through calls: every lock the callee (transitively)
    acquires is acquired while the caller's held set is held."""
    edges: List[Edge] = []
    for key, fi in corpus.funcs.items():
        for held_ids, callee_key, node in fi.calls_under_lock:
            callee = corpus.funcs.get(callee_key)
            if callee is None:
                continue
            for acq in callee.trans_acquires:
                for held in held_ids:
                    if held != acq:
                        edges.append(Edge(
                            held=held, acquired=acq, path=fi.module.path,
                            line=node.lineno, func=fi.qualname,
                            via=callee_key))
    return edges


def _find_cycles(edges: List[Edge]) -> List[List[str]]:
    """Strongly-connected components of size > 1 in the lock graph."""
    graph: Dict[str, Set[str]] = {}
    for e in edges:
        graph.setdefault(e.held, set()).add(e.acquired)
        graph.setdefault(e.acquired, set())
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    def strongconnect(v: str) -> None:
        # iterative Tarjan (recursion depth is unbounded on long chains)
        work = [(v, iter(sorted(graph[v])))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(graph[w]))))
                    advanced = True
                    break
                elif w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                if len(scc) > 1:
                    sccs.append(sorted(scc))

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)
    return sccs


def _shared_write_findings(corpus: Corpus,
                           analyses: Dict[str, _FuncAnalysis]
                           ) -> List[Finding]:
    out: List[Finding] = []
    for key, an in analyses.items():
        fi = corpus.funcs[key]
        if not fi.thread_scope:
            continue
        qual_leaf = fi.qualname.rsplit(".", 1)[-1]
        if qual_leaf in ("__init__", "__enter__"):
            continue   # construction happens-before thread start
        seen: Set[Tuple[str, str]] = set()
        for owner, attr, node in an.unguarded_self_writes:
            ci = corpus.classes.get(owner)
            if ci is None or (owner, attr) in seen:
                continue
            if ci.attr_types.get(attr) in _THREADSAFE_TYPES:
                continue
            touchers = (ci.attr_writers.get(attr, set())
                        | ci.attr_readers.get(attr, set()))
            outside = [t for t in touchers
                       if t in corpus.funcs
                       and not corpus.funcs[t].thread_scope
                       and not t.endswith(".__init__")]
            if not outside:
                continue
            seen.add((owner, attr))
            ref = "self" if owner == fi.cls else owner
            out.append(Finding(
                fi.module.path, node.lineno, node.col_offset, "TL201",
                f"unguarded write to shared '{ref}.{attr}' on a thread "
                f"reachable from a Thread target (also touched by "
                f"{sorted(outside)[0].split(':', 1)[1]}) — guard both "
                "sides with one lock", fi.qualname))
        for name, node in an.unguarded_global_writes:
            mod = fi.module
            readers = (mod.global_readers.get(name, set())
                       | mod.global_writers.get(name, set()))
            outside = [t for t in readers
                       if t in corpus.funcs
                       and not corpus.funcs[t].thread_scope]
            if not outside:
                continue
            out.append(Finding(
                fi.module.path, node.lineno, node.col_offset, "TL201",
                f"unguarded write to module global '{name}' on a thread "
                "reachable from a Thread target — guard both sides with "
                "one lock", fi.qualname))
    return out


_HANDLER_SAFE_LEAVES = {"append"}


def _handler_findings(corpus: Corpus,
                      analyses: Dict[str, _FuncAnalysis]) -> List[Finding]:
    """TL401: lock acquisition, jax calls, blocking waits or file I/O in
    a signal-handler closure.  Deliberately NOT flagged: logging (its
    RLock is reentrant for the interrupted main thread) and
    ``threading.Thread(...).start()`` — handing work to a thread is the
    FIX pattern (obs/profiler.py)."""
    out: List[Finding] = []
    for key, an in analyses.items():
        fi = corpus.funcs[key]
        if not fi.handler_scope:
            continue
        node = fi.node
        if isinstance(node, ast.Lambda):
            continue
        # skip nested function bodies: work handed to a worker thread
        # (`def work(): ...jax...; Thread(target=work).start()`) is the
        # documented FIX pattern, not handler-context work — the nested
        # def is only flagged if something CALLS it from handler scope
        # (then it is its own handler-scope FuncInfo)
        nested = {sub for stmt in ast.iter_child_nodes(node)
                  for sub in ast.walk(stmt)
                  if isinstance(sub, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda))}

        def _walk_own(n):
            yield n
            for child in ast.iter_child_nodes(n):
                if child in nested:
                    continue
                yield from _walk_own(child)

        for sub in _walk_own(node):
            bad: Optional[str] = None
            if isinstance(sub, ast.With):
                for item in sub.items:
                    if an._lock_node(item.context_expr) is not None:
                        bad = "acquires a lock"
            elif isinstance(sub, ast.Call):
                canon = canonical(fi.module.aliases, sub.func) or ""
                leaf = canon.rsplit(".", 1)[-1]
                if canon.startswith("jax"):
                    bad = f"calls into jax ('{canon}')"
                elif canon in _BLOCKING_CANON or canon.startswith(
                        _BLOCKING_CANON_PREFIXES):
                    bad = f"blocking call '{canon}'"
                elif leaf == "open" and canon == "open":
                    bad = "file I/O"
                elif isinstance(sub.func, ast.Attribute):
                    a = sub.func.attr
                    if a == "acquire":
                        bad = "acquires a lock"
                    elif a in ("get", "put") and \
                            an._type_of(sub.func.value) == "Queue":
                        bad = f"Queue.{a}"
                    elif a == "join" and not sub.args:
                        bad = "blocking join"
            if bad:
                out.append(Finding(
                    fi.module.path, sub.lineno, sub.col_offset, "TL401",
                    f"signal handler {bad} — handlers run on the main "
                    "thread at an arbitrary bytecode boundary and must "
                    "only flip state (do the work on a worker thread; "
                    "see obs/profiler.py install_sigusr2)",
                    fi.qualname))
    return out


# --------------------------------------------------------------------------
# driver
# --------------------------------------------------------------------------

def analyze_paths(paths: Sequence[str]
                  ) -> Tuple[List[Finding], List[Edge], List[List[str]],
                             Corpus]:
    """Full analysis: returns (findings, lock edges, cycles, corpus)."""
    files = iter_py_files(paths)
    mods = [m for m in (load_module(f) for f in files) if m is not None]
    corpus_classes: Set[str] = set()
    for m in mods:
        for node in ast.walk(m.tree):
            if isinstance(node, ast.ClassDef):
                corpus_classes.add(node.name)
    for m in mods:
        _Scanner(m, corpus_classes).visit(m.tree)
        # module-level statements (signal.signal / Thread registrations
        # outside any function) analyze as a synthetic "<module>" scope
        m.funcs["<module>"] = FuncInfo(qualname="<module>", module=m,
                                       node=m.tree)
    corpus = Corpus(mods)

    analyses: Dict[str, _FuncAnalysis] = {}
    thread_roots: List[str] = []
    handler_roots: List[str] = []
    findings: List[Finding] = []
    edges: List[Edge] = []
    # two passes: attr types accrete across classes first (done in
    # _Scanner), then function bodies analyze against the full corpus
    for m in mods:
        for q, fi in m.funcs.items():
            an = _FuncAnalysis(fi, corpus)
            an.run()
            analyses[f"{m.uid}:{q}"] = an
            thread_roots.extend(an.thread_targets)
            handler_roots.extend(an.handler_targets)
            findings.extend(an.findings)
            edges.extend(an.edges)
    # module-global read index for TL201 (simple name loads)
    for m in mods:
        global_names = set(m.global_writers)
        for q, fi in m.funcs.items():
            for sub in ast.walk(fi.node):
                if isinstance(sub, ast.Name) and \
                        isinstance(sub.ctx, ast.Load) and \
                        sub.id in global_names:
                    m.global_readers.setdefault(sub.id, set()).add(
                        f"{m.uid}:{q}")

    _fixpoint_scope(corpus, thread_roots, "thread_scope")
    _fixpoint_scope(corpus, handler_roots, "handler_scope")
    _trans_acquires(corpus)
    edges.extend(_closure_edges(corpus))

    cycles = _find_cycles(edges)
    cycle_nodes = {n for scc in cycles for n in scc}
    seen_edges: Set[Tuple[str, str]] = set()
    for e in edges:
        if e.held in cycle_nodes and e.acquired in cycle_nodes:
            scc = next(s for s in cycles if e.held in s)
            if e.acquired not in scc:
                continue
            if (e.held, e.acquired) in seen_edges:
                continue
            seen_edges.add((e.held, e.acquired))
            findings.append(Finding(
                e.path, e.line, 0, "TL101",
                f"lock-order cycle: '{e.acquired}' acquired while "
                f"holding '{e.held}' but the cycle "
                f"{' -> '.join(scc + [scc[0]])} means another thread can "
                "acquire them in the opposite order — pick one global "
                "order", e.func))

    findings.extend(_shared_write_findings(corpus, analyses))
    findings.extend(_handler_findings(corpus, analyses))

    # waivers per module
    by_path: Dict[str, List[Finding]] = {}
    for f in findings:
        by_path.setdefault(f.path, []).append(f)
    out: List[Finding] = []
    for m in mods:
        out.extend(apply_waivers(m.path, m.waivers,
                                 by_path.get(m.path, []), RULES,
                                 prefix="TL", tool="threadlint"))
    out.sort(key=lambda f: (f.path, f.line, f.col, f.code))
    return out, edges, cycles, corpus


def lint_paths(paths: Sequence[str]) -> List[Finding]:
    return analyze_paths(paths)[0]


def lock_graph(paths: Sequence[str]) -> Dict:
    """The lock-order graph as a JSON-able dict (the ``--graph`` dump
    documented in docs/ANALYSIS.md)."""
    _, edges, cycles, corpus = analyze_paths(paths)
    nodes: Dict[str, Dict] = {}
    for m in corpus.mods:
        for ld in m.module_locks.values():
            nodes[ld.node_id] = {"id": ld.node_id, "kind": ld.kind,
                                 "defined": f"{ld.path}:{ld.line}"}
        for ci in m.classes.values():
            for ld in ci.lock_attrs.values():
                nodes[ld.node_id] = {"id": ld.node_id, "kind": ld.kind,
                                     "defined": f"{ld.path}:{ld.line}"}
    seen: Set[Tuple[str, str]] = set()
    edge_list = []
    for e in sorted(edges, key=lambda e: (e.held, e.acquired, e.line)):
        if (e.held, e.acquired) in seen:
            continue
        seen.add((e.held, e.acquired))
        for nid in (e.held, e.acquired):
            nodes.setdefault(nid, {"id": nid, "kind": "?", "defined": "?"})
        edge_list.append({"held": e.held, "acquired": e.acquired,
                          "site": f"{e.path}:{e.line}", "func": e.func,
                          **({"via": e.via} if e.via else {})})
    return {"nodes": sorted(nodes.values(), key=lambda n: n["id"]),
            "edges": edge_list,
            "cycles": cycles}


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="threadlint",
        description="concurrency static analysis (rules: docs/ANALYSIS.md)")
    p.add_argument("paths", nargs="*", default=["mx_rcnn_tpu"],
                   help="files or directories to lint")
    p.add_argument("--json", action="store_true",
                   help="emit findings as JSON records")
    p.add_argument("--show-waived", action="store_true",
                   help="also print waived findings")
    p.add_argument("--list-rules", action="store_true")
    p.add_argument("--graph", action="store_true",
                   help="emit the lock-order graph as JSON and exit 0")
    args = p.parse_args(argv)
    if args.list_rules:
        for code, desc in sorted(RULES.items()):
            print(f"{code}  {desc}")
        return 0
    rc = check_paths_exist("threadlint", args.paths)
    if rc is not None:
        return rc
    if args.graph:
        print(json.dumps(lock_graph(args.paths), indent=1))
        return 0
    findings = lint_paths(args.paths)
    active = [f for f in findings if f.waived is None]
    waived = [f for f in findings if f.waived is not None]
    shown = findings if args.show_waived else active
    if args.json:
        for f in shown:
            print(json.dumps({"path": f.path, "line": f.line,
                              "col": f.col + 1, "code": f.code,
                              "message": f.message, "func": f.func,
                              "waived": f.waived}))
    else:
        for f in shown:
            print(f.render())
    print(f"threadlint: {len(active)} finding(s), {len(waived)} waived",
          file=sys.stderr)
    return 1 if active else 0


if __name__ == "__main__":
    raise SystemExit(main())
