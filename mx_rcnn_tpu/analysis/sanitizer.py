"""Runtime lock sanitizer: threadlint's dynamic twin.

The static pass (``analysis/threadlint.py``) reasons about lock order
from the AST; this module records the REAL acquisition order of a live
process and turns three runtime hazards into reportable (or fatal)
events:

* **order inversions** — lock B taken while holding A after some thread
  has taken A while holding B: the pair can deadlock under the right
  interleaving even if it never has yet;
* **hold-time budget violations** — a lock held longer than
  ``MXRCNN_LOCK_BUDGET_MS`` (serving locks are supposed to bound a few
  dict ops; a model run or disk write under one is a latency cliff);
* **stalls** — an ``acquire`` blocked longer than
  ``MXRCNN_LOCK_STALL_S``: the watchdog thread dumps every thread's
  stack (the post-mortem a wedged 'Sl' process never gives you) and
  records the trip.

Zero-cost when off: nothing in the runtime packages imports this
module's wrappers — arming happens by MONKEY-PATCHING
``threading.Lock`` / ``threading.RLock`` before the subsystems build
their locks (``install()``), so production code keeps calling plain
``threading.Lock()``.  Only locks allocated from ``mx_rcnn_tpu`` code
are wrapped (the allocation site is checked): jax/stdlib internals keep
their raw locks, both for overhead and because their ordering is not
ours to police.

Arming (the smokes; ``make threadlint-smoke``)::

    MXRCNN_THREAD_SANITIZER=1       # record + report
    MXRCNN_THREAD_SANITIZER=strict  # raise on the inverting acquire
    MXRCNN_LOCK_BUDGET_MS=200       # hold-time budget (0 = off)
    MXRCNN_LOCK_STALL_S=30          # watchdog threshold

``maybe_install_from_env()`` is called at CLI startup
(``tools/loadgen.py``, ``tools/crashloop.py``, ``tools/train.py``); on
exit an armed process prints one ``LOCKSAN_REPORT {json}`` line and the
``--check`` modes fold :func:`check_clean` into their exit code.
``threading.Condition()`` needs no patching: its default lock is
``threading.RLock()``, which resolves to the patched factory.
"""

from __future__ import annotations

import atexit
import faulthandler
import json
import logging
import os
import sys
import threading
import time
import traceback
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger("mx_rcnn_tpu")

# originals captured at import — the wrappers and the sanitizer's own
# state must use RAW locks (a sanitized sanitizer would recurse)
_RAW_LOCK = threading.Lock
_RAW_RLOCK = threading.RLock

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class _State:
    """Process-wide sanitizer state (one instance, raw-locked)."""

    def __init__(self):
        self.lock = _RAW_LOCK()
        self.strict = False
        self.budget_ms = 0.0
        self.stall_s = 30.0
        self.installed = False
        self.locks_wrapped = 0
        # first-seen order edges: (held, acquired) -> "file:line in thread"
        self.edges: Dict[Tuple[str, str], str] = {}
        self.inversions: List[Dict] = []
        self.budget_violations: List[Dict] = []
        self.watchdog_trips: List[Dict] = []
        # in-progress blocking acquires: id -> (thread, lockname, t0)
        self.pending: Dict[int, Tuple[str, str, float]] = {}
        self.pending_seq = 0
        self.tls = threading.local()
        self.watchdog: Optional[threading.Thread] = None
        self.watchdog_stop = threading.Event()

    def held(self) -> List[str]:
        h = getattr(self.tls, "held", None)
        if h is None:
            h = self.tls.held = []
        return h


_S = _State()

# watchdog-trip listeners: the flight recorder (obs/flightrec.py)
# registers here so a stalled lock dumps a black-box record alongside
# the stack dump.  Raw-locked (sanitizer-internal state), invoked
# OUTSIDE _S.lock and fail-soft — a listener exception must not kill
# the watchdog thread.
_trip_listeners_lock = _RAW_LOCK()
_trip_listeners: List = []


def add_trip_listener(fn) -> None:
    """Register ``fn(trip_record)`` to run on every watchdog trip."""
    with _trip_listeners_lock:
        if fn not in _trip_listeners:
            _trip_listeners.append(fn)


def remove_trip_listener(fn) -> None:
    with _trip_listeners_lock:
        if fn in _trip_listeners:
            _trip_listeners.remove(fn)


def _notify_trip(rec: Dict) -> None:
    with _trip_listeners_lock:
        listeners = list(_trip_listeners)
    for fn in listeners:
        try:
            fn(rec)
        except Exception:
            logger.exception("lock sanitizer: trip listener failed")


def _site() -> str:
    """Allocation/acquisition site: first frame outside this module and
    the threading module."""
    f = sys._getframe(2)
    skip = (__file__, threading.__file__)
    while f is not None and f.f_code.co_filename in skip:
        f = f.f_back
    if f is None:
        return "?"
    fn = f.f_code.co_filename
    rel = os.path.relpath(fn, os.path.dirname(_PKG_DIR)) \
        if fn.startswith(os.path.dirname(_PKG_DIR)) else fn
    return f"{rel}:{f.f_lineno}"


def _from_package() -> bool:
    """True when the allocating frame lives under mx_rcnn_tpu (wrapped)
    — jax/stdlib allocations stay raw."""
    f = sys._getframe(2)
    skip = (__file__, threading.__file__)
    while f is not None and f.f_code.co_filename in skip:
        f = f.f_back
    return f is not None and f.f_code.co_filename.startswith(_PKG_DIR)


class SanitizerError(RuntimeError):
    """Raised on an order inversion in strict mode."""


class _SanBase:
    """Shared acquire/release accounting for the Lock/RLock wrappers."""

    def __init__(self, inner, name: str):
        self._inner = inner
        self.san_name = name
        self._acquired_at = 0.0
        self._hold_site = ""

    # -- accounting ---------------------------------------------------------

    def _before_acquire(self, blocking: bool) -> Optional[int]:
        pid = None
        if blocking:
            with _S.lock:
                _S.pending_seq += 1
                pid = _S.pending_seq
                _S.pending[pid] = (threading.current_thread().name,
                                   self.san_name, time.monotonic())
        return pid

    def _after_acquire(self, pid: Optional[int], got: bool,
                       order_track: bool) -> None:
        if pid is not None:
            with _S.lock:
                _S.pending.pop(pid, None)
        if not got or not order_track:
            return
        held = _S.held()
        site = _site()
        inversion = None
        with _S.lock:
            # held entries are (instance id, node name): node names are
            # allocation SITES (all Replica._lock instances share one
            # graph node, mirroring the static lock-order graph), so
            # instance identity must be tracked separately or a
            # same-site pair would shadow its own ordering edge
            for hid, h in held:
                if hid == id(self):
                    continue
                edge = (h, self.san_name)
                rev = (self.san_name, h)
                if rev in _S.edges and edge not in _S.edges:
                    inversion = {
                        "held": h, "acquired": self.san_name,
                        "site": site,
                        "reverse_seen_at": _S.edges[rev],
                        "thread": threading.current_thread().name,
                    }
                    _S.inversions.append(inversion)
                _S.edges.setdefault(
                    edge, f"{site} in {threading.current_thread().name}")
        held.append((id(self), self.san_name))
        self._acquired_at = time.monotonic()
        self._hold_site = site
        if inversion is not None:
            logger.error("lock sanitizer: ORDER INVERSION %s", inversion)
            if _S.strict:
                # undo the acquisition before raising: the caller's
                # with-block body never runs, so nothing would ever
                # release the inner lock — every other thread needing it
                # would hang instead of seeing the leg fail fast
                held.pop()
                self._strict_unwind()
                raise SanitizerError(
                    f"lock-order inversion: acquired {self.san_name!r} "
                    f"while holding {inversion['held']!r} at {site}, but "
                    f"the opposite order was taken at "
                    f"{inversion['reverse_seen_at']}")

    def _after_release(self, order_track: bool) -> None:
        if not order_track:
            return
        held = _S.held()
        for i in range(len(held) - 1, -1, -1):  # most recent acquisition
            if held[i][0] == id(self):
                del held[i]
                break
        if _S.budget_ms > 0 and self._acquired_at:
            ms = (time.monotonic() - self._acquired_at) * 1e3
            if ms > _S.budget_ms:
                rec = {"lock": self.san_name, "held_ms": round(ms, 2),
                       "budget_ms": _S.budget_ms,
                       "acquired_at": self._hold_site,
                       "thread": threading.current_thread().name}
                with _S.lock:
                    _S.budget_violations.append(rec)
                logger.warning("lock sanitizer: hold-time budget "
                               "exceeded %s", rec)

    def _strict_unwind(self) -> None:
        """Release the just-acquired inner lock on a strict-mode raise
        (the RLock wrapper also rolls back its owner/depth)."""
        self._inner.release()

    # -- lock protocol ------------------------------------------------------

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<sanitized {type(self).__name__} {self.san_name}>"


class SanLock(_SanBase):
    """Instrumented non-reentrant lock.  Deliberately does NOT expose
    ``_is_owned``/``_release_save`` — ``threading.Condition`` then uses
    its acquire/release fallbacks, which keep the held-set accurate."""

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        pid = self._before_acquire(blocking and timeout == -1)
        got = self._inner.acquire(blocking, timeout)
        self._after_acquire(pid, got, order_track=True)
        return got

    def release(self) -> None:
        self._after_release(order_track=True)
        self._inner.release()


class SanRLock(_SanBase):
    """Instrumented reentrant lock.  Ordering is tracked on the 0→1
    depth transition only; exposes the ``_release_save`` /
    ``_acquire_restore`` / ``_is_owned`` trio so ``Condition.wait`` on a
    held RLock stays correct AND tracked."""

    def __init__(self, inner, name: str):
        super().__init__(inner, name)
        self._owner: Optional[int] = None
        self._depth = 0

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        me = threading.get_ident()
        reentrant = self._owner == me
        pid = self._before_acquire(blocking and timeout == -1
                                   and not reentrant)
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._owner = me
            self._depth += 1
        self._after_acquire(pid, got, order_track=got and self._depth == 1)
        return got

    __enter__ = _SanBase.__enter__

    def _strict_unwind(self) -> None:
        self._depth -= 1
        if self._depth == 0:
            self._owner = None
        self._inner.release()

    def release(self) -> None:
        if self._depth == 1:
            self._after_release(order_track=True)
            self._owner = None
        self._depth -= 1
        self._inner.release()

    # Condition support: full release/restore across a wait()
    def _release_save(self):
        self._after_release(order_track=True)
        depth, self._depth, self._owner = self._depth, 0, None
        return (self._inner._release_save(), depth)

    def _acquire_restore(self, state):
        inner_state, depth = state
        self._inner._acquire_restore(inner_state)
        self._owner = threading.get_ident()
        self._depth = depth
        self._after_acquire(None, True, order_track=True)

    def _is_owned(self):
        return self._inner._is_owned()


def _make_lock():
    inner = _RAW_LOCK()
    if not _S.installed or not _from_package():
        return inner
    with _S.lock:
        _S.locks_wrapped += 1
    return SanLock(inner, f"Lock@{_site()}")


def _make_rlock():
    inner = _RAW_RLOCK()
    if not _S.installed or not _from_package():
        return inner
    with _S.lock:
        _S.locks_wrapped += 1
    return SanRLock(inner, f"RLock@{_site()}")


# --------------------------------------------------------------------------
# watchdog
# --------------------------------------------------------------------------

def _watchdog_loop(stop: threading.Event) -> None:
    # `stop` is captured per-thread: if uninstall()'s bounded join times
    # out (e.g. the stack dump blocked on a full stderr pipe), the
    # orphan still sees ITS set event and exits when unblocked — a fresh
    # install starts a new thread with a new event, never two live loops
    reported: set = set()   # pending ids already tripped — one trip (and
    # one all-stack dump) per stalled acquire, not one per poll tick
    while not stop.wait(min(_S.stall_s / 4.0, 1.0)):
        now = time.monotonic()
        stuck = []
        with _S.lock:
            reported &= set(_S.pending)   # completed acquires forget
            for pid, (thread, lockname, t0) in _S.pending.items():
                if now - t0 > _S.stall_s and pid not in reported:
                    reported.add(pid)
                    stuck.append({"thread": thread, "lock": lockname,
                                  "waited_s": round(now - t0, 1)})
        for rec in stuck:
            with _S.lock:
                _S.watchdog_trips.append(rec)
            logger.critical(
                "lock sanitizer WATCHDOG: %s blocked %.0fs acquiring %s "
                "— dumping all stacks", rec["thread"], rec["waited_s"],
                rec["lock"])
            try:
                faulthandler.dump_traceback(file=sys.stderr)
            except Exception:
                for tid, frame in sys._current_frames().items():
                    print(f"--- thread {tid} ---", file=sys.stderr)
                    traceback.print_stack(frame, file=sys.stderr)
            _notify_trip(rec)


# --------------------------------------------------------------------------
# public API
# --------------------------------------------------------------------------

def install(strict: bool = False, budget_ms: float = 0.0,
            stall_s: float = 30.0) -> None:
    """Arm the sanitizer: patch ``threading.Lock``/``threading.RLock``
    so every lock subsequently allocated from package code is
    instrumented, and start the stall watchdog.  Idempotent."""
    _S.strict = strict
    _S.budget_ms = float(budget_ms)
    _S.stall_s = float(stall_s)
    if _S.installed:
        return   # knobs refreshed above; factories already patched
    _S.installed = True
    threading.Lock = _make_lock
    threading.RLock = _make_rlock
    _S.watchdog_stop = threading.Event()
    _S.watchdog = threading.Thread(target=_watchdog_loop,
                                   args=(_S.watchdog_stop,),
                                   name="locksan-watchdog", daemon=True)
    _S.watchdog.start()
    logger.info("lock sanitizer armed (strict=%s budget_ms=%s stall_s=%s)",
                strict, budget_ms, stall_s)


def uninstall() -> None:
    """Restore the raw factories (tests).  Already-wrapped locks stay
    wrapped — only future allocations revert."""
    threading.Lock = _RAW_LOCK
    threading.RLock = _RAW_RLOCK
    _S.installed = False
    try:
        atexit.unregister(_report_at_exit)
    except Exception:
        pass
    _S.watchdog_stop.set()
    if _S.watchdog is not None:
        _S.watchdog.join(timeout=2.0)
    _S.watchdog = None


def reset() -> None:
    """Drop recorded edges/events (tests; keeps the armed state)."""
    with _S.lock:
        _S.edges.clear()
        _S.inversions.clear()
        _S.budget_violations.clear()
        _S.watchdog_trips.clear()
        _S.pending.clear()


def armed() -> bool:
    return _S.installed


def report() -> Dict:
    with _S.lock:
        return {
            "armed": _S.installed,
            "strict": _S.strict,
            "locks_wrapped": _S.locks_wrapped,
            "order_edges": len(_S.edges),
            "inversions": list(_S.inversions),
            "budget_violations": list(_S.budget_violations),
            "watchdog_trips": list(_S.watchdog_trips),
        }


def check_clean() -> bool:
    """True iff no inversion and no watchdog trip was recorded (budget
    violations are advisory — reported, not fatal)."""
    with _S.lock:
        return not _S.inversions and not _S.watchdog_trips


def check_problems() -> List[str]:
    """``--check`` integration for the smoke CLIs: human-readable
    problem strings when the sanitizer is armed and dirty; empty when
    clean or not armed (budget violations are advisory)."""
    if not _S.installed:
        return []
    rep = report()
    out: List[str] = []
    if rep["inversions"]:
        out.append(f"lock sanitizer recorded "
                   f"{len(rep['inversions'])} order inversion(s): "
                   f"{rep['inversions'][:3]}")
    if rep["watchdog_trips"]:
        out.append(f"lock sanitizer watchdog tripped "
                   f"{len(rep['watchdog_trips'])} time(s): "
                   f"{rep['watchdog_trips'][:3]}")
    return out


def maybe_install_from_env() -> bool:
    """CLI hook: arm from ``MXRCNN_THREAD_SANITIZER`` (off/1/strict) and
    register the exit-line reporter.  Returns the armed state."""
    mode = os.environ.get("MXRCNN_THREAD_SANITIZER", "").strip().lower()
    if mode in ("", "0", "off", "false"):
        return False
    budget = float(os.environ.get("MXRCNN_LOCK_BUDGET_MS", "0") or 0)
    stall = float(os.environ.get("MXRCNN_LOCK_STALL_S", "30") or 30)
    install(strict=mode == "strict", budget_ms=budget, stall_s=stall)
    atexit.register(_report_at_exit)
    return True


def _report_at_exit() -> None:
    rep = report()
    print("LOCKSAN_REPORT " + json.dumps(rep), flush=True)
    if not check_clean():
        # atexit cannot change the exit code reliably; the --check modes
        # and the smoke drivers grep for this marker
        print("LOCKSAN_DIRTY", flush=True)
