"""netlint — AST linter for the network surface (sockets, HTTP, the
binary wire).

Why a fifth linter: PR 15 made bytes arrive from other machines — the
MXR1/MXD1 prepared-frame wire (``serve/remote.py``), per-host agent
HTTP planes (``serve/agent.py``), remote metric scrapes
(``obs/collect.py — HttpSource``) and the scheduler's actuation RPCs
(``serve/scheduler.py — AgentAdmin``) — and none of the four in-tree
linters audit what that changed: UNTRUSTED input.  The ROADMAP
north-star (serve heavy traffic from millions of users) demands that
every socket read be bounded, timed out, and reject-never-crash *by
construction*; netlint machine-checks that, the same way persistlint
(PR 12) made durability violations unshippable.  The runtime twin is
``analysis/wirefuzz.py`` — a deterministic seeded mutation engine run
against the REAL decoders/servers (``make wirefuzz-smoke``), with
sensitivity proven by planted-vulnerable arms.

The socket-allocation model (what netlint tracks):

* an ALLOCATION is ``socket.socket`` / ``socket.create_connection`` /
  ``http.client.HTTP(S)Connection`` / ``urllib.request.urlopen``, plus
  the derived objects: ``conn.getresponse()``, ``sock.accept()``,
  ``sock.makefile()`` (each inherits its source's timedness);
* an allocation is TIMED when the call carries a non-None ``timeout=``
  or the bound name later gets ``.settimeout(...)``; ``self.<attr>``
  allocations are tracked per class (an ``__init__`` that allocates
  untimed is visible to every method), and helper CLOSURE rides the
  same call-graph machinery graphlint/persistlint use: a function
  whose return expression is (or resolves to) an untimed allocation
  is an untimed *factory*, and its callers' bound names inherit that;
* BLOCKING OPS are ``connect / recv / recv_into / recvfrom / accept /
  makefile / send / sendall / request / getresponse / read / readline /
  readinto`` — each is flagged only on a name the model tracks, so
  file ``.read()`` and queue ``.send()`` never false-positive.

Rule catalogue (bad/good examples: docs/ANALYSIS.md "netlint"):

* NL101 — blocking socket op on an allocation with no timeout: a
  half-open peer (SIGKILL'd host, black-holed route) wedges the
  calling thread forever.
* NL102 — socket/connection bound to a local name and used, but not
  closed on exception paths (no ``with``, no ``finally``/handler
  close) and never handed off (returned / stored on ``self`` or a
  container / passed to a callee): an exception between allocation
  and close leaks the fd.
* NL201 — ``struct.unpack``/``unpack_from`` of a buffer with no
  preceding length check: a truncated frame dies as an untyped
  ``struct.error`` instead of the decoder's typed ValueError.
* NL202 — a length/count parsed off the wire (a target of an unpack
  assignment, or derived from one) sizes a recv/allocation
  (``recv(n)`` / ``read(n)`` / ``bytearray(n)`` / ``np.zeros`` /
  ``np.frombuffer(count=...)`` / ``b"x" * n``) with no bound against
  anything: a peer writing 2^31 into a length field makes this
  process allocate it.
* NL203 — unbounded response buffering: an argless ``.read()`` on a
  network response, or a byte-accumulating recv/read loop with no
  max-size comparison inside the loop (route through
  ``netio.read_limited``).
* NL204 — an HTTP handler body read (``...rfile.read``) that is
  argless or sized by a Content-Length-derived name that was never
  bounded (route through ``netio.read_request_body`` — 411/413/400).
* NL301 — a retry loop (a loop whose exception handler ``continue``\\ s)
  lacking backoff (``time.sleep``/``.wait`` in the loop) or an attempt
  cap (finite iterable / bounded while-test): retries without both
  turn one struggling peer into a self-inflicted flood.

Waivers: same protocol as the other linters (``analysis/common.py``) —
``# netlint: disable=NL101 <reason>`` on the line or the line above; a
reasonless waiver is NL001, an unknown rule NL002.

CLI::

    python -m mx_rcnn_tpu.analysis.netlint [paths...] [--json]
        [--show-waived] [--list-rules]

Exit status 0 iff no unwaived findings.
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import sys
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from mx_rcnn_tpu.analysis.common import (Finding, apply_waivers, canonical,
                                         check_paths_exist,
                                         collect_import_aliases,
                                         iter_py_files, parse_waivers)

RULES: Dict[str, str] = {
    "NL001": "waiver without a reason (every waiver must say why)",
    "NL002": "waiver names an unknown rule code",
    "NL101": "blocking socket op on an allocation with no timeout",
    "NL102": "socket/connection not closed on exception paths",
    "NL201": "struct.unpack of a buffer without a preceding length "
             "check",
    "NL202": "wire-derived length sizes a recv/allocation without a "
             "bound",
    "NL203": "unbounded response read (argless .read() or uncapped "
             "accumulation loop)",
    "NL204": "HTTP handler body read without a Content-Length bound",
    "NL301": "retry loop without both backoff and an attempt cap",
}

# canonical allocator name -> (kind, index of timeout coverage)
_ALLOCATORS: Dict[str, str] = {
    "socket.socket": "socket",
    "socket.create_connection": "socket",
    "http.client.HTTPConnection": "conn",
    "http.client.HTTPSConnection": "conn",
    "urllib.request.urlopen": "resp",
}
# allocators whose CALL can carry timeout= (socket.socket cannot — it
# needs a later .settimeout)
_TIMEOUT_KWARG = {"socket.create_connection",
                  "http.client.HTTPConnection",
                  "http.client.HTTPSConnection",
                  "urllib.request.urlopen"}

# attr ops that derive a new tracked object from an existing one,
# inheriting its timedness
_DERIVERS = {"getresponse": "resp", "accept": "socket",
             "makefile": "resp"}

# blocking ops, flagged ONLY on tracked untimed names (NL101)
_BLOCKING_OPS = {"connect", "recv", "recv_into", "recvfrom", "accept",
                 "makefile", "send", "sendall", "request",
                 "getresponse", "read", "readline", "readinto"}

# wire-length allocation sinks (NL202): canonical call names whose
# positional size argument must be bounded first
_ALLOC_SINKS = {"bytearray", "bytes", "numpy.zeros", "numpy.empty",
                "numpy.ones", "numpy.full", "numpy.frombuffer"}
# attr-call sinks: .recv(n) / .read(n) with a wire-derived n
_ATTR_SINKS = {"recv", "read"}


# --------------------------------------------------------------------------
# module model
# --------------------------------------------------------------------------

@dataclass
class FuncRec:
    qualname: str
    node: ast.AST
    cls: Optional[str] = None
    # resolved direct callee keys ("<uid>:<qualname>")
    callees: Set[str] = field(default_factory=set)
    # (kind, timed) when the function's return expression is a tracked
    # network allocation — the factory closure (None = not a factory)
    net_return: Optional[Tuple[str, bool]] = None
    # callee leaf names appearing in return expressions, for the
    # factory fixpoint ("return make_conn()")
    return_callee_keys: Set[str] = field(default_factory=set)


@dataclass
class ModRec:
    path: str
    name: str
    uid: str
    tree: ast.Module
    aliases: Dict[str, str] = field(default_factory=dict)
    waivers: Dict[int, Tuple[Set[str], str]] = field(default_factory=dict)
    funcs: Dict[str, FuncRec] = field(default_factory=dict)
    # class -> {attr: (kind, timed)} from self.<attr> = <allocator>
    net_attrs: Dict[str, Dict[str, Tuple[str, bool]]] = field(
        default_factory=dict)
    # class -> set of base-name dotted strings
    bases: Dict[str, Set[str]] = field(default_factory=dict)


class NCorpus:
    """Cross-module function index (persistlint's PCorpus shape):
    top-level functions by qualname, methods resolvable by unique leaf
    — the closure channel for untimed-factory inference."""

    def __init__(self, mods: List[ModRec]):
        self.mods = mods
        self.funcs: Dict[str, FuncRec] = {}
        self.by_leaf: Dict[str, List[str]] = {}
        for m in mods:
            for q, fr in m.funcs.items():
                key = f"{m.uid}:{q}"
                self.funcs[key] = fr
                self.by_leaf.setdefault(q.rsplit(".", 1)[-1],
                                        []).append(key)

    def unique_leaf(self, leaf: str) -> Optional[str]:
        cands = self.by_leaf.get(leaf, [])
        return cands[0] if len(cands) == 1 else None


def _load(path: str) -> Optional[ModRec]:
    try:
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
        tree = ast.parse(source, filename=path)
    except (OSError, SyntaxError) as e:
        print(f"netlint: cannot parse {path}: {e}", file=sys.stderr)
        return None
    m = ModRec(path=path, name=os.path.basename(path)[:-3], uid=path,
               tree=tree)
    m.aliases = collect_import_aliases(tree)
    m.waivers = parse_waivers(source, "netlint")
    return m


def _alloc_of(call: ast.Call, aliases: Dict[str, str]
              ) -> Optional[Tuple[str, bool]]:
    """(kind, timed-at-call) when ``call`` is a tracked allocator."""
    canon = canonical(aliases, call.func) or ""
    kind = _ALLOCATORS.get(canon)
    if kind is None:
        return None
    timed = False
    if canon in _TIMEOUT_KWARG:
        for kw in call.keywords:
            if kw.arg == "timeout":
                timed = not (isinstance(kw.value, ast.Constant)
                             and kw.value.value is None)
    return kind, timed


class _Collector(ast.NodeVisitor):
    """Pass 1: functions, class bases, per-class self-attr network
    allocations (+ their later settimeouts), factory returns."""

    def __init__(self, mod: ModRec):
        self.mod = mod
        self.cls_stack: List[str] = []
        self.func_stack: List[FuncRec] = []

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.cls_stack.append(node.name)
        self.mod.net_attrs.setdefault(node.name, {})
        bases = set()
        for b in node.bases:
            d = canonical(self.mod.aliases, b)
            if d:
                bases.add(d)
        self.mod.bases[node.name] = bases
        self.generic_visit(node)
        self.cls_stack.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        if self.func_stack:
            qual = f"{self.func_stack[-1].qualname}.{node.name}"
        elif self.cls_stack:
            qual = f"{self.cls_stack[-1]}.{node.name}"
        else:
            qual = node.name
        fr = FuncRec(qualname=qual, node=node,
                     cls=self.cls_stack[-1] if self.cls_stack else None)
        self.mod.funcs[qual] = fr
        self.func_stack.append(fr)
        self.generic_visit(node)
        self.func_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Return(self, node: ast.Return) -> None:
        if self.func_stack and isinstance(node.value, ast.Call):
            fr = self.func_stack[-1]
            alloc = _alloc_of(node.value, self.mod.aliases)
            if alloc is not None:
                fr.net_return = alloc
            elif isinstance(node.value.func, ast.Name):
                fr.return_callee_keys.add(node.value.func.id)
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        # self.<attr> = <allocator>: class-level untimed-socket record
        if self.cls_stack and isinstance(node.value, ast.Call):
            alloc = _alloc_of(node.value, self.mod.aliases)
            if alloc is not None:
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == "self":
                        self.mod.net_attrs[self.cls_stack[-1]][t.attr] \
                            = alloc
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        # self.<attr>.settimeout(x) anywhere in the class marks the
        # attr timed (order-insensitive, conservative)
        if self.cls_stack and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "settimeout" \
                and isinstance(node.func.value, ast.Attribute) \
                and isinstance(node.func.value.value, ast.Name) \
                and node.func.value.value.id == "self":
            attrs = self.mod.net_attrs[self.cls_stack[-1]]
            attr = node.func.value.attr
            if attr in attrs:
                attrs[attr] = (attrs[attr][0], True)
        self.generic_visit(node)


def _factory_fixpoint(corpus: NCorpus) -> None:
    """Propagate net_return through ``return helper()`` chains."""
    changed = True
    while changed:
        changed = False
        for m in corpus.mods:
            for fr in m.funcs.values():
                if fr.net_return is not None:
                    continue
                for leaf in fr.return_callee_keys:
                    key = (f"{m.uid}:{leaf}" if leaf in m.funcs
                           else corpus.unique_leaf(leaf))
                    sub = corpus.funcs.get(key) if key else None
                    if sub is not None and sub.net_return is not None:
                        fr.net_return = sub.net_return
                        changed = True
                        break


# --------------------------------------------------------------------------
# pass 2: per-function checks
# --------------------------------------------------------------------------

def _name_of(node: ast.AST) -> Optional[str]:
    return node.id if isinstance(node, ast.Name) else None


def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _walk_own(root: ast.AST):
    """ast.walk that does NOT descend into nested function/class
    definitions — those are linted as their own FuncRecs, so walking
    them here would double-report every finding."""
    stack = [root]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef, ast.ClassDef)):
                continue
            stack.append(child)


def _loop_own(loop: ast.AST):
    """Walk a loop's body without descending into NESTED loops (or
    defs): an inner retry/accumulation loop is judged on its own, not
    re-attributed to every enclosing loop."""
    stack = list(ast.iter_child_nodes(loop))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.While, ast.For, ast.FunctionDef,
                             ast.AsyncFunctionDef, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


class _FuncCheck:
    """All per-function rule checks."""

    def __init__(self, mod: ModRec, fr: FuncRec, corpus: NCorpus):
        self.mod = mod
        self.fr = fr
        self.corpus = corpus
        self.findings: List[Finding] = []
        # tracked net objects: name -> {kind, timed, line, col,
        #                               with_bound}
        self.net: Dict[str, Dict] = {}
        self._collect_net_objects()

    def _canon(self, func: ast.AST) -> str:
        return canonical(self.mod.aliases, func) or ""

    # -- allocation tracking ------------------------------------------------

    def _factory_alloc(self, call: ast.Call
                       ) -> Optional[Tuple[str, bool]]:
        """A call to a local/unique function whose return is a tracked
        allocation — the helper closure."""
        fn = call.func
        key = None
        if isinstance(fn, ast.Name):
            if fn.id in self.mod.funcs:
                key = f"{self.mod.uid}:{fn.id}"
            else:
                key = self.corpus.unique_leaf(fn.id)
        elif isinstance(fn, ast.Attribute):
            if isinstance(fn.value, ast.Name) and fn.value.id == "self" \
                    and self.fr.cls:
                q = f"{self.fr.cls}.{fn.attr}"
                if q in self.mod.funcs:
                    key = f"{self.mod.uid}:{q}"
            if key is None:
                key = self.corpus.unique_leaf(fn.attr)
        rec = self.corpus.funcs.get(key) if key else None
        return rec.net_return if rec is not None else None

    def _track(self, name: str, kind: str, timed: bool,
               node: ast.AST, with_bound: bool) -> None:
        self.net[name] = {"kind": kind, "timed": timed,
                          "line": node.lineno, "col": node.col_offset,
                          "with": with_bound}

    def _collect_net_objects(self) -> None:
        # source order: derivations (r = conn.getresponse()) must see
        # their source already tracked, so the allocation sweep cannot
        # run in raw stack-walk order
        walk = sorted(_walk_own(self.fr.node),
                      key=lambda n: (getattr(n, "lineno", 0),
                                     getattr(n, "col_offset", 0)))
        for sub in walk:
            if isinstance(sub, ast.Assign) and \
                    isinstance(sub.value, ast.Call):
                alloc = (_alloc_of(sub.value, self.mod.aliases)
                         or self._factory_alloc(sub.value)
                         or self._derived_alloc(sub.value))
                if alloc is None:
                    continue
                kind, timed = alloc
                for t in sub.targets:
                    if isinstance(t, ast.Name):
                        self._track(t.id, kind, timed, sub.value, False)
                    elif isinstance(t, ast.Tuple) and t.elts and \
                            isinstance(t.elts[0], ast.Name):
                        # s2, addr = sock.accept()
                        self._track(t.elts[0].id, kind, timed,
                                    sub.value, False)
            elif isinstance(sub, ast.With):
                for item in sub.items:
                    if not isinstance(item.context_expr, ast.Call):
                        continue
                    alloc = (_alloc_of(item.context_expr,
                                       self.mod.aliases)
                             or self._factory_alloc(item.context_expr)
                             or self._derived_alloc(item.context_expr))
                    if alloc is None:
                        continue
                    kind, timed = alloc
                    if isinstance(item.optional_vars, ast.Name):
                        self._track(item.optional_vars.id, kind, timed,
                                    item.context_expr, True)
        # later .settimeout(x) on a tracked name marks it timed
        for sub in walk:
            if isinstance(sub, ast.Call) and \
                    isinstance(sub.func, ast.Attribute) and \
                    sub.func.attr == "settimeout":
                tgt = _name_of(sub.func.value)
                if tgt in self.net and not (
                        sub.args
                        and isinstance(sub.args[0], ast.Constant)
                        and sub.args[0].value is None):
                    self.net[tgt]["timed"] = True

    def _derived_alloc(self, call: ast.Call
                       ) -> Optional[Tuple[str, bool]]:
        """conn.getresponse() / sock.accept() / sock.makefile() on a
        tracked name: a new tracked object inheriting timedness."""
        fn = call.func
        if not (isinstance(fn, ast.Attribute)
                and fn.attr in _DERIVERS):
            return None
        src = _name_of(fn.value)
        rec = self.net.get(src) if src else None
        if rec is None:
            rec = self._self_attr_rec(fn.value)
        if rec is None:
            return None
        return _DERIVERS[fn.attr], bool(rec["timed"]) \
            if isinstance(rec, dict) else rec[1]

    def _self_attr_rec(self, node: ast.AST):
        """(kind, timed) for ``self.<attr>`` network attrs."""
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == "self" and self.fr.cls:
            return self.mod.net_attrs.get(self.fr.cls, {}).get(node.attr)
        return None

    # -- driver -------------------------------------------------------------

    def run(self) -> List[Finding]:
        self._check_nl101()
        self._check_nl102()
        self._check_nl201()
        self._check_nl202_nl204()
        self._check_nl203()
        self._check_nl301()
        return self.findings

    def _emit(self, node: ast.AST, code: str, msg: str) -> None:
        self.findings.append(Finding(
            self.mod.path, node.lineno, node.col_offset, code, msg,
            self.fr.qualname))

    # -- NL101 --------------------------------------------------------------

    def _check_nl101(self) -> None:
        for sub in _walk_own(self.fr.node):
            if not (isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr in _BLOCKING_OPS):
                continue
            recv = sub.func.value
            name = _name_of(recv)
            rec = self.net.get(name) if name else None
            if rec is not None:
                if not rec["timed"]:
                    self._emit(sub, "NL101",
                               f"blocking .{sub.func.attr}() on "
                               f"{name!r}, allocated with no timeout "
                               f"(line {rec['line']}) — a half-open "
                               "peer wedges this thread forever")
                continue
            attr_rec = self._self_attr_rec(recv)
            if attr_rec is not None and not attr_rec[1]:
                self._emit(sub, "NL101",
                           f"blocking .{sub.func.attr}() on untimed "
                           f"self.{recv.attr} (allocated in this class "
                           "with no timeout/settimeout)")

    # -- NL102 --------------------------------------------------------------

    def _check_nl102(self) -> None:
        # names closed in finally / except handlers
        safe_closed: Set[str] = set()
        for sub in _walk_own(self.fr.node):
            if not isinstance(sub, ast.Try):
                continue
            cleanup = list(sub.finalbody)
            for h in sub.handlers:
                cleanup.extend(h.body)
            for stmt in cleanup:
                for c in ast.walk(stmt):
                    if isinstance(c, ast.Call) and \
                            isinstance(c.func, ast.Attribute) and \
                            c.func.attr == "close":
                        nm = _name_of(c.func.value)
                        if nm:
                            safe_closed.add(nm)
        handed_off: Set[str] = set()
        used: Set[str] = set()
        for sub in _walk_own(self.fr.node):
            if isinstance(sub, ast.Return) and sub.value is not None:
                handed_off |= _names_in(sub.value)
            elif isinstance(sub, ast.Assign):
                # self.x = s / container[i] = s: ownership transfer
                if any(isinstance(t, (ast.Attribute, ast.Subscript))
                       for t in sub.targets):
                    handed_off |= _names_in(sub.value)
            elif isinstance(sub, ast.Call):
                for a in list(sub.args) + [kw.value
                                           for kw in sub.keywords]:
                    handed_off |= _names_in(a)
                if isinstance(sub.func, ast.Attribute):
                    nm = _name_of(sub.func.value)
                    if nm:
                        used.add(nm)
        for name, rec in self.net.items():
            if rec["with"] or name in safe_closed \
                    or name in handed_off or name not in used:
                continue
            self._emit_at(rec, "NL102",
                          f"{name!r} ({rec['kind']}) is used but never "
                          "closed on exception paths — bind it in a "
                          "'with', or close it in a finally/handler, "
                          "or hand ownership off")

    def _emit_at(self, rec: Dict, code: str, msg: str) -> None:
        self.findings.append(Finding(
            self.mod.path, rec["line"], rec["col"], code, msg,
            self.fr.qualname))

    # -- NL201 --------------------------------------------------------------

    def _len_checked_names(self) -> Dict[str, int]:
        """{buffer name: first line where a Compare involves its
        length} — ``len(buf)`` inside any comparison, directly or via
        a ``v = len(buf)`` alias, or a bare ``if not buf`` guard."""
        len_alias: Dict[str, str] = {}  # alias var -> buffer name
        for sub in _walk_own(self.fr.node):
            if isinstance(sub, ast.Assign) and \
                    isinstance(sub.value, ast.Call) and \
                    isinstance(sub.value.func, ast.Name) and \
                    sub.value.func.id == "len" and sub.value.args:
                buf = _name_of(sub.value.args[0])
                tgt = (_name_of(sub.targets[0])
                       if len(sub.targets) == 1 else None)
                if buf and tgt:
                    len_alias[tgt] = buf
        checked: Dict[str, int] = {}

        def note(name: Optional[str], line: int) -> None:
            if name and (name not in checked or line < checked[name]):
                checked[name] = line

        for sub in _walk_own(self.fr.node):
            if isinstance(sub, ast.Compare):
                for inner in ast.walk(sub):
                    if isinstance(inner, ast.Call) and \
                            isinstance(inner.func, ast.Name) and \
                            inner.func.id == "len" and inner.args:
                        note(_name_of(inner.args[0]), sub.lineno)
                    elif isinstance(inner, ast.Name) and \
                            inner.id in len_alias:
                        note(len_alias[inner.id], sub.lineno)
            elif isinstance(sub, ast.UnaryOp) and \
                    isinstance(sub.op, ast.Not):
                note(_name_of(sub.operand), sub.lineno)
        return checked

    def _unpack_calls(self) -> List[Tuple[ast.Call, Optional[str]]]:
        """(call, buffer name) for struct.unpack/unpack_from and
        Struct-instance .unpack/.unpack_from calls."""
        out = []
        for sub in _walk_own(self.fr.node):
            if not (isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr in ("unpack", "unpack_from")):
                continue
            canon = self._canon(sub.func)
            idx = 1 if canon.startswith("struct.") else 0
            buf = sub.args[idx] if len(sub.args) > idx else None
            if isinstance(buf, ast.Subscript):
                buf = buf.value
            out.append((sub, _name_of(buf) if buf is not None
                        else None))
        return out

    def _check_nl201(self) -> None:
        unpacks = self._unpack_calls()
        if not unpacks:
            return
        checked = self._len_checked_names()
        for call, buf in unpacks:
            if buf is None:
                continue
            if buf in checked and checked[buf] <= call.lineno:
                continue
            self._emit(call, "NL201",
                       f"struct unpack of {buf!r} with no preceding "
                       "length check — a truncated frame dies as an "
                       "untyped struct.error instead of the decoder's "
                       "typed ValueError")

    # -- NL202 / NL204 ------------------------------------------------------

    def _derivation(self, seeds: Set[str]) -> Set[str]:
        """Closure of ``seeds`` through assignments (both directions
        collapse into one component: a check on ``nbytes = k * 20``
        clears ``k`` and vice versa)."""
        derived = set(seeds)
        changed = True
        while changed:
            changed = False
            for sub in _walk_own(self.fr.node):
                if not isinstance(sub, ast.Assign):
                    continue
                tgts = {t.id for t in sub.targets
                        if isinstance(t, ast.Name)}
                srcs = _names_in(sub.value)
                if (srcs & derived and not tgts <= derived) or \
                        (tgts & derived and not srcs <= derived):
                    if srcs & derived:
                        new = tgts - derived
                    else:
                        new = srcs - derived
                    if new:
                        derived |= new
                        changed = True
        return derived

    def _compare_lines(self, names: Set[str]) -> List[int]:
        out = []
        for sub in _walk_own(self.fr.node):
            if isinstance(sub, ast.Compare) and \
                    _names_in(sub) & names:
                out.append(sub.lineno)
        return out

    def _wire_names(self) -> Set[str]:
        """Targets of assignments whose value contains an unpack call
        or int.from_bytes — lengths/counts parsed off the wire."""
        out: Set[str] = set()
        for sub in _walk_own(self.fr.node):
            if not isinstance(sub, ast.Assign):
                continue
            has_unpack = any(
                isinstance(c, ast.Call)
                and isinstance(c.func, ast.Attribute)
                and c.func.attr in ("unpack", "unpack_from",
                                    "from_bytes")
                for c in ast.walk(sub.value))
            if not has_unpack:
                continue
            for t in sub.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
                elif isinstance(t, (ast.Tuple, ast.List)):
                    out |= {e.id for e in t.elts
                            if isinstance(e, ast.Name)}
        return out

    def _check_nl202_nl204(self) -> None:
        wire = self._wire_names()
        if wire:
            component = self._derivation(wire)
            cmp_lines = self._compare_lines(component)
            for call, size_names, what in self._size_sinks():
                hot = size_names & component
                if not hot:
                    continue
                if any(ln <= call.lineno for ln in cmp_lines):
                    continue
                self._emit(call, "NL202",
                           f"wire-derived length {sorted(hot)} sizes "
                           f"{what} with no bound checked first — a "
                           "peer writing 2^31 into a length field "
                           "makes this process allocate it")
        self._check_nl204()

    def _size_sinks(self
                    ) -> List[Tuple[ast.Call, Set[str], str]]:
        """(call, names inside its size expression, description) for
        every allocation-ish sink in the function."""
        out = []
        for sub in _walk_own(self.fr.node):
            if isinstance(sub, ast.Call):
                canon = self._canon(sub.func)
                if canon in _ALLOC_SINKS:
                    names: Set[str] = set()
                    if sub.args:
                        names |= _names_in(sub.args[0])
                    for kw in sub.keywords:
                        if kw.arg in ("count", "shape"):
                            names |= _names_in(kw.value)
                    out.append((sub, names, f"{canon}()"))
                elif isinstance(sub.func, ast.Attribute) and \
                        sub.func.attr in _ATTR_SINKS and sub.args:
                    out.append((sub, _names_in(sub.args[0]),
                                f".{sub.func.attr}()"))
            elif isinstance(sub, ast.BinOp) and \
                    isinstance(sub.op, ast.Mult):
                # b"\0" * n — bytes/str repetition sized off the wire
                for side, other in ((sub.left, sub.right),
                                    (sub.right, sub.left)):
                    if isinstance(side, ast.Constant) and \
                            isinstance(side.value, (bytes, str)):
                        fake = ast.Call(func=ast.Name(id="_mul",
                                                      ctx=ast.Load()),
                                        args=[], keywords=[])
                        fake.lineno = sub.lineno
                        fake.col_offset = sub.col_offset
                        out.append((fake, _names_in(other),
                                    "a bytes repetition"))
        return out

    # -- NL204 --------------------------------------------------------------

    def _check_nl204(self) -> None:
        # names derived from self.headers (Content-Length parses)
        header_seeds: Set[str] = set()
        for sub in _walk_own(self.fr.node):
            if not isinstance(sub, ast.Assign):
                continue
            from_headers = any(
                isinstance(a, ast.Attribute) and a.attr == "headers"
                for a in ast.walk(sub.value))
            if from_headers:
                header_seeds |= {t.id for t in sub.targets
                                 if isinstance(t, ast.Name)}
        component = (self._derivation(header_seeds)
                     if header_seeds else set())
        cmp_lines = self._compare_lines(component) if component else []
        for sub in _walk_own(self.fr.node):
            if not (isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr == "read"
                    and isinstance(sub.func.value, ast.Attribute)
                    and sub.func.value.attr == "rfile"):
                continue
            if not sub.args:
                self._emit(sub, "NL204",
                           "argless rfile.read() in an HTTP handler — "
                           "route the body through "
                           "netio.read_request_body (411/413/400)")
                continue
            names = _names_in(sub.args[0])
            hot = names & component
            if hot and not any(ln <= sub.lineno for ln in cmp_lines):
                self._emit(sub, "NL204",
                           f"rfile.read sized by Content-Length-"
                           f"derived {sorted(hot)} with no bound "
                           "checked first — a multi-GB claimed length "
                           "is read whole (use "
                           "netio.read_request_body)")

    # -- NL203 --------------------------------------------------------------

    def _check_nl203(self) -> None:
        # (a) argless .read() on a tracked network response
        for sub in _walk_own(self.fr.node):
            if not (isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr == "read"
                    and not sub.args and not sub.keywords):
                continue
            name = _name_of(sub.func.value)
            rec = self.net.get(name) if name else None
            is_resp = (rec is not None and rec["kind"] == "resp")
            if not is_resp:
                attr_rec = self._self_attr_rec(sub.func.value)
                is_resp = attr_rec is not None and attr_rec[0] == "resp"
            if is_resp:
                self._emit(sub, "NL203",
                           "argless .read() buffers the whole response "
                           "— a peer can stream unbounded bytes into "
                           "this process (use netio.read_limited)")
        # (b) uncapped byte-accumulation loops
        for loop in _walk_own(self.fr.node):
            if not isinstance(loop, (ast.While, ast.For)):
                continue
            # names assigned inside the loop from a recv/read call
            chunk_names: Set[str] = set()
            for sub in _loop_own(loop):
                if isinstance(sub, ast.Assign) and \
                        self._is_recv_read(sub.value):
                    chunk_names |= {t.id for t in sub.targets
                                    if isinstance(t, ast.Name)}
            accums = []
            for sub in _loop_own(loop):
                if not (isinstance(sub, ast.AugAssign)
                        and isinstance(sub.op, ast.Add)
                        and isinstance(sub.target, ast.Name)):
                    continue
                feeds = (self._is_recv_read(sub.value)
                         or _names_in(sub.value) & chunk_names)
                if feeds:
                    accums.append(sub)
            if not accums:
                continue
            acc_names = {a.target.id for a in accums}
            capped = any(
                isinstance(sub, ast.Compare)
                and _names_in(sub) & acc_names
                for sub in _loop_own(loop))
            if not capped:
                for a in accums:
                    self._emit(a, "NL203",
                               f"{a.target.id!r} accumulates "
                               "recv/read bytes with no max-size "
                               "comparison in the loop (use "
                               "netio.read_limited)")

    def _is_recv_read(self, node: ast.AST) -> bool:
        return (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("recv", "read", "recv_into",
                                       "recvfrom"))

    # -- NL301 --------------------------------------------------------------

    def _check_nl301(self) -> None:
        for loop in _walk_own(self.fr.node):
            if not isinstance(loop, (ast.While, ast.For)):
                continue
            # a NETWORK retry loop: an exception handler inside it
            # that explicitly `continue`s (service loops that merely
            # log and fall through are not retries), guarding a try
            # body that actually touches the network (parse-retry
            # loops over files/strings are not this rule's business)
            retries = False
            for sub in _loop_own(loop):
                if isinstance(sub, ast.Try):
                    handler_continues = any(
                        isinstance(s, ast.Continue)
                        for h in sub.handlers
                        for stmt in h.body
                        for s in ast.walk(stmt))
                    if handler_continues and self._try_is_net(sub):
                        retries = True
            if not retries:
                continue
            backoff = any(
                isinstance(sub, ast.Call)
                and (self._canon(sub.func) == "time.sleep"
                     or (isinstance(sub.func, ast.Attribute)
                         and sub.func.attr in ("sleep", "wait")))
                for sub in _loop_own(loop))
            capped = self._loop_capped(loop)
            if backoff and capped:
                continue
            missing = []
            if not backoff:
                missing.append("backoff")
            if not capped:
                missing.append("an attempt cap")
            self._emit(loop, "NL301",
                       f"retry loop without {' or '.join(missing)} — "
                       "retries need BOTH (exponential sleep + finite "
                       "attempts) or one struggling peer becomes a "
                       "self-inflicted flood")

    _NET_OPS = {"request", "getresponse", "recv", "recv_into",
                "recvfrom", "connect", "sendall", "urlopen"}

    def _try_is_net(self, tr: ast.Try) -> bool:
        for stmt in tr.body:
            for sub in ast.walk(stmt):
                if not isinstance(sub, ast.Call):
                    continue
                canon = self._canon(sub.func)
                if canon in ("urllib.request.urlopen",
                             "socket.create_connection"):
                    return True
                if isinstance(sub.func, ast.Attribute) and \
                        sub.func.attr in self._NET_OPS:
                    return True
        return False

    def _loop_capped(self, loop: ast.AST) -> bool:
        if isinstance(loop, ast.For):
            it = loop.iter
            if isinstance(it, (ast.Tuple, ast.List)):
                return True  # finite literal
            if isinstance(it, ast.Call) and \
                    isinstance(it.func, ast.Name) and \
                    it.func.id in ("range", "enumerate", "reversed"):
                return True
            return False
        test = loop.test
        if isinstance(test, ast.Constant) and test.value:
            return False  # while True
        # any non-trivially-true test reads as a bounded condition
        return not isinstance(test, ast.Constant)


# --------------------------------------------------------------------------
# driver
# --------------------------------------------------------------------------

def analyze_paths(paths: Sequence[str]) -> List[Finding]:
    files = iter_py_files(paths)
    mods = [m for m in (_load(f) for f in files) if m is not None]
    for m in mods:
        _Collector(m).visit(m.tree)
    corpus = NCorpus(mods)
    _factory_fixpoint(corpus)
    findings: List[Finding] = []
    for m in mods:
        mod_findings: List[Finding] = []
        for q, fr in m.funcs.items():
            mod_findings.extend(_FuncCheck(m, fr, corpus).run())
        findings.extend(apply_waivers(m.path, m.waivers, mod_findings,
                                      RULES, prefix="NL",
                                      tool="netlint"))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.code))
    return findings


def lint_paths(paths: Sequence[str]) -> List[Finding]:
    return analyze_paths(paths)


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="netlint",
        description="network-surface static analysis "
                    "(rules: docs/ANALYSIS.md)")
    p.add_argument("paths", nargs="*", default=["mx_rcnn_tpu"],
                   help="files or directories to lint")
    p.add_argument("--json", action="store_true",
                   help="emit findings as JSON records")
    p.add_argument("--show-waived", action="store_true",
                   help="also print waived findings")
    p.add_argument("--list-rules", action="store_true")
    args = p.parse_args(argv)
    if args.list_rules:
        for code, desc in sorted(RULES.items()):
            print(f"{code}  {desc}")
        return 0
    rc = check_paths_exist("netlint", args.paths)
    if rc is not None:
        return rc
    findings = lint_paths(args.paths)
    active = [f for f in findings if f.waived is None]
    waived = [f for f in findings if f.waived is not None]
    shown = findings if args.show_waived else active
    if args.json:
        for f in shown:
            print(json.dumps({"path": f.path, "line": f.line,
                              "col": f.col + 1, "code": f.code,
                              "message": f.message, "func": f.func,
                              "waived": f.waived}))
    else:
        for f in shown:
            print(f.render())
    print(f"netlint: {len(active)} finding(s), {len(waived)} waived",
          file=sys.stderr)
    return 1 if active else 0


if __name__ == "__main__":
    raise SystemExit(main())
