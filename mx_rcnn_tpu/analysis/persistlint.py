"""persistlint — AST linter for the durable-write surface (the
tmp → fsync → rename → dir-fsync → manifest-last idiom).

Why a fourth linter: every headline durability invariant in this tree —
bit-identical checkpoint restores (docs/FT.md), admission-checked export
stores (docs/SERVING.md "Fleet tier"), byte-identical bulk-sink resume
after SIGKILL (docs/SERVING.md "Bulk tier") — rests on ONE
hand-maintained protocol: write to a uniquely-staged tmp, fsync the
file, rename over the target, fsync the directory, and write the
commit-point manifest LAST.  ALICE (Pillai et al., OSDI '14) showed
that exactly these application-level persistence protocols are where
real systems silently lose crash safety; before this pass the tree had
already drifted (``serve/export.py`` hand-rolled its own
tmp→fsync→replace and skipped the dir-fsync; ``data/cache.py``
committed via bare ``os.replace``).  persistlint machine-checks the
protocol; the runtime twin is ``analysis/crashsim.py``, which records a
real workload's write ops and enumerates every crash state against the
real recovery paths (CrashMonkey-style; ``make crashsim-smoke``).

The durable-path model (what counts as a durable artifact):

* a write is DURABLE when its path expression carries a durable
  artifact name — resolved through constants, f-strings, ``+``/``%``
  concatenation, ``os.path.join``, module-level string constants,
  ``self.<attr>`` assignments anywhere in the class, local assignments,
  and the RETURN expressions of called naming helpers
  (``checkpoint_path`` → ``.ckpt``, ``manifest_path`` →
  ``.manifest.json``, ``BulkSink.shard_path`` → ``shard-`` …) — the
  call-graph closure into the checkpoint/export/bulk/manifest writers;
* the durable fragments are ``manifest`` / ``.ckpt`` / ``.jaxexp`` /
  ``shard-`` / ``summary.json`` / ``events.jsonl`` (case-insensitive);
  everything else (bench reports, eval dumps, rebuildable pickles) is
  EPHEMERAL by inference — and anything inside the durable surface
  that is genuinely ephemeral takes a reasoned waiver;
* a ``manifest`` fragment additionally marks a write as a COMMIT-POINT
  write for the ordering rule.

Rule catalogue (bad/good examples: docs/ANALYSIS.md "persistlint"):

* PL101 — a raw write-mode ``open`` reaches a durable artifact path
  without going through ``utils/checkpoint.py — _atomic_write``.  The
  staging write of the atomic idiom itself (an ``open`` whose path is
  later the SOURCE of an ``os.replace``/``os.rename`` in the same
  function) is exempt — PL102/PL103/PL105 govern it instead.
* PL102 — ``os.rename``/``os.replace`` whose source was not fsynced
  first: the rename can persist while the data does not, publishing a
  torn file under the durable name.
* PL103 — rename without a following directory fsync: a host crash
  can lose the rename itself, so a commit the caller was told is
  durable silently vanishes (the exact bug ``serve/export.py`` had).
* PL104 — manifest-ordering violation: a commit-point write placed
  before a payload write in the same function (intra-function
  statement order, with call-closure classification of helpers) —
  a crash between the two leaves a manifest naming files that do not
  exist yet.
* PL105 — a ``.tmp`` staging file with no exception-path cleanup (no
  enclosing ``try`` whose handler/finally unlinks it): failed writes
  leak staging files, and a non-uniquely-named orphan can be adopted
  by a later writer.
* PL201 — ``json.dump(s)`` of a sha-pinned or commit-point artifact
  without canonical ``sort_keys=True``: byte-identity invariants
  (manifest admission, fingerprints) must not depend on dict insertion
  order.

Waivers: same protocol as the other linters (``analysis/common.py``) —
``# persistlint: disable=PL101 <reason>`` on the line or the line
above; a reasonless waiver is PL001, an unknown rule PL002.

CLI::

    python -m mx_rcnn_tpu.analysis.persistlint [paths...] [--json]
        [--show-waived] [--list-rules]

Exit status 0 iff no unwaived findings.
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import sys
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from mx_rcnn_tpu.analysis.common import (Finding, apply_waivers, canonical,
                                         check_paths_exist,
                                         collect_import_aliases,
                                         iter_py_files, parse_waivers)

RULES: Dict[str, str] = {
    "PL001": "waiver without a reason (every waiver must say why)",
    "PL002": "waiver names an unknown rule code",
    "PL101": "raw write-mode open of a durable artifact path (use "
             "utils/checkpoint._atomic_write)",
    "PL102": "os.rename/os.replace whose source was not fsynced",
    "PL103": "rename without a following directory fsync",
    "PL104": "commit-point (manifest) write before the payload it names",
    "PL105": "tmp staging file not cleaned up on exception paths",
    "PL201": "json.dump of a sha-pinned artifact without sort_keys=True",
}

# the durable-path model: fragments that mark a path as a durable
# artifact (docs/ANALYSIS.md "persistlint" documents the triage line
# between these and the ephemeral bench/eval/report surface)
DURABLE_FRAGMENTS = ("manifest", ".ckpt", ".jaxexp", "shard-",
                     "summary.json", "events.jsonl")
# fragments that additionally mark a COMMIT-POINT write (PL104)
COMMIT_FRAGMENTS = ("manifest",)

# the blessed atomic-write channel: calls to these (or to functions
# that transitively call them) are not raw writes
_ATOMIC_LEAVES = {"_atomic_write"}

_WRITE_MODES = ("w", "a", "x")


# --------------------------------------------------------------------------
# module model
# --------------------------------------------------------------------------

@dataclass
class FuncRec:
    qualname: str
    node: ast.AST
    cls: Optional[str] = None
    # constant fragments appearing in this function's return expressions
    return_frags: Set[str] = field(default_factory=set)
    # resolved direct callee keys ("<uid>:<qualname>")
    callees: Set[str] = field(default_factory=set)


@dataclass
class ModRec:
    path: str
    name: str
    uid: str
    tree: ast.Module
    aliases: Dict[str, str] = field(default_factory=dict)
    waivers: Dict[int, Tuple[Set[str], str]] = field(default_factory=dict)
    funcs: Dict[str, FuncRec] = field(default_factory=dict)
    # module-level NAME = "str" constants -> fragments
    const_frags: Dict[str, Set[str]] = field(default_factory=dict)
    # class -> {attr: fragments} from self.<attr> = <expr> assignments
    attr_frags: Dict[str, Dict[str, Set[str]]] = field(default_factory=dict)


class PCorpus:
    """Cross-module index for fragment resolution through naming
    helpers: top-level functions by name (when unambiguous) and methods
    by leaf name (when exactly one class defines them)."""

    def __init__(self, mods: List[ModRec]):
        self.mods = mods
        self.funcs: Dict[str, FuncRec] = {}
        self.by_leaf: Dict[str, List[str]] = {}
        for m in mods:
            for q, fr in m.funcs.items():
                key = f"{m.uid}:{q}"
                self.funcs[key] = fr
                self.by_leaf.setdefault(q.rsplit(".", 1)[-1],
                                        []).append(key)

    def unique_leaf(self, leaf: str) -> Optional[str]:
        cands = self.by_leaf.get(leaf, [])
        return cands[0] if len(cands) == 1 else None


def _load(path: str) -> Optional[ModRec]:
    try:
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
        tree = ast.parse(source, filename=path)
    except (OSError, SyntaxError) as e:
        print(f"persistlint: cannot parse {path}: {e}", file=sys.stderr)
        return None
    m = ModRec(path=path, name=os.path.basename(path)[:-3], uid=path,
               tree=tree)
    m.aliases = collect_import_aliases(tree)
    m.waivers = parse_waivers(source, "persistlint")
    return m


def _const_frags_of(node: ast.AST) -> Set[str]:
    """Literal string fragments syntactically inside an expression
    (constants, f-string parts) — no name resolution."""
    out: Set[str] = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            out.add(sub.value.lower())
    return out


class _Collector(ast.NodeVisitor):
    """Pass 1: functions (with return fragments + callees), module
    string constants, per-class self-attr fragments."""

    def __init__(self, mod: ModRec):
        self.mod = mod
        self.cls_stack: List[str] = []
        self.func_stack: List[FuncRec] = []

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.cls_stack.append(node.name)
        self.mod.attr_frags.setdefault(node.name, {})
        self.generic_visit(node)
        self.cls_stack.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        if self.func_stack:
            qual = f"{self.func_stack[-1].qualname}.{node.name}"
        elif self.cls_stack:
            qual = f"{self.cls_stack[-1]}.{node.name}"
        else:
            qual = node.name
        fr = FuncRec(qualname=qual, node=node,
                     cls=self.cls_stack[-1] if self.cls_stack else None)
        self.mod.funcs[qual] = fr
        self.func_stack.append(fr)
        self.generic_visit(node)
        self.func_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Return(self, node: ast.Return) -> None:
        if self.func_stack and node.value is not None:
            self.func_stack[-1].return_frags |= _const_frags_of(node.value)
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            if isinstance(t, ast.Name) and not self.func_stack \
                    and not self.cls_stack:
                frags = _const_frags_of(node.value)
                if frags:
                    self.mod.const_frags[t.id] = frags
            elif isinstance(t, ast.Attribute) and \
                    isinstance(t.value, ast.Name) and \
                    t.value.id == "self" and self.cls_stack:
                frags = _const_frags_of(node.value)
                if frags:
                    self.mod.attr_frags[self.cls_stack[-1]].setdefault(
                        t.attr, set()).update(frags)
        self.generic_visit(node)


# --------------------------------------------------------------------------
# fragment resolution (the durable-path inference)
# --------------------------------------------------------------------------

class _Resolver:
    """Resolves a path expression to its constant string fragments,
    following local assignments, self-attrs, module constants and the
    return expressions of called naming helpers (depth-bounded)."""

    def __init__(self, mod: ModRec, fr: FuncRec, corpus: PCorpus):
        self.mod = mod
        self.fr = fr
        self.corpus = corpus
        # local name -> fragments, from every assignment in the function
        # (order-insensitive: staging paths are assigned before use)
        self.local_frags: Dict[str, Set[str]] = {}
        for sub in ast.walk(fr.node):
            if isinstance(sub, ast.Assign):
                frags = self.frags(sub.value, depth=1)
                for t in sub.targets:
                    if isinstance(t, ast.Name) and frags:
                        self.local_frags.setdefault(t.id,
                                                    set()).update(frags)

    def frags(self, node: ast.AST, depth: int = 0) -> Set[str]:
        if depth > 4 or node is None:
            return set()
        out: Set[str] = set()
        if isinstance(node, ast.Constant):
            if isinstance(node.value, str):
                out.add(node.value.lower())
            return out
        if isinstance(node, ast.JoinedStr):
            for v in node.values:
                out |= self.frags(v, depth + 1)
            return out
        if isinstance(node, ast.FormattedValue):
            return self.frags(node.value, depth + 1)
        if isinstance(node, ast.BinOp):
            return (self.frags(node.left, depth + 1)
                    | self.frags(node.right, depth + 1))
        if isinstance(node, ast.Name):
            if node.id in self.local_frags:
                out |= self.local_frags[node.id]
            if node.id in self.mod.const_frags:
                out |= self.mod.const_frags[node.id]
            alias = self.mod.aliases.get(node.id)
            if alias:  # ``from x import MANIFEST_NAME`` style constants
                for m in self.corpus.mods:
                    leaf = alias.rsplit(".", 1)[-1]
                    if leaf in m.const_frags:
                        out |= m.const_frags[leaf]
            return out
        if isinstance(node, ast.Attribute):
            if isinstance(node.value, ast.Name) and \
                    node.value.id == "self" and self.fr.cls:
                out |= self.mod.attr_frags.get(self.fr.cls, {}).get(
                    node.attr, set())
            return out
        if isinstance(node, ast.Call):
            # the call-graph closure into naming helpers: the callee's
            # return-expression fragments count, plus the args' own
            key = self._callee(node.func)
            if key is not None:
                out |= self.corpus.funcs[key].return_frags
            for a in node.args:
                out |= self.frags(a, depth + 1)
            return out
        if isinstance(node, (ast.Tuple, ast.List)):
            for e in node.elts:
                out |= self.frags(e, depth + 1)
        return out

    def _callee(self, func: ast.AST) -> Optional[str]:
        if isinstance(func, ast.Name):
            if func.id in self.mod.funcs:
                return f"{self.mod.uid}:{func.id}"
            alias = self.mod.aliases.get(func.id)
            leaf = (alias or func.id).rsplit(".", 1)[-1]
            return self.corpus.unique_leaf(leaf)
        if isinstance(func, ast.Attribute):
            # self.helper() within the same class, else unique leaf
            if isinstance(func.value, ast.Name) and \
                    func.value.id == "self" and self.fr.cls:
                q = f"{self.fr.cls}.{func.attr}"
                if q in self.mod.funcs:
                    return f"{self.mod.uid}:{q}"
            return self.corpus.unique_leaf(func.attr)
        return None


def _durable(frags: Set[str]) -> bool:
    return any(d in f for f in frags for d in DURABLE_FRAGMENTS)


def _commit(frags: Set[str]) -> bool:
    return any(c in f for f in frags for c in COMMIT_FRAGMENTS)


# --------------------------------------------------------------------------
# atomic-writer closure
# --------------------------------------------------------------------------

def _atomic_writer_keys(corpus: PCorpus) -> Set[str]:
    """Functions that ARE the atomic channel: ``_atomic_write`` and
    everything that transitively calls it."""
    keys = {k for k, fr in corpus.funcs.items()
            if fr.qualname.rsplit(".", 1)[-1] in _ATOMIC_LEAVES}
    changed = True
    while changed:
        changed = False
        for k, fr in corpus.funcs.items():
            if k in keys:
                continue
            if fr.callees & keys:
                keys.add(k)
                changed = True
    return keys


# --------------------------------------------------------------------------
# pass 2: per-function checks
# --------------------------------------------------------------------------

def _open_mode(call: ast.Call) -> Optional[str]:
    """The mode of an ``open`` call (positional or kwarg), None when not
    a constant (conservative: unknown modes are not flagged)."""
    mode = None
    if len(call.args) >= 2:
        mode = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if mode is None:
        return "r"
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
        return mode.value
    return None


def _is_write_mode(mode: Optional[str]) -> bool:
    return mode is not None and any(c in mode for c in _WRITE_MODES)


class _FuncCheck:
    """All per-function rule checks (one linear walk in statement
    order, mirroring the on-disk op order the function would emit)."""

    def __init__(self, mod: ModRec, fr: FuncRec, corpus: PCorpus,
                 atomic_keys: Set[str],
                 resolver: Optional[_Resolver] = None):
        self.mod = mod
        self.fr = fr
        self.corpus = corpus
        self.atomic_keys = atomic_keys
        self.res = resolver or _Resolver(mod, fr, corpus)
        self.findings: List[Finding] = []
        # collected call sites in lexical order
        self.opens: List[Tuple[ast.Call, Set[str], Optional[str]]] = []
        self.renames: List[Tuple[ast.Call, Optional[str], Set[str]]] = []
        # (node, is_dir, bound src name or None): a file fsync through a
        # with-alias fileno() binds to THAT staged file — one fsync must
        # never vouch for a second staged file's rename
        self.fsyncs: List[Tuple[ast.Call, bool, Optional[str]]] = []
        self.durable_writes: List[Tuple[int, bool]] = []  # (line, commit)
        # names assigned from os.open(...) — their fsync is a dir fsync
        self.osopen_names: Set[str] = set()
        # Name -> open-call line for write-mode opens (rename-src pairing)
        self.open_src_names: Dict[str, ast.Call] = {}
        self.tmp_opens: List[Tuple[ast.Call, Optional[str]]] = []

    # -- helpers ------------------------------------------------------------

    def _canon(self, func: ast.AST) -> str:
        return canonical(self.mod.aliases, func) or ""

    def _src_name(self, node: ast.AST) -> Optional[str]:
        return node.id if isinstance(node, ast.Name) else None

    def run(self) -> List[Finding]:
        node = self.fr.node
        # pre-pass: names assigned from os.open / write-mode open
        for sub in ast.walk(node):
            if isinstance(sub, (ast.Assign, ast.withitem)):
                val = sub.value if isinstance(sub, ast.Assign) \
                    else sub.context_expr
                if isinstance(val, ast.Call) and \
                        self._canon(val.func) == "os.open":
                    tgts = sub.targets if isinstance(sub, ast.Assign) \
                        else [sub.optional_vars]
                    for t in tgts:
                        if isinstance(t, ast.Name):
                            self.osopen_names.add(t.id)
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                self._classify_call(sub)
        self._check_pl101()
        self._check_pl102_pl103()
        self._check_pl104()
        self._check_pl105()
        return self.findings

    def _classify_call(self, call: ast.Call) -> None:
        canon = self._canon(call.func)
        if canon == "open" and call.args:
            mode = _open_mode(call)
            if _is_write_mode(mode):
                frags = self.res.frags(call.args[0])
                self.opens.append((call, frags, mode))
                src = self._src_name(call.args[0])
                if src:
                    self.open_src_names[src] = call
                if any(".tmp" in f for f in frags):
                    self.tmp_opens.append((call, src))
        elif canon in ("os.rename", "os.replace") and len(call.args) >= 2:
            frags = self.res.frags(call.args[1])
            self.renames.append((call, self._src_name(call.args[0]),
                                 frags))
            if _durable(frags):
                self.durable_writes.append((call.lineno, _commit(frags)))
        elif canon == "os.fsync" and call.args:
            arg = call.args[0]
            is_dir = isinstance(arg, ast.Name) and \
                arg.id in self.osopen_names
            self.fsyncs.append((call, is_dir, self._fsync_src(arg)))
        elif canon in ("json.dumps", "json.dump"):
            self._check_pl201(call, canon)
        else:
            key = self.res._callee(call.func)
            if key is not None and key in self.atomic_keys:
                # an atomic durable write: classify for PL104 ordering.
                # path-unresolvable atomic writes default to PAYLOAD (a
                # commit write is identified by its manifest fragment)
                frags = self.res.frags(call.args[0]) if call.args \
                    else set()
                commit = _commit(frags) or bool(
                    self.corpus.funcs[key].return_frags
                    and _commit(self.corpus.funcs[key].return_frags))
                # known commit-writer helpers: their NAME says manifest
                leaf = self.corpus.funcs[key].qualname.rsplit(
                    ".", 1)[-1].lower()
                commit = commit or "manifest" in leaf
                self.durable_writes.append((call.lineno, commit))

    # -- PL101 --------------------------------------------------------------

    def _check_pl101(self) -> None:
        if self._in_atomic_channel():
            return
        rename_srcs = {s for _, s, _ in self.renames if s}
        for call, frags, mode in self.opens:
            if not _durable(frags):
                continue
            src = self._src_name(call.args[0])
            if src and src in rename_srcs:
                continue  # the staging write of an atomic idiom
            self.findings.append(Finding(
                self.mod.path, call.lineno, call.col_offset, "PL101",
                f"raw open(..., {mode!r}) writes a durable artifact "
                "path — route it through utils/checkpoint._atomic_write "
                "(tmp -> fsync -> rename -> dir-fsync) or waive with the "
                "ephemeral/contract reason", self.fr.qualname))
            self.durable_writes.append((call.lineno, _commit(frags)))

    def _in_atomic_channel(self) -> bool:
        key = f"{self.mod.uid}:{self.fr.qualname}"
        fr = self.corpus.funcs.get(key)
        return fr is not None and fr.qualname.rsplit(
            ".", 1)[-1] in _ATOMIC_LEAVES

    # -- PL102 / PL103 ------------------------------------------------------

    def _fsync_src(self, arg: ast.AST) -> Optional[str]:
        """The staged-file NAME an ``os.fsync(f.fileno())`` vouches for,
        via the with-alias of the open that produced ``f`` (None when
        the fd expression is anything else)."""
        if isinstance(arg, ast.Call) and \
                isinstance(arg.func, ast.Attribute) and \
                arg.func.attr == "fileno" and \
                isinstance(arg.func.value, ast.Name):
            alias = arg.func.value.id
            for sub in ast.walk(self.fr.node):
                if not isinstance(sub, ast.With):
                    continue
                for item in sub.items:
                    if isinstance(item.optional_vars, ast.Name) and \
                            item.optional_vars.id == alias and \
                            isinstance(item.context_expr, ast.Call):
                        c = item.context_expr
                        if self._canon(c.func) == "open" and c.args:
                            return self._src_name(c.args[0])
        return None

    def _check_pl102_pl103(self) -> None:
        for call, src, frags in self.renames:
            file_sync_before = any(
                f.lineno <= call.lineno and not is_dir
                # bound fsyncs vouch only for their own staged file;
                # unbindable fd expressions stay a conservative match
                and (bound is None or src is None or bound == src)
                for f, is_dir, bound in self.fsyncs)
            if not file_sync_before:
                self.findings.append(Finding(
                    self.mod.path, call.lineno, call.col_offset, "PL102",
                    "rename source was never fsynced — the rename can "
                    "persist while the data does not, publishing a torn "
                    "file under the durable name", self.fr.qualname))
            dir_sync_after = any(
                f.lineno >= call.lineno and is_dir
                for f, is_dir, _bound in self.fsyncs)
            if not dir_sync_after:
                self.findings.append(Finding(
                    self.mod.path, call.lineno, call.col_offset, "PL103",
                    "rename without a following directory fsync — a host "
                    "crash can lose the rename, so a commit the caller "
                    "was told is durable silently vanishes",
                    self.fr.qualname))

    # -- PL104 --------------------------------------------------------------

    def _check_pl104(self) -> None:
        commits = [line for line, c in self.durable_writes if c]
        payloads = [line for line, c in self.durable_writes if not c]
        if commits and payloads and min(commits) < max(payloads):
            line = min(commits)
            self.findings.append(Finding(
                self.mod.path, line, 0, "PL104",
                "commit-point (manifest) write precedes a payload write "
                "in this function — a crash between the two leaves a "
                "manifest naming files that do not exist yet; the "
                "manifest must be written LAST", self.fr.qualname))

    # -- PL105 --------------------------------------------------------------

    def _check_pl105(self) -> None:
        if not self.tmp_opens:
            return
        # try blocks whose handler/finally unlink something
        cleanup_spans: List[Tuple[int, int]] = []
        for sub in ast.walk(self.fr.node):
            if not isinstance(sub, ast.Try):
                continue
            cleanup = []
            for h in sub.handlers:
                cleanup.extend(h.body)
            cleanup.extend(sub.finalbody)
            has_unlink = any(
                isinstance(c, ast.Call)
                and self._canon(c.func) in ("os.unlink", "os.remove")
                for stmt in cleanup for c in ast.walk(stmt))
            if has_unlink:
                end = max((s.end_lineno or s.lineno)
                          for s in sub.body) if sub.body else sub.lineno
                cleanup_spans.append((sub.lineno, end))
        for call, _src in self.tmp_opens:
            covered = any(a <= call.lineno <= b for a, b in cleanup_spans)
            if not covered:
                self.findings.append(Finding(
                    self.mod.path, call.lineno, call.col_offset, "PL105",
                    "tmp staging file is not cleaned up on exception "
                    "paths — wrap the write in try/except (unlink the "
                    "tmp, re-raise) and give concurrent writers "
                    "uniquely-named staging files (pid/thread suffix)",
                    self.fr.qualname))

    # -- PL201 --------------------------------------------------------------

    def _check_pl201(self, call: ast.Call, canon: str) -> None:
        for kw in call.keywords:
            if kw.arg == "sort_keys" and isinstance(kw.value, ast.Constant) \
                    and kw.value.value:
                return
        if self._dump_is_pinned(call, canon):
            self.findings.append(Finding(
                self.mod.path, call.lineno, call.col_offset, "PL201",
                f"{canon} of a sha-pinned/commit artifact without "
                "sort_keys=True — byte identity must not depend on dict "
                "insertion order", self.fr.qualname))

    def _dump_is_pinned(self, call: ast.Call, canon: str) -> bool:
        """A dump is pinned when its bytes feed a hash or land on a
        commit-point path — found by scanning the enclosing expression
        tree (hashlib call / atomic write with a manifest path whose
        args contain this dump)."""
        for sub in ast.walk(self.fr.node):
            if not isinstance(sub, ast.Call) or sub is call:
                continue
            contains = any(inner is call for inner in ast.walk(sub))
            if not contains:
                continue
            c = self._canon(sub.func)
            if c.startswith("hashlib."):
                return True
            key = self.res._callee(sub.func)
            if key is not None and key in self.atomic_keys and sub.args:
                if _commit(self.res.frags(sub.args[0])):
                    return True
        # json.dump(obj, f) into a file opened on a commit path
        if canon == "json.dump" and len(call.args) >= 2:
            fname = self._src_name(call.args[1])
            for ocall, frags, _mode in self.opens:
                if _commit(frags):
                    with_name = self._with_alias(ocall)
                    if with_name and with_name == fname:
                        return True
        return False

    def _with_alias(self, ocall: ast.Call) -> Optional[str]:
        for sub in ast.walk(self.fr.node):
            if isinstance(sub, ast.With):
                for item in sub.items:
                    if item.context_expr is ocall and \
                            isinstance(item.optional_vars, ast.Name):
                        return item.optional_vars.id
        return None


# --------------------------------------------------------------------------
# driver
# --------------------------------------------------------------------------

def analyze_paths(paths: Sequence[str]) -> List[Finding]:
    files = iter_py_files(paths)
    mods = [m for m in (_load(f) for f in files) if m is not None]
    for m in mods:
        _Collector(m).visit(m.tree)
        # callee edges for the atomic-writer closure
    corpus = PCorpus(mods)
    # one resolver per function, reused by the check pass (building the
    # local-fragment map walks the whole body — do it once, not twice)
    resolvers: Dict[str, _Resolver] = {}
    for m in mods:
        for q, fr in m.funcs.items():
            res = _Resolver(m, fr, corpus)
            resolvers[f"{m.uid}:{q}"] = res
            for sub in ast.walk(fr.node):
                if isinstance(sub, ast.Call):
                    key = res._callee(sub.func)
                    if key is not None:
                        fr.callees.add(key)
    atomic_keys = _atomic_writer_keys(corpus)
    findings: List[Finding] = []
    for m in mods:
        mod_findings: List[Finding] = []
        for q, fr in m.funcs.items():
            mod_findings.extend(
                _FuncCheck(m, fr, corpus, atomic_keys,
                           resolver=resolvers[f"{m.uid}:{q}"]).run())
        findings.extend(apply_waivers(m.path, m.waivers, mod_findings,
                                      RULES, prefix="PL",
                                      tool="persistlint"))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.code))
    return findings


def lint_paths(paths: Sequence[str]) -> List[Finding]:
    return analyze_paths(paths)


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="persistlint",
        description="durability static analysis (rules: docs/ANALYSIS.md)")
    p.add_argument("paths", nargs="*", default=["mx_rcnn_tpu"],
                   help="files or directories to lint")
    p.add_argument("--json", action="store_true",
                   help="emit findings as JSON records")
    p.add_argument("--show-waived", action="store_true",
                   help="also print waived findings")
    p.add_argument("--list-rules", action="store_true")
    args = p.parse_args(argv)
    if args.list_rules:
        for code, desc in sorted(RULES.items()):
            print(f"{code}  {desc}")
        return 0
    rc = check_paths_exist("persistlint", args.paths)
    if rc is not None:
        return rc
    findings = lint_paths(args.paths)
    active = [f for f in findings if f.waived is None]
    waived = [f for f in findings if f.waived is not None]
    shown = findings if args.show_waived else active
    if args.json:
        for f in shown:
            print(json.dumps({"path": f.path, "line": f.line,
                              "col": f.col + 1, "code": f.code,
                              "message": f.message, "func": f.func,
                              "waived": f.waived}))
    else:
        for f in shown:
            print(f.render())
    print(f"persistlint: {len(active)} finding(s), {len(waived)} waived",
          file=sys.stderr)
    return 1 if active else 0


if __name__ == "__main__":
    raise SystemExit(main())
