"""wirefuzz: deterministic wire-protocol fuzzing for the cross-host plane.

No reference equivalent.  netlint (``analysis/netlint.py``) proves the
network surface is SHAPED right — timeouts at allocation sites, bounded
reads, length checks before unpacks.  This module is its runtime twin:
it feeds the real decoders and the real HTTP servers deterministically
malformed bytes and asserts the CONTRACT those shapes exist for:

* a malformed frame is a TYPED rejection (``ValueError`` in-process, a
  4xx over HTTP) — never a crash, never a 500;
* no input makes a decoder allocate unboundedly (a wire-read length
  field must be validated against the buffer before it sizes anything);
* no input wedges a handler past its deadline, and the server still
  answers ``/healthz`` and serves a good frame AFTERWARD;
* socket-level faults between head and agent (drop / delay / split /
  truncate mid-frame / black-hole) end in reroute + exactly-once, never
  a lost or doubled request.

Everything is seeded (``random.Random(seed)``) so a corpus is
reproducible byte-for-byte: a failure report names the mutation and the
seed regenerates it exactly (``tests/test_netlint.py`` pins this).
The driver that aims this at the MXR1/MXD1 codec, a live agent, and
``HttpSource`` — and the planted-arm sensitivity proof — is
``tools/wirefuzz.py``; results land in ``NETFUZZ_r16.json``
(docs/ANALYSIS.md "wirefuzz").
"""

from __future__ import annotations

import contextlib
import random
import socket
import threading
import time
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

# ---------------------------------------------------------------------------
# outcomes
# ---------------------------------------------------------------------------

REJECTED = "rejected"                    # typed ValueError — the contract
ACCEPTED_VALID = "accepted_valid"        # benign mutation decoded fine
ACCEPTED_MALFORMED = "accepted_malformed"  # VIOLATION: must_reject decoded
CRASHED = "crashed"                      # VIOLATION: untyped exception
HUNG = "hung"                            # VIOLATION: past the deadline
ALLOC = "alloc_cap"                      # VIOLATION: unbounded allocation

VIOLATIONS = (ACCEPTED_MALFORMED, CRASHED, HUNG, ALLOC)


class Mutation:
    """One corpus entry: a name (stable across runs for the same seed),
    the mutated bytes, and whether the decoder MUST reject them.
    ``must_reject=False`` marks data-carrying mutations (payload bytes,
    benign header fields) that may decode to different values but must
    still never crash/hang/over-allocate."""

    __slots__ = ("name", "data", "must_reject")

    def __init__(self, name: str, data: bytes, must_reject: bool):
        self.name = name
        self.data = data
        self.must_reject = must_reject

    def __repr__(self):
        return (f"Mutation({self.name!r}, {len(self.data)}B, "
                f"must_reject={self.must_reject})")


# ---------------------------------------------------------------------------
# seeded corpus generation
# ---------------------------------------------------------------------------

class Mutator:
    """Deterministic mutation engine over a VALID frame.

    The caller describes the frame's header layout as spans:
    ``reject_spans`` are load-bearing fields (magic, version, dims, any
    length/count) where a flip must produce a rejection;
    ``benign_spans`` are data-carrying fields (reserved, timeouts,
    im_info) where a flip must merely not crash.  Same seed + same
    frame → the identical corpus, names and bytes.
    """

    def __init__(self, seed: int = 0):
        self.seed = int(seed)
        self.rng = random.Random(self.seed)

    def corpus(self, frame: bytes, head_size: int,
               reject_spans: Sequence[Tuple[str, int, int]],
               benign_spans: Sequence[Tuple[str, int, int]] = (),
               payload_flips: int = 4,
               extra: Iterable[Mutation] = ()) -> List[Mutation]:
        if len(frame) <= head_size:
            raise ValueError("corpus wants a frame with a payload")
        muts: List[Mutation] = []

        # -- truncation at every structural boundary ------------------
        cuts = {0, 1, 3}
        for _name, a, b in list(reject_spans) + list(benign_spans):
            cuts.add(a)
            cuts.add(b)
        cuts.update({head_size - 1, head_size,
                     head_size + (len(frame) - head_size) // 2,
                     len(frame) - 1})
        for c in sorted(x for x in cuts if 0 <= x < len(frame)):
            muts.append(Mutation(f"trunc@{c}", frame[:c], True))

        # -- bit flips in load-bearing header fields ------------------
        for name, a, b in reject_spans:
            for _ in range(max(2, b - a)):
                off = self.rng.randrange(a, b)
                bit = self.rng.randrange(8)
                d = bytearray(frame)
                d[off] ^= 1 << bit
                muts.append(Mutation(f"flip:{name}@{off}.{bit}",
                                     bytes(d), True))

        # -- bit flips in data-carrying header fields (benign) --------
        for name, a, b in benign_spans:
            for _ in range(max(1, (b - a) // 2)):
                off = self.rng.randrange(a, b)
                bit = self.rng.randrange(8)
                d = bytearray(frame)
                d[off] ^= 1 << bit
                muts.append(Mutation(f"flip:{name}@{off}.{bit}",
                                     bytes(d), False))

        # -- payload flips: decode fine, different values, no crash ---
        for _ in range(payload_flips):
            off = self.rng.randrange(head_size, len(frame))
            bit = self.rng.randrange(8)
            d = bytearray(frame)
            d[off] ^= 1 << bit
            muts.append(Mutation(f"flip:payload@{off}.{bit}",
                                 bytes(d), False))

        # -- structural edits ----------------------------------------
        muts.append(Mutation("empty", b"", True))
        muts.append(Mutation("garbage", bytes(
            self.rng.randrange(256) for _ in range(head_size + 16)), True))
        muts.append(Mutation("magic:xxxx",
                             b"XXXX" + frame[4:], True))
        muts.append(Mutation("trailing-junk", frame + b"\xde\xad", True))
        muts.append(Mutation("header-only", frame[:head_size], True))
        muts.extend(extra)
        return muts

    @staticmethod
    def fingerprint(muts: Sequence[Mutation]) -> str:
        """Stable digest of a corpus (names + bytes) — the determinism
        pin: same seed, same frame → same fingerprint."""
        import hashlib

        h = hashlib.sha256()
        for m in muts:
            h.update(m.name.encode())
            h.update(b"\x00" + m.data + b"\x01")
        return h.hexdigest()


# ---------------------------------------------------------------------------
# allocation guard
# ---------------------------------------------------------------------------

class AllocationCapExceeded(Exception):
    """A decoder asked numpy for more memory than the guard's cap —
    i.e. a wire-read length sized an allocation without a bound."""


def _nbytes_of(fname: str, args, kwargs) -> Optional[int]:
    import numpy as np

    try:
        if fname == "frombuffer":
            count = kwargs.get("count", args[2] if len(args) > 2 else -1)
            dtype = kwargs.get("dtype", args[1] if len(args) > 1
                               else np.float64)
            if count is None or int(count) < 0:
                return None  # whole-buffer read: bounded by the buffer
            return int(count) * np.dtype(dtype).itemsize
        shape = kwargs.get("shape", args[0] if args else None)
        dtype = kwargs.get("dtype",
                           args[2 if fname == "full" else 1]
                           if len(args) > (2 if fname == "full" else 1)
                           else np.float64)
        if shape is None:
            return None
        if not isinstance(shape, (tuple, list)):
            shape = (shape,)
        n = 1
        for s in shape:
            n *= int(s)
        return n * np.dtype(dtype).itemsize
    except Exception:
        return None  # unparseable call: let numpy raise its own error


@contextlib.contextmanager
def alloc_guard(cap_bytes: int = 64 << 20):
    """Monkeypatch numpy's allocators so any request past ``cap_bytes``
    raises :class:`AllocationCapExceeded` instead of attempting a
    multi-GB allocation.  Single-threaded use (the codec leg)."""
    import numpy as np

    names = ("zeros", "empty", "ones", "full", "frombuffer")
    orig = {n: getattr(np, n) for n in names}

    def wrap(fname, fn):
        def g(*args, **kwargs):
            est = _nbytes_of(fname, args, kwargs)
            if est is not None and est > cap_bytes:
                raise AllocationCapExceeded(
                    f"np.{fname} asked for {est} bytes (cap {cap_bytes})")
            return fn(*args, **kwargs)
        return g

    for n in names:
        setattr(np, n, wrap(n, orig[n]))
    try:
        yield
    finally:
        for n in names:
            setattr(np, n, orig[n])


# ---------------------------------------------------------------------------
# in-process codec leg
# ---------------------------------------------------------------------------

def run_case(decode: Callable[[bytes], object], m: Mutation,
             deadline_s: float = 5.0,
             alloc_cap: int = 64 << 20) -> Dict:
    """One mutation against one decoder, under the alloc guard and a
    wall-clock deadline.  ``ValueError`` is the ONLY typed rejection."""
    t0 = time.monotonic()
    try:
        with alloc_guard(alloc_cap):
            decode(m.data)
    except ValueError:
        outcome = REJECTED
    except AllocationCapExceeded as e:
        return {"case": m.name, "outcome": ALLOC, "detail": str(e)}
    except Exception as e:
        return {"case": m.name, "outcome": CRASHED,
                "detail": f"{type(e).__name__}: {e}"}
    else:
        outcome = ACCEPTED_MALFORMED if m.must_reject else ACCEPTED_VALID
    dt = time.monotonic() - t0
    if dt > deadline_s:
        return {"case": m.name, "outcome": HUNG,
                "detail": f"{dt:.1f}s > {deadline_s:.1f}s"}
    return {"case": m.name, "outcome": outcome}


def fuzz_codec(decode: Callable[[bytes], object],
               muts: Sequence[Mutation], deadline_s: float = 5.0,
               alloc_cap: int = 64 << 20) -> List[Dict]:
    return [run_case(decode, m, deadline_s, alloc_cap) for m in muts]


def summarize(results: Iterable[Dict]) -> Dict:
    counts: Dict[str, int] = {}
    violations: List[Dict] = []
    n = 0
    for r in results:
        n += 1
        counts[r["outcome"]] = counts.get(r["outcome"], 0) + 1
        if r["outcome"] in VIOLATIONS:
            violations.append(r)
    return {"cases": n, "outcomes": counts, "violations": violations}


# ---------------------------------------------------------------------------
# raw-socket HTTP leg
# ---------------------------------------------------------------------------

def _http_request_bytes(path: str, body: bytes, ctype: str,
                        content_length: Optional[int]) -> bytes:
    head = [f"POST {path} HTTP/1.1", "Host: fuzz",
            f"Content-Type: {ctype}"]
    if content_length is not None:
        head.append(f"Content-Length: {content_length}")
    head.append("Connection: close")
    return ("\r\n".join(head) + "\r\n\r\n").encode() + body


def _read_http_response(sock: socket.socket,
                        max_bytes: int = 1 << 20) -> Tuple[int, bytes]:
    """Minimal capped response reader: returns (status, raw).  Raises
    ``socket.timeout`` past the socket's deadline, ``ValueError`` on an
    unparseable status line or an over-cap body."""
    buf = b""
    while b"\r\n\r\n" not in buf:
        chunk = sock.recv(4096)
        if not chunk:
            break
        buf += chunk
        if len(buf) > max_bytes:
            raise ValueError("response headers exceed cap")
    if not buf:
        raise ValueError("connection closed before any response")
    line = buf.split(b"\r\n", 1)[0].decode("latin-1", "replace")
    parts = line.split()
    if len(parts) < 2 or not parts[1].isdigit():
        raise ValueError(f"bad status line {line!r}")
    status = int(parts[1])
    # drain the rest (Connection: close) under the same cap
    while len(buf) <= max_bytes:
        try:
            chunk = sock.recv(4096)
        except socket.timeout:
            break
        if not chunk:
            break
        buf += chunk
    return status, buf


def http_post_raw(host: str, port: int, path: str, body: bytes,
                  mode: str = "whole", ctype: str = "application/x-mxr1",
                  content_length: str = "auto",
                  timeout_s: float = 10.0,
                  trickle_bytes: int = 64,
                  trickle_delay_s: float = 0.01) -> Dict:
    """One raw HTTP POST with byte-level control over delivery.

    modes: ``whole`` (one sendall), ``split`` (two halves, 50 ms gap),
    ``trickle`` (headers whole, then the first ``trickle_bytes`` body
    bytes one at a time, then the rest), ``disconnect`` (headers + half
    the body, then close — no response expected).
    ``content_length``: ``"auto"`` (=len(body)), ``"absent"`` (no CL
    header → the server must 411), or an int to LIE (a multi-GB claim
    must 413 before a body byte is read).

    Returns ``{"status": int|None, "error": str|None, "elapsed_s": f}``.
    """
    cl: Optional[int]
    if content_length == "auto":
        cl = len(body)
    elif content_length == "absent":
        cl = None
    else:
        cl = int(content_length)
    req = _http_request_bytes(path, b"", ctype, cl)
    t0 = time.monotonic()
    status = None
    err = None
    sock = socket.create_connection((host, port), timeout=timeout_s)
    try:
        try:
            sock.sendall(req)
            if mode == "whole":
                sock.sendall(body)
            elif mode == "split":
                half = len(body) // 2
                sock.sendall(body[:half])
                time.sleep(0.05)
                sock.sendall(body[half:])
            elif mode == "trickle":
                n = min(trickle_bytes, len(body))
                for i in range(n):
                    sock.sendall(body[i:i + 1])
                    time.sleep(trickle_delay_s)
                sock.sendall(body[n:])
            elif mode == "disconnect":
                sock.sendall(body[:max(1, len(body) // 2)])
                sock.shutdown(socket.SHUT_RDWR)
                return {"status": None, "error": "client-disconnect",
                        "elapsed_s": round(time.monotonic() - t0, 3)}
            else:
                raise ValueError(f"unknown mode {mode!r}")
        except (BrokenPipeError, ConnectionResetError):
            # the server refused mid-send (e.g. a 408 at its body
            # deadline while we were still trickling): its response is
            # sitting in our receive buffer — read it, don't lose it
            pass
        status, _raw = _read_http_response(sock)
    except (socket.timeout, TimeoutError):
        err = "timeout"
    except (OSError, ValueError) as e:
        err = f"{type(e).__name__}: {e}"
    finally:
        sock.close()
    return {"status": status, "error": err,
            "elapsed_s": round(time.monotonic() - t0, 3)}


def http_case_outcome(res: Dict, must_reject: bool,
                      deadline_s: float) -> str:
    """Map an ``http_post_raw`` result to a fuzz outcome.  The HTTP
    contract: malformed client input is a 4xx (never 5xx), a valid
    frame is 200, and either way the answer lands inside the deadline.
    A connection the server dropped without a response counts as a
    rejection (it refused the input without wedging)."""
    if res.get("elapsed_s", 0.0) > deadline_s:
        return HUNG
    if res.get("error") == "timeout":
        return HUNG
    st = res.get("status")
    if st is None:
        return REJECTED  # dropped connection: refused, not wedged
    if 400 <= st < 500:
        return REJECTED
    if st == 200:
        return ACCEPTED_MALFORMED if must_reject else ACCEPTED_VALID
    return CRASHED  # 5xx for client-fault input breaks the contract


# ---------------------------------------------------------------------------
# socket-level fault proxy (head ↔ agent)
# ---------------------------------------------------------------------------

class FaultProxy:
    """A TCP proxy that injects one deterministic fault per accepted
    connection, chosen by ``schedule(conn_index)`` from:

    ``pass`` (forward untouched), ``delay`` (0.2 s stall before the
    first upstream write), ``split`` (forward in 7-byte writes),
    ``truncate`` (forward half of the first client read, then close
    both sides — mid-frame disconnect), ``blackhole`` (accept and read
    but never forward — the peer looks alive and says nothing),
    ``reset`` (close the client immediately).

    The head's reroute/exactly-once machinery is the system under test:
    every request submitted through the proxy must reach exactly one
    terminal state (tools/wirefuzz.py leg D).
    """

    MODES = ("pass", "delay", "split", "truncate", "blackhole", "reset")

    def __init__(self, upstream_host: str, upstream_port: int,
                 schedule: Optional[Callable[[int], str]] = None,
                 seed: int = 0, io_timeout_s: float = 30.0):
        self.upstream = (upstream_host, upstream_port)
        rng = random.Random(seed)
        self.schedule = schedule or (
            lambda i: self.MODES[rng.randrange(len(self.MODES))])
        self.io_timeout_s = float(io_timeout_s)
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.settimeout(0.5)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind(("127.0.0.1", 0))
        self._lsock.listen(64)
        self.address = self._lsock.getsockname()[:2]
        self._stop = threading.Event()
        self._conn_index = 0
        self._lock = threading.Lock()
        self._live: set = set()   # sockets snapped by kill_live()
        self.faults_applied: List[str] = []
        self._threads: List[threading.Thread] = []
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()

    # -- lifecycle ---------------------------------------------------
    def close(self) -> None:
        self._stop.set()
        self._lsock.close()
        self.kill_live()
        self._accept_thread.join(timeout=5.0)
        with self._lock:
            threads = list(self._threads)
        for t in threads:
            t.join(timeout=2.0)

    def kill_live(self) -> None:
        """Snap every connection currently riding the proxy — a
        keep-alive peer is forced to reconnect, so the NEXT scheduled
        fault mode actually gets a connection to apply to."""
        with self._lock:
            socks = list(self._live)
            self._live.clear()
        for s in socks:
            with contextlib.suppress(OSError):
                s.close()

    # -- internals ---------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                client, _addr = self._lsock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            with self._lock:
                idx = self._conn_index
                self._conn_index += 1
            mode = self.schedule(idx)
            with self._lock:
                self.faults_applied.append(mode)
            t = threading.Thread(target=self._serve_conn,
                                 args=(client, mode), daemon=True)
            t.start()
            with self._lock:
                self._threads.append(t)

    def _serve_conn(self, client: socket.socket, mode: str) -> None:
        client.settimeout(self.io_timeout_s)
        if mode == "reset":
            client.close()
            return
        try:
            up = socket.create_connection(self.upstream,
                                          timeout=self.io_timeout_s)
        except OSError:
            client.close()
            return
        with self._lock:
            self._live.add(client)
            self._live.add(up)
        try:
            if mode == "blackhole":
                # swallow the request, say nothing until the client
                # gives up (its timeout is the system under test)
                try:
                    while not self._stop.is_set():
                        if not client.recv(4096):
                            break
                except (socket.timeout, OSError):
                    pass
                return
            if mode == "truncate":
                try:
                    first = client.recv(4096)
                    if first:
                        up.sendall(first[:max(1, len(first) // 2)])
                except (socket.timeout, OSError):
                    pass
                return  # finally closes both: mid-frame disconnect
            first_write = [mode == "delay"]

            def pump(src, dst):
                try:
                    while not self._stop.is_set():
                        data = src.recv(4096)
                        if not data:
                            break
                        if first_write[0]:
                            first_write[0] = False
                            time.sleep(0.2)
                        if mode == "split":
                            for i in range(0, len(data), 7):
                                dst.sendall(data[i:i + 7])
                        else:
                            dst.sendall(data)
                except (socket.timeout, OSError):
                    pass
                finally:
                    with contextlib.suppress(OSError):
                        dst.shutdown(socket.SHUT_WR)

            t = threading.Thread(target=pump, args=(up, client),
                                 daemon=True)
            t.start()
            pump(client, up)
            t.join(timeout=self.io_timeout_s)
        finally:
            with self._lock:
                self._live.discard(client)
                self._live.discard(up)
            up.close()
            client.close()
