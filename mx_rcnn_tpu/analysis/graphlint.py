"""graphlint — AST linter enforcing TPU-graph hygiene on graph-scope code.

Why a linter: the whole design premise of this reproduction is that the
hot path is ONE XLA program with static shapes (the reference's per-step
host bounces — NumPy ``Proposal``, dynamic ``nonzero`` shapes — are the
sin being fixed).  Nothing in the type system enforces that: a single
careless ``.item()``, boolean-mask index, or per-call ``jax.jit(partial)``
silently reintroduces host syncs or per-step recompiles, and the only
symptom is a bench regression rounds later.  graphlint catches these bug
classes at lint time; ``tests/test_recompile_guard.py`` is the runtime
twin (jit cache-miss budget + tracer-leak checks).

Rule families (full catalogue with bad/good examples: docs/ANALYSIS.md):

* GL1xx — host-sync discipline: no host numpy / ``.item()`` / scalar
  coercions / ``print`` on traced values inside jitted scopes.
* GL2xx — static-shape discipline: no ``jnp.nonzero`` / one-arg
  ``jnp.where`` / boolean-mask indexing / Python control flow on tracers.
* GL3xx — jit-cache hygiene: no per-call jit of fresh lambdas/partials,
  no jit construction inside loops, no mutable defaults on static args.
* GL4xx — dtype/constant hygiene: no float64-promoting literals, no
  module-level jnp constants (they initialize the backend at import —
  see the comments in ``ops/nms.py`` / ``ops/targets.py``), no bare
  list/tuple operands in traced arithmetic.

Scope inference: a function is **jit-scoped** when it is (a) decorated
with ``jax.jit`` / ``functools.partial(jax.jit, ...)`` / a custom-VJP
builder, (b) passed (directly, as a lambda, or through a local
``functools.partial`` alias) to a tracing transform (``jit``, ``vmap``,
``grad``, ``lax.scan``/``cond``/``while_loop``, ``shard_map``,
``pallas_call``, ``defvjp``, ...), (c) a method of a ``flax.linen.Module``
subclass, (d) lexically nested in a jit-scoped function, (e) marked
``# graphlint: jit`` (for functions that are traced through indirection
the AST cannot follow, e.g. a closure returned by a factory and jitted by
the caller), or (f) **called** from a jit-scoped function — a transitive
closure over the module-local + cross-module call graph, so hygiene rules
follow the trace into helpers like ``ops/boxes.py`` without annotations.

False-positive suppression: inside a jit-scoped function, expressions are
classified **static** (Python values fixed at trace time — safe to
coerce, branch on, or hand to host numpy) by local dataflow: literals,
parameters named in ``static_argnames``/``static_argnums``/
``nondiff_argnums``, parameters annotated with scalar/config/host-numpy
types, ``.shape``/``.size``/``.ndim``/``.dtype`` reads, ``self`` fields
of flax modules, arithmetic/comparisons/whitelisted builtins over those,
and calls whose return annotation is a static type.  Everything else is
presumed traced.

Waivers: append ``# graphlint: disable=GL101 <reason>`` to the offending
line (or put the comment on its own line directly above).  A waiver MUST
carry a reason — a bare waiver is itself a finding (GL001) — so every
intentional exception is documented in place.

CLI::

    python -m mx_rcnn_tpu.analysis.graphlint [paths...] [--json]
        [--show-waived] [--list-rules]

Exit status 0 iff no unwaived findings.
"""

from __future__ import annotations

import argparse
import ast
import io
import json
import os
import re
import sys
import tokenize
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from mx_rcnn_tpu.analysis.common import (Finding, apply_waivers,
                                         canonical, check_paths_exist,
                                         collect_import_aliases, dotted,
                                         iter_py_files, waiver_re)

RULES: Dict[str, str] = {
    "GL001": "waiver without a reason (every waiver must say why)",
    "GL002": "waiver names an unknown rule code",
    "GL101": "host numpy call on traced values in jit scope",
    "GL102": "host materialization (.item()/.tolist()/device_get) in jit scope",
    "GL103": "float()/int()/bool() coercion of a traced value in jit scope",
    "GL104": "host print() in jit scope (use jax.debug.print)",
    "GL105": "host clock / obs span in jit scope (measures tracing, not "
             "compute)",
    "GL201": "dynamic-shape op (nonzero/argwhere/one-arg where) in jit scope",
    "GL202": "boolean-mask indexing in jit scope (dynamic result shape)",
    "GL203": "Python if/while on a traced value in jit scope",
    "GL301": "jax.jit of a fresh lambda/partial (new jit cache per call)",
    "GL302": "jax.jit built inside a loop or jitted-and-called in one expression",
    "GL303": "static jit argument with a mutable default",
    "GL401": "float64-promoting dtype in graph scope",
    "GL402": "module-level jnp constant (initializes the backend at import)",
    "GL403": "traced arithmetic with a bare list/tuple literal operand",
}

# transforms whose callable arguments are traced
_TRANSFORMS = {
    "jit", "vmap", "pmap", "grad", "value_and_grad", "checkpoint", "remat",
    "custom_vjp", "custom_jvp", "pallas_call", "scan", "while_loop", "cond",
    "switch", "fori_loop", "map", "shard_map", "shard_map_compat",
    "defvjp", "defjvp", "associative_scan", "named_call",
}

# annotations whose values are host/static at trace time
_STATIC_ANN = re.compile(
    r"^(?:Tuple|tuple|Sequence|List|list|Optional|int|float|bool|str|"
    r"Config|np\.ndarray|numpy\.ndarray|\[|\]|,|\.\.\.|\s|\||None)+$"
)

_STATIC_BUILTINS = {
    "len", "int", "float", "bool", "str", "min", "max", "round", "abs",
    "sum", "tuple", "list", "range", "sorted", "isinstance", "getattr",
    "hasattr", "divmod", "repr",
}

# host clocks (GL105): inside a jitted function these run at TRACE time —
# the measured interval is tracing/compilation, not the compute the
# author meant to time.  Same for the obs/trace.py span() context manager
# (host-side instrumentation belongs AROUND the jitted call, never in it).
_HOST_CLOCKS = {
    "time.time", "time.time_ns", "time.perf_counter",
    "time.perf_counter_ns", "time.monotonic", "time.monotonic_ns",
    "time.process_time", "time.process_time_ns",
}

# dynamic-output-shape ops (GL201); one-arg `where` is handled separately
_DYNAMIC_SHAPE_OPS = {"nonzero", "flatnonzero", "argwhere", "unique",
                      "extract", "compress"}

# waiver/Finding machinery shared with threadlint/configlint
# (analysis/common.py); the pragmas below are graphlint-specific
_WAIVER_RE = waiver_re("graphlint")
_PRAGMA_JIT_RE = re.compile(r"graphlint:\s*jit\b")
_PRAGMA_HOST_RE = re.compile(r"graphlint:\s*host\b")


@dataclass
class FuncInfo:
    node: ast.AST                      # FunctionDef / AsyncFunctionDef / Lambda
    qualname: str
    module: "ModuleInfo"
    jit: bool = False
    jit_reason: str = ""
    host_pragma: bool = False
    static_params: Set[str] = field(default_factory=set)
    parent: Optional["FuncInfo"] = None
    callees: Set[Tuple[str, str]] = field(default_factory=set)  # (mod, name)


@dataclass
class ModuleInfo:
    path: str
    name: str                          # dotted module name if under a package
    tree: ast.Module
    lines: List[str]
    graph_scope: bool
    aliases: Dict[str, str] = field(default_factory=dict)  # local -> canonical
    funcs: Dict[str, FuncInfo] = field(default_factory=dict)  # by qualname
    by_name: Dict[str, FuncInfo] = field(default_factory=dict)  # top-level defs
    waivers: Dict[int, Tuple[Set[str], str]] = field(default_factory=dict)
    jit_pragmas: Set[int] = field(default_factory=set)
    host_pragmas: Set[int] = field(default_factory=set)


# --------------------------------------------------------------------------
# name resolution helpers
# --------------------------------------------------------------------------

_dotted = dotted


def _canonical(mod: ModuleInfo, node: ast.AST) -> Optional[str]:
    """Resolve a Name/Attribute chain through the module's import aliases:
    ``jnp.where`` -> ``jax.numpy.where``, ``pl.pallas_call`` ->
    ``jax.experimental.pallas.pallas_call``."""
    return canonical(mod.aliases, node)


def _is_np(canon: Optional[str]) -> bool:
    return canon is not None and (canon == "numpy"
                                  or canon.startswith("numpy."))


def _is_jnp(canon: Optional[str]) -> bool:
    return canon is not None and (canon.startswith("jax.numpy.")
                                  or canon == "jax.numpy")


def _ann_is_static(ann: Optional[ast.AST]) -> bool:
    if ann is None:
        return False
    try:
        text = ast.unparse(ann)
    except Exception:
        return False
    return bool(_STATIC_ANN.match(text.strip().strip('"\'')))


# --------------------------------------------------------------------------
# pass 1: per-module collection
# --------------------------------------------------------------------------

def _collect_comments(source: str, mod: ModuleInfo) -> None:
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            line = tok.start[0]
            m = _WAIVER_RE.search(tok.string)
            if m:
                codes = {c.strip().upper() for c in m.group(1).split(",")
                         if c.strip()}
                mod.waivers[line] = (codes, m.group(2).strip())
            if _PRAGMA_JIT_RE.search(tok.string):
                mod.jit_pragmas.add(line)
            if _PRAGMA_HOST_RE.search(tok.string):
                mod.host_pragmas.add(line)
    except tokenize.TokenError:
        pass


def _collect_imports(mod: ModuleInfo) -> None:
    mod.aliases.update(collect_import_aliases(mod.tree))


def _static_params_of(mod: ModuleInfo, node: ast.AST) -> Set[str]:
    """Parameters fixed at trace time: static_argnames/static_argnums of a
    jit decorator, nondiff_argnums of custom_vjp, scalar-annotated args."""
    static: Set[str] = set()
    if isinstance(node, ast.Lambda):
        return static
    args = node.args
    allargs = args.posonlyargs + args.args + args.kwonlyargs
    for a in allargs:
        if _ann_is_static(a.annotation):
            static.add(a.arg)
    positions: List[int] = []
    names: List[str] = []
    for dec in node.decorator_list:
        for call in [n for n in ast.walk(dec) if isinstance(n, ast.Call)]:
            for kw in call.keywords:
                if kw.arg in ("static_argnames",):
                    for c in ast.walk(kw.value):
                        if isinstance(c, ast.Constant) and isinstance(c.value, str):
                            names.append(c.value)
                if kw.arg in ("static_argnums", "nondiff_argnums"):
                    for c in ast.walk(kw.value):
                        if isinstance(c, ast.Constant) and isinstance(c.value, int):
                            positions.append(c.value)
    static.update(names)
    ordered = [a.arg for a in args.posonlyargs + args.args]
    for i in positions:
        if 0 <= i < len(ordered):
            static.add(ordered[i])
    return static


def _jit_decorated(mod: ModuleInfo, node: ast.AST) -> Optional[str]:
    """Non-None (a human-readable reason) when a decorator makes the
    function traced: jax.jit, partial(jax.jit, ...), jax.checkpoint,
    jax.custom_vjp/custom_jvp (possibly partial-wrapped)."""
    if isinstance(node, ast.Lambda):
        return None
    for dec in node.decorator_list:
        targets = [dec]
        if isinstance(dec, ast.Call):
            targets.append(dec.func)
            targets.extend(dec.args)  # functools.partial(jax.jit, ...)
            for t in list(targets):
                if isinstance(t, ast.Call):
                    targets.append(t.func)
                    targets.extend(t.args)
        for t in targets:
            canon = _canonical(mod, t)
            if canon and canon.startswith("jax") and \
                    canon.rsplit(".", 1)[-1] in _TRANSFORMS:
                return f"@{canon.rsplit('.', 1)[-1]}"
    return None


class _ModuleScanner(ast.NodeVisitor):
    """Collects FuncInfos, flax-module classes, and jit roots."""

    def __init__(self, mod: ModuleInfo):
        self.mod = mod
        self.stack: List[FuncInfo] = []
        self.class_stack: List[Tuple[str, bool]] = []  # (name, is_flax)

    def _qual(self, name: str) -> str:
        prefix = ""
        if self.stack:
            prefix = self.stack[-1].qualname + "."
        elif self.class_stack:
            prefix = ".".join(c for c, _ in self.class_stack) + "."
        return prefix + name

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        is_flax = any(
            (_canonical(self.mod, b) or "").endswith("Module")
            for b in node.bases)
        self.class_stack.append((node.name, is_flax))
        self.generic_visit(node)
        self.class_stack.pop()

    def _add_func(self, node, name: str) -> FuncInfo:
        info = FuncInfo(node=node, qualname=self._qual(name), module=self.mod,
                        parent=self.stack[-1] if self.stack else None)
        info.static_params = _static_params_of(self.mod, node)
        reason = _jit_decorated(self.mod, node)
        in_flax_class = bool(self.class_stack and self.class_stack[-1][1]
                             and not self.stack)
        if any(l in self.mod.host_pragmas for l in
               (node.lineno, node.lineno - 1)):
            info.host_pragma = True
        elif reason:
            info.jit, info.jit_reason = True, reason
        elif in_flax_class:
            info.jit, info.jit_reason = True, "flax module method"
            info.static_params.add("self")
        elif any(l in self.mod.jit_pragmas for l in
                 (node.lineno, node.lineno - 1)):
            info.jit, info.jit_reason = True, "# graphlint: jit"
        self.mod.funcs[info.qualname] = info
        if not self.stack and not self.class_stack:
            self.mod.by_name[name] = info
        return info

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        info = self._add_func(node, node.name)
        self.stack.append(info)
        self.generic_visit(node)
        self.stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        info = self._add_func(node, f"<lambda:{node.lineno}>")
        self.stack.append(info)
        self.generic_visit(node)
        self.stack.pop()


def _mark_transform_roots(mod: ModuleInfo) -> None:
    """Mark functions passed to tracing transforms as jit roots.  Handles
    direct names, lambdas, inline ``functools.partial(f, ...)``, local
    aliases ``g = functools.partial(f, ...)``, and ``obj.defvjp(fwd, bwd)``.
    """
    partial_alias: Dict[str, str] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            canon = _canonical(mod, node.value.func) or ""
            if canon.endswith("partial") and node.value.args:
                inner = _dotted(node.value.args[0])
                if inner and len(node.targets) == 1:
                    tgt = _dotted(node.targets[0])
                    if tgt:
                        partial_alias[tgt] = inner

    def mark(name: Optional[str]) -> None:
        if not name:
            return
        name = partial_alias.get(name, name)
        info = mod.by_name.get(name) or mod.funcs.get(name)
        if info is None:  # nested def referenced by bare name
            for q, fi in mod.funcs.items():
                if q.split(".")[-1] == name:
                    info = fi
                    break
        if info is not None and not info.host_pragma and not info.jit:
            info.jit, info.jit_reason = True, "passed to transform"

    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        canon = _canonical(mod, node.func) or ""
        leaf = canon.rsplit(".", 1)[-1]
        is_defvjp = (isinstance(node.func, ast.Attribute)
                     and node.func.attr in ("defvjp", "defjvp"))
        if not is_defvjp:
            if leaf not in _TRANSFORMS:
                continue
            if not (canon.startswith("jax") or leaf == "shard_map_compat"
                    or "pallas" in canon or "shard_map" in canon):
                continue
            if ".tree" in canon or "tree_util" in canon:
                continue  # jax.tree.map is a pytree map, not a transform
            if leaf == "partial" or canon.endswith("functools.partial"):
                continue
        cands = list(node.args) + [kw.value for kw in node.keywords
                                   if kw.arg in ("f", "fun", "body_fun",
                                                 "cond_fun", "kernel")]
        for arg in cands:
            if isinstance(arg, ast.Lambda):
                info = mod.funcs.get(f"<lambda:{arg.lineno}>")
                for fi in mod.funcs.values():
                    if fi.node is arg:
                        info = fi
                if info is not None and not info.host_pragma:
                    info.jit, info.jit_reason = True, "lambda under transform"
            elif isinstance(arg, ast.Call):
                inner_canon = _canonical(mod, arg.func) or ""
                if inner_canon.endswith("partial") and arg.args:
                    mark(_dotted(arg.args[0]))
            else:
                mark(_dotted(arg))


def load_module(path: str, pkg_root: str) -> Optional[ModuleInfo]:
    try:
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
        tree = ast.parse(source, filename=path)
    except (OSError, SyntaxError) as e:
        print(f"graphlint: cannot parse {path}: {e}", file=sys.stderr)
        return None
    rel = os.path.relpath(path, pkg_root) if pkg_root else path
    dotted = rel[:-3].replace(os.sep, ".") if rel.endswith(".py") else rel
    # graph scope keys off the path RELATIVE to the linted root (plus the
    # immediate parent for single-file invocations, e.g. the test
    # fixture) — absolute components would misclassify a checkout that
    # happens to live under a directory named ops/core/models/parallel
    graph_dirs = ("ops", "core", "models", "parallel")
    parent = os.path.basename(os.path.dirname(os.path.abspath(path)))
    graph_scope = (any(p in graph_dirs for p in rel.split(os.sep))
                   or parent in graph_dirs)
    mod = ModuleInfo(path=path, name=dotted, tree=tree,
                     lines=source.splitlines(), graph_scope=graph_scope)
    _collect_comments(source, mod)
    _collect_imports(mod)
    _ModuleScanner(mod).visit(tree)
    _mark_transform_roots(mod)
    return mod


# --------------------------------------------------------------------------
# pass 2: cross-module jit closure
# --------------------------------------------------------------------------

def _collect_callees(mod: ModuleInfo) -> None:
    """Record, per function, calls that resolve to module-local defs or to
    names imported from sibling package modules."""
    class V(ast.NodeVisitor):
        def __init__(self):
            self.stack: List[FuncInfo] = []

        def _enter(self, node):
            for fi in mod.funcs.values():
                if fi.node is node:
                    self.stack.append(fi)
                    return fi
            return None

        def visit_FunctionDef(self, node):
            fi = self._enter(node)
            self.generic_visit(node)
            if fi:
                self.stack.pop()

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Lambda(self, node):
            fi = self._enter(node)
            self.generic_visit(node)
            if fi:
                self.stack.pop()

        def visit_Call(self, node):
            if self.stack:
                names: List[Optional[str]] = [_dotted(node.func)]
                canon = _canonical(mod, node.func) or ""
                # functools.partial(f, ...): the wrapped f is the callee
                if canon.endswith("partial") and node.args:
                    names.append(_dotted(node.args[0]))
                for name in names:
                    if not name:
                        continue
                    if name in mod.by_name:
                        self.stack[-1].callees.add((mod.name, name))
                    else:
                        full = mod.aliases.get(name)
                        if full and "." in full:
                            m, _, f = full.rpartition(".")
                            self.stack[-1].callees.add((m, f))
            self.generic_visit(node)

    V().visit(mod.tree)


def _propagate_jit(mods: List[ModuleInfo]) -> None:
    by_modname: Dict[str, ModuleInfo] = {}
    for m in mods:
        by_modname[m.name] = m
        # also index by the tail of the dotted name so absolute imports
        # (mx_rcnn_tpu.ops.boxes) match modules loaded from a subtree path
        by_modname.setdefault(m.name.rsplit(".", 1)[-1], m)
        _collect_callees(m)

    def resolve(ref: Tuple[str, str]) -> Optional[FuncInfo]:
        modname, fname = ref
        m = by_modname.get(modname) or by_modname.get(
            modname.rsplit(".", 1)[-1])
        if m is None:
            return None
        return m.by_name.get(fname)

    changed = True
    while changed:
        changed = False
        for m in mods:
            for fi in m.funcs.values():
                jit = fi.jit
                if not jit and fi.parent is not None and fi.parent.jit:
                    jit = True
                    fi.jit_reason = f"nested in {fi.parent.qualname}"
                if not jit or fi.host_pragma:
                    continue
                if not fi.jit:
                    fi.jit = True
                    changed = True
                for ref in fi.callees:
                    callee = resolve(ref)
                    if callee is not None and not callee.jit \
                            and not callee.host_pragma:
                        callee.jit = True
                        callee.jit_reason = f"called from {fi.qualname}"
                        changed = True


# --------------------------------------------------------------------------
# static-expression classification (local dataflow)
# --------------------------------------------------------------------------

class _StaticEnv:
    def __init__(self, mod: ModuleInfo, statics: Set[str]):
        self.mod = mod
        self.statics = set(statics)
        self.mask_names: Set[str] = set()   # names holding boolean masks

    def is_static(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Constant):
            return True
        if isinstance(node, ast.Name):
            return node.id in self.statics
        if isinstance(node, ast.Attribute):
            if node.attr in ("shape", "size", "ndim", "dtype"):
                return True
            canon = _canonical(self.mod, node)
            if canon and (canon.startswith("jax") or _is_np(canon)):
                # module attributes (jnp.float32, np.pi) are trace-time
                # constants; module CALLS are handled under ast.Call
                return True
            return self.is_static(node.value)
        if isinstance(node, ast.Subscript):
            return self.is_static(node.value) and self.is_static(node.slice)
        if isinstance(node, (ast.Tuple, ast.List)):
            return all(self.is_static(e) for e in node.elts)
        if isinstance(node, ast.BinOp):
            return self.is_static(node.left) and self.is_static(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.is_static(node.operand)
        if isinstance(node, ast.BoolOp):
            return all(self.is_static(v) for v in node.values)
        if isinstance(node, ast.Compare):
            return (self.is_static(node.left)
                    and all(self.is_static(c) for c in node.comparators))
        if isinstance(node, ast.IfExp):
            return (self.is_static(node.test) and self.is_static(node.body)
                    and self.is_static(node.orelse))
        if isinstance(node, ast.Slice):
            return all(p is None or self.is_static(p)
                       for p in (node.lower, node.upper, node.step))
        if isinstance(node, ast.Starred):
            return self.is_static(node.value)
        if isinstance(node, ast.JoinedStr):
            return True
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            return self._comp_static(node, [node.elt])
        if isinstance(node, ast.DictComp):
            return self._comp_static(node, [node.key, node.value])
        if isinstance(node, ast.Call):
            return self._call_static(node)
        return False

    def _comp_static(self, node, elts) -> bool:
        """A comprehension over static iterables of static elements is
        static (the loop targets are bound static while judging ``elt``)."""
        if not all(self.is_static(g.iter) for g in node.generators):
            return False
        added: Set[str] = set()
        for g in node.generators:
            for t in ast.walk(g.target):
                if isinstance(t, ast.Name) and t.id not in self.statics:
                    added.add(t.id)
        self.statics |= added
        try:
            return all(self.is_static(e) for e in elts) and all(
                self.is_static(cond)
                for g in node.generators for cond in g.ifs)
        finally:
            self.statics -= added

    def _call_static(self, node: ast.Call) -> bool:
        args_static = (all(self.is_static(a) for a in node.args)
                       and all(self.is_static(k.value)
                               for k in node.keywords))
        if isinstance(node.func, ast.Name):
            if node.func.id in _STATIC_BUILTINS:
                return args_static
            # locally-defined helper returning a static type
            # (e.g. ``_pick_blocks(...) -> Tuple[int, int]``)
            fi = self.mod.by_name.get(node.func.id)
            if fi is not None and not isinstance(fi.node, ast.Lambda) \
                    and _ann_is_static(fi.node.returns):
                return args_static
        canon = _canonical(self.mod, node.func)
        if _is_np(canon):
            # host numpy over static values is trace-time constant folding
            return args_static
        if isinstance(node.func, ast.Attribute):
            # str/tuple methods on a static receiver (x.replace, x.split)
            return self.is_static(node.func.value) and args_static
        return False

    def _is_masky(self, value: ast.AST) -> bool:
        """Comparison-shaped values (potential boolean masks)."""
        if isinstance(value, ast.Compare):
            return True
        if isinstance(value, ast.BoolOp):
            return any(self._is_masky(v) for v in value.values)
        if isinstance(value, ast.Name):
            return value.id in self.mask_names
        if isinstance(value, ast.BinOp) and isinstance(
                value.op, (ast.BitAnd, ast.BitOr)):
            return (self._is_masky(value.left)
                    or self._is_masky(value.right))
        return False

    def bind(self, target: ast.AST, value: ast.AST) -> None:
        """Record one assignment for the static/mask name sets."""
        static = self.is_static(value)
        masky = self._is_masky(value) and not static
        if isinstance(target, ast.Name):
            if static:
                self.statics.add(target.id)
            else:
                self.statics.discard(target.id)
            if masky:
                self.mask_names.add(target.id)
            else:
                self.mask_names.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            if static:
                for e in target.elts:
                    if isinstance(e, ast.Name):
                        self.statics.add(e.id)
            else:
                vals = (value.elts if isinstance(value, (ast.Tuple, ast.List))
                        and len(value.elts) == len(target.elts) else None)
                for i, e in enumerate(target.elts):
                    if not isinstance(e, ast.Name):
                        continue
                    if vals is not None:
                        self.bind(e, vals[i])
                    elif isinstance(value, ast.Attribute) \
                            and value.attr == "shape":
                        self.statics.add(e.id)  # ``a, b = x.shape``
                    else:
                        self.statics.discard(e.id)


# --------------------------------------------------------------------------
# rule checks
# --------------------------------------------------------------------------

class _Checker:
    def __init__(self, mod: ModuleInfo):
        self.mod = mod
        self.findings: List[Finding] = []

    def report(self, node: ast.AST, code: str, message: str,
               func: str = "") -> None:
        self.findings.append(Finding(
            path=self.mod.path, line=node.lineno, col=node.col_offset,
            code=code, message=message, func=func))

    # ---- module-level rules ------------------------------------------------

    def check_module_level(self) -> None:
        if not self.mod.graph_scope:
            return
        for stmt in self.mod.tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef, ast.Import, ast.ImportFrom)):
                continue
            for node in ast.walk(stmt):
                if isinstance(node, ast.Call):
                    canon = _canonical(self.mod, node.func)
                    if _is_jnp(canon):
                        self.report(node, "GL402",
                                    f"module-level '{_dotted(node.func)}' "
                                    "call bakes a device constant at import")
        for node in ast.walk(self.mod.tree):
            if isinstance(node, ast.Attribute) and node.attr == "float64":
                canon = _canonical(self.mod, node)
                if canon in ("numpy.float64", "jax.numpy.float64"):
                    self.report(node, "GL401",
                                f"'{_dotted(node)}' promotes to float64")
            if isinstance(node, ast.keyword) and node.arg == "dtype" \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id == "float":
                self.report(node.value, "GL401",
                            "dtype=float resolves to float64 on the host")
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "astype" and node.args \
                    and isinstance(node.args[0], ast.Name) \
                    and node.args[0].id == "float":
                self.report(node, "GL401",
                            ".astype(float) promotes to float64")

    def check_jit_construction(self) -> None:
        """GL301/GL302/GL303 — apply to graph-scope modules everywhere
        (jit objects are built in host code)."""
        if not self.mod.graph_scope:
            return
        loops: List[ast.AST] = [n for n in ast.walk(self.mod.tree)
                                if isinstance(n, (ast.For, ast.While))]

        def inside_loop(node: ast.AST) -> bool:
            return any(loop.lineno <= node.lineno
                       <= getattr(loop, "end_lineno", loop.lineno)
                       for loop in loops)

        in_func_lines: List[Tuple[int, int]] = [
            (fi.node.lineno, getattr(fi.node, "end_lineno", fi.node.lineno))
            for fi in self.mod.funcs.values()
            if not isinstance(fi.node, ast.Lambda)]

        def inside_func(node: ast.AST) -> bool:
            return any(a <= node.lineno <= b for a, b in in_func_lines)

        for node in ast.walk(self.mod.tree):
            if not isinstance(node, ast.Call):
                continue
            canon = _canonical(self.mod, node.func) or ""
            if canon.rsplit(".", 1)[-1] == "jit" and canon.startswith("jax"):
                if inside_loop(node):
                    self.report(node, "GL302",
                                "jax.jit built inside a loop retraces "
                                "every iteration")
                if node.args and inside_func(node):
                    a = node.args[0]
                    is_partial = (isinstance(a, ast.Call) and
                                  (_canonical(self.mod, a.func) or ""
                                   ).endswith("partial"))
                    if isinstance(a, ast.Lambda) or is_partial:
                        self.report(node, "GL301",
                                    "jax.jit of a fresh lambda/partial — a "
                                    "new callable (and jit cache) per call")
            # immediate invocation: jax.jit(f)(x)
            if isinstance(node.func, ast.Call):
                inner = _canonical(self.mod, node.func.func) or ""
                if inner.rsplit(".", 1)[-1] == "jit" \
                        and inner.startswith("jax"):
                    self.report(node, "GL302",
                                "jax.jit(f)(...) discards the jit cache "
                                "after one call")
        # GL303: mutable defaults on static params of jit-decorated defs
        for fi in self.mod.funcs.values():
            if isinstance(fi.node, ast.Lambda) or not fi.jit:
                continue
            if not _jit_decorated(self.mod, fi.node):
                continue
            args = fi.node.args
            pos = args.posonlyargs + args.args
            defaults = args.defaults
            off = len(pos) - len(defaults)
            pairs = [(a, d) for a, d in zip(pos[off:], defaults)]
            pairs += [(a, d) for a, d in zip(args.kwonlyargs,
                                             args.kw_defaults) if d]
            for a, d in pairs:
                if a.arg in fi.static_params and isinstance(
                        d, (ast.List, ast.Dict, ast.Set)):
                    self.report(d, "GL303",
                                f"static arg '{a.arg}' has a mutable "
                                "(unhashable) default", fi.qualname)

    # ---- jit-scope rules ---------------------------------------------------

    def check_jit_scopes(self) -> None:
        for fi in self.mod.funcs.values():
            if fi.jit and not fi.host_pragma:
                self._check_one(fi)

    def _body_nodes(self, fi: FuncInfo):
        """Statements of this function, excluding nested function bodies
        (nested defs are checked as their own FuncInfos)."""
        own: List[ast.AST] = []
        nested = [f.node for f in self.mod.funcs.values()
                  if f.parent is fi]

        def walk(node):
            for child in ast.iter_child_nodes(node):
                if child in nested:
                    continue
                own.append(child)
                walk(child)
        body = fi.node.body if not isinstance(fi.node, ast.Lambda) \
            else [fi.node.body]
        for stmt in body:
            if isinstance(stmt, ast.AST):
                own.append(stmt)
                walk(stmt)
        return own

    def _inherited_statics(self, fi: FuncInfo) -> Set[str]:
        statics = set(fi.static_params)
        p = fi.parent
        while p is not None:
            statics |= p.static_params
            p = p.parent
        return statics

    def _check_one(self, fi: FuncInfo) -> None:
        env = _StaticEnv(self.mod, self._inherited_statics(fi))
        q = fi.qualname
        for node in self._body_nodes(fi):
            # dataflow first so later statements see earlier bindings
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    env.bind(t, node.value)
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                env.bind(node.target, node.value)
            elif isinstance(node, ast.AugAssign):
                if isinstance(node.target, ast.Name) \
                        and not env.is_static(node.value):
                    env.statics.discard(node.target.id)

            if isinstance(node, ast.Call):
                self._check_call(node, env, q)
            elif isinstance(node, (ast.If, ast.While)):
                self._check_branch(node, env, q)
            elif isinstance(node, ast.Subscript):
                self._check_subscript(node, env, q)
            elif isinstance(node, ast.BinOp):
                self._check_binop(node, env, q)

    def _check_call(self, node: ast.Call, env: _StaticEnv, q: str) -> None:
        canon = _canonical(self.mod, node.func)
        name = _dotted(node.func) or "<call>"
        if _is_np(canon) and not env._call_static(node):
            self.report(node, "GL101",
                        f"host numpy call '{name}' on traced values forces "
                        "a device sync", q)
            return
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in ("item", "tolist") \
                and not env.is_static(node.func.value):
            self.report(node, "GL102",
                        f"'.{node.func.attr}()' materializes a traced value "
                        "on the host", q)
        if canon in ("jax.device_get",):
            self.report(node, "GL102",
                        "jax.device_get inside a traced scope", q)
        if isinstance(node.func, ast.Name) \
                and node.func.id in ("float", "int", "bool") and node.args:
            if not all(env.is_static(a) for a in node.args):
                self.report(node, "GL103",
                            f"{node.func.id}() on a traced value is a "
                            "blocking host sync (and a retrace trap)", q)
        if isinstance(node.func, ast.Name) and node.func.id == "print":
            self.report(node, "GL104",
                        "host print() in jit scope runs at TRACE time only "
                        "— use jax.debug.print", q)
        if canon in _HOST_CLOCKS:
            self.report(node, "GL105",
                        f"host clock '{name}' in jit scope fires at TRACE "
                        "time — it measures tracing, not compute; time "
                        "around the jitted call instead", q)
        if canon is not None and canon.rsplit(".", 1)[-1] == "span" \
                and ("obs.trace" in canon or canon.endswith("obs.span")):
            self.report(node, "GL105",
                        "obs span in jit scope wraps TRACING, not device "
                        "compute — put the span around the jitted call "
                        "(device time comes from the profiler)", q)
        if canon is not None and canon.startswith("jax"):
            leaf = canon.rsplit(".", 1)[-1]
            if leaf in _DYNAMIC_SHAPE_OPS:
                self.report(node, "GL201",
                            f"'{name}' has a data-dependent output shape",
                            q)
            if leaf == "where" and len(node.args) == 1 and not node.keywords:
                self.report(node, "GL201",
                            "one-arg jnp.where is nonzero() in disguise "
                            "(dynamic output shape)", q)

    def _check_branch(self, node, env: _StaticEnv, q: str) -> None:
        test = node.test
        if env.is_static(test):
            return
        # only flag tests that visibly involve array computation — a bare
        # unresolved Name is more often a host flag than a tracer, and
        # the runtime leak/concretization checks catch those
        involves_array = False
        for sub in ast.walk(test):
            if isinstance(sub, ast.Call):
                canon = _canonical(self.mod, sub.func)
                if canon is not None and (canon.startswith("jax")):
                    involves_array = True
            if isinstance(sub, ast.Name) and sub.id in env.mask_names:
                involves_array = True
        if involves_array:
            kw = "while" if isinstance(node, ast.While) else "if"
            self.report(node, "GL203",
                        f"Python '{kw}' on a traced value — use jnp.where/"
                        "lax.cond (this either crashes under jit or burns "
                        "a recompile per value)", q)

    def _check_subscript(self, node: ast.Subscript, env: _StaticEnv,
                         q: str) -> None:
        sl = node.slice
        parts = sl.elts if isinstance(sl, ast.Tuple) else [sl]
        for p in parts:
            booly = isinstance(p, (ast.Compare, ast.BoolOp)) \
                and not env.is_static(p)
            if isinstance(p, ast.UnaryOp) and isinstance(p.op, ast.Invert):
                booly = booly or not env.is_static(p.operand)
            if isinstance(p, ast.Name) and p.id in env.mask_names:
                booly = True
            if isinstance(p, ast.Call):
                canon = _canonical(self.mod, p.func) or ""
                if canon.rsplit(".", 1)[-1].startswith("logical_"):
                    booly = True
            if booly:
                self.report(node, "GL202",
                            "boolean-mask indexing has a data-dependent "
                            "shape — use jnp.where or fixed-size top_k", q)
                return

    def _check_binop(self, node: ast.BinOp, env: _StaticEnv, q: str) -> None:
        if not isinstance(node.op, (ast.Add, ast.Sub, ast.Mult, ast.Div)):
            return
        for lit, other in ((node.left, node.right),
                           (node.right, node.left)):
            # List literals only: ``tup + tup`` is shape concatenation, the
            # idiomatic static-shape arithmetic this repo is full of
            if isinstance(lit, ast.List) and lit.elts \
                    and env.is_static(lit) and not env.is_static(other):
                self.report(node, "GL403",
                            "bare list literal in traced arithmetic "
                            "— wrap in jnp.asarray(..., dtype) to pin "
                            "dtype and rank", q)
                return


# --------------------------------------------------------------------------
# driver
# --------------------------------------------------------------------------

_iter_py_files = iter_py_files


def _apply_waivers(mod: ModuleInfo, findings: List[Finding]) -> List[Finding]:
    # the waivers themselves are linted: no reason -> GL001; bad code -> GL002
    return apply_waivers(mod.path, mod.waivers, findings, RULES,
                         prefix="GL", tool="graphlint")


def lint_paths(paths: Sequence[str],
               pkg_root: Optional[str] = None) -> List[Finding]:
    """Lint all .py files under ``paths``; returns findings (waived ones
    carry their waiver reason).  ``pkg_root`` anchors dotted module names
    for the cross-module closure (default: common parent of ``paths``)."""
    files = _iter_py_files(paths)
    if pkg_root is None:
        pkg_root = os.path.commonpath([os.path.abspath(p) for p in paths]) \
            if paths else "."
        if os.path.isfile(pkg_root):
            pkg_root = os.path.dirname(pkg_root)
    mods = [m for m in (load_module(f, pkg_root) for f in files)
            if m is not None]
    _propagate_jit(mods)
    findings: List[Finding] = []
    for mod in mods:
        c = _Checker(mod)
        c.check_module_level()
        c.check_jit_construction()
        c.check_jit_scopes()
        findings.extend(_apply_waivers(mod, c.findings))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.code))
    return findings


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="graphlint",
        description="TPU-graph hygiene linter (rules: docs/ANALYSIS.md)")
    p.add_argument("paths", nargs="*", default=["mx_rcnn_tpu"],
                   help="files or directories to lint")
    p.add_argument("--json", action="store_true",
                   help="emit findings as JSON records")
    p.add_argument("--show-waived", action="store_true",
                   help="also print waived findings")
    p.add_argument("--list-rules", action="store_true")
    args = p.parse_args(argv)
    if args.list_rules:
        for code, desc in sorted(RULES.items()):
            print(f"{code}  {desc}")
        return 0
    # a typo'd path (or a package rename) must FAIL the gate, not lint
    # zero files and pass vacuously
    rc = check_paths_exist("graphlint", args.paths)
    if rc is not None:
        return rc
    findings = lint_paths(args.paths)
    active = [f for f in findings if f.waived is None]
    waived = [f for f in findings if f.waived is not None]
    shown = findings if args.show_waived else active
    if args.json:
        for f in shown:
            print(json.dumps({"path": f.path, "line": f.line,
                              "col": f.col + 1, "code": f.code,
                              "message": f.message, "func": f.func,
                              "waived": f.waived}))
    else:
        for f in shown:
            print(f.render())
    print(f"graphlint: {len(active)} finding(s), {len(waived)} waived",
          file=sys.stderr)
    return 1 if active else 0


if __name__ == "__main__":
    raise SystemExit(main())
