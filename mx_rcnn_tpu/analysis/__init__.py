"""Static analysis + runtime sanitizers for the repo's machine-checked
invariants (rule catalogues and waiver syntax: docs/ANALYSIS.md).

Five linters share one Finding/waiver protocol (``common.py``), each
paired with a runtime twin:

* ``graphlint`` — TPU-graph hygiene: the hot path is ONE XLA program
  with static shapes (PAPER.md, docs/DESIGN.md); flags host syncs,
  dynamic-shape ops, jit-cache churn, dtype hazards in graph-scope
  code.  Runtime twin: ``tests/test_recompile_guard.py`` (jit
  cache-miss budget + tracer-leak checks).
* ``threadlint`` — concurrency hygiene over the serve/ft/obs/data
  planes: cross-module lock-order graph with cycle detection
  (``--graph`` dumps it as JSON), unguarded thread-shared writes,
  blocking calls under locks, signal-handler safety, Condition
  predicates.  Runtime twin: ``sanitizer.py`` — opt-in instrumented
  ``Lock``/``RLock`` recording real acquisition order, hold budgets
  and stalls (``MXRCNN_THREAD_SANITIZER``; ``make threadlint-smoke``).
* ``configlint`` — config-surface hygiene: every ``cfg.<section>.<key>``
  read must exist in the ``config.py`` dataclasses (CL101), and
  declared keys nobody reads are dead (CL201).
* ``persistlint`` — durability hygiene over the durable-write surface:
  every checkpoint / export-store / bulk-sink / manifest write must
  ride the tmp→fsync→rename→dir-fsync, manifest-last idiom
  (``utils/checkpoint._atomic_write``); flags raw durable writes,
  un-fsynced renames, missing dir-fsyncs, manifest-before-payload
  ordering, leaked staging files and unsorted sha-pinned dumps.
  Runtime twin: ``crashsim.py`` — an interposition shim records the
  real commit workloads' write ops, enumerates every crash state the
  persistence model allows, and runs the REAL recovery paths against
  each, asserting recover-or-refuse (``make crashsim-smoke``).
* ``netlint`` — network-surface hygiene over the cross-host plane:
  tracked socket/connection/response objects must be timed (NL101)
  and exception-safe (NL102), wire decodes length-checked (NL201)
  with every peer-supplied length bounded before it sizes an
  allocation (NL202), response/body reads byte-capped and
  deadline-bounded through ``netio`` (NL203/NL204), and retry loops
  backed off AND capped (NL301).  Runtime twin: ``wirefuzz.py`` — a
  deterministic seeded mutation engine (truncations, field flips,
  inflation arms), an allocation guard, a raw-HTTP client with
  byte-level delivery control, and a socket-level fault proxy; the
  driver ``tools/wirefuzz.py`` runs the corpus against the real
  codec, a live agent, a malicious metrics server and a faulted
  head↔agent link, with planted-vulnerable arms proving sensitivity
  (``make wirefuzz-smoke``).

All five run in ``make lint`` (first leg of ``make test-gate``):
``python -m mx_rcnn_tpu.analysis.<tool> mx_rcnn_tpu``.

Import ``RULES`` / ``lint_paths`` from the tool modules directly (kept
out of this namespace so ``python -m`` does not double-load them).
"""
