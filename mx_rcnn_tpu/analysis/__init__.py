"""Static analysis for TPU-graph hygiene.

The repo's core performance invariant (PAPER.md, docs/DESIGN.md) is that
the hot path is ONE XLA program with static shapes — no host syncs, no
per-step recompiles.  ``graphlint`` makes that invariant machine-checked:
``python -m mx_rcnn_tpu.analysis.graphlint mx_rcnn_tpu`` (also ``make
lint``) walks the graph-scope packages and reports violations by rule
code.  The runtime counterpart lives in ``tests/test_recompile_guard.py``
(jit cache-miss budget + tracer-leak checks).  Rule catalogue and waiver
syntax: docs/ANALYSIS.md.

Import ``RULES`` / ``lint_paths`` from ``mx_rcnn_tpu.analysis.graphlint``
directly (kept out of this namespace so ``python -m`` does not double-load
the module).
"""
