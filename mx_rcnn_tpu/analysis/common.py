"""Shared machinery for the analysis passes (graphlint / threadlint /
configlint): the ``Finding`` record, the reasoned-waiver protocol, import
alias resolution and file iteration.

Extracted from ``graphlint.py`` (ISSUE 10) so every linter in the package
speaks the same waiver dialect and renders findings identically:

* a waiver is ``# <tool>: disable=<CODE>[,<CODE>...] <reason>`` on the
  offending line or the line directly above; the reason is MANDATORY — a
  bare waiver is itself a finding (``<PREFIX>001``), and a waiver naming
  an unknown rule is ``<PREFIX>002``;
* ``Finding.render()`` gives the one-line ``path:line:col CODE message``
  format every CLI prints and every test asserts against.

Each tool keeps its own rule catalogue, scope model and checks — only the
protocol plumbing lives here.
"""

from __future__ import annotations

import ast
import io
import os
import re
import sys
import tokenize
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set, Tuple


@dataclass
class Finding:
    path: str
    line: int
    col: int
    code: str
    message: str
    func: str = ""
    waived: Optional[str] = None  # the waiver reason when waived

    def render(self) -> str:
        where = f" [in {self.func}]" if self.func else ""
        tail = f"  (waived: {self.waived})" if self.waived is not None else ""
        return (f"{self.path}:{self.line}:{self.col + 1} {self.code} "
                f"{self.message}{where}{tail}")


def waiver_re(tool: str) -> re.Pattern:
    """The per-tool waiver comment pattern:
    ``# <tool>: disable=CODE[,CODE...] <reason>``."""
    return re.compile(tool + r":\s*disable=([A-Za-z0-9,]+)\s*(.*)$")


def parse_waivers(source: str, tool: str
                  ) -> Dict[int, Tuple[Set[str], str]]:
    """Collect ``{line: ({codes}, reason)}`` waivers for ``tool`` from the
    comment stream (tokenize, so strings containing the pattern don't
    count)."""
    pat = waiver_re(tool)
    waivers: Dict[int, Tuple[Set[str], str]] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type != tokenize.COMMENT:
                continue
            m = pat.search(tok.string)
            if m:
                codes = {c.strip().upper() for c in m.group(1).split(",")
                         if c.strip()}
                waivers[tok.start[0]] = (codes, m.group(2).strip())
    except tokenize.TokenError:
        pass
    return waivers


def apply_waivers(path: str, waivers: Dict[int, Tuple[Set[str], str]],
                  findings: List[Finding], rules: Dict[str, str],
                  prefix: str, tool: str) -> List[Finding]:
    """Mark findings waived when a matching waiver sits on their line (or
    the line above), then lint the waivers themselves: no reason →
    ``<prefix>001``; unknown rule code → ``<prefix>002``."""
    for f in findings:
        for line in (f.line, f.line - 1):
            w = waivers.get(line)
            if w is None:
                continue
            codes, reason = w
            if f.code in codes:
                f.waived = reason
                break
    out = list(findings)
    for line, (codes, reason) in sorted(waivers.items()):
        if not reason:
            out.append(Finding(path, line, 0, f"{prefix}001",
                               f"waiver must state a reason: "
                               f"'# {tool}: disable={prefix}xxx <why>'"))
        for c in codes:
            if c not in rules:
                out.append(Finding(path, line, 0, f"{prefix}002",
                                   f"waiver names unknown rule {c!r}"))
    return out


# --------------------------------------------------------------------------
# name resolution
# --------------------------------------------------------------------------

def dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def collect_import_aliases(tree: ast.Module) -> Dict[str, str]:
    """``{local name: canonical dotted name}`` from the module's imports
    (``import jax.numpy as jnp`` → ``jnp: jax.numpy``)."""
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


def canonical(aliases: Dict[str, str], node: ast.AST) -> Optional[str]:
    """Resolve a Name/Attribute chain through import aliases:
    ``jnp.where`` → ``jax.numpy.where``."""
    d = dotted(node)
    if d is None:
        return None
    head, _, rest = d.partition(".")
    full = aliases.get(head, head)
    return f"{full}.{rest}" if rest else full


# --------------------------------------------------------------------------
# file iteration + CLI plumbing
# --------------------------------------------------------------------------

def iter_py_files(paths: Sequence[str]) -> List[str]:
    files: List[str] = []
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            files.append(p)
        elif os.path.isdir(p):
            for root, dirs, names in os.walk(p):
                dirs[:] = [d for d in dirs
                           if d not in ("__pycache__", ".git")]
                files.extend(os.path.join(root, n)
                             for n in sorted(names) if n.endswith(".py"))
    return sorted(set(files))


def check_paths_exist(tool: str, paths: Sequence[str]) -> Optional[int]:
    """A typo'd path (or a package rename) must FAIL the gate, not lint
    zero files and pass vacuously.  Returns an exit code, or None when
    the paths are usable."""
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(f"{tool}: path(s) do not exist: {missing}", file=sys.stderr)
        return 2
    if not iter_py_files(paths):
        print(f"{tool}: no .py files under {list(paths)}", file=sys.stderr)
        return 2
    return None
