"""Distributed execution: the TPU-native replacement for MXNet KVStore.

Reference (SURVEY.md §5.8): gradients were pushed per-array to
``kvstore='device'`` (in-process multi-GPU allreduce) or ``'dist_sync'``
(ps-lite parameter server) after backward, pulled before update.

Here data parallelism is SPMD over a ``jax.sharding.Mesh``: the batch is
sharded over the ``'data'`` axis, parameters are replicated, and the
gradient all-reduce is a ``lax.pmean`` over ICI fused *inside* the compiled
step — there is no host-driven sync phase at all.  Multi-host scaling uses
the same program over a larger mesh (DCN axis between slices).
"""

from mx_rcnn_tpu.parallel.dp import (  # noqa: F401
    device_mesh,
    make_dp_cached_step,
    make_dp_train_step,
    shard_batch,
    replicate,
)
