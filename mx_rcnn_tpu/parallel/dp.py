"""Data-parallel training over a device mesh.

Replaces MXNet ``kvstore='device'`` (ref ``train_end2end.py`` passes the
ctx list + kvstore into ``MutableModule.fit``; MXNet pushes/pulls each
gradient array through the KVStore).  Here the whole step — forward,
backward, ``lax.pmean`` gradient sync over ICI, SGD update — is one XLA
program per device, built with ``jax.shard_map`` over a 1-D ``'data'`` mesh:

* batch leaves are sharded on their leading (image) axis,
* params / optimizer state are replicated (every device applies the same
  psum-averaged update, so replicas stay bit-identical),
* per-image RNG is decorrelated across shards by folding in the device's
  mesh position.

``BATCH_IMAGES`` keeps the reference's per-device meaning (SURVEY.md §2:
"BATCH_IMAGES is per GPU"): a global batch of ``n_devices × batch_images``
feeds the mesh.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.core.train import Batch, TrainState, make_train_step
from mx_rcnn_tpu.models.faster_rcnn import FasterRCNN


def device_mesh(n_devices: Optional[int] = None,
                devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """1-D data-parallel mesh over the first ``n_devices`` devices."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), ("data",))


def replicate(tree, mesh: Mesh):
    """Place a pytree fully-replicated on the mesh."""
    sharding = NamedSharding(mesh, P())
    return jax.device_put(tree, sharding)


def shard_batch(batch: Batch, mesh: Mesh) -> Batch:
    """Shard every batch leaf along its leading (image) axis."""
    sharding = NamedSharding(mesh, P("data"))
    return jax.tree.map(lambda x: jax.device_put(x, sharding), batch)


def make_dp_train_step(model: FasterRCNN, cfg: Config, tx, mesh: Mesh,
                       mode: str = "e2e"):
    """Jitted SPMD train step over ``mesh``.

    Takes (replicated state, sharded batch, replicated key); returns
    (replicated state, replicated metrics).  Gradient sync is the
    ``lax.pmean('data')`` inside ``core.train.make_train_step``.
    """
    base = make_train_step(model, cfg, tx, axis_name="data", mode=mode)

    def shard_fn(state: TrainState, batch: Batch, key: jax.Array):
        # decorrelate per-image sampling RNG across mesh positions
        key = jax.random.fold_in(key, jax.lax.axis_index("data"))
        return base(state, batch, key)

    sharded = jax.shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P(), P("data"), P()),
        out_specs=(P(), P()),
        check_vma=False,  # RNG fold_in of axis_index is deliberately varying
    )
    # donate the replicated state: in-place HBM update, no per-step copy
    return jax.jit(sharded, donate_argnums=(0,))
