"""Data-parallel training over a device mesh.

Replaces MXNet ``kvstore='device'`` (ref ``train_end2end.py`` passes the
ctx list + kvstore into ``MutableModule.fit``; MXNet pushes/pulls each
gradient array through the KVStore).  Here the whole step — forward,
backward, ``lax.pmean`` gradient sync over ICI, SGD update — is one XLA
program per device, built with ``jax.shard_map`` over a 1-D ``('data',)``
mesh (single host/slice) or a 2-D ``('dcn', 'ici')`` mesh (multi-host, see
:func:`device_mesh`):

* batch leaves are sharded on their leading (image) axis,
* params / optimizer state are replicated (every device applies the same
  psum-averaged update, so replicas stay bit-identical),
* per-image RNG is decorrelated across shards by folding in the device's
  mesh position.

``BATCH_IMAGES`` keeps the reference's per-device meaning (SURVEY.md §2:
"BATCH_IMAGES is per GPU"): a global batch of ``n_devices × batch_images``
feeds the mesh.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.core.train import Batch, TrainState, make_train_step
from mx_rcnn_tpu.models.faster_rcnn import FasterRCNN


def shard_map_compat(f, *, mesh: Mesh, in_specs, out_specs):
    """``jax.shard_map`` across jax versions.

    New jax exposes ``jax.shard_map`` with ``check_vma``; 0.4.x has
    ``jax.experimental.shard_map.shard_map`` with ``check_rep``.  Both
    checks are disabled for the same reason: the RNG fold_in of
    ``axis_index`` is deliberately replica-varying.
    """
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_rep=False)


def device_mesh(n_devices: Optional[int] = None,
                devices: Optional[Sequence[jax.Device]] = None,
                dcn_size: int = 1) -> Mesh:
    """Data-parallel mesh over the first ``n_devices`` devices.

    ``dcn_size=1`` (single host/slice): 1-D ``('data',)`` mesh — gradient
    pmean rides ICI only.

    ``dcn_size>1`` (multi-host/multi-slice): 2-D ``('dcn', 'ici')`` mesh
    with the slow inter-host axis OUTERMOST, so XLA decomposes the gradient
    all-reduce hierarchically — reduce-scatter/all-gather inside each slice
    over ICI, then one small cross-slice all-reduce over DCN — instead of a
    flat ring across the slow links.  This is the scaling analog of the
    reference's unused ``kvstore='dist_sync'`` parameter server (SURVEY.md
    §5.8), expressed as mesh axes instead of a server process.  On a real
    multi-host deployment call ``jax.distributed.initialize()`` first and
    pass ``jax.devices()`` (globally ordered host-major, which matches the
    host-outermost reshape here).
    """
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    devices = np.asarray(devices)
    if dcn_size <= 1:
        return Mesh(devices, ("data",))
    if len(devices) % dcn_size != 0:
        raise ValueError(
            f"{len(devices)} devices not divisible by dcn_size={dcn_size}")
    return Mesh(devices.reshape(dcn_size, -1), ("dcn", "ici"))


def data_axes(mesh: Mesh):
    """The mesh axis name(s) the batch is sharded (and grads reduced) over."""
    return mesh.axis_names if len(mesh.axis_names) > 1 else mesh.axis_names[0]


def own_leaves(tree):
    """Force every numpy leaf into a PRIVATE jax-owned copy before it
    feeds a DONATING step.  On the CPU backend, device placement of a
    numpy array can be zero-copy — and a checkpoint restore
    (``flax.serialization.msgpack_restore``) hands back numpy leaves
    that are views of one shared buffer.  Donating such a buffer lets
    XLA recycle memory the host side still owns: the elastic storm
    caught epoch checkpoints committing float-garbage ``step`` values
    in the first generation trained after a live resize (docs/FT.md
    "Elasticity" — the third CPU aliasing bug in this family, after the
    two ``ft/`` found in PR 3).  ``jnp.array(..., copy=True)`` contracts
    a private copy; jax Arrays pass through untouched."""
    return jax.tree.map(
        lambda x: jnp.array(x, copy=True)
        if isinstance(x, np.ndarray) else x, tree)


def replicate(tree, mesh: Mesh):
    """Place a pytree fully-replicated on the mesh (numpy leaves forced
    to private jax-owned copies first — see :func:`own_leaves`; the DP
    step donates this state)."""
    sharding = NamedSharding(mesh, P())
    return jax.device_put(own_leaves(tree), sharding)


def shard_batch(batch: Batch, mesh: Mesh) -> Batch:
    """Shard every batch leaf along its leading (image) axis."""
    sharding = NamedSharding(mesh, P(data_axes(mesh)))
    return jax.tree.map(lambda x: jax.device_put(x, sharding), batch)


def stack_microbatches(batches: Sequence[Batch]):
    """Stack ``grad_accum`` consecutive loader batches into one
    accumulation batch: leaves ``(N, ...) -> (grad_accum, N, ...)``.
    Host-side numpy (the loader hands over host arrays); placement
    happens in :func:`shard_accum_batch` / the jitted step."""
    return jax.tree.map(lambda *xs: np.stack([np.asarray(x) for x in xs]),
                        *batches)


def shard_accum_batch(batch: Batch, mesh: Mesh) -> Batch:
    """Shard an accumulation batch (leading microbatch axis, images on
    axis 1): microbatches replicated in sequence, images sharded —
    ``P(None, data_axes)``, matching ``make_dp_train_step``'s
    ``grad_accum > 1`` in_spec."""
    sharding = NamedSharding(mesh, P(None, data_axes(mesh)))
    return jax.tree.map(lambda x: jax.device_put(x, sharding), batch)


def _folded_step(model: FasterRCNN, cfg: Config, tx, axes, mode: str,
                 grad_accum: int = 1):
    """The per-shard step body shared by the streaming and cached DP paths:
    decorrelates per-image sampling RNG across mesh positions.  For a 2-D
    (dcn, ici) mesh ``axis_index`` over both axes is the linearized
    position, so an N-device run gives identical per-image keys regardless
    of the mesh factorization."""
    base = make_train_step(model, cfg, tx, axis_name=axes, mode=mode,
                           grad_accum=grad_accum)

    # graphlint: jit (runs under shard_map built by the two factories below)
    def shard_fn(state: TrainState, batch: Batch, key: jax.Array):
        key = jax.random.fold_in(key, jax.lax.axis_index(axes))
        return base(state, batch, key)

    return shard_fn


def make_dp_train_step(model: FasterRCNN, cfg: Config, tx, mesh: Mesh,
                       mode: str = "e2e", grad_accum: int = 1):
    """Jitted SPMD train step over ``mesh``.

    Takes (replicated state, sharded batch, replicated key); returns
    (replicated state, replicated metrics).  Gradient sync is the
    ``lax.pmean`` over ALL of the mesh's axes (``'data'``, or
    ``('dcn', 'ici')`` for a hierarchical mesh) inside
    ``core.train.make_train_step``.

    ``grad_accum > 1`` (the elastic shrink path): the batch carries a
    leading microbatch axis — microbatches stay whole (``None``) and the
    image axis (now axis 1) shards, so each device accumulates over ITS
    slice of every microbatch and the pmean after accumulation yields the
    effective-global-batch gradient in one collective per optimizer step.
    """
    axes = data_axes(mesh)
    shard_fn = _folded_step(model, cfg, tx, axes, mode,
                            grad_accum=grad_accum)

    batch_spec = P(axes) if grad_accum <= 1 else P(None, axes)
    sharded = shard_map_compat(
        shard_fn,
        mesh=mesh,
        in_specs=(P(), batch_spec, P()),
        out_specs=(P(), P()),
    )
    # donate the replicated state: in-place HBM update, no per-step copy
    return jax.jit(sharded, donate_argnums=(0,))


def make_dp_cached_step(model: FasterRCNN, cfg: Config, tx, mesh: Mesh,
                        num_batches: int, shuffle: bool = True,
                        mode: str = "e2e"):
    """SPMD train step fed from a mesh-sharded HBM epoch cache
    (``data/device_cache.py`` with ``build_caches(..., mesh=mesh)``).

    Signature matches ``make_cached_step``'s wrapping —
    ``(replicated state, sharded epoch data, replicated idx, replicated
    key) -> (state, idx+1, metrics)`` — but runs under ``shard_map``: each
    device gathers ITS slice of the selected batch from its local shard
    (the epoch is laid out ``P(None, data_axes)``, so the image-granular
    gather stays shard-local — inside shard_map the leaves carry LOCAL
    shapes and the per-epoch regroup permutes each device's own images),
    RNG decorrelates per mesh position, and gradients pmean over all mesh
    axes inside the step.  The epoch permutation draws from the
    replicated key, so devices stay in lockstep.  Disclosed residual vs
    streaming DP: images never migrate across devices between epochs
    (data/device_cache.py module docstring).
    """
    from mx_rcnn_tpu.data.device_cache import make_cached_step

    axes = data_axes(mesh)
    cached = make_cached_step(_folded_step(model, cfg, tx, axes, mode),
                              num_batches, shuffle=shuffle)
    sharded = shard_map_compat(
        cached,
        mesh=mesh,
        in_specs=(P(), P(None, axes), P(), P()),
        out_specs=(P(), P(), P()),
    )
    return jax.jit(sharded, donate_argnums=(0, 2))
