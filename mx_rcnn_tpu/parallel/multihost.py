"""Multi-process (multi-host) data parallelism helpers.

Reference: MXNet ``kvstore='dist_sync'`` — the reference's parameter-server
pass-through for multi-machine training (SURVEY.md §5.8; present as a flag,
never exercised by its scripts).  The TPU-native equivalent is
``jax.distributed``: every host runs the SAME SPMD program over the global
``(dcn, ici)`` mesh (``parallel/dp.py — device_mesh``), gradients pmean
over both axes, and XLA routes the per-slice reduction over ICI and the
small cross-host exchange over DCN — no parameter server, no separate
communication library.

The pieces here are the host-boundary glue the single-process path does
not need: assembling process-local numpy shards into global arrays, and
replicating host-identical values (states initialized from the same seed)
across processes.  ``tools/multihost_demo.py`` wires them into a runnable
two-process demonstration on CPU devices; the same calls serve a real
multi-host TPU pod (one process per host, ``jax.distributed.initialize``
with the pod coordinator).
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.experimental import multihost_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mx_rcnn_tpu.parallel.dp import data_axes


def initialize(coordinator: str, num_processes: int, process_id: int,
               local_devices: Optional[int] = None) -> None:
    """``jax.distributed.initialize`` wrapper (one process per host).

    ``local_devices``: with CPU devices, pins the per-process device count
    (the multi-host-without-a-cluster test rig); on real TPU hosts leave
    None — the runtime discovers the local chips.
    """
    import os

    if local_devices is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count="
                f"{local_devices}").strip()
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        # CPU cross-process computations need a collectives backend; the
        # default CPU client has none and every multi-process program
        # fails with "Multiprocess computations aren't implemented on the
        # CPU backend".  Gloo ships in jaxlib; must be selected BEFORE
        # the backend initializes (harmless on TPU — guard on platform).
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except AttributeError:  # older jaxlib without the knob
            pass
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)


def global_mesh(dcn_size: Optional[int] = None) -> Mesh:
    """Hierarchical mesh over ALL processes' devices; ``dcn_size`` defaults
    to the process count (one slice per host — jax.devices() orders
    devices host-major, matching the host-outermost reshape)."""
    from mx_rcnn_tpu.parallel.dp import device_mesh

    if dcn_size is None:
        dcn_size = jax.process_count()
    return device_mesh(dcn_size=dcn_size)


def global_batch(batch, mesh: Mesh, accum: bool = False):
    """Assemble each process's LOCAL batch shard into global arrays sharded
    over the mesh's data axes (the multi-host analog of
    ``dp.shard_batch``).  Every process passes only its own images.

    ``accum=True``: the batch carries a leading microbatch axis
    (grad-accumulation — ft/elastic.py) and the image axis is axis 1, so
    the spec becomes ``P(None, data_axes)`` (the multi-host analog of
    ``dp.shard_accum_batch``)."""
    spec = P(None, data_axes(mesh)) if accum else P(data_axes(mesh))
    return jax.tree.map(
        lambda x: multihost_utils.host_local_array_to_global_array(
            np.asarray(x), mesh, spec),
        batch)


def local_image_slice(batch, accum: bool = False):
    """This process's contiguous slice of a GLOBAL batch's image axis
    (axis 0, or axis 1 for accumulation batches): processes iterate the
    same deterministic loader and each feeds rows
    ``[pid * per, (pid + 1) * per)`` into :func:`global_batch`.

    FALLBACK path since r7: it slices a batch every process fully
    DECODED, so decode work is duplicated N-fold.  The fit loop now
    prefers loader row shards (``data/loader.py — set_shard``, wired by
    ``tools/train.py`` from the process topology), where each process
    decodes only its own rows — same rows, same bytes, 1/N the decode
    (docs/DATA.md).  This slice remains for loaders without shard
    support; either way the assembled global batch is bit-identical to
    the single-process one, which is what keeps elastic resumes
    on-recipe."""
    pid, n = jax.process_index(), jax.process_count()
    axis = 1 if accum else 0

    def sl(x):
        x = np.asarray(x)
        per = x.shape[axis] // n
        idx = [slice(None)] * x.ndim
        idx[axis] = slice(pid * per, (pid + 1) * per)
        return x[tuple(idx)]

    return jax.tree.map(sl, batch)


def replicate_global(tree, mesh: Mesh):
    """Replicate host-identical values across every process/device (states
    initialized from one seed are bit-identical on every host — asserted
    cheaply via a checksum in the demo).

    Leaves route through a jax-OWNED single-device copy
    (``jnp.array(..., copy=True)``) before global assembly: restored
    states arrive as numpy views of one shared msgpack buffer, the DP
    step DONATES this tree, and ``host_local_array_to_global_array`` can
    zero-copy a host buffer WITHOUT holding a reference — passing it a
    temporary numpy copy segfaults once the copy is freed, and passing
    the caller's view risks donated-buffer aliasing (``parallel/dp.py —
    own_leaves`` has the full story).  Per-device buffers built from an
    owned jax array are safe on both counts."""
    import jax.numpy as jnp

    sharding = NamedSharding(mesh, P())

    def rep(x):
        owned = jnp.array(np.asarray(x), copy=True)
        local = [jax.device_put(owned, d) for d in mesh.local_devices]
        return jax.make_array_from_single_device_arrays(
            owned.shape, sharding, local)

    return jax.tree.map(rep, tree)
