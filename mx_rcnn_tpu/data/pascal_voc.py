"""PASCAL VOC dataset.

Reference: ``rcnn/dataset/pascal_voc.py — PascalVOC`` (XML annotation
parsing, comp4 detection-file writing, ``evaluate_detections``) and
``rcnn/dataset/pascal_voc_eval.py — voc_eval``.

``image_set`` is "<year>_<set>" (e.g. ``2007_trainval``, ``2007_test``) as
in the reference; the devkit layout is ``VOCdevkit/VOC<year>/{Annotations,
ImageSets/Main,JPEGImages}``.
"""

from __future__ import annotations

import os
import xml.etree.ElementTree as ET
from typing import Dict, List

import numpy as np

from mx_rcnn_tpu.data.roidb import IMDB, Roidb
from mx_rcnn_tpu.data.voc_eval import voc_ap, voc_eval

CLASSES = (
    "__background__",
    "aeroplane", "bicycle", "bird", "boat", "bottle", "bus", "car", "cat",
    "chair", "cow", "diningtable", "dog", "horse", "motorbike", "person",
    "pottedplant", "sheep", "sofa", "train", "tvmonitor",
)


class PascalVOC(IMDB):
    def __init__(self, image_set: str, root_path: str, dataset_path: str,
                 use_difficult: bool = False):
        year, sset = image_set.split("_", 1)
        super().__init__("voc_" + year, sset, root_path, dataset_path)
        self.year = year
        self.sset = sset
        self.classes = CLASSES
        self.use_difficult = use_difficult
        self.devkit_path = dataset_path
        self.voc_path = os.path.join(dataset_path, "VOC" + year)
        self.image_index = self._load_image_index()
        self.num_images = len(self.image_index)

    def _load_image_index(self) -> List[str]:
        index_file = os.path.join(self.voc_path, "ImageSets", "Main",
                                  self.sset + ".txt")
        with open(index_file) as f:
            return [line.strip().split()[0] for line in f if line.strip()]

    def image_path(self, index: str) -> str:
        return os.path.join(self.voc_path, "JPEGImages", index + ".jpg")

    def _annotation_path(self, index: str) -> str:
        return os.path.join(self.voc_path, "Annotations", index + ".xml")

    def _load_annotations(self) -> Roidb:
        roidb = []
        class_to_id = {c: i for i, c in enumerate(self.classes)}
        for index in self.image_index:
            tree = ET.parse(self._annotation_path(index))
            size = tree.find("size")
            width = int(size.find("width").text)
            height = int(size.find("height").text)
            boxes, classes = [], []
            for obj in tree.findall("object"):
                difficult = obj.find("difficult")
                if (not self.use_difficult and difficult is not None
                        and int(difficult.text) == 1):
                    continue
                name = obj.find("name").text.lower().strip()
                if name not in class_to_id:
                    continue
                bb = obj.find("bndbox")
                # ref: "Make pixel indexes 0-based"
                x1 = float(bb.find("xmin").text) - 1
                y1 = float(bb.find("ymin").text) - 1
                x2 = float(bb.find("xmax").text) - 1
                y2 = float(bb.find("ymax").text) - 1
                boxes.append([x1, y1, x2, y2])
                classes.append(class_to_id[name])
            roidb.append(dict(
                image=self.image_path(index),
                index=index,
                height=height,
                width=width,
                boxes=np.asarray(boxes, np.float32).reshape(-1, 4),
                gt_classes=np.asarray(classes, np.int32),
                flipped=False,
            ))
        return roidb

    # ---- evaluation (ref evaluate_detections → voc_eval) ------------------

    def _det_file(self, cls: str, out_dir: str) -> str:
        os.makedirs(out_dir, exist_ok=True)
        return os.path.join(out_dir, f"comp4_det_{self.sset}_{cls}.txt")

    def write_detections(self, all_boxes, out_dir: str) -> None:
        """Write per-class comp4 txt files:
        ``image_id score x1 y1 x2 y2`` with 1-based pixel coords."""
        for c, cls in enumerate(self.classes):
            if cls == "__background__":
                continue
            with open(self._det_file(cls, out_dir), "w") as f:
                for i, index in enumerate(self.image_index):
                    dets = all_boxes[c][i]
                    for k in range(len(dets)):
                        f.write(
                            f"{index} {dets[k, 4]:.6f} "
                            f"{dets[k, 0] + 1:.1f} {dets[k, 1] + 1:.1f} "
                            f"{dets[k, 2] + 1:.1f} {dets[k, 3] + 1:.1f}\n")

    def evaluate_detections(self, all_boxes, out_dir: str = None
                            ) -> Dict[str, float]:
        """Per-class 07-metric AP + mAP (ref evaluate_detections).

        ``all_boxes[class][image] = (k, 5)`` arrays.
        """
        use_07 = True  # ref uses the 11-point metric for VOC07
        if out_dir is not None:
            self.write_detections(all_boxes, out_dir)
        gt = {}
        for i, index in enumerate(self.image_index):
            rec = self._gt_for_eval(index)
            gt[index] = rec
        results = {}
        aps = []
        for c, cls in enumerate(self.classes):
            if cls == "__background__":
                continue
            dets = {
                self.image_index[i]: np.asarray(all_boxes[c][i]).reshape(-1, 5)
                for i in range(self.num_images)
            }
            ap = voc_eval(dets, gt, c, ovthresh=0.5, use_07_metric=use_07)
            results[cls] = ap
            aps.append(ap)
        results["mAP"] = float(np.mean(aps)) if aps else 0.0
        return results

    def _gt_for_eval(self, index: str):
        tree = ET.parse(self._annotation_path(index))
        boxes, classes, difficult = [], [], []
        class_to_id = {c: i for i, c in enumerate(self.classes)}
        for obj in tree.findall("object"):
            name = obj.find("name").text.lower().strip()
            if name not in class_to_id:
                continue
            d = obj.find("difficult")
            bb = obj.find("bndbox")
            boxes.append([float(bb.find("xmin").text) - 1,
                          float(bb.find("ymin").text) - 1,
                          float(bb.find("xmax").text) - 1,
                          float(bb.find("ymax").text) - 1])
            classes.append(class_to_id[name])
            difficult.append(int(d.text) if d is not None else 0)
        return dict(
            boxes=np.asarray(boxes, np.float32).reshape(-1, 4),
            gt_classes=np.asarray(classes, np.int32),
            difficult=np.asarray(difficult, bool),
        )
