"""Image IO and geometric transforms.

Reference: ``rcnn/io/image.py`` — ``get_image`` (cv2 imread + flip via
roidb flag), ``resize`` (short side to SCALES[0]=600, long side capped at
1000), ``transform`` (BGR→RGB, subtract PIXEL_MEANS, HWC→CHW) and
``tensor_vstack`` (pad to per-batch max shape).

TPU-native: layout stays NHWC (HWC per image); padding targets one of the
static shape buckets from ``BucketConfig`` rather than the batch max, so
every batch compiles to one of a handful of XLA programs.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import numpy as np

try:
    import cv2

    _HAS_CV2 = True
except Exception:  # pragma: no cover - cv2 is present in the image
    from PIL import Image

    _HAS_CV2 = False


def imread_rgb(path: str) -> np.ndarray:
    """Read an image file as RGB uint8 (H, W, 3)."""
    if _HAS_CV2:
        img = cv2.imread(path, cv2.IMREAD_COLOR)
        if img is None:
            raise FileNotFoundError(f"cannot read image {path!r}")
        return img[:, :, ::-1]  # BGR → RGB (ref transform does the same)
    with Image.open(path) as im:  # pragma: no cover
        return np.asarray(im.convert("RGB"))


def compute_scale(h: int, w: int, target_size: int, max_size: int) -> float:
    """The reference resize rule (``rcnn/io/image.py — resize``): scale so
    the short side hits ``target_size`` unless that pushes the long side
    past ``max_size``.  Single source of truth — the loader predicts bucket
    membership with the same formula the resize applies."""
    short, long = min(h, w), max(h, w)
    scale = float(target_size) / short
    if round(scale * long) > max_size:
        scale = float(max_size) / long
    return scale


def resize_keep_ratio(img: np.ndarray, target_size: int, max_size: int
                      ) -> Tuple[np.ndarray, float]:
    """Scale so the short side is ``target_size`` without the long side
    exceeding ``max_size`` (ref ``rcnn/io/image.py — resize``).

    Returns (resized image, scale factor).
    """
    h, w = img.shape[:2]
    scale = compute_scale(h, w, target_size, max_size)
    new_w, new_h = int(round(w * scale)), int(round(h * scale))
    if _HAS_CV2:
        # BGR→RGB and flips arrive as negative-stride views; cv2 needs
        # contiguous input
        out = cv2.resize(np.ascontiguousarray(img), (new_w, new_h),
                         interpolation=cv2.INTER_LINEAR)
    else:  # pragma: no cover
        out = np.asarray(Image.fromarray(img).resize((new_w, new_h)))
    return out, scale


def bucket_fit(h: int, w: int, bucket: Tuple[int, int]) -> float:
    """Shrink factor that makes an (h, w) resized image fit ``bucket``
    (1.0 when it already fits).  Single source of truth shared by the
    decode path (:func:`load_resized_uint8`) and the cache's scale
    predictor (``data/cache.py — plan_scale``) so the two can never
    desync (advisor r3)."""
    bh, bw = bucket
    if h > bh or w > bw:
        return min(bh / h, bw / w)
    return 1.0


def choose_bucket(h: int, w: int, buckets: Sequence[Tuple[int, int]]
                  ) -> Tuple[int, int]:
    """Pick the smallest bucket that fits (h, w); falls back to the bucket
    with the matching orientation (ref ASPECT_GROUPING maps wide/tall images
    to landscape/portrait groups)."""
    fitting = [b for b in buckets if b[0] >= h and b[1] >= w]
    if fitting:
        return min(fitting, key=lambda b: b[0] * b[1])
    # no bucket fits (shouldn't happen with ref scales 600/1000) — take the
    # same-orientation bucket; caller will downscale to fit
    landscape = w >= h
    same = [b for b in buckets if (b[1] >= b[0]) == landscape]
    return max(same or buckets, key=lambda b: b[0] * b[1])


def estimate_bucket(h: int, w: int, scale: int, max_size: int,
                    buckets: Sequence[Tuple[int, int]]) -> Tuple[int, int]:
    """Dims-only bucket estimate (no pixel work): the bucket an (h, w)
    image serves in after ``resize_keep_ratio``.  One helper shared by
    the serving engine's admission pre-check and the fleet router's
    lane choice, so the two can never disagree on where a request
    queues."""
    s = compute_scale(h, w, scale, max_size)
    return choose_bucket(int(round(h * s)), int(round(w * s)), buckets)


def load_resized_uint8(
    path: str,
    flipped: bool,
    scale: int,
    max_size: int,
    bucket: Tuple[int, int],
) -> Tuple[np.ndarray, float]:
    """Decode → flip → resize (→ shrink-to-fit the bucket), staying uint8.

    Returns an UNPADDED contiguous (h, w, 3) uint8 RGB image with
    ``h <= bucket[0]`` and ``w <= bucket[1]``, plus ``im_scale``.  This is
    the cacheable half of the host pipeline: everything downstream (pad,
    normalize) is either a memcpy or runs on device
    (``ops/normalize.py``)."""
    # stay uint8 through decode/flip/resize (cv2 resizes uint8 ~3x faster
    # than fp32 and the arrays are 4x smaller)
    img = imread_rgb(path)
    if flipped:
        img = img[:, ::-1, :]
    img, im_scale = resize_keep_ratio(img, scale, max_size)
    h, w = img.shape[:2]
    fit = bucket_fit(h, w, bucket)
    if fit != 1.0:  # bucket smaller than resize target: shrink to fit
        new_w, new_h = int(w * fit), int(h * fit)
        if _HAS_CV2:
            img = cv2.resize(img, (new_w, new_h))
        else:  # pragma: no cover
            img = np.asarray(Image.fromarray(np.ascontiguousarray(img)
                                             ).resize((new_w, new_h)))
        im_scale *= fit
    return np.ascontiguousarray(img), im_scale


def pad_normalize(img: np.ndarray, pixel_means: Sequence[float],
                  bucket: Tuple[int, int]) -> np.ndarray:
    """Unpadded (h, w, 3) uint8 RGB → padded (bh, bw, 3) fp32 mean-
    subtracted bucket canvas.  THE single pad+normalize step: both
    preprocessing tails (:func:`load_and_transform`,
    :func:`resize_to_bucket`) and the v2 wire's agent-side admission
    (``serve/remote.py`` u8 source frames) call this one function, so a
    canvas built on the head and a canvas built by an agent from the
    same u8 pixels are BIT-identical by construction — not by two code
    paths happening to agree (pinned by tests/test_wire_v2.py)."""
    img = np.asarray(img)
    h, w = img.shape[:2]
    bh, bw = bucket
    if h > bh or w > bw:
        raise ValueError(f"image ({h}, {w}) does not fit bucket "
                         f"({bh}, {bw})")
    out = np.zeros((bh, bw, 3), dtype=np.float32)
    # the fp32 cast fuses with the mean subtraction into the padded output
    # buffer (device-side normalization via ops/normalize.py computes the
    # identical float32 values)
    np.subtract(img, np.asarray(pixel_means, dtype=np.float32),
                out=out[:h, :w], casting="unsafe")
    return out


def load_and_transform(
    path: str,
    flipped: bool,
    pixel_means: Sequence[float],
    scale: int,
    max_size: int,
    bucket: Tuple[int, int],
) -> Tuple[np.ndarray, float]:
    """Full per-image host pipeline: read → flip → resize → mean-subtract →
    pad into the bucket.  Returns ((bh, bw, 3) fp32 image, im_scale)."""
    img, im_scale = load_resized_uint8(path, flipped, scale, max_size, bucket)
    return pad_normalize(img, pixel_means, bucket), im_scale


def resize_to_bucket(img: np.ndarray, pixel_means: Sequence[float], scale: int,
                     max_size: int, buckets: Sequence[Tuple[int, int]]
                     ) -> Tuple[np.ndarray, float, Tuple[int, int]]:
    """In-memory variant of :func:`load_and_transform` (demo path): same
    uint8-resize → fused subtract/cast pipeline, so demo preprocessing is
    pixel-identical to the train/eval loader."""
    resized, im_scale = resize_keep_ratio(np.asarray(img), scale, max_size)
    h, w = resized.shape[:2]
    bucket = choose_bucket(h, w, buckets)
    fit = bucket_fit(h, w, bucket)
    if fit != 1.0:  # bucket smaller than resize target: shrink to fit,
        # same step as load_resized_uint8 (choose_bucket's contract)
        new_w, new_h = int(w * fit), int(h * fit)
        if _HAS_CV2:
            resized = cv2.resize(resized, (new_w, new_h))
        else:  # pragma: no cover
            resized = np.asarray(
                Image.fromarray(np.ascontiguousarray(resized))
                .resize((new_w, new_h)))
        im_scale *= fit
        h, w = resized.shape[:2]
    return pad_normalize(resized, pixel_means, bucket), im_scale, bucket
