"""COCO dataset.

Reference: ``rcnn/dataset/coco.py — coco(IMDB)`` backed by the vendored
``rcnn/pycocotools``.  pycocotools is unavailable here, so annotation
loading is plain-json (the instances_*.json schema) and evaluation uses the
NumPy reimplementation in ``coco_eval.py``.  Category ids are remapped to
contiguous class ids 1..80 with 0 = background (81 classes total), as the
reference does.
"""

from __future__ import annotations

import json
import os
from collections import defaultdict
from typing import Dict, List

import numpy as np

from mx_rcnn_tpu.data.coco_eval import evaluate_bbox
from mx_rcnn_tpu.data.roidb import IMDB, Roidb


class COCODataset(IMDB):
    def __init__(self, image_set: str, root_path: str, dataset_path: str):
        super().__init__("coco", image_set, root_path, dataset_path)
        self.ann_file = os.path.join(
            dataset_path, "annotations", f"instances_{image_set}.json")
        self.image_dir = os.path.join(dataset_path, image_set)
        self._load_index()

    def _load_index(self) -> None:
        with open(self.ann_file) as f:
            ann = json.load(f)
        cats = sorted(ann["categories"], key=lambda c: c["id"])
        self.classes = ["__background__"] + [c["name"] for c in cats]
        self.cat_ids = [c["id"] for c in cats]
        self.cat_to_class = {cid: i + 1 for i, cid in enumerate(self.cat_ids)}
        self.images = {im["id"]: im for im in ann["images"]}
        self.image_index = sorted(self.images.keys())
        self.num_images = len(self.image_index)
        self.anns_by_image: Dict[int, List[dict]] = defaultdict(list)
        for a in ann.get("annotations", []):
            self.anns_by_image[a["image_id"]].append(a)

    def image_path(self, image_id: int) -> str:
        return os.path.join(self.image_dir, self.images[image_id]["file_name"])

    def _load_annotations(self) -> Roidb:
        roidb = []
        for image_id in self.image_index:
            info = self.images[image_id]
            w, h = info["width"], info["height"]
            boxes, classes = [], []
            for a in self.anns_by_image.get(image_id, []):
                if a.get("iscrowd", 0):
                    continue
                x, y, bw, bh = a["bbox"]  # COCO xywh → xyxy, clipped
                x1 = max(0.0, x)
                y1 = max(0.0, y)
                x2 = min(w - 1.0, x + max(0.0, bw - 1))
                y2 = min(h - 1.0, y + max(0.0, bh - 1))
                if a.get("area", bw * bh) > 0 and x2 >= x1 and y2 >= y1:
                    boxes.append([x1, y1, x2, y2])
                    classes.append(self.cat_to_class[a["category_id"]])
            roidb.append(dict(
                image=self.image_path(image_id),
                index=image_id,
                height=h,
                width=w,
                boxes=np.asarray(boxes, np.float32).reshape(-1, 4),
                gt_classes=np.asarray(classes, np.int32),
                flipped=False,
            ))
        return roidb

    def evaluate_detections(self, all_boxes, out_dir: str = None
                            ) -> Dict[str, float]:
        """COCO bbox AP@[.5:.95] etc. (ref: results json → COCOeval)."""
        dets = {}
        gts = {}
        for i, image_id in enumerate(self.image_index):
            per_cat_d = {}
            for c in range(1, self.num_classes):
                d = np.asarray(all_boxes[c][i]).reshape(-1, 5)
                if len(d):
                    per_cat_d[c] = d
            dets[image_id] = per_cat_d
            per_cat_g: Dict[int, dict] = {}
            for a in self.anns_by_image.get(image_id, []):
                c = self.cat_to_class[a["category_id"]]
                x, y, bw, bh = a["bbox"]
                entry = per_cat_g.setdefault(
                    c, {"boxes": [], "iscrowd": [], "area": []})
                entry["boxes"].append([x, y, x + bw, y + bh])
                entry["iscrowd"].append(bool(a.get("iscrowd", 0)))
                entry["area"].append(a.get("area", bw * bh))
            gts[image_id] = {
                c: {k: np.asarray(v) for k, v in e.items()}
                for c, e in per_cat_g.items()
            }
        if out_dir:
            os.makedirs(out_dir, exist_ok=True)
            self._write_results_json(all_boxes, out_dir)
        return evaluate_bbox(dets, gts, list(range(1, self.num_classes)))

    def ann_rle(self, a: dict, image_id: int) -> dict:
        """An annotation's segmentation as a ``native`` RLE dict.

        Handles all three COCO encodings (ref ``pycocotools.coco — annToRLE``):
        polygon lists (union of ``from_poly`` fills), uncompressed RLE
        (counts as an int list), and compressed RLE (counts string); falls
        back to the bbox rectangle when no segmentation is present.
        """
        from mx_rcnn_tpu import native

        info = self.images[image_id]
        h, w = info["height"], info["width"]
        seg = a.get("segmentation")
        if isinstance(seg, list) and seg:
            return native.merge(
                [native.from_poly(p, h, w) for p in seg])
        if isinstance(seg, dict):
            counts = seg["counts"]
            if isinstance(counts, list):  # uncompressed (crowd) RLE
                return native.from_uncompressed(seg["size"], counts)
            if isinstance(counts, str):
                counts = counts.encode()
            return {"size": list(seg["size"]), "counts": counts}
        x, y, bw, bh = a["bbox"]
        return native.from_bbox([x, y, bw, bh], h, w)

    def evaluate_segmentations(self, dets_by_image_cat,
                               out_dir: str = None) -> Dict[str, float]:
        """COCO segm-mode AP over mask detections (the vendored
        pycocotools' iouType='segm' path).

        ``dets_by_image_cat``: image id → {class id → list of (rle, score)
        pairs} in ``native`` RLE format.  Ground-truth masks come from the
        annotations via :meth:`ann_rle` (crowds included as ignore
        regions).  Returns the same metric dict as bbox eval.
        """
        from mx_rcnn_tpu.data.coco_eval import evaluate_segm

        gts: Dict[int, dict] = {}
        for image_id in self.image_index:
            per_cat: Dict[int, dict] = {}
            for a in self.anns_by_image.get(image_id, []):
                c = self.cat_to_class[a["category_id"]]
                e = per_cat.setdefault(c, {"rles": [], "iscrowd": [],
                                           "area": []})
                e["rles"].append(self.ann_rle(a, image_id))
                e["iscrowd"].append(bool(a.get("iscrowd", 0)))
                bw, bh = a["bbox"][2], a["bbox"][3]
                e["area"].append(a.get("area", bw * bh))
            gts[image_id] = {
                c: {"rles": e["rles"],
                    "iscrowd": np.asarray(e["iscrowd"], bool),
                    "area": np.asarray(e["area"], float)}
                for c, e in per_cat.items()
            }
        return evaluate_segm(dets_by_image_cat, gts,
                             list(range(1, self.num_classes)))

    def _write_results_json(self, all_boxes, out_dir: str) -> None:
        """Standard COCO results format (xywh), ref coco results dumping."""
        results = []
        class_to_cat = {v: k for k, v in self.cat_to_class.items()}
        for i, image_id in enumerate(self.image_index):
            for c in range(1, self.num_classes):
                for d in np.asarray(all_boxes[c][i]).reshape(-1, 5):
                    results.append({
                        "image_id": int(image_id),
                        "category_id": int(class_to_cat[c]),
                        "bbox": [float(d[0]), float(d[1]),
                                 float(d[2] - d[0]), float(d[3] - d[1])],
                        "score": float(d[4]),
                    })
        with open(os.path.join(out_dir, "detections_results.json"), "w") as f:
            json.dump(results, f)
