"""Double-buffered host→device staging for the training input plane.

Reference: none — the reference's synchronous loader hands host arrays to
``module.forward`` and eats the transfer inside the step.  Pre-r7 this
framework did the JAX analog: the fit loop called ``next(batch_iter)``
(host numpy, overlapped assembly via ``loader._prefetched``) and let jit
argument transfer move the bytes host→device INSIDE the step dispatch —
so every step paid the transfer on the critical path, and the only way
``data_wait_frac ~ 0`` held was the HBM-resident epoch cache
(``data/device_cache.py``), which requires the dataset to fit in device
memory (the docs/PERF.md "HBM-resident" asterisk).

:class:`DeviceStager` removes that requirement: ONE daemon thread pulls
assembled batches from the source iterator, applies ``place`` (a plain
``jax.device_put`` on a single device, or the mesh sharding placement
from ``parallel/dp.py``) and keeps up to ``depth`` DEVICE-RESIDENT
batches in a bounded queue.  The fit loop's ``next()`` then returns an
already-placed batch in ~queue-pop time; assembly and transfer of batch
k+1 overlap step k.  ``depth=2`` is classic double buffering; each slot
costs one batch of device memory (uint8 images keep that small).

Semantics are strictly pass-through: same batches, same order, same
values — the stager only changes WHERE the ``device_put`` happens
(pinned by tests/test_streaming.py).  Exceptions from the source
iterator or the placement re-raise in the consumer; early abandonment
(consumer ``close()``) releases the worker without draining the epoch.

Obs (``cfg.obs.enabled``): ``loader.staged_batches`` counts placed
batches, ``loader.stage_put_ms`` the worker-side assemble+place time,
``loader.stage_hits``/``loader.stage_misses`` whether the consumer found
a batch ready (hit = the overlap did its job; the data-smoke gate
asserts hits > 0), and the ``loader.stage_depth`` gauge the occupancy
at each pop.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Iterable, Iterator, Optional

_END = object()


class DeviceStager:
    """Stage ``place(batch)`` results from a background thread, ``depth``
    batches ahead of the consumer.

    Args:
      source: iterable of host batches (a loader iterator, possibly
        wrapped in grad-accum stacking).
      place: host batch -> device-resident batch (``jax.device_put`` or a
        mesh-sharding placement).  Runs ONLY on the worker thread.
      depth: max device-resident batches in flight (>= 1).
      rec: an ``obs/metrics.py`` Registry, or None (the default) to keep
        the hot path metric-free.
    """

    def __init__(self, source: Iterable, place: Callable, depth: int = 2,
                 rec=None):
        self._q: "queue.Queue" = queue.Queue(maxsize=max(int(depth), 1))
        self._rec = rec
        self._closed = False
        self._thread = threading.Thread(
            target=self._run, args=(iter(source), place),
            name="device-stager", daemon=True)
        self._thread.start()

    def _run(self, it: Iterator, place: Callable) -> None:
        try:
            while not self._closed:
                t0 = time.perf_counter()
                try:
                    batch = next(it)
                except StopIteration:
                    break
                placed = place(batch)
                if self._rec is not None:
                    self._rec.inc("loader.staged_batches")
                    self._rec.observe("loader.stage_put_ms",
                                      (time.perf_counter() - t0) * 1e3)
                self._put(placed)
        except BaseException as e:  # noqa: BLE001 — re-raised in consumer
            self._put(e)
            return
        self._put(_END)

    def _put(self, item) -> None:
        # bounded put that gives up when the consumer closed mid-epoch —
        # a plain blocking put would leave the thread wedged forever on a
        # full queue nobody drains
        while not self._closed:
            try:
                self._q.put(item, timeout=0.1)
                return
            except queue.Full:
                continue

    def __iter__(self) -> Iterator:
        while True:
            if self._rec is not None:
                try:
                    item = self._q.get_nowait()
                    waited = False
                except queue.Empty:
                    item = self._q.get()
                    waited = True
            else:
                item = self._q.get()
            if item is _END:
                return
            if isinstance(item, BaseException):
                raise item
            if self._rec is not None:
                # counted only for REAL batches: the end-of-epoch
                # sentinel pop must not skew the hit rate
                self._rec.inc("loader.stage_misses" if waited
                              else "loader.stage_hits")
                self._rec.set_gauge("loader.stage_depth", self._q.qsize())
            yield item

    def close(self) -> None:
        """Release the worker (early abandonment or normal epoch end);
        idempotent.  Queued device batches are dropped on the floor —
        device buffers free with their last reference."""
        self._closed = True
        while True:  # unblock a worker parked on a full queue
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._thread.join(timeout=5.0)
