"""HBM-resident epoch cache: feed training entirely from device memory.

No reference equivalent — the reference streams every batch host→device
each step (``rcnn/core/loader.py`` + MXNet IO), which is the right call
when the interconnect is PCIe and the host has cores to spare.  On a
TPU fed through a high-latency link (a tunneled dev chip, or a weak host
in general), per-step transfers dominate: every host→device RPC costs a
round trip, and a 27 ms train step cannot hide a ~40-80 ms transfer
latency.

The TPU-native answer for RAM-scale datasets (benchmarks, VOC-sized sets,
synthetic suites): stage ONE epoch of already-assembled batches in HBM
(uint8 images keep it 4x smaller — 32 batches of 2x608x1024 ≈ 120 MB),
then let each step GATHER its batch from the resident buffer with an
index derived on device.  Steady-state host↔device traffic per step: one
dispatch RPC, zero data bytes.  Measured on the tunneled v5e chip this
takes sustained training from 9.5 to 69.5 imgs/s — 0.95x the pure device
rate (see docs/PERF.md).

Shuffle semantics (r5 — closes the r2-r4 disclosed deviation): the epoch
is staged as batches but gathered at IMAGE granularity — each step slices
``batch_images`` image indices out of a per-epoch on-device permutation
of ALL images, so batch COMPOSITION re-randomizes every epoch exactly
like the streaming loader's in-bucket regrouping (rounds 2-4 permuted
batch ORDER only, with composition frozen at staging).  ``shuffle=False``
replays the staged batches verbatim (bitwise contract vs streaming).
Residual deviation (multi-chip only, disclosed): the mesh layout shards
each staged batch's image axis, so regrouping happens WITHIN a device's
shard — images never migrate across devices between epochs, where the
streaming path's global regroup would move them.  Single-chip semantics
are now exactly the streaming loader's.
"""

from __future__ import annotations

from typing import Callable, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class DeviceEpochCache:
    """One bucket's epoch of batches, stacked and resident on device.

    ``data`` is the batch pytree with a leading ``num_batches`` axis.
    Multi-bucket datasets build one cache per bucket
    (:func:`build_caches`).
    """

    def __init__(self, batches: List, device=None):
        if not batches:
            raise ValueError("empty batch list")
        shapes = {tuple(b.images.shape) for b in batches}
        if len(shapes) > 1:
            raise ValueError(f"mixed bucket shapes in one cache: {shapes}")
        stacked = jax.tree.map(lambda *xs: np.stack(xs), *batches)
        self.num_batches = len(batches)
        # ``device`` may be a Device or a Sharding (multi-chip: shard each
        # batch's image axis over the mesh — axis 1 of the stacked layout)
        self.data = (jax.device_put(stacked, device) if device is not None
                     else jax.device_put(stacked))
        self.nbytes = sum(x.nbytes for x in jax.tree.leaves(stacked))

    def index_handle(self) -> jnp.ndarray:
        """A fresh device-resident step counter for :func:`make_cached_step`
        (int32 scalar; carried through the step so the host never ships an
        index)."""
        return jnp.zeros((), jnp.int32)


def build_caches(loader, max_bytes: int = 4 << 30,
                 mesh=None) -> List[DeviceEpochCache]:
    """Materialize one epoch from ``loader`` and upload it, grouped by
    bucket shape.  Raises if the epoch exceeds ``max_bytes`` (caller falls
    back to the streaming loader).  With ``mesh``, each batch's image axis
    is sharded over the mesh's data axes (every device holds its slice of
    every batch — the multi-chip layout for :func:`make_dp_cached_step`),
    and ``max_bytes`` bounds the PER-DEVICE footprint."""
    placement = None
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        from mx_rcnn_tpu.parallel.dp import data_axes

        placement = NamedSharding(mesh, P(None, data_axes(mesh)))
        max_bytes *= mesh.size
    by_shape = {}
    total = 0
    for b in loader:
        by_shape.setdefault(tuple(b.images.shape), []).append(b)
        total += sum(x.nbytes for x in jax.tree.leaves(b))
        if total > max_bytes:
            raise MemoryError(
                f"epoch exceeds device cache budget ({total} > {max_bytes} "
                f"bytes); use the streaming loader")
    return [DeviceEpochCache(bs, device=placement)
            for bs in by_shape.values()]


def make_cached_step(base_step: Callable, num_batches: int,
                     shuffle: bool = True) -> Callable:
    """Wrap a ``(state, batch, key) -> (state, metrics)`` train step into a
    ``(state, data, idx, key) -> (state, idx', metrics)`` step that gathers
    its batch from a resident :class:`DeviceEpochCache` epoch.

    ``idx`` is the cache's device-resident step counter
    (:meth:`DeviceEpochCache.index_handle`).  With ``shuffle`` the batch
    at position ``p = idx % num_batches`` of epoch ``e`` is the images at
    ``perm_e[p*bi : (p+1)*bi]`` for a per-epoch device permutation of ALL
    staged images — composition re-randomizes every epoch (module
    docstring); ``shuffle=False`` replays staged batch ``p`` verbatim.
    Jit with ``donate_argnums=(0, 2)`` — state and counter update in
    place; the epoch data is a non-donated resident buffer.
    """

    def step(state, data, idx, key):
        pos = jnp.mod(idx, num_batches)
        if shuffle:
            epoch = idx // num_batches
            # tag the permutation stream so it never collides with the
            # train step's fold_in(key, state.step) stream (epoch e and
            # step s=e would otherwise share a key)
            perm_key = jax.random.fold_in(
                jax.random.fold_in(key, 0x5A5A5A5), epoch)
            # IMAGE-granular gather: slice this step's batch_images out
            # of a per-epoch permutation of all images, so composition
            # re-randomizes each epoch (module docstring).  Leaf shapes
            # are (num_batches, bi, ...) — under shard_map these are the
            # LOCAL shapes, so the flatten+gather stays shard-local.
            bi = jax.tree.leaves(data)[0].shape[1]
            perm = jax.random.permutation(perm_key, num_batches * bi)
            img_idx = jax.lax.dynamic_slice(perm, (pos * bi,), (bi,))
            batch = jax.tree.map(
                lambda x: x.reshape((x.shape[0] * x.shape[1],)
                                    + x.shape[2:])[img_idx],
                data)
        else:
            batch = jax.tree.map(
                lambda x: jax.lax.dynamic_index_in_dim(x, pos,
                                                       keepdims=False),
                data)
        new_state, metrics = base_step(state, batch, key)
        return new_state, idx + 1, metrics

    return step
