"""Synthetic detection dataset: colored rectangles on textured backgrounds.

No reference equivalent — the reference assumes VOC/COCO downloads.  This
dataset generates deterministic images on first use (cached as PNGs under
``root_path``) so the whole pipeline — loader, training, eval, demo — runs
end-to-end on a machine with no datasets.  Class k draws rectangles with a
class-specific color, so the task is learnable to high mAP and serves as an
integration-level correctness check of the entire framework.
"""

from __future__ import annotations

import os
from typing import Dict, List

import numpy as np

from mx_rcnn_tpu.data.roidb import IMDB, Roidb
from mx_rcnn_tpu.data.voc_eval import voc_eval


def _class_color(c: int) -> np.ndarray:
    rng = np.random.RandomState(1234 + c)
    return rng.randint(40, 255, size=3).astype(np.uint8)


class SyntheticDataset(IMDB):
    def __init__(self, image_set: str, root_path: str, dataset_path: str,
                 num_images: int = 32, num_classes: int = 21,
                 image_size=(320, 400), max_objects: int = 4):
        super().__init__("synthetic", image_set, root_path,
                         dataset_path or os.path.join(root_path, "synthetic"))
        self.classes = ["__background__"] + [
            f"class{i}" for i in range(1, num_classes)]
        self.num_images = num_images
        self.image_size = image_size
        self.max_objects = max_objects
        seed = abs(hash(image_set)) % (2 ** 31)
        self._rng = np.random.RandomState(seed)
        self.image_dir = os.path.join(self.data_path, self.image_set)
        self._specs = self._make_specs()
        self.image_index = list(range(num_images))

    def _make_specs(self) -> List[Dict]:
        h, w = self.image_size
        specs = []
        for i in range(self.num_images):
            n = self._rng.randint(1, self.max_objects + 1)
            boxes, classes = [], []
            for _ in range(n):
                # object sizes scale with the canvas so tiny test images work
                bw = self._rng.randint(max(16, w // 5), max(17, w // 2))
                bh = self._rng.randint(max(16, h // 5), max(17, h // 2))
                x1 = self._rng.randint(0, w - bw)
                y1 = self._rng.randint(0, h - bh)
                boxes.append([x1, y1, x1 + bw - 1, y1 + bh - 1])
                classes.append(self._rng.randint(1, self.num_classes))
            specs.append(dict(
                boxes=np.asarray(boxes, np.float32),
                gt_classes=np.asarray(classes, np.int32),
                noise_seed=int(self._rng.randint(0, 2 ** 31)),
            ))
        return specs

    def _render(self, spec: Dict) -> np.ndarray:
        h, w = self.image_size
        rng = np.random.RandomState(spec["noise_seed"])
        img = rng.randint(0, 60, size=(h, w, 3)).astype(np.uint8)
        for box, cls in zip(spec["boxes"], spec["gt_classes"]):
            x1, y1, x2, y2 = box.astype(int)
            img[y1:y2 + 1, x1:x2 + 1] = _class_color(int(cls))
        return img

    def image_path(self, i: int) -> str:
        return os.path.join(self.image_dir, f"{self.image_set}_{i:05d}.png")

    def _materialize(self) -> None:
        os.makedirs(self.image_dir, exist_ok=True)
        for i, spec in enumerate(self._specs):
            path = self.image_path(i)
            if not os.path.exists(path):
                img = self._render(spec)
                try:
                    import cv2

                    cv2.imwrite(path, img[:, :, ::-1])
                except Exception:  # pragma: no cover
                    from PIL import Image

                    Image.fromarray(img).save(path)

    def _load_annotations(self) -> Roidb:
        self._materialize()
        h, w = self.image_size
        return [
            dict(
                image=self.image_path(i),
                index=i,
                height=h,
                width=w,
                boxes=spec["boxes"].copy(),
                gt_classes=spec["gt_classes"].copy(),
                flipped=False,
            )
            for i, spec in enumerate(self._specs)
        ]

    def gt_roidb(self) -> Roidb:
        # no pkl cache: generation is deterministic and instant
        return self._load_annotations()

    def evaluate_detections(self, all_boxes, out_dir: str = None
                            ) -> Dict[str, float]:
        gt = {
            i: dict(
                boxes=spec["boxes"],
                gt_classes=spec["gt_classes"],
                difficult=np.zeros(len(spec["boxes"]), bool),
            )
            for i, spec in enumerate(self._specs)
        }
        aps = []
        results = {}
        for c in range(1, self.num_classes):
            dets = {
                i: np.asarray(all_boxes[c][i]).reshape(-1, 5)
                for i in range(self.num_images)
            }
            has_gt = any((g["gt_classes"] == c).any() for g in gt.values())
            if not has_gt:
                continue
            ap = voc_eval(dets, gt, c, ovthresh=0.5, use_07_metric=True)
            results[self.classes[c]] = ap
            aps.append(ap)
        results["mAP"] = float(np.mean(aps)) if aps else 0.0
        return results
