"""Synthetic detection dataset: colored rectangles on textured backgrounds.

No reference equivalent — the reference assumes VOC/COCO downloads.  This
dataset generates deterministic images on first use (cached as PNGs under
``root_path``) so the whole pipeline — loader, training, eval, demo — runs
end-to-end on a machine with no datasets.  Class k draws rectangles with a
class-specific color, so the task is learnable to high mAP and serves as an
integration-level correctness check of the entire framework.
"""

from __future__ import annotations

import os
import zlib
from typing import Dict, List

import numpy as np

from mx_rcnn_tpu.data.roidb import IMDB, Roidb
from mx_rcnn_tpu.data.voc_eval import voc_eval


def _class_color(c: int) -> np.ndarray:
    rng = np.random.RandomState(1234 + c)
    return rng.randint(40, 255, size=3).astype(np.uint8)


class SyntheticDataset(IMDB):
    def __init__(self, image_set: str, root_path: str, dataset_path: str,
                 num_images: int = None, num_classes: int = 4,
                 image_size=(320, 400), max_objects: int = 3):
        if num_images is None:
            num_images = 64 if "train" in image_set else 16
        super().__init__("synthetic", image_set, root_path,
                         dataset_path or os.path.join(root_path, "synthetic"))
        self.classes = ["__background__"] + [
            f"class{i}" for i in range(1, num_classes)]
        self.num_images = num_images
        self.image_size = image_size
        self.max_objects = max_objects
        # stable across processes (str hash() is PYTHONHASHSEED-randomized,
        # which would regenerate different images each run and desync any
        # cached PNGs from the in-memory ground truth)
        seed = zlib.crc32(image_set.encode()) % (2 ** 31)
        self._rng = np.random.RandomState(seed)
        self.image_dir = os.path.join(self.data_path, self.image_set)
        self._specs = self._make_specs()
        self.image_index = list(range(num_images))

    @staticmethod
    def _iou(a, b) -> float:
        ix = max(0.0, min(a[2], b[2]) - max(a[0], b[0]) + 1)
        iy = max(0.0, min(a[3], b[3]) - max(a[1], b[1]) + 1)
        inter = ix * iy
        area = lambda r: (r[2] - r[0] + 1) * (r[3] - r[1] + 1)
        return inter / (area(a) + area(b) - inter)

    def _make_specs(self) -> List[Dict]:
        h, w = self.image_size
        specs = []
        for i in range(self.num_images):
            n = self._rng.randint(1, self.max_objects + 1)
            boxes, classes = [], []
            for _ in range(n):
                # rejection-sample low-overlap placements: heavily overlapping
                # solid rectangles occlude each other (later draws overwrite
                # earlier pixels), which would make gt boxes unlearnable and
                # the eval mAP ceiling ill-defined
                for _attempt in range(20):
                    # object sizes scale with the canvas so tiny images work
                    bw = self._rng.randint(max(16, w // 5), max(17, w // 2))
                    bh = self._rng.randint(max(16, h // 5), max(17, h // 2))
                    x1 = self._rng.randint(0, w - bw)
                    y1 = self._rng.randint(0, h - bh)
                    cand = [x1, y1, x1 + bw - 1, y1 + bh - 1]
                    if all(self._iou(cand, b) < 0.2 for b in boxes):
                        boxes.append(cand)
                        classes.append(self._rng.randint(1, self.num_classes))
                        break
            specs.append(dict(
                boxes=np.asarray(boxes, np.float32),
                gt_classes=np.asarray(classes, np.int32),
                noise_seed=int(self._rng.randint(0, 2 ** 31)),
            ))
        return specs

    def _render(self, spec: Dict) -> np.ndarray:
        h, w = self.image_size
        rng = np.random.RandomState(spec["noise_seed"])
        img = rng.randint(0, 60, size=(h, w, 3)).astype(np.uint8)
        for box, cls in zip(spec["boxes"], spec["gt_classes"]):
            x1, y1, x2, y2 = box.astype(int)
            img[y1:y2 + 1, x1:x2 + 1] = _class_color(int(cls))
        return img

    def image_path(self, i: int) -> str:
        return os.path.join(self.image_dir, f"{self.image_set}_{i:05d}.png")

    def _spec_signature(self) -> str:
        """Content hash of the generation parameters + all gt.  The PNG cache
        is only valid for exactly this dataset; reusing stale pixels against
        fresh in-memory gt would silently break the learnable-color
        invariant the dataset exists for."""
        h = zlib.crc32(repr((self.num_images, self.num_classes,
                             self.image_size, self.max_objects)).encode())
        for spec in self._specs:
            h = zlib.crc32(spec["boxes"].tobytes(), h)
            h = zlib.crc32(spec["gt_classes"].tobytes(), h)
            h = zlib.crc32(str(spec["noise_seed"]).encode(), h)
        return f"{h:08x}"

    def _materialize(self) -> None:
        os.makedirs(self.image_dir, exist_ok=True)
        stamp = os.path.join(self.image_dir, f".spec-{self._spec_signature()}")
        fresh = os.path.exists(stamp)
        for i, spec in enumerate(self._specs):
            path = self.image_path(i)
            if not fresh or not os.path.exists(path):
                img = self._render(spec)
                try:
                    import cv2

                    cv2.imwrite(path, img[:, :, ::-1])
                except Exception:  # pragma: no cover
                    from PIL import Image

                    Image.fromarray(img).save(path)
        if not fresh:
            # drop stamps of other configurations: their pixels were just
            # overwritten, so leaving them would validate a stale cache if
            # that configuration is ever requested again (A→B→A pattern)
            for name in os.listdir(self.image_dir):
                if name.startswith(".spec-"):
                    os.unlink(os.path.join(self.image_dir, name))
            with open(stamp, "w"):
                pass

    def _load_annotations(self) -> Roidb:
        self._materialize()
        h, w = self.image_size
        return [
            dict(
                image=self.image_path(i),
                index=i,
                height=h,
                width=w,
                boxes=spec["boxes"].copy(),
                gt_classes=spec["gt_classes"].copy(),
                flipped=False,
            )
            for i, spec in enumerate(self._specs)
        ]

    def gt_roidb(self) -> Roidb:
        # no pkl cache: generation is deterministic and instant
        return self._load_annotations()

    def evaluate_detections(self, all_boxes, out_dir: str = None
                            ) -> Dict[str, float]:
        gt = {
            i: dict(
                boxes=spec["boxes"],
                gt_classes=spec["gt_classes"],
                difficult=np.zeros(len(spec["boxes"]), bool),
            )
            for i, spec in enumerate(self._specs)
        }
        aps = []
        results = {}
        for c in range(1, self.num_classes):
            dets = {
                i: np.asarray(all_boxes[c][i]).reshape(-1, 5)
                for i in range(self.num_images)
            }
            has_gt = any((g["gt_classes"] == c).any() for g in gt.values())
            if not has_gt:
                continue
            ap = voc_eval(dets, gt, c, ovthresh=0.5, use_07_metric=True)
            results[self.classes[c]] = ap
            aps.append(ap)
        results["mAP"] = float(np.mean(aps)) if aps else 0.0
        return results


# fixed well-separated saturated palette: hue is the class signature (the
# task must be LEARNABLE); everything else — scale, pattern, brightness,
# occlusion, distractors — is intra-class variation that makes it HARD
_HARD_PALETTE = np.array([
    [220, 40, 40],    # red
    [40, 200, 40],    # green
    [50, 80, 230],    # blue
    [230, 220, 40],   # yellow
    [220, 50, 220],   # magenta
    [40, 220, 220],   # cyan
    [240, 140, 30],   # orange
    [150, 60, 220],   # purple
], np.uint8)


class HardSyntheticDataset(SyntheticDataset):
    """Harder generated benchmark (VERDICT r03 item 3): the 16-image easy
    set (±0.1 mAP seed spread) cannot catch point-level accuracy
    regressions, so this set adds, deterministically per seed:

    * **scale**: object sizes log-uniform over canvas/12 .. canvas/2,
    * **crowding**: 2..max_objects (default 8) objects per image,
    * **occlusion**: placements may overlap up to IoU 0.4 (later draws
      overwrite earlier pixels, but each box keeps >=~50% visible),
    * **appearance noise**: per-instance brightness jitter and an optional
      darker stripe pattern — class identity stays the hue,
    * **distractors**: gray/desaturated rectangles that are not any class
      (hard negatives for the RPN and the classifier).

    Defaults: 9 classes (8 fg + background), 400 train / 100 test images on
    a 240x320 canvas — 400, not fewer, because measured seed spread scales
    with training-set size (200 imgs: 0.043 mAP spread; 400 imgs: <0.02 —
    docs/GAUNTLET.md), and the gauntlet's regression budget needs the
    tight end.  Deterministic per (image_set, generation params);
    evaluation inherits the VOC-style AP of :class:`SyntheticDataset`.
    """

    def __init__(self, image_set: str, root_path: str, dataset_path: str,
                 num_images: int = None, num_classes: int = 9,
                 image_size=(240, 320), max_objects: int = 8):
        if num_images is None:
            num_images = 400 if "train" in image_set else 100
        if num_classes > len(_HARD_PALETTE) + 1:
            raise ValueError(
                f"num_classes <= {len(_HARD_PALETTE) + 1} supported")
        super().__init__(image_set, root_path,
                         dataset_path
                         or os.path.join(root_path, "synthetic_hard"),
                         num_images=num_images, num_classes=num_classes,
                         image_size=image_size, max_objects=max_objects)

    # every gt box must keep at least this fraction of its own pixels
    # visible after all later draws — an almost-fully-overdrawn gt box is
    # unfindable even by a perfect detector and would reintroduce the
    # seed-dependent mAP noise floor this set exists to eliminate
    MIN_VISIBLE = 0.5

    def _make_specs(self) -> List[Dict]:
        h, w = self.image_size
        lo, hi = np.log(max(12.0, w / 12)), np.log(w / 2)
        specs = []
        for i in range(self.num_images):
            n = self._rng.randint(2, self.max_objects + 1)
            boxes, classes = [], []
            # painter's-algorithm owner grid: visibility is checked against
            # the TOTAL coverage of each earlier box, not pairwise IoU (a
            # box can be buried by several small overlaps)
            owner = np.full((h, w), -1, np.int32)
            visible = []  # visible pixel count per placed box
            areas = []
            for _ in range(n):
                for _attempt in range(25):
                    bw = int(round(np.exp(self._rng.uniform(lo, hi))))
                    bh = int(round(np.exp(self._rng.uniform(lo, hi))))
                    bw, bh = min(bw, w - 2), min(bh, h - 2)
                    x1 = self._rng.randint(0, w - bw)
                    y1 = self._rng.randint(0, h - bh)
                    cand = [x1, y1, x1 + bw - 1, y1 + bh - 1]
                    # quick pairwise cap (moderate occlusion allowed) ...
                    if not all(self._iou(cand, b) < 0.4 for b in boxes):
                        continue
                    # ... then the true visibility check: how much of each
                    # earlier box would remain after this draw?
                    region = owner[y1:y1 + bh, x1:x1 + bw]
                    covered = np.bincount(region[region >= 0],
                                          minlength=len(boxes))
                    if any((visible[e] - covered[e]) / areas[e]
                           < self.MIN_VISIBLE for e in range(len(boxes))):
                        continue
                    for e in range(len(boxes)):
                        visible[e] -= int(covered[e])
                    owner[y1:y1 + bh, x1:x1 + bw] = len(boxes)
                    boxes.append(cand)
                    classes.append(self._rng.randint(1, self.num_classes))
                    visible.append(bh * bw)
                    areas.append(bh * bw)
                    break
            # 2..4 distractor rectangles (class of none)
            n_distract = self._rng.randint(2, 5)
            distract = []
            for _ in range(n_distract):
                dw = self._rng.randint(12, max(13, w // 4))
                dh = self._rng.randint(12, max(13, h // 4))
                dx = self._rng.randint(0, w - dw)
                dy = self._rng.randint(0, h - dh)
                cand = [dx, dy, dx + dw - 1, dy + dh - 1]
                # distractors must not occlude real objects into ambiguity
                if all(self._iou(cand, b) < 0.2 for b in boxes):
                    distract.append(cand)
            specs.append(dict(
                boxes=np.asarray(boxes, np.float32),
                gt_classes=np.asarray(classes, np.int32),
                distractors=np.asarray(distract, np.float32).reshape(-1, 4),
                noise_seed=int(self._rng.randint(0, 2 ** 31)),
            ))
        return specs

    def _render(self, spec: Dict) -> np.ndarray:
        h, w = self.image_size
        rng = np.random.RandomState(spec["noise_seed"])
        img = rng.randint(0, 90, size=(h, w, 3)).astype(np.uint8)
        # distractors first: never on top of an object
        for box in spec["distractors"]:
            x1, y1, x2, y2 = box.astype(int)
            g = rng.randint(60, 140)
            jit = rng.randint(-15, 16, 3)
            img[y1:y2 + 1, x1:x2 + 1] = np.clip(g + jit, 0, 255
                                                ).astype(np.uint8)
        for box, cls in zip(spec["boxes"], spec["gt_classes"]):
            x1, y1, x2, y2 = box.astype(int)
            color = _HARD_PALETTE[int(cls) - 1].astype(np.float32)
            # per-instance brightness jitter (±25%): intra-class variation
            color = np.clip(color * rng.uniform(0.75, 1.25), 0, 255)
            patch = np.broadcast_to(
                color, (y2 - y1 + 1, x2 - x1 + 1, 3)).copy()
            if rng.rand() < 0.5:  # darker stripe pattern, axis random
                period = rng.randint(4, 9)
                axis = rng.randint(2)
                idx = np.arange(patch.shape[axis])
                stripe = (idx // max(1, period // 2)) % 2 == 1
                if axis == 0:
                    patch[stripe, :, :] *= 0.6
                else:
                    patch[:, stripe, :] *= 0.6
            img[y1:y2 + 1, x1:x2 + 1] = patch.astype(np.uint8)
            # thin dark outline helps delineate occluded stacks — real
            # detectors get edges for free; solid same-color overlaps would
            # be genuinely ambiguous even for a perfect model
            img[y1:y2 + 1, [x1, x2]] = 20
            img[[y1, y2], x1:x2 + 1] = 20
        return img

    def _spec_signature(self) -> str:
        base = super()._spec_signature()
        hshd = zlib.crc32(b"hard", int(base, 16))
        for spec in self._specs:
            hshd = zlib.crc32(spec["distractors"].tobytes(), hshd)
        return f"{hshd:08x}"


class StreamSyntheticDataset(SyntheticDataset):
    """COCO-cardinality rehearsal set (ROADMAP item 3 / docs/DATA.md).

    Every pre-r7 loader/cache/decode-pool claim was measured on <=400
    generated images — sets that fit in HBM, let alone RAM.  The
    reference trained 118k-image COCO epochs; this set rehearses that
    CARDINALITY (default 10k train / 1k test, 80 fg classes to match
    COCO's class count) without rehearsing COCO's resolution: the
    240x320 canvas keeps one-time materialization and per-image decode
    cheap enough that a 1-core host can drive a full streaming epoch,
    while the image COUNT exercises exactly the paths small sets cannot
    — bounded cache windows, shard unions, mid-epoch cursors.

    Generation-cost deltas vs :class:`SyntheticDataset` (which is
    O(canvas) of np.random per image and writes poorly-compressible
    full-canvas noise):

    * the background is a 16x16 noise TILE repeated across the canvas —
      PNGs compress ~10x smaller (10k images ~ 150 MB, not ~2 GB) and
      encode/decode markedly faster, at zero cost to the class-color
      learnability invariant,
    * class identity stays the ``_class_color`` hue (deterministic for
      ANY class count — 80 works as well as 4).

    Deterministic per (image_set, generation params) like every
    synthetic set; evaluation inherits the VOC-style AP machinery.
    """

    def __init__(self, image_set: str, root_path: str, dataset_path: str,
                 num_images: int = None, num_classes: int = 81,
                 image_size=(240, 320), max_objects: int = 6):
        if num_images is None:
            num_images = 10_000 if "train" in image_set else 1_000
        super().__init__(image_set, root_path,
                         dataset_path
                         or os.path.join(root_path, "synthetic_stream"),
                         num_images=num_images, num_classes=num_classes,
                         image_size=image_size, max_objects=max_objects)

    def _render(self, spec: Dict) -> np.ndarray:
        h, w = self.image_size
        rng = np.random.RandomState(spec["noise_seed"])
        tile = rng.randint(0, 60, size=(16, 16, 3)).astype(np.uint8)
        img = np.tile(tile, ((h + 15) // 16, (w + 15) // 16, 1))[:h, :w]
        img = np.ascontiguousarray(img)
        for box, cls in zip(spec["boxes"], spec["gt_classes"]):
            x1, y1, x2, y2 = box.astype(int)
            img[y1:y2 + 1, x1:x2 + 1] = _class_color(int(cls))
        return img

    def _spec_signature(self) -> str:
        # distinct from the base class: the pixels differ (tiled
        # background), so a PNG cache written by one class must never
        # validate for the other
        base = super()._spec_signature()
        return f"{zlib.crc32(b'stream', int(base, 16)):08x}"
