"""Pre-decoded image cache: resized uint8 images, RAM with disk spill.

No direct reference equivalent — the reference re-decodes every JPEG every
epoch (``rcnn/io/image.py — get_image``), which is fine when a GPU step is
slow, but a single host core feeding a TPU chip spends ~11 ms/image on
decode+resize while the chip finishes a 2-image step in ~25 ms.  Caching
the deterministic decode→flip→resize result (``load_resized_uint8``)
reduces steady-state host work per image to a sub-millisecond memcpy.

Design:
* the cached value is ONLY pixels (uint8, post-resize, pre-pad); im_scale
  is a pure function of the record geometry (:func:`plan_scale`), so the
  cache can never desync scales from pixels,
* RAM tier: LRU dict under a byte budget (default 2 GiB — a 600x1000
  resized image is ~1.9 MB, so ~1k images; VOC07 trainval+flips need
  ~19 GB, hence the disk tier),
* disk tier: one ``.npy`` per image under ``cache_dir``, written atomically
  (tmp + rename) so concurrent loader threads/processes never observe a
  torn file; repeat reads ride the OS page cache — exactly the "free" RAM
  this host has,
* keys hash the absolute path + file mtime/size + flip + geometry params,
  so one directory safely serves multiple datasets/configs and a replaced
  source image invalidates its entry instead of serving stale pixels.

Thread-safe: the loader's prefetch pool calls ``load`` concurrently.
"""

from __future__ import annotations

import hashlib
import os
import threading
from collections import OrderedDict
from typing import Optional, Tuple

import numpy as np

from mx_rcnn_tpu.data.image import (bucket_fit, compute_scale,
                                    load_resized_uint8)


def plan_scale(height: int, width: int, scale: int, max_size: int,
               bucket: Tuple[int, int]) -> float:
    """The im_scale ``load_resized_uint8`` will produce for an original of
    (height, width) — including the shrink-to-fit correction.  Pure
    function of geometry: cache hits get the exact scale the decode path
    would have returned without touching pixels.  Both the resize rule
    (:func:`compute_scale`) and the shrink correction
    (:func:`bucket_fit`) are the decode path's own helpers, so the two
    computations cannot drift apart."""
    s = compute_scale(height, width, scale, max_size)
    rh, rw = int(round(height * s)), int(round(width * s))
    return s * bucket_fit(rh, rw, bucket)


class DecodedImageCache:
    """Cache of ``load_resized_uint8`` pixel results.

    Args:
      ram_bytes: RAM tier budget in bytes (0 disables the RAM tier).
      cache_dir: disk tier directory (None disables the disk tier).
    """

    def __init__(self, ram_bytes: int = 2 << 30,
                 cache_dir: Optional[str] = None):
        self.ram_bytes = int(ram_bytes)
        self.cache_dir = cache_dir
        if cache_dir:
            os.makedirs(cache_dir, exist_ok=True)
        self._ram: "OrderedDict[str, np.ndarray]" = OrderedDict()
        self._ram_used = 0
        self._lock = threading.Lock()
        # stable-prefix -> set of versioned filenames currently on disk;
        # built from ONE os.listdir on first write, then kept in sync by
        # the writers in this process.  Eviction of superseded versions is
        # an O(1) lookup here — the previous per-miss glob over the whole
        # cache_dir made a cold COCO-scale first epoch O(N^2) in name
        # comparisons (advisor r4).  Stale entries (another process wrote
        # concurrently) only cost a missed best-effort eviction, the same
        # race the glob had.
        self._disk_index: Optional[dict] = None
        self.hits = 0
        self.misses = 0

    @staticmethod
    def _key(path: str, flipped: bool, scale: int, max_size: int,
             bucket: Tuple[int, int]) -> str:
        # Two-part key: a STABLE digest of path+geometry, then a VERSION
        # suffix from the file's mtime_ns+size.  The version guarantees a
        # re-generated/replaced source image can never be served stale
        # pixels from cache_dir (advisor r3); the stable prefix lets the
        # writer evict superseded versions so repeated dataset regeneration
        # doesn't grow cache_dir unboundedly.  A missing file falls through
        # to the decode path, which raises its own error.
        try:
            st = os.stat(path)
            stamp = f"{st.st_mtime_ns}:{st.st_size}"
        except OSError:
            stamp = "0:0"
        ident = f"{os.path.abspath(path)}|{int(flipped)}|{scale}|" \
                f"{max_size}|{bucket[0]}x{bucket[1]}"
        stem = os.path.splitext(os.path.basename(path))[0]
        # full-width digest: a truncated hash colliding would silently
        # serve another image's pixels
        digest = hashlib.sha1(ident.encode()).hexdigest()
        version = hashlib.sha1(stamp.encode()).hexdigest()[:16]
        return f"{digest}-{stem}{'-f' if flipped else ''}.{version}"

    def _build_disk_index(self) -> dict:
        """One listdir pass over cache_dir → {stable prefix: {versioned
        filenames}} — built lazily on the first disk write, then kept in
        sync by :meth:`_record_version`."""
        index: dict = {}
        try:
            names = os.listdir(self.cache_dir)
        except OSError:
            names = []
        hexdigits = set("0123456789abcdef")
        for name in names:
            if not name.endswith(".npy"):
                continue
            stem = name[:-len(".npy")]
            head, _, version = stem.rpartition(".")
            # only properly-versioned entries (16-hex suffix) are indexed;
            # anything else is either a pre-versioning legacy name
            # (cleared by direct unlink in the writer) or a foreign file
            # we must not touch.  The check also keeps dotted image stems
            # (`img.v2.jpg`) from being split at the wrong dot.
            if head and len(version) == 16 and set(version) <= hexdigits:
                index.setdefault(head, set()).add(name)
        return index

    def _record_version(self, prefix: str, fn: str) -> list:
        """Record ``fn`` as the current on-disk version for ``prefix``;
        return the superseded sibling filenames the caller should unlink.
        The listdir-sized index build runs OUTSIDE the lock (it would
        otherwise stall every _ram_get for hundreds of ms on a warm
        COCO-scale dir); the dict/set mutations run UNDER it (the loader's
        prefetch threads can miss on the same key concurrently)."""
        if self._disk_index is None:
            built = self._build_disk_index()
            with self._lock:
                if self._disk_index is None:
                    self._disk_index = built
        with self._lock:
            entries = self._disk_index.setdefault(prefix, set())
            stale = [n for n in entries if n != fn]
            entries.difference_update(stale)
            entries.add(fn)
        return stale

    def _ram_get(self, key: str) -> Optional[np.ndarray]:
        with self._lock:
            img = self._ram.get(key)
            if img is not None:
                self._ram.move_to_end(key)
            return img

    def _ram_put(self, key: str, img: np.ndarray) -> None:
        if self.ram_bytes <= 0 or img.nbytes > self.ram_bytes:
            return
        with self._lock:
            if key in self._ram:
                return
            self._ram[key] = img
            self._ram_used += img.nbytes
            while self._ram_used > self.ram_bytes:
                _, old = self._ram.popitem(last=False)
                self._ram_used -= old.nbytes

    def load(self, path: str, flipped: bool, scale: int, max_size: int,
             bucket: Tuple[int, int]) -> np.ndarray:
        """Cached decode→flip→resize; returns the (h, w, 3) uint8 image
        (unpadded).  The caller derives im_scale via :func:`plan_scale`."""
        key = self._key(path, flipped, scale, max_size, bucket)
        img = self._ram_get(key)
        from_disk = False
        if img is None and self.cache_dir:
            fp = os.path.join(self.cache_dir, key + ".npy")
            if os.path.exists(fp):
                try:
                    img = np.load(fp)
                    from_disk = True
                except Exception:
                    img = None  # torn/corrupt file: fall through to decode
        if img is not None:
            self.hits += 1
            self._ram_put(key, img)
            if from_disk and self._disk_index is not None:
                # a disk HIT on a version this process's index doesn't
                # know can mean a sibling process wrote the new version
                # (so only ITS index would evict our stale one — it never
                # writes again after we start hitting its file).  The
                # index is already built, so this is an O(1) check that
                # closes the cross-process leak at zero listdir cost.
                prefix = key.rsplit(".", 1)[0]
                for old in self._record_version(prefix, key + ".npy"):
                    try:
                        os.unlink(os.path.join(self.cache_dir, old))
                    except OSError:  # already gone
                        pass
            return img
        self.misses += 1
        img, _ = load_resized_uint8(path, flipped, scale, max_size, bucket)
        self._ram_put(key, img)
        if self.cache_dir:
            fp = os.path.join(self.cache_dir, key + ".npy")
            tmp = fp + f".tmp{os.getpid()}-{threading.get_ident()}"
            try:
                # write via the handle: np.save(path) would append another
                # ".npy" to the tmp name and break the atomic rename
                with open(tmp, "wb") as f:
                    np.save(f, img)
                # persistlint: disable=PL102,PL103 TRIAGED (ISSUE 12): the cache is rebuildable, not durable state — a crash-torn or lost .npy fails np.load (or the size/magic check) and falls through to a fresh decode that overwrites it (pinned by test_data.py::test_torn_disk_cache_falls_through_to_decode); an fsync per image would put disk-flush latency on the prefetch pool's ~ms hot path for zero correctness gain
                os.replace(tmp, fp)
                # evict superseded versions of this entry (same stable
                # prefix, different mtime/size version) so regenerating the
                # dataset N times doesn't keep N dead copies on disk; also
                # the pre-versioning legacy name `prefix.npy`, which the
                # new keys can never read again.  Sibling versions come
                # from the one-time directory index (O(1) per write) — not
                # a per-miss glob, which made cold first epochs O(N^2)
                prefix = key.rsplit(".", 1)[0]
                for old in self._record_version(prefix,
                                                os.path.basename(fp)):
                    try:
                        os.unlink(os.path.join(self.cache_dir, old))
                    except OSError:  # already gone
                        pass
                try:  # targeted single unlink, no directory scan
                    os.unlink(os.path.join(self.cache_dir,
                                           prefix + ".npy"))
                except OSError:  # never existed (the common case)
                    pass
            except OSError:  # disk full etc. — the cache stays best-effort
                if os.path.exists(tmp):
                    os.unlink(tmp)
        return img
