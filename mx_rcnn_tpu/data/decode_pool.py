"""Process-parallel image decoding for the host input pipeline.

Reference: none — the reference loader is synchronous (SURVEY.md §3.1
footnote), which is fine when one GPU consumes ~4 imgs/s but not when 8
TPU chips consume ~580.  The thread prefetcher (``loader.py —
_prefetched``) overlaps batch ASSEMBLY with device steps, but Python-side
work (PNG/JPEG entropy decode beyond the GIL-released cv2 kernels, numpy
copies, cache bookkeeping) still serializes on one interpreter; a process
pool removes that ceiling.

Design:
* **spawn** context — fork is unsafe once JAX/XLA threads exist, and the
  loaders run in processes that have usually initialized a backend,
* workers are tiny: they import only the decode path (numpy/cv2), never
  JAX; each holds its own :class:`DecodedImageCache` whose RAM tier is
  per-process but whose DISK tier is shared — the cache's atomic
  tmp+rename writes are already multi-process safe,
* the parent never ships pixels TO workers, only paths; pixels come back
  once per decode (~2 MB/image IPC, amortized against ~11 ms of decode),
* ``im_scale`` is NOT returned by workers: it is a pure function of record
  geometry (``cache.plan_scale``), pinned equal to the decode path's scale
  by test, so the parent derives it locally and the wire format stays a
  single uint8 array.

Honest scaling note (docs/PERF.md): this box has 1 CPU core, so local
measurements can show overhead, not speedup; ``tools/loader_bench.py``
reports per-worker efficiency and states the extrapolation assumption
explicitly instead of extrapolating silently.

Standard multiprocessing caveat: construct the pool only from code
reachable under ``if __name__ == "__main__":`` (or from an importable
module) — the spawn context re-imports ``__main__`` in each worker, which
fails for stdin/interactive scripts.
"""

from __future__ import annotations

import multiprocessing as mp
from concurrent.futures import ProcessPoolExecutor
from typing import Optional, Tuple

import numpy as np

# per-worker singleton cache (initialized once per process, not per task)
_WORKER_CACHE = None


def _init_worker(cache_dir: Optional[str], ram_bytes: int) -> None:
    global _WORKER_CACHE
    if cache_dir or ram_bytes > 0:
        from mx_rcnn_tpu.data.cache import DecodedImageCache

        _WORKER_CACHE = DecodedImageCache(ram_bytes=ram_bytes,
                                          cache_dir=cache_dir)
    else:
        _WORKER_CACHE = None


def _decode(path: str, flipped: bool, scale: int, max_size: int,
            bucket: Tuple[int, int]) -> np.ndarray:
    """Worker task: decode→flip→resize, returning the unpadded uint8
    pixels (the parent derives im_scale via ``plan_scale``)."""
    if _WORKER_CACHE is not None:
        return _WORKER_CACHE.load(path, flipped, scale, max_size, bucket)
    from mx_rcnn_tpu.data.image import load_resized_uint8

    img, _ = load_resized_uint8(path, flipped, scale, max_size, bucket)
    return img


class DecodePool:
    """A spawn-context process pool decoding images for the loaders.

    Args:
      num_procs: worker process count (>= 1).
      cache_dir: optional shared disk cache directory (multi-process safe).
      ram_bytes: per-WORKER RAM cache budget (0 disables; note the total
        RSS across workers is ``num_procs * ram_bytes``).
    """

    def __init__(self, num_procs: int, cache_dir: Optional[str] = None,
                 ram_bytes: int = 0):
        if num_procs < 1:
            raise ValueError("num_procs must be >= 1")
        self.num_procs = num_procs
        self._ex = ProcessPoolExecutor(
            num_procs, mp_context=mp.get_context("spawn"),
            initializer=_init_worker, initargs=(cache_dir, ram_bytes))

    def submit(self, path: str, flipped: bool, scale: int, max_size: int,
               bucket: Tuple[int, int]):
        """Schedule one decode; returns a Future of the uint8 pixels."""
        return self._ex.submit(_decode, path, flipped, scale, max_size,
                               bucket)

    def close(self) -> None:
        self._ex.shutdown(wait=False, cancel_futures=True)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
