"""Batch loaders producing static-shape device batches.

Reference: ``rcnn/core/loader.py`` — ``AnchorLoader`` (shuffle with
aspect-ratio grouping, image load/augment, host-side ``assign_anchor``,
pad-to-batch-max) and ``TestLoader``.

TPU-native differences:
* anchor/proposal target assignment happens ON DEVICE inside the jitted
  train step, so ``AnchorLoader`` here only assembles (images, im_info, gt)
  — with a single host core feeding up to 8 chips, host-side assignment
  would dominate the step time,
* images pad into static buckets; aspect grouping (ref ASPECT_GROUPING)
  becomes bucket grouping: every batch holds images of one bucket so a
  fixed set of XLA programs serves the whole epoch,
* gt arrays are padded to ``max_gt_boxes`` with a validity mask instead of
  variable-length label blobs.
"""

from __future__ import annotations

import logging
import threading
import time
import zlib

from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import (Callable, Dict, Iterable, Iterator, List, Optional,
                    Sequence, Tuple)

import numpy as np

from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.core.train import Batch
from mx_rcnn_tpu.data.cache import DecodedImageCache, plan_scale
from mx_rcnn_tpu.data.image import (choose_bucket, compute_scale,
                                    load_resized_uint8)
from mx_rcnn_tpu.data.roidb import Roidb


# host baseline reserved under data.ram_ceiling_mb before any cache
# budget is granted: interpreter + jax/XLA runtime + code + loader
# scratch.  Measured ~0.6-0.9 GB on this box with the CPU backend up;
# 1 GiB keeps the derivation conservative (docs/DATA.md "RAM ceiling").
_PROCESS_FLOOR_BYTES = 1 << 30


def stream_cache_budget(cfg: Config, n_images: Optional[int] = None,
                        image_bytes: Optional[int] = None,
                        batch_bytes: int = 0) -> int:
    """The decoded-image cache RAM budget in BYTES, derived from the
    bounded working set instead of the raw ``default.image_cache_mb``
    number (extends the PR 1 clamp, which only guarded the per-worker
    split of an unexamined total).

    Three clamps, applied in order and logged once:

    * the configured ``default.image_cache_mb`` is the ceiling ask,
    * ``n_images * image_bytes`` (decoded size of the WHOLE set at the
      bucket resolution) caps it — a 64-image smoke must not reserve the
      2 GiB a COCO-scale run wants,
    * under ``data.ram_ceiling_mb``, the cache gets what remains after
      the process floor (~1 GiB interpreter + runtime) and the streaming
      window (prefetch depth + assembly workers + stage depth, one batch
      each) are reserved — the streaming loader's RSS stays bounded no
      matter how big the epoch is (``tools/data_bench.py`` measures it
      against the ceiling).
    """
    d = cfg.default
    budget = d.image_cache_mb << 20
    if budget <= 0:
        return 0
    why = [f"configured={d.image_cache_mb}MB"]
    if n_images and image_bytes:
        dataset = int(n_images) * int(image_bytes)
        if dataset < budget:
            budget = dataset
            why.append(f"dataset={dataset >> 20}MB ({n_images} images)")
    data = getattr(cfg, "data", None)
    ceiling = (data.ram_ceiling_mb << 20) if data is not None else 0
    if ceiling > 0:
        depth = data.stage_depth if data.staging else 0
        window = (d.prefetch + max(d.num_workers, 1) + depth + 1) \
            * max(int(batch_bytes), 0)
        room = max(ceiling - _PROCESS_FLOOR_BYTES - window, 0)
        if room < budget:
            budget = room
            why.append(f"ceiling={data.ram_ceiling_mb}MB - floor "
                       f"{_PROCESS_FLOOR_BYTES >> 20}MB - window "
                       f"{window >> 20}MB")
    logging.getLogger("mx_rcnn_tpu").info(
        "decoded-image cache budget: %d MB (%s)", budget >> 20,
        ", ".join(why))
    return budget


def cache_from_config(cfg: Config, n_images: Optional[int] = None,
                      image_bytes: Optional[int] = None,
                      batch_bytes: int = 0) -> DecodedImageCache | None:
    """Build the decoded-image cache the config asks for (None = disabled).
    With ``n_images``/``image_bytes`` the RAM tier is budgeted from the
    bounded streaming window (:func:`stream_cache_budget`) instead of the
    raw config number."""
    d = cfg.default
    if d.image_cache_mb <= 0 and not d.image_cache_dir:
        return None
    budget = stream_cache_budget(cfg, n_images, image_bytes, batch_bytes)
    if budget <= 0 and not d.image_cache_dir:
        return None
    return DecodedImageCache(ram_bytes=budget,
                             cache_dir=d.image_cache_dir or None)


def decode_pool_from_config(cfg: Config, n_images: Optional[int] = None,
                            image_bytes: Optional[int] = None,
                            batch_bytes: int = 0):
    """Build the process decode pool the config asks for (None = in-thread
    decode).  Callers own the pool: close() it when the loaders are done
    (``tools/train.py`` wraps fit in try/finally)."""
    d = cfg.default
    if d.decode_procs <= 0:
        return None
    from mx_rcnn_tpu.data.decode_pool import DecodePool

    # decode runs in the worker processes, so the RAM tier must live there
    # too: split the budget across workers (the parent's cache is never
    # consulted on this path — advisor r4).  The budget itself is the
    # window-derived stream_cache_budget, NOT the raw config number, so
    # the total across workers stays bounded for streaming sets too.
    # The disk tier stays shared via cache_dir.
    total = stream_cache_budget(cfg, n_images, image_bytes, batch_bytes)
    per_worker = total // d.decode_procs
    if total > 0 and per_worker < (1 << 20):
        # an integer-division share of 0 would silently disable the RAM
        # tier the config asked for (ADVICE r5); clamp to a useful floor
        logging.getLogger("mx_rcnn_tpu").warning(
            "cache budget %d MB split across decode_procs=%d leaves under "
            "1 MB per worker; clamping each worker's RAM tier to 1 MB "
            "(raise image_cache_mb / data.ram_ceiling_mb to silence)",
            total >> 20, d.decode_procs)
        per_worker = 1 << 20
    if total > 0:
        logging.getLogger("mx_rcnn_tpu").info(
            "decode_procs=%d: %d MB RAM tier moves into the workers at "
            "%d MB each (total RSS budget unchanged)",
            d.decode_procs, total >> 20, per_worker >> 20)
    return DecodePool(d.decode_procs, cache_dir=d.image_cache_dir or None,
                      ram_bytes=per_worker)


class _ImageSource:
    """Shared decode/cache plumbing for the three loaders.

    ``raw_images=True`` (the TPU-native default) emits uint8 batches that
    the model normalizes on device (``ops/normalize.py``); False reproduces
    the reference's host-side fp32 mean-subtract.  Either mode can sit on a
    :class:`DecodedImageCache`.  im_info records the ACTUAL post-resize dims
    (matching the reference, which reads them off the resized array) — the
    device-side normalization mask depends on them being exact.
    """

    def _init_source(self, cfg: Config, raw_images, cache,
                     decode_pool=None) -> None:
        self.raw_images = (cfg.default.raw_images if raw_images is None
                           else raw_images)
        self.cache = cache
        self.decode_pool = decode_pool
        self._pixel_means = np.asarray(cfg.network.pixel_means, np.float32)
        # observability (docs/OBSERVABILITY.md): with cfg.obs.enabled the
        # loader records decode/assemble time and prefetch queue depth
        # into the process registry; None (the default) keeps the hot
        # path at a single attribute check
        self._rec = None
        obs = getattr(cfg, "obs", None)
        if obs is not None and obs.enabled:
            from mx_rcnn_tpu.obs.metrics import registry

            self._rec = registry()
        # decode accounting (docs/DATA.md): images_decoded counts every
        # image THIS loader decoded (the per-process shard-ownership
        # measurement); record_decodes() additionally collects the
        # (roidb index, flipped) identity of each — the exactly-once
        # audit the streaming invariants are checked against.  Guarded
        # by a lock: _images_into runs on the prefetch pool's threads.
        self.images_decoded = 0
        self.decoded_ids: Optional[List[Tuple[int, bool]]] = None
        self._decode_count_lock = threading.Lock()

    def _write_slot(self, out: np.ndarray, img: np.ndarray) -> Tuple[int, int]:
        h, w = img.shape[:2]
        if self.raw_images:
            out[:h, :w] = img
        else:
            np.subtract(img, self._pixel_means, out=out[:h, :w],
                        casting="unsafe")
        return h, w

    def _image_into(self, out: np.ndarray, rec, bucket) -> Tuple[int, int, float]:
        """Decode ``rec`` (through the cache if present) and write it into
        ``out`` (one padded bucket slot).  Returns (h, w, im_scale)."""
        cfg = self.cfg
        scale, max_size = cfg.bucket.scale, cfg.bucket.max_size
        flipped = rec.get("flipped", False)
        if self.cache is not None:
            img = self.cache.load(rec["image"], flipped, scale, max_size,
                                  bucket)
            im_scale = plan_scale(rec["height"], rec["width"], scale,
                                  max_size, bucket)
        else:
            img, im_scale = load_resized_uint8(rec["image"], flipped, scale,
                                               max_size, bucket)
        h, w = self._write_slot(out, img)
        return h, w, im_scale

    def _images_into(self, images: np.ndarray, recs, bucket
                     ) -> List[Tuple[int, int, float]]:
        """Decode ``recs`` into the padded batch buffer; returns one
        (h, w, im_scale) per record.  With a :class:`DecodePool`
        (``data/decode_pool.py``) the decodes run in worker PROCESSES —
        all images of the batch in flight at once — and im_scale is
        derived parent-side from the record geometry (``plan_scale`` is
        pinned equal to the decode path's scale); without one, the decode
        runs in-thread through the optional cache."""
        with self._decode_count_lock:
            self.images_decoded += len(recs)
            if self.decoded_ids is not None:
                self.decoded_ids.extend(
                    (int(rec.get("index", -1)),
                     bool(rec.get("flipped", False))) for rec in recs)
        if self._rec is None:
            return self._decode_into(images, recs, bucket)
        self._rec.inc("loader.images_decoded", len(recs))
        t0 = time.perf_counter()
        out = self._decode_into(images, recs, bucket)
        self._rec.observe("loader.decode_ms",
                          (time.perf_counter() - t0) * 1e3)
        return out

    def _decode_into(self, images: np.ndarray, recs, bucket
                     ) -> List[Tuple[int, int, float]]:
        if self.decode_pool is None:
            return [self._image_into(images[j], rec, bucket)
                    for j, rec in enumerate(recs)]
        cfg = self.cfg
        scale, max_size = cfg.bucket.scale, cfg.bucket.max_size
        futs = [self.decode_pool.submit(rec["image"],
                                        rec.get("flipped", False),
                                        scale, max_size, bucket)
                for rec in recs]
        infos = []
        for j, (rec, fut) in enumerate(zip(recs, futs)):
            h, w = self._write_slot(images[j], fut.result())
            infos.append((h, w, plan_scale(rec["height"], rec["width"],
                                           scale, max_size, bucket)))
        return infos

    def _image_buffer(self, n: int, bucket) -> np.ndarray:
        dtype = np.uint8 if self.raw_images else np.float32
        return np.zeros((n, bucket[0], bucket[1], 3), dtype)

    def record_decodes(self, on: bool = True) -> None:
        """Start (or stop) collecting the (roidb index, flipped) identity
        of every decoded image — the exactly-once audit used by the
        streaming tests and ``tools/data_bench.py``."""
        with self._decode_count_lock:
            self.decoded_ids = [] if on else None


def _prefetched(work: Iterable, make: Callable, num_workers: int,
                prefetch: int, rec=None) -> Iterator:
    """Run ``make(item)`` on a thread pool, keeping up to ``prefetch``
    results in flight; yield results in submission order.

    The reference loader is synchronous (SURVEY.md §3.1 — "no multiprocess
    prefetch"); feeding a ~30 imgs/s TPU chip from single-threaded cv2 would
    starve it, so batch assembly (imdecode + resize + pad — all
    GIL-releasing cv2/numpy) overlaps with device steps.  Thread pool, not
    processes: the arrays are large and fork/pickle would cost more than
    the GIL does.  num_workers=0 degrades to the synchronous path.

    ``rec`` (an ``obs/metrics.py`` Registry, None = off): records
    per-batch assembly wall time (``loader.assemble_ms``, measured in the
    worker thread so it is the true build cost, not the consumer's wait)
    and the prefetch depth still in flight at each yield
    (``loader.queue_depth`` — 0 means the consumer is decode-starved).
    """
    if rec is not None:
        inner = make

        def make(item):
            t0 = time.perf_counter()
            out = inner(item)
            rec.observe("loader.assemble_ms",
                        (time.perf_counter() - t0) * 1e3)
            rec.inc("loader.batches")
            return out

    if num_workers <= 0:
        for item in work:
            yield make(item)
        return
    ex = ThreadPoolExecutor(num_workers)
    futures: deque = deque()
    it = iter(work)
    exhausted = False
    try:
        while True:
            while not exhausted and len(futures) < max(prefetch, 1):
                try:  # guard ONLY the source iterator: a StopIteration
                    item = next(it)  # escaping from a worker must propagate
                except StopIteration:
                    exhausted = True
                    break
                futures.append(ex.submit(make, item))
            if not futures:
                break
            fut = futures.popleft()
            if rec is not None:
                rec.set_gauge("loader.queue_depth", len(futures))
            yield fut.result()
    finally:
        # early abandonment (consumer break / error): drop queued work and
        # return without waiting on in-flight batch builds
        ex.shutdown(wait=False, cancel_futures=True)


def _check_proposals(proposals, roidb) -> list:
    """Shared by the two proposal-fed loaders: one proposal set per roidb
    record, in order."""
    if len(proposals) != len(roidb):
        raise ValueError(
            f"{len(proposals)} proposal sets for {len(roidb)} roidb records")
    return list(proposals)


def _fill_rois(proposals, indices, scales, max_rois: int
               ) -> Tuple[np.ndarray, np.ndarray]:
    """Pack raw-coordinate (k, 5) proposal arrays into the padded
    (n, max_rois, 4) input-coordinate ROI buffer + validity mask.  ONE
    implementation for the training side (:class:`ROIIter`) and the eval
    side (:class:`ROITestLoader`) so the two stages can never disagree on
    ROI semantics."""
    n = len(indices)
    rois = np.zeros((n, max_rois, 4), np.float32)
    rois_valid = np.zeros((n, max_rois), bool)
    for j, i in enumerate(indices):
        p = np.asarray(proposals[i], np.float32).reshape(-1, 5)
        k = min(len(p), max_rois)
        rois[j, :k] = p[:k, :4] * scales[j]
        rois_valid[j, :k] = True
    return rois, rois_valid


def _bucket_of(rec, buckets, scale, max_size) -> Tuple[int, int]:
    """Bucket for a roidb record after reference resizing."""
    h, w = rec["height"], rec["width"]
    s = compute_scale(h, w, scale, max_size)
    return choose_bucket(int(round(h * s)), int(round(w * s)), buckets)


class AnchorLoader(_ImageSource):
    """Training loader (name kept for reference parity).

    Iterating yields ``Batch`` namedtuples of static shape; all images in a
    batch share one bucket.  One pass = one epoch (ref DataIter.reset
    semantics are replaced by re-iterating).
    """

    def __init__(self, roidb: Roidb, cfg: Config, batch_images: int = None,
                 shuffle: bool = True, seed: int = 0,
                 num_workers: int = None, prefetch: int = None,
                 raw_images: bool = None, cache: DecodedImageCache = None,
                 decode_pool=None, shard: Tuple[int, int] = None):
        self.roidb = list(roidb)
        self.cfg = cfg
        self._init_source(cfg, raw_images, cache, decode_pool)
        self.batch_images = batch_images or cfg.train.batch_images
        self.shuffle = shuffle
        self.seed = seed
        self.num_workers = (cfg.default.num_workers if num_workers is None
                            else num_workers)
        self.prefetch = (cfg.default.prefetch if prefetch is None
                         else prefetch)
        self._epoch = 0
        self._skip_next = 0
        self.shard = None
        if shard is not None:
            self.set_shard(*shard)
        b = cfg.bucket
        self.buckets = tuple(tuple(s) for s in b.shapes)
        self._bucket_ids = [
            _bucket_of(rec, self.buckets, b.scale, b.max_size)
            for rec in self.roidb
        ]

    def __len__(self) -> int:
        return sum(
            len(self._indices_for(bucket)) // self.batch_images
            for bucket in set(self._bucket_ids)
        )

    def _indices_for(self, bucket) -> List[int]:
        return [i for i, b in enumerate(self._bucket_ids) if b == bucket]

    def _make_batch(self, indices: Sequence[int], bucket) -> Batch:
        cfg = self.cfg
        g = cfg.train.max_gt_boxes
        n = len(indices)
        images = self._image_buffer(n, bucket)
        im_info = np.zeros((n, 3), np.float32)
        gt_boxes = np.zeros((n, g, 4), np.float32)
        gt_classes = np.zeros((n, g), np.int32)
        gt_valid = np.zeros((n, g), bool)
        recs = [self.roidb[i] for i in indices]
        infos = self._images_into(images, recs, bucket)
        for j, rec in enumerate(recs):
            h, w, im_scale = infos[j]
            im_info[j] = (h, w, im_scale)
            k = min(len(rec["boxes"]), g)
            if k:
                gt_boxes[j, :k] = rec["boxes"][:k] * im_scale
                gt_classes[j, :k] = rec["gt_classes"][:k]
                gt_valid[j, :k] = True
        return Batch(images, im_info, gt_boxes, gt_classes, gt_valid)

    def set_shard(self, shard_id: int, num_shards: int) -> None:
        """Own rows ``[shard_id * per, (shard_id + 1) * per)`` of every
        batch, ``per = batch_images / num_shards`` (docs/DATA.md).

        The batch PLAN stays the global one — identical on every process
        for a given (seed, epoch) — and each process decodes only its
        row slice, so the union of all shards' yields is bit-identical
        to the unsharded batches and an N-process world decodes 1/N of
        the epoch instead of all of it.  ``num_shards <= 0`` clears the
        shard (own everything); callable between epochs for resize-time
        remaps (ft/elastic.py relaunch path).
        """
        if num_shards is None or num_shards <= 1:
            self.shard = None
            return
        if not 0 <= shard_id < num_shards:
            raise ValueError(
                f"shard_id={shard_id} out of range for {num_shards} shards")
        if self.batch_images % num_shards:
            raise ValueError(
                f"batch_images={self.batch_images} is not divisible by "
                f"num_shards={num_shards} — rows cannot be owned evenly "
                f"(choose a divisor topology)")
        self.shard = (int(shard_id), int(num_shards))

    def _shard_rows(self, batches: List) -> List:
        """Slice this shard's rows out of every global (bucket, indices)
        batch plan entry (no-op without a shard)."""
        if self.shard is None:
            return batches
        sid, n = self.shard
        per = self.batch_images // n
        return [(bucket, idx[sid * per:(sid + 1) * per])
                for bucket, idx in batches]

    def set_epoch(self, epoch: int) -> None:
        """Pin the shuffle order of the NEXT iteration to ``epoch``.

        The shuffle RNG is derived from (seed, epoch), so a run resumed at
        epoch k replays the identical batch order the uninterrupted run saw
        — required for the bit-exact-resume invariant (utils/checkpoint.py).
        The fit loop calls this each epoch; without it, iterating advances
        the epoch automatically.
        """
        self._epoch = epoch

    def skip_next_batches(self, n: int) -> None:
        """Drop the first ``n`` batches of the NEXT iteration only
        (mid-epoch preemption resume).  The skip happens on the batch
        ORDER list, before any image is decoded — skipping 9000 consumed
        COCO batches costs nothing."""
        self._skip_next = n

    def __iter__(self) -> Iterator[Batch]:
        rng = np.random.RandomState(
            (self.seed * 1_000_003 + self._epoch) % (2 ** 31))
        self._epoch += 1
        order_by_bucket = {}
        for bucket in sorted(set(self._bucket_ids)):
            idx = self._indices_for(bucket)
            if self.shuffle:
                rng.shuffle(idx)
            order_by_bucket[bucket] = idx
        # interleave buckets batch-by-batch (ref shuffles group pairs)
        batches = []
        for bucket, idx in order_by_bucket.items():
            for s in range(0, len(idx) - self.batch_images + 1,
                           self.batch_images):
                batches.append((bucket, idx[s:s + self.batch_images]))
        if self.shuffle:
            rng.shuffle(batches)
        if self._skip_next:
            batches = batches[self._skip_next:]
            self._skip_next = 0
        yield from _prefetched(
            self._shard_rows(batches),
            lambda b: self._make_batch(b[1], b[0]),
            self.num_workers, self.prefetch, rec=self._rec)


class StreamLoader(AnchorLoader):
    """Sharded streaming training loader with a TOPOLOGY-INVARIANT epoch
    plan (docs/DATA.md; ``cfg.data.streaming`` selects it).

    :class:`AnchorLoader`'s plan shuffles per-bucket index lists, chunks
    them into batches, then shuffles the BATCH list — the final shuffle
    consumes RNG state that depends on the batch count, so two
    topologies (different ``batch_images`` after an elastic resize, or
    different grad-accum splits) see different image ORDERS and a
    mid-epoch cursor cannot transfer between them.  This loader's plan
    is a pure function of (seed, epoch) at IMAGE granularity:

    * each bucket's epoch order comes from its own RNG seeded
      ``(seed, epoch, bucket)`` — invariant to batch size, worker count,
      shard count and process count,
    * batches are consecutive chunks of each bucket's stream; the
      global batch sequence interleaves buckets by largest-remaining-
      fraction (deterministic, computable from image counts + batch
      size alone),
    * a shard owns rows of every batch exactly like the parent class.

    Consequences: the first K images consumed are the same SET under
    any batch size that divides K, so :meth:`resume_at` can reposition
    mid-epoch across a topology change — it replays the plan the
    CHECKPOINT-WRITING run used (its ``loader_batch_images`` from the
    manifest data cursor) to find each bucket's consumed prefix, then
    chunks the remainder at the CURRENT batch size.  Shard unions and
    kill/resume unions are each-image-exactly-once per epoch
    (tests/test_streaming.py pins 1/2/4-way shards, worker counts and
    shrink-mid-epoch remaps).

    Remainder semantics: images beyond the last full batch of a
    bucket's stream are dropped for the epoch (the parent's contract);
    a resume under a SMALLER batch size can cover part of that tail —
    never a duplicate, and exactly-once whenever batch size divides the
    bucket populations (the rehearsal and smoke configurations).

    Disclosed limitation (multi-bucket only): the cursor records images
    consumed, not per-bucket offsets, so :meth:`resume_at` reconstructs
    consumption by replaying ONE plan at the cursor's batch size.  After
    TWO kills in the SAME epoch with a topology change in between, the
    writing run's actual consumption was a mix of two plans and the
    replay can mis-split it across buckets — exactly-once then holds
    per bucket-stream prefix, not globally, until the epoch boundary
    resets everything.  Single-bucket datasets (every current synthetic
    set) are immune: the offset IS the image count.
    """

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self._resume: Optional[Tuple[int, int]] = None

    # -- the plan ------------------------------------------------------------
    def _bucket_orders(self, epoch: int) -> Dict[Tuple[int, int], List[int]]:
        """{bucket: per-epoch image order} — each bucket's order from its
        OWN rng, so it never depends on how other buckets consumed RNG
        state (the invariance root)."""
        orders = {}
        for bucket in sorted(set(self._bucket_ids)):
            idx = self._indices_for(bucket)
            if self.shuffle:
                s = zlib.crc32(
                    f"{self.seed}:{epoch}:{bucket[0]}x{bucket[1]}".encode()
                ) % (2 ** 31)
                np.random.RandomState(s).shuffle(idx)
            orders[bucket] = idx
        return orders

    @staticmethod
    def _interleave(counts: Dict) -> List:
        """Deterministic bucket sequence: always draw the bucket with the
        largest remaining fraction of its own batches (stable tie-break
        on the bucket tuple), so buckets drain proportionally and the
        sequence depends only on the per-bucket batch counts."""
        remaining = {b: n for b, n in counts.items() if n > 0}
        totals = dict(remaining)
        seq = []
        while remaining:
            bucket = max(sorted(remaining),
                         key=lambda b: remaining[b] / totals[b])
            seq.append(bucket)
            remaining[bucket] -= 1
            if not remaining[bucket]:
                del remaining[bucket]
        return seq

    def _plan(self, epoch: int, batch_images: int,
              offsets: Optional[Dict] = None,
              orders: Optional[Dict] = None) -> List:
        """The epoch's global batch plan [(bucket, indices), ...] for a
        given batch size, optionally starting each bucket's stream at a
        consumed-prefix ``offsets[bucket]``.  ``orders`` lets a caller
        that already built the per-bucket epoch orders (an O(corpus)
        shuffle) reuse them."""
        if orders is None:
            orders = self._bucket_orders(epoch)
        off = dict(offsets or {})
        streams = {b: o[off.get(b, 0):] for b, o in orders.items()}
        counts = {b: len(s) // batch_images for b, s in streams.items()}
        pos = {b: 0 for b in streams}
        plan = []
        for bucket in self._interleave(counts):
            p = pos[bucket]
            plan.append((bucket, streams[bucket][p:p + batch_images]))
            pos[bucket] = p + batch_images
        return plan

    # __len__ is inherited: full batches per bucket, identical formula

    # -- cursor resume -------------------------------------------------------
    def resume_at(self, images_consumed: int,
                  old_batch_images: int = None) -> None:
        """Position the NEXT iteration after ``images_consumed`` images of
        the epoch (pin the epoch first via :meth:`set_epoch`).

        ``old_batch_images`` is the batch size of the run that recorded
        the cursor (``manifest.data_cursor`` via ``topology.global_batch
        / grad_accum``); it defaults to the current size.  The consumed
        prefix is found by replaying the OLD plan, so the resumed epoch
        continues exactly where the killed run stopped even when the
        topology (and therefore the batch size) changed in between."""
        images = int(images_consumed)
        old_bi = int(old_batch_images or self.batch_images)
        if images % old_bi:
            raise ValueError(
                f"cursor images_consumed={images} is not a multiple of "
                f"the recording run's batch_images={old_bi} — the cursor "
                f"does not sit on a batch boundary")
        self._resume = (images, old_bi)

    def _consumed_offsets(self, epoch: int, images: int,
                          old_bi: int) -> Dict:
        """Per-bucket consumed-image counts after ``images`` images of the
        epoch under the OLD plan (batch size ``old_bi``; the batch-
        boundary modulo was validated in :meth:`resume_at`)."""
        plan = self._plan(epoch, old_bi)
        nb = images // old_bi
        if nb > len(plan):
            raise ValueError(
                f"cursor consumed {nb} batches but the epoch only has "
                f"{len(plan)} at batch_images={old_bi} — wrong epoch or "
                f"wrong dataset")
        offsets: Dict = {}
        for bucket, idx in plan[:nb]:
            offsets[bucket] = offsets.get(bucket, 0) + len(idx)
        return offsets

    def _epoch_plan(self, epoch: int) -> List:
        """The batch plan the next iteration will run, with any pending
        resume/skip applied (consumed here; split from ``__iter__`` so
        plan semantics are testable without decoding pixels)."""
        plan = None
        if self._resume is not None:
            images, old_bi = self._resume
            self._resume = None
            if old_bi == self.batch_images:
                # same topology: trim the ORIGINAL plan, preserving the
                # uninterrupted run's exact tail order (re-interleaving
                # the remainder would keep the SET but reorder batches,
                # breaking step-exact resume on multi-bucket sets).
                # The batch-boundary modulo was validated in resume_at.
                plan = self._plan(epoch, old_bi)[images // old_bi:]
            else:
                # topology changed: find each bucket stream's consumed
                # prefix under the OLD plan, re-chunk the remainder at
                # the current size (exactly-once; batch ORDER is the
                # new topology's — there is no old order to preserve)
                offsets = self._consumed_offsets(epoch, images, old_bi)
                plan = self._plan(epoch, self.batch_images, offsets)
        if plan is None:
            plan = self._plan(epoch, self.batch_images)
        if self._skip_next:  # same-topology skip (fit's generic fallback)
            plan = plan[self._skip_next:]
            self._skip_next = 0
        return plan

    def __iter__(self) -> Iterator[Batch]:
        epoch = self._epoch
        self._epoch += 1
        plan = self._epoch_plan(epoch)
        yield from _prefetched(
            self._shard_rows(plan),
            lambda b: self._make_batch(b[1], b[0]),
            self.num_workers, self.prefetch, rec=self._rec)


class StreamTestLoader(StreamLoader):
    """Eval-mode streaming loader: the topology-invariant
    :class:`StreamLoader` plan pointed at INFERENCE (docs/SERVING.md
    "Bulk tier").  Yields ``(Batch, indices, scales)`` exactly like
    :class:`TestLoader` (gt fields zero-filled, ``indices`` are roidb
    positions, ``scales`` un-map detections to raw image coordinates),
    but the batch sequence is the deterministic StreamLoader plan
    EXTENDED to cover every image: after the interleaved full batches,
    each bucket's remainder is appended as one final PARTIAL batch
    (sorted bucket order), so a corpus pass decodes each image exactly
    once — the scoring plane's "N in = N accounted" invariant needs the
    tail that :class:`StreamLoader` (whose contract is training batches
    of static shape) drops.

    The plan is a pure function of ``(seed, epoch=0)``, so a resumed run
    recomputes it and repositions with :meth:`skip_next_batches` — the
    bulk sink's cursor is a committed-batch count, and batch ``k`` of a
    resumed run is IDENTICAL (bucket, image indices, row order) to batch
    ``k`` of the uninterrupted run (the byte-identical-union invariant
    of ``serve/bulk.py`` rests on this).  Default ``shuffle=False``:
    corpus scoring wants the stable roidb order (still bucket-grouped by
    the plan); ``shuffle=True`` keeps the per-bucket RNG order available
    for sampling runs.
    """

    def __init__(self, roidb, cfg: Config, batch_images: int = None,
                 shuffle: bool = False, seed: int = 0, **kw):
        super().__init__(roidb, cfg,
                         batch_images or cfg.test.batch_images,
                         shuffle, seed, **kw)

    def __len__(self) -> int:
        import math

        return sum(
            math.ceil(len(self._indices_for(bucket)) / self.batch_images)
            for bucket in set(self._bucket_ids)
        )

    def _plan(self, epoch: int, batch_images: int,
              offsets: Optional[Dict] = None,
              orders: Optional[Dict] = None) -> List:
        if orders is None:  # built once: the parent reuses it below
            orders = self._bucket_orders(epoch)
        plan = super()._plan(epoch, batch_images, offsets, orders=orders)
        # append each bucket's dropped remainder as one partial batch
        consumed: Dict = {}
        for bucket, idx in plan:
            consumed[bucket] = consumed.get(bucket, 0) + len(idx)
        off = dict(offsets or {})
        for bucket in sorted(orders):
            stream = orders[bucket][off.get(bucket, 0):]
            tail = stream[consumed.get(bucket, 0):]
            if tail:
                plan.append((bucket, tail))
        return plan

    def _make_batch(self, indices: Sequence[int], bucket):
        cfg = self.cfg
        n = len(indices)
        images = self._image_buffer(n, bucket)
        im_info = np.zeros((n, 3), np.float32)
        scales = np.zeros((n,), np.float32)
        recs = [self.roidb[i] for i in indices]
        infos = self._images_into(images, recs, bucket)
        for j, (h, w, im_scale) in enumerate(infos):
            im_info[j] = (h, w, im_scale)
            scales[j] = im_scale
        g = cfg.train.max_gt_boxes
        batch = Batch(
            images, im_info,
            np.zeros((n, g, 4), np.float32),
            np.zeros((n, g), np.int32),
            np.zeros((n, g), bool),
        )
        return batch, list(indices), scales


class ROIIter(AnchorLoader):
    """RCNN-only training loader from precomputed proposals (ref
    ``rcnn/core/loader.py — ROIIter`` feeding ``get_rcnn_batch``).

    ``proposals[i]`` is the (k, 5) [x1 y1 x2 y2 score] array for roidb
    record ``i`` in RAW image coordinates (as returned by
    ``core.tester.generate_proposals``); boxes are scaled into input
    coordinates per image and padded to ``max_rois`` slots.  Target
    sampling itself runs on device (``ops.targets.proposal_target``),
    mirroring the reference's host-side ``sample_rois``.
    """

    def __init__(self, roidb: Roidb, cfg: Config, proposals: Sequence,
                 batch_images: int = None, shuffle: bool = True,
                 seed: int = 0, max_rois: int = None,
                 num_workers: int = None, prefetch: int = None,
                 raw_images: bool = None, cache: DecodedImageCache = None,
                 decode_pool=None, shard: Tuple[int, int] = None):
        super().__init__(roidb, cfg, batch_images, shuffle, seed,
                         num_workers=num_workers, prefetch=prefetch,
                         raw_images=raw_images, cache=cache,
                         decode_pool=decode_pool, shard=shard)
        self.proposals = _check_proposals(proposals, self.roidb)
        self.max_rois = max_rois or cfg.test.proposal_post_nms_top_n

    def _make_batch(self, indices: Sequence[int], bucket):
        from mx_rcnn_tpu.core.train import RCNNBatch

        base = super()._make_batch(indices, bucket)
        rois, rois_valid = _fill_rois(self.proposals, indices,
                                      base.im_info[:, 2], self.max_rois)
        return RCNNBatch(*base, rois=rois, rois_valid=rois_valid)


class TestLoader(_ImageSource):
    """Evaluation loader (ref ``TestLoader``): yields
    ``(Batch, indices, scales)`` — gt fields are zero-filled, ``indices``
    are roidb positions and ``scales`` un-map detections back to raw image
    coordinates (ref pred_eval divides boxes by im_scale)."""

    def __init__(self, roidb: Roidb, cfg: Config, batch_images: int = None,
                 num_workers: int = None, prefetch: int = None,
                 raw_images: bool = None, cache: DecodedImageCache = None,
                 decode_pool=None):
        self.roidb = list(roidb)
        self.cfg = cfg
        self._init_source(cfg, raw_images, cache, decode_pool)
        self.batch_images = batch_images or cfg.test.batch_images
        self.num_workers = (cfg.default.num_workers if num_workers is None
                            else num_workers)
        self.prefetch = (cfg.default.prefetch if prefetch is None
                         else prefetch)
        b = cfg.bucket
        self.buckets = tuple(tuple(s) for s in b.shapes)
        self._bucket_ids = [
            _bucket_of(rec, self.buckets, b.scale, b.max_size)
            for rec in self.roidb
        ]

    def __len__(self) -> int:
        import math

        return sum(
            math.ceil(
                len([i for i, b in enumerate(self._bucket_ids) if b == bucket])
                / self.batch_images)
            for bucket in set(self._bucket_ids)
        )

    def _make_batch(self, chunk: Sequence[int], bucket):
        cfg = self.cfg
        n = len(chunk)
        images = self._image_buffer(n, bucket)
        im_info = np.zeros((n, 3), np.float32)
        scales = np.zeros((n,), np.float32)
        # the flipped flag is honored here: eval roidbs never set it, but
        # alternate training generates proposals over the flip-augmented
        # TRAIN roidb through this loader
        recs = [self.roidb[i] for i in chunk]
        infos = self._images_into(images, recs, bucket)
        for j, (h, w, im_scale) in enumerate(infos):
            im_info[j] = (h, w, im_scale)
            scales[j] = im_scale
        g = cfg.train.max_gt_boxes
        batch = Batch(
            images, im_info,
            np.zeros((n, g, 4), np.float32),
            np.zeros((n, g), np.int32),
            np.zeros((n, g), bool),
        )
        return batch, list(chunk), scales

    def __iter__(self):
        batches = []
        for bucket in sorted(set(self._bucket_ids)):
            idx = [i for i, b in enumerate(self._bucket_ids) if b == bucket]
            for s in range(0, len(idx), self.batch_images):
                batches.append((bucket, idx[s:s + self.batch_images]))
        yield from _prefetched(
            batches, lambda b: self._make_batch(b[1], b[0]),
            self.num_workers, self.prefetch, rec=self._rec)


class ROITestLoader(TestLoader):
    """Evaluation loader for RCNN-only checkpoints: like :class:`TestLoader`
    but each batch also carries precomputed proposals (ref the
    HAS_RPN=False ``TestLoader`` feeding ``rcnn/tools/test_rcnn.py``).

    ``proposals[i]`` is the (k, 5) [x1 y1 x2 y2 score] array for roidb
    record ``i`` in RAW image coordinates (the pkl written by
    ``tools/test_rpn.py``); boxes are scaled into input coordinates and
    padded to ``max_rois`` slots, mirroring :class:`ROIIter` on the
    training side so the two stages see identical ROI semantics.
    """

    def __init__(self, roidb: Roidb, cfg: Config, proposals: Sequence,
                 batch_images: int = None, max_rois: int = None,
                 num_workers: int = None, prefetch: int = None,
                 raw_images: bool = None, cache: DecodedImageCache = None,
                 decode_pool=None):
        super().__init__(roidb, cfg, batch_images, num_workers=num_workers,
                         prefetch=prefetch, raw_images=raw_images,
                         cache=cache, decode_pool=decode_pool)
        self.proposals = _check_proposals(proposals, self.roidb)
        # same default slot count as the training-side ROIIter: proposal
        # dumps are post-NMS-capped at proposal_post_nms_top_n
        self.max_rois = max_rois or cfg.test.proposal_post_nms_top_n

    def _make_batch(self, chunk: Sequence[int], bucket):
        from mx_rcnn_tpu.core.train import RCNNBatch

        base, indices, scales = super()._make_batch(chunk, bucket)
        rois, rois_valid = _fill_rois(self.proposals, indices, scales,
                                      self.max_rois)
        return (RCNNBatch(*base, rois=rois, rois_valid=rois_valid),
                indices, scales)
