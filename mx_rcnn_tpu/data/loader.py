"""Batch loaders producing static-shape device batches.

Reference: ``rcnn/core/loader.py`` — ``AnchorLoader`` (shuffle with
aspect-ratio grouping, image load/augment, host-side ``assign_anchor``,
pad-to-batch-max) and ``TestLoader``.

TPU-native differences:
* anchor/proposal target assignment happens ON DEVICE inside the jitted
  train step, so ``AnchorLoader`` here only assembles (images, im_info, gt)
  — with a single host core feeding up to 8 chips, host-side assignment
  would dominate the step time,
* images pad into static buckets; aspect grouping (ref ASPECT_GROUPING)
  becomes bucket grouping: every batch holds images of one bucket so a
  fixed set of XLA programs serves the whole epoch,
* gt arrays are padded to ``max_gt_boxes`` with a validity mask instead of
  variable-length label blobs.
"""

from __future__ import annotations

import logging
import time

from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Iterable, Iterator, List, Sequence, Tuple

import numpy as np

from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.core.train import Batch
from mx_rcnn_tpu.data.cache import DecodedImageCache, plan_scale
from mx_rcnn_tpu.data.image import (choose_bucket, compute_scale,
                                    load_resized_uint8)
from mx_rcnn_tpu.data.roidb import Roidb


def cache_from_config(cfg: Config) -> DecodedImageCache | None:
    """Build the decoded-image cache the config asks for (None = disabled)."""
    d = cfg.default
    if d.image_cache_mb <= 0 and not d.image_cache_dir:
        return None
    return DecodedImageCache(ram_bytes=d.image_cache_mb << 20,
                             cache_dir=d.image_cache_dir or None)


def decode_pool_from_config(cfg: Config):
    """Build the process decode pool the config asks for (None = in-thread
    decode).  Callers own the pool: close() it when the loaders are done
    (``tools/train.py`` wraps fit in try/finally)."""
    d = cfg.default
    if d.decode_procs <= 0:
        return None
    from mx_rcnn_tpu.data.decode_pool import DecodePool

    # decode runs in the worker processes, so the RAM tier must live there
    # too: split the configured budget across workers (the parent's cache
    # is never consulted on this path — advisor r4).  The disk tier stays
    # shared via cache_dir.
    per_worker = (d.image_cache_mb << 20) // d.decode_procs
    if d.image_cache_mb > 0 and per_worker < (1 << 20):
        # an integer-division share of 0 would silently disable the RAM
        # tier the config asked for (ADVICE r5); clamp to a useful floor
        logging.getLogger("mx_rcnn_tpu").warning(
            "image_cache_mb=%d split across decode_procs=%d leaves under "
            "1 MB per worker; clamping each worker's RAM tier to 1 MB "
            "(raise image_cache_mb to at least decode_procs to silence)",
            d.image_cache_mb, d.decode_procs)
        per_worker = 1 << 20
    if d.image_cache_mb > 0:
        logging.getLogger("mx_rcnn_tpu").info(
            "decode_procs=%d: image_cache_mb=%d RAM tier moves into the "
            "workers at %d MB each (total RSS budget unchanged)",
            d.decode_procs, d.image_cache_mb, per_worker >> 20)
    return DecodePool(d.decode_procs, cache_dir=d.image_cache_dir or None,
                      ram_bytes=per_worker)


class _ImageSource:
    """Shared decode/cache plumbing for the three loaders.

    ``raw_images=True`` (the TPU-native default) emits uint8 batches that
    the model normalizes on device (``ops/normalize.py``); False reproduces
    the reference's host-side fp32 mean-subtract.  Either mode can sit on a
    :class:`DecodedImageCache`.  im_info records the ACTUAL post-resize dims
    (matching the reference, which reads them off the resized array) — the
    device-side normalization mask depends on them being exact.
    """

    def _init_source(self, cfg: Config, raw_images, cache,
                     decode_pool=None) -> None:
        self.raw_images = (cfg.default.raw_images if raw_images is None
                           else raw_images)
        self.cache = cache
        self.decode_pool = decode_pool
        self._pixel_means = np.asarray(cfg.network.pixel_means, np.float32)
        # observability (docs/OBSERVABILITY.md): with cfg.obs.enabled the
        # loader records decode/assemble time and prefetch queue depth
        # into the process registry; None (the default) keeps the hot
        # path at a single attribute check
        self._rec = None
        obs = getattr(cfg, "obs", None)
        if obs is not None and obs.enabled:
            from mx_rcnn_tpu.obs.metrics import registry

            self._rec = registry()

    def _write_slot(self, out: np.ndarray, img: np.ndarray) -> Tuple[int, int]:
        h, w = img.shape[:2]
        if self.raw_images:
            out[:h, :w] = img
        else:
            np.subtract(img, self._pixel_means, out=out[:h, :w],
                        casting="unsafe")
        return h, w

    def _image_into(self, out: np.ndarray, rec, bucket) -> Tuple[int, int, float]:
        """Decode ``rec`` (through the cache if present) and write it into
        ``out`` (one padded bucket slot).  Returns (h, w, im_scale)."""
        cfg = self.cfg
        scale, max_size = cfg.bucket.scale, cfg.bucket.max_size
        flipped = rec.get("flipped", False)
        if self.cache is not None:
            img = self.cache.load(rec["image"], flipped, scale, max_size,
                                  bucket)
            im_scale = plan_scale(rec["height"], rec["width"], scale,
                                  max_size, bucket)
        else:
            img, im_scale = load_resized_uint8(rec["image"], flipped, scale,
                                               max_size, bucket)
        h, w = self._write_slot(out, img)
        return h, w, im_scale

    def _images_into(self, images: np.ndarray, recs, bucket
                     ) -> List[Tuple[int, int, float]]:
        """Decode ``recs`` into the padded batch buffer; returns one
        (h, w, im_scale) per record.  With a :class:`DecodePool`
        (``data/decode_pool.py``) the decodes run in worker PROCESSES —
        all images of the batch in flight at once — and im_scale is
        derived parent-side from the record geometry (``plan_scale`` is
        pinned equal to the decode path's scale); without one, the decode
        runs in-thread through the optional cache."""
        if self._rec is None:
            return self._decode_into(images, recs, bucket)
        t0 = time.perf_counter()
        out = self._decode_into(images, recs, bucket)
        self._rec.observe("loader.decode_ms",
                          (time.perf_counter() - t0) * 1e3)
        return out

    def _decode_into(self, images: np.ndarray, recs, bucket
                     ) -> List[Tuple[int, int, float]]:
        if self.decode_pool is None:
            return [self._image_into(images[j], rec, bucket)
                    for j, rec in enumerate(recs)]
        cfg = self.cfg
        scale, max_size = cfg.bucket.scale, cfg.bucket.max_size
        futs = [self.decode_pool.submit(rec["image"],
                                        rec.get("flipped", False),
                                        scale, max_size, bucket)
                for rec in recs]
        infos = []
        for j, (rec, fut) in enumerate(zip(recs, futs)):
            h, w = self._write_slot(images[j], fut.result())
            infos.append((h, w, plan_scale(rec["height"], rec["width"],
                                           scale, max_size, bucket)))
        return infos

    def _image_buffer(self, n: int, bucket) -> np.ndarray:
        dtype = np.uint8 if self.raw_images else np.float32
        return np.zeros((n, bucket[0], bucket[1], 3), dtype)


def _prefetched(work: Iterable, make: Callable, num_workers: int,
                prefetch: int, rec=None) -> Iterator:
    """Run ``make(item)`` on a thread pool, keeping up to ``prefetch``
    results in flight; yield results in submission order.

    The reference loader is synchronous (SURVEY.md §3.1 — "no multiprocess
    prefetch"); feeding a ~30 imgs/s TPU chip from single-threaded cv2 would
    starve it, so batch assembly (imdecode + resize + pad — all
    GIL-releasing cv2/numpy) overlaps with device steps.  Thread pool, not
    processes: the arrays are large and fork/pickle would cost more than
    the GIL does.  num_workers=0 degrades to the synchronous path.

    ``rec`` (an ``obs/metrics.py`` Registry, None = off): records
    per-batch assembly wall time (``loader.assemble_ms``, measured in the
    worker thread so it is the true build cost, not the consumer's wait)
    and the prefetch depth still in flight at each yield
    (``loader.queue_depth`` — 0 means the consumer is decode-starved).
    """
    if rec is not None:
        inner = make

        def make(item):
            t0 = time.perf_counter()
            out = inner(item)
            rec.observe("loader.assemble_ms",
                        (time.perf_counter() - t0) * 1e3)
            rec.inc("loader.batches")
            return out

    if num_workers <= 0:
        for item in work:
            yield make(item)
        return
    ex = ThreadPoolExecutor(num_workers)
    futures: deque = deque()
    it = iter(work)
    exhausted = False
    try:
        while True:
            while not exhausted and len(futures) < max(prefetch, 1):
                try:  # guard ONLY the source iterator: a StopIteration
                    item = next(it)  # escaping from a worker must propagate
                except StopIteration:
                    exhausted = True
                    break
                futures.append(ex.submit(make, item))
            if not futures:
                break
            fut = futures.popleft()
            if rec is not None:
                rec.set_gauge("loader.queue_depth", len(futures))
            yield fut.result()
    finally:
        # early abandonment (consumer break / error): drop queued work and
        # return without waiting on in-flight batch builds
        ex.shutdown(wait=False, cancel_futures=True)


def _check_proposals(proposals, roidb) -> list:
    """Shared by the two proposal-fed loaders: one proposal set per roidb
    record, in order."""
    if len(proposals) != len(roidb):
        raise ValueError(
            f"{len(proposals)} proposal sets for {len(roidb)} roidb records")
    return list(proposals)


def _fill_rois(proposals, indices, scales, max_rois: int
               ) -> Tuple[np.ndarray, np.ndarray]:
    """Pack raw-coordinate (k, 5) proposal arrays into the padded
    (n, max_rois, 4) input-coordinate ROI buffer + validity mask.  ONE
    implementation for the training side (:class:`ROIIter`) and the eval
    side (:class:`ROITestLoader`) so the two stages can never disagree on
    ROI semantics."""
    n = len(indices)
    rois = np.zeros((n, max_rois, 4), np.float32)
    rois_valid = np.zeros((n, max_rois), bool)
    for j, i in enumerate(indices):
        p = np.asarray(proposals[i], np.float32).reshape(-1, 5)
        k = min(len(p), max_rois)
        rois[j, :k] = p[:k, :4] * scales[j]
        rois_valid[j, :k] = True
    return rois, rois_valid


def _bucket_of(rec, buckets, scale, max_size) -> Tuple[int, int]:
    """Bucket for a roidb record after reference resizing."""
    h, w = rec["height"], rec["width"]
    s = compute_scale(h, w, scale, max_size)
    return choose_bucket(int(round(h * s)), int(round(w * s)), buckets)


class AnchorLoader(_ImageSource):
    """Training loader (name kept for reference parity).

    Iterating yields ``Batch`` namedtuples of static shape; all images in a
    batch share one bucket.  One pass = one epoch (ref DataIter.reset
    semantics are replaced by re-iterating).
    """

    def __init__(self, roidb: Roidb, cfg: Config, batch_images: int = None,
                 shuffle: bool = True, seed: int = 0,
                 num_workers: int = None, prefetch: int = None,
                 raw_images: bool = None, cache: DecodedImageCache = None,
                 decode_pool=None):
        self.roidb = list(roidb)
        self.cfg = cfg
        self._init_source(cfg, raw_images, cache, decode_pool)
        self.batch_images = batch_images or cfg.train.batch_images
        self.shuffle = shuffle
        self.seed = seed
        self.num_workers = (cfg.default.num_workers if num_workers is None
                            else num_workers)
        self.prefetch = (cfg.default.prefetch if prefetch is None
                         else prefetch)
        self._epoch = 0
        self._skip_next = 0
        b = cfg.bucket
        self.buckets = tuple(tuple(s) for s in b.shapes)
        self._bucket_ids = [
            _bucket_of(rec, self.buckets, b.scale, b.max_size)
            for rec in self.roidb
        ]

    def __len__(self) -> int:
        return sum(
            len(self._indices_for(bucket)) // self.batch_images
            for bucket in set(self._bucket_ids)
        )

    def _indices_for(self, bucket) -> List[int]:
        return [i for i, b in enumerate(self._bucket_ids) if b == bucket]

    def _make_batch(self, indices: Sequence[int], bucket) -> Batch:
        cfg = self.cfg
        g = cfg.train.max_gt_boxes
        n = len(indices)
        images = self._image_buffer(n, bucket)
        im_info = np.zeros((n, 3), np.float32)
        gt_boxes = np.zeros((n, g, 4), np.float32)
        gt_classes = np.zeros((n, g), np.int32)
        gt_valid = np.zeros((n, g), bool)
        recs = [self.roidb[i] for i in indices]
        infos = self._images_into(images, recs, bucket)
        for j, rec in enumerate(recs):
            h, w, im_scale = infos[j]
            im_info[j] = (h, w, im_scale)
            k = min(len(rec["boxes"]), g)
            if k:
                gt_boxes[j, :k] = rec["boxes"][:k] * im_scale
                gt_classes[j, :k] = rec["gt_classes"][:k]
                gt_valid[j, :k] = True
        return Batch(images, im_info, gt_boxes, gt_classes, gt_valid)

    def set_epoch(self, epoch: int) -> None:
        """Pin the shuffle order of the NEXT iteration to ``epoch``.

        The shuffle RNG is derived from (seed, epoch), so a run resumed at
        epoch k replays the identical batch order the uninterrupted run saw
        — required for the bit-exact-resume invariant (utils/checkpoint.py).
        The fit loop calls this each epoch; without it, iterating advances
        the epoch automatically.
        """
        self._epoch = epoch

    def skip_next_batches(self, n: int) -> None:
        """Drop the first ``n`` batches of the NEXT iteration only
        (mid-epoch preemption resume).  The skip happens on the batch
        ORDER list, before any image is decoded — skipping 9000 consumed
        COCO batches costs nothing."""
        self._skip_next = n

    def __iter__(self) -> Iterator[Batch]:
        rng = np.random.RandomState(
            (self.seed * 1_000_003 + self._epoch) % (2 ** 31))
        self._epoch += 1
        order_by_bucket = {}
        for bucket in sorted(set(self._bucket_ids)):
            idx = self._indices_for(bucket)
            if self.shuffle:
                rng.shuffle(idx)
            order_by_bucket[bucket] = idx
        # interleave buckets batch-by-batch (ref shuffles group pairs)
        batches = []
        for bucket, idx in order_by_bucket.items():
            for s in range(0, len(idx) - self.batch_images + 1,
                           self.batch_images):
                batches.append((bucket, idx[s:s + self.batch_images]))
        if self.shuffle:
            rng.shuffle(batches)
        if self._skip_next:
            batches = batches[self._skip_next:]
            self._skip_next = 0
        yield from _prefetched(
            batches, lambda b: self._make_batch(b[1], b[0]),
            self.num_workers, self.prefetch, rec=self._rec)


class ROIIter(AnchorLoader):
    """RCNN-only training loader from precomputed proposals (ref
    ``rcnn/core/loader.py — ROIIter`` feeding ``get_rcnn_batch``).

    ``proposals[i]`` is the (k, 5) [x1 y1 x2 y2 score] array for roidb
    record ``i`` in RAW image coordinates (as returned by
    ``core.tester.generate_proposals``); boxes are scaled into input
    coordinates per image and padded to ``max_rois`` slots.  Target
    sampling itself runs on device (``ops.targets.proposal_target``),
    mirroring the reference's host-side ``sample_rois``.
    """

    def __init__(self, roidb: Roidb, cfg: Config, proposals: Sequence,
                 batch_images: int = None, shuffle: bool = True,
                 seed: int = 0, max_rois: int = None,
                 num_workers: int = None, prefetch: int = None,
                 raw_images: bool = None, cache: DecodedImageCache = None,
                 decode_pool=None):
        super().__init__(roidb, cfg, batch_images, shuffle, seed,
                         num_workers=num_workers, prefetch=prefetch,
                         raw_images=raw_images, cache=cache,
                         decode_pool=decode_pool)
        self.proposals = _check_proposals(proposals, self.roidb)
        self.max_rois = max_rois or cfg.test.proposal_post_nms_top_n

    def _make_batch(self, indices: Sequence[int], bucket):
        from mx_rcnn_tpu.core.train import RCNNBatch

        base = super()._make_batch(indices, bucket)
        rois, rois_valid = _fill_rois(self.proposals, indices,
                                      base.im_info[:, 2], self.max_rois)
        return RCNNBatch(*base, rois=rois, rois_valid=rois_valid)


class TestLoader(_ImageSource):
    """Evaluation loader (ref ``TestLoader``): yields
    ``(Batch, indices, scales)`` — gt fields are zero-filled, ``indices``
    are roidb positions and ``scales`` un-map detections back to raw image
    coordinates (ref pred_eval divides boxes by im_scale)."""

    def __init__(self, roidb: Roidb, cfg: Config, batch_images: int = None,
                 num_workers: int = None, prefetch: int = None,
                 raw_images: bool = None, cache: DecodedImageCache = None,
                 decode_pool=None):
        self.roidb = list(roidb)
        self.cfg = cfg
        self._init_source(cfg, raw_images, cache, decode_pool)
        self.batch_images = batch_images or cfg.test.batch_images
        self.num_workers = (cfg.default.num_workers if num_workers is None
                            else num_workers)
        self.prefetch = (cfg.default.prefetch if prefetch is None
                         else prefetch)
        b = cfg.bucket
        self.buckets = tuple(tuple(s) for s in b.shapes)
        self._bucket_ids = [
            _bucket_of(rec, self.buckets, b.scale, b.max_size)
            for rec in self.roidb
        ]

    def __len__(self) -> int:
        import math

        return sum(
            math.ceil(
                len([i for i, b in enumerate(self._bucket_ids) if b == bucket])
                / self.batch_images)
            for bucket in set(self._bucket_ids)
        )

    def _make_batch(self, chunk: Sequence[int], bucket):
        cfg = self.cfg
        n = len(chunk)
        images = self._image_buffer(n, bucket)
        im_info = np.zeros((n, 3), np.float32)
        scales = np.zeros((n,), np.float32)
        # the flipped flag is honored here: eval roidbs never set it, but
        # alternate training generates proposals over the flip-augmented
        # TRAIN roidb through this loader
        recs = [self.roidb[i] for i in chunk]
        infos = self._images_into(images, recs, bucket)
        for j, (h, w, im_scale) in enumerate(infos):
            im_info[j] = (h, w, im_scale)
            scales[j] = im_scale
        g = cfg.train.max_gt_boxes
        batch = Batch(
            images, im_info,
            np.zeros((n, g, 4), np.float32),
            np.zeros((n, g), np.int32),
            np.zeros((n, g), bool),
        )
        return batch, list(chunk), scales

    def __iter__(self):
        batches = []
        for bucket in sorted(set(self._bucket_ids)):
            idx = [i for i, b in enumerate(self._bucket_ids) if b == bucket]
            for s in range(0, len(idx), self.batch_images):
                batches.append((bucket, idx[s:s + self.batch_images]))
        yield from _prefetched(
            batches, lambda b: self._make_batch(b[1], b[0]),
            self.num_workers, self.prefetch, rec=self._rec)


class ROITestLoader(TestLoader):
    """Evaluation loader for RCNN-only checkpoints: like :class:`TestLoader`
    but each batch also carries precomputed proposals (ref the
    HAS_RPN=False ``TestLoader`` feeding ``rcnn/tools/test_rcnn.py``).

    ``proposals[i]`` is the (k, 5) [x1 y1 x2 y2 score] array for roidb
    record ``i`` in RAW image coordinates (the pkl written by
    ``tools/test_rpn.py``); boxes are scaled into input coordinates and
    padded to ``max_rois`` slots, mirroring :class:`ROIIter` on the
    training side so the two stages see identical ROI semantics.
    """

    def __init__(self, roidb: Roidb, cfg: Config, proposals: Sequence,
                 batch_images: int = None, max_rois: int = None,
                 num_workers: int = None, prefetch: int = None,
                 raw_images: bool = None, cache: DecodedImageCache = None,
                 decode_pool=None):
        super().__init__(roidb, cfg, batch_images, num_workers=num_workers,
                         prefetch=prefetch, raw_images=raw_images,
                         cache=cache, decode_pool=decode_pool)
        self.proposals = _check_proposals(proposals, self.roidb)
        # same default slot count as the training-side ROIIter: proposal
        # dumps are post-NMS-capped at proposal_post_nms_top_n
        self.max_rois = max_rois or cfg.test.proposal_post_nms_top_n

    def _make_batch(self, chunk: Sequence[int], bucket):
        from mx_rcnn_tpu.core.train import RCNNBatch

        base, indices, scales = super()._make_batch(chunk, bucket)
        rois, rois_valid = _fill_rois(self.proposals, indices, scales,
                                      self.max_rois)
        return (RCNNBatch(*base, rois=rois, rois_valid=rois_valid),
                indices, scales)
