"""COCO-style bbox mAP evaluation, dependency-free.

Reference: the vendored ``rcnn/pycocotools/cocoeval.py — COCOeval`` (bbox
mode).  pycocotools is not installable in this environment, so the bbox
evaluation protocol is reimplemented here in NumPy: greedy score-ordered
matching per (category, IoU threshold), crowd boxes as ignore regions,
101-point interpolated precision averaged over IoU 0.50:0.95:0.05, plus the
AP50/AP75 and small/medium/large area breakdowns.  RLE mask evaluation is
NOT reimplemented (the reference only uses bbox eval for Faster R-CNN).
"""

from __future__ import annotations

from typing import Dict, List, Mapping, Sequence

import numpy as np

IOU_THRS = np.round(np.arange(0.5, 1.0, 0.05), 2)
RECALL_THRS = np.linspace(0.0, 1.0, 101)
AREA_RANGES = {
    "all": (0.0, 1e10),
    "small": (0.0, 32.0 ** 2),
    "medium": (32.0 ** 2, 96.0 ** 2),
    "large": (96.0 ** 2, 1e10),
}


def _iou_xyxy(dets: np.ndarray, gts: np.ndarray, iscrowd: np.ndarray
              ) -> np.ndarray:
    """IoU matrix (D, G); for crowd gt, IoU = intersection / det area
    (pycocotools semantics)."""
    d = dets[:, None, :]
    g = gts[None, :, :]
    iw = np.minimum(d[..., 2], g[..., 2]) - np.maximum(d[..., 0], g[..., 0])
    ih = np.minimum(d[..., 3], g[..., 3]) - np.maximum(d[..., 1], g[..., 1])
    iw = np.maximum(iw, 0.0)
    ih = np.maximum(ih, 0.0)
    inter = iw * ih
    area_d = (dets[:, 2] - dets[:, 0]) * (dets[:, 3] - dets[:, 1])
    area_g = (gts[:, 2] - gts[:, 0]) * (gts[:, 3] - gts[:, 1])
    union = area_d[:, None] + area_g[None, :] - inter
    union = np.where(iscrowd[None, :], area_d[:, None], union)
    return np.where(union > 0, inter / np.maximum(union, 1e-12), 0.0)


def _evaluate_image(dets: np.ndarray, gt_boxes: np.ndarray,
                    gt_ignore: np.ndarray, iscrowd: np.ndarray,
                    max_dets: int):
    """Match one image's detections for all IoU thresholds at once.

    Returns (det_scores (D,), det_matched (T, D), det_ignore (T, D),
    num_gt_not_ignored).
    """
    order = np.argsort(-dets[:, 4], kind="mergesort")[:max_dets]
    dets = dets[order]
    nd = len(dets)
    ngt = len(gt_boxes)
    t = len(IOU_THRS)
    matched = np.zeros((t, nd), bool)
    ignored = np.zeros((t, nd), bool)
    if ngt:
        # sort gt: real first, ignored last (pycocotools order)
        gt_order = np.argsort(gt_ignore, kind="mergesort")
        gt_boxes = gt_boxes[gt_order]
        gt_ignore_s = gt_ignore[gt_order]
        crowd_s = iscrowd[gt_order]
        ious = _iou_xyxy(dets[:, :4], gt_boxes, crowd_s)
        for ti, thr in enumerate(IOU_THRS):
            gt_used = np.zeros(ngt, bool)
            for di in range(nd):
                best_iou = min(thr, 1 - 1e-10)
                best_g = -1
                for gi in range(ngt):
                    if gt_used[gi] and not crowd_s[gi]:
                        continue
                    # stop matching real gt once we reach ignored ones if a
                    # real match was already found
                    if best_g > -1 and not gt_ignore_s[best_g] and gt_ignore_s[gi]:
                        break
                    if ious[di, gi] < best_iou:
                        continue
                    best_iou = ious[di, gi]
                    best_g = gi
                if best_g >= 0:
                    gt_used[best_g] = True
                    matched[ti, di] = True
                    ignored[ti, di] = gt_ignore_s[best_g]
    return dets[:, 4], matched, ignored, int((~gt_ignore).sum())


def evaluate_bbox(
    dets_by_image_cat: Mapping[str, Mapping[int, np.ndarray]],
    gt_by_image_cat: Mapping[str, Mapping[int, Dict]],
    categories: Sequence[int],
    max_dets: int = 100,
) -> Dict[str, float]:
    """COCO bbox AP.

    Args:
      dets_by_image_cat: image id → {category → (k, 5) [x1 y1 x2 y2 score]}.
      gt_by_image_cat: image id → {category → dict(boxes (n, 4),
        iscrowd (n,) bool, area (n,))}; area defaults to box area.
      categories: category ids to evaluate.
    Returns dict with AP, AP50, AP75, AP_small/medium/large, AR_100.
    """
    images = list(gt_by_image_cat.keys())
    t = len(IOU_THRS)
    precisions = {k: [] for k in AREA_RANGES}  # per (cat): (T, 101) arrays
    recalls = {k: [] for k in AREA_RANGES}

    for cat in categories:
        per_area_stats = {k: [] for k in AREA_RANGES}
        for area_name, (lo, hi) in AREA_RANGES.items():
            scores_all, matched_all, ignored_all = [], [], []
            npos = 0
            for img in images:
                gt = gt_by_image_cat[img].get(cat)
                if gt is None:
                    gt_boxes = np.zeros((0, 4))
                    iscrowd = np.zeros((0,), bool)
                    areas = np.zeros((0,))
                else:
                    gt_boxes = np.asarray(gt["boxes"]).reshape(-1, 4)
                    iscrowd = np.asarray(
                        gt.get("iscrowd", np.zeros(len(gt_boxes), bool)), bool)
                    areas = np.asarray(gt.get(
                        "area",
                        (gt_boxes[:, 2] - gt_boxes[:, 0])
                        * (gt_boxes[:, 3] - gt_boxes[:, 1])))
                gt_ignore = iscrowd | (areas < lo) | (areas >= hi)
                dets = dets_by_image_cat.get(img, {}).get(cat)
                dets = (np.asarray(dets).reshape(-1, 5) if dets is not None
                        else np.zeros((0, 5)))
                if len(dets) == 0 and len(gt_boxes) == 0:
                    continue
                s, m, ig, np_img = _evaluate_image(
                    dets, gt_boxes, gt_ignore, iscrowd, max_dets)
                # detections outside the area range that match nothing are
                # ignored too (pycocotools marks unmatched out-of-range dets)
                d_area = (dets[:, 2] - dets[:, 0]) * (dets[:, 3] - dets[:, 1])
                order = np.argsort(-dets[:, 4], kind="mergesort")[:max_dets]
                oor = (d_area[order] < lo) | (d_area[order] >= hi)
                ig = ig | (~m & oor[None, :])
                scores_all.append(s)
                matched_all.append(m)
                ignored_all.append(ig)
                npos += np_img
            if npos == 0:
                per_area_stats[area_name] = None
                continue
            scores = np.concatenate(scores_all) if scores_all else np.zeros(0)
            matched = (np.concatenate(matched_all, axis=1) if matched_all
                       else np.zeros((t, 0), bool))
            ignored = (np.concatenate(ignored_all, axis=1) if ignored_all
                       else np.zeros((t, 0), bool))
            order = np.argsort(-scores, kind="mergesort")
            matched = matched[:, order]
            ignored = ignored[:, order]
            prec_interp = np.zeros((t, len(RECALL_THRS)))
            rec_final = np.zeros(t)
            for ti in range(t):
                keep = ~ignored[ti]
                tps = np.cumsum(matched[ti][keep])
                fps = np.cumsum(~matched[ti][keep])
                rec = tps / npos
                prec = tps / np.maximum(tps + fps, 1e-12)
                # make precision monotonically decreasing then sample
                for i in range(len(prec) - 1, 0, -1):
                    prec[i - 1] = max(prec[i - 1], prec[i])
                idx = np.searchsorted(rec, RECALL_THRS, side="left")
                valid = idx < len(prec)
                prec_interp[ti, valid] = prec[idx[valid]]
                rec_final[ti] = rec[-1] if len(rec) else 0.0
            per_area_stats[area_name] = (prec_interp, rec_final)
        for area_name in AREA_RANGES:
            st = per_area_stats[area_name]
            if st is not None:
                precisions[area_name].append(st[0])
                recalls[area_name].append(st[1])

    def mean_ap(area: str, thr_idx=None) -> float:
        ps = precisions[area]
        if not ps:
            return float("nan")
        arr = np.stack(ps)  # (cats, T, 101)
        if thr_idx is not None:
            arr = arr[:, thr_idx:thr_idx + 1]
        return float(arr.mean())

    out = {
        "AP": mean_ap("all"),
        "AP50": mean_ap("all", 0),
        "AP75": mean_ap("all", 5),
        "AP_small": mean_ap("small"),
        "AP_medium": mean_ap("medium"),
        "AP_large": mean_ap("large"),
    }
    if recalls["all"]:
        out["AR_100"] = float(np.stack(recalls["all"]).mean())
    return out
