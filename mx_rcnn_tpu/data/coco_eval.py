"""COCO-style mAP evaluation (bbox AND segm modes), dependency-free.

Reference: the vendored ``rcnn/pycocotools/cocoeval.py — COCOeval``.
pycocotools is not installable in this environment, so the evaluation
protocol is reimplemented here in NumPy: greedy score-ordered matching per
(category, IoU threshold), crowd annotations as ignore regions, 101-point
interpolated precision averaged over IoU 0.50:0.95:0.05, plus the AP50/AP75
and small/medium/large area breakdowns.  The matcher is vectorized over the
10 thresholds and fuzz-checked against a direct transcription of the
published algorithm (``tests/test_coco_eval.py``).  Segm mode
(:func:`evaluate_segm`) computes IoUs and areas from RLE masks via the
native maskApi port (``mx_rcnn_tpu/native``) and shares the matcher and
accumulation with bbox mode exactly, mirroring pycocotools' iouType switch.
"""

from __future__ import annotations

from typing import Dict, List, Mapping, Sequence

import numpy as np

IOU_THRS = np.round(np.arange(0.5, 1.0, 0.05), 2)
RECALL_THRS = np.linspace(0.0, 1.0, 101)
AREA_RANGES = {
    "all": (0.0, 1e10),
    "small": (0.0, 32.0 ** 2),
    "medium": (32.0 ** 2, 96.0 ** 2),
    "large": (96.0 ** 2, 1e10),
}


def _iou_xyxy(dets: np.ndarray, gts: np.ndarray, iscrowd: np.ndarray
              ) -> np.ndarray:
    """IoU matrix (D, G); for crowd gt, IoU = intersection / det area
    (pycocotools semantics)."""
    d = dets[:, None, :]
    g = gts[None, :, :]
    iw = np.minimum(d[..., 2], g[..., 2]) - np.maximum(d[..., 0], g[..., 0])
    ih = np.minimum(d[..., 3], g[..., 3]) - np.maximum(d[..., 1], g[..., 1])
    iw = np.maximum(iw, 0.0)
    ih = np.maximum(ih, 0.0)
    inter = iw * ih
    area_d = (dets[:, 2] - dets[:, 0]) * (dets[:, 3] - dets[:, 1])
    area_g = (gts[:, 2] - gts[:, 0]) * (gts[:, 3] - gts[:, 1])
    union = area_d[:, None] + area_g[None, :] - inter
    union = np.where(iscrowd[None, :], area_d[:, None], union)
    return np.where(union > 0, inter / np.maximum(union, 1e-12), 0.0)


def _last_argmax(a: np.ndarray) -> np.ndarray:
    """Row-wise argmax returning the LAST index among ties — the greedy
    matcher's update rule (`iou < best → continue; best ← iou` updates on
    equality, so a later gt with equal IoU wins)."""
    n = a.shape[1]
    return n - 1 - np.argmax(a[:, ::-1], axis=1)


def _evaluate_image(dets: np.ndarray, gt_boxes: np.ndarray,
                    gt_ignore: np.ndarray, iscrowd: np.ndarray,
                    max_dets: int, ious: np.ndarray = None):
    """Match one image's detections for all IoU thresholds at once.

    Semantics are the pycocotools greedy matcher
    (``cocoeval.py — evaluateImg``), vectorized over the 10 IoU thresholds
    and the gt axis; only the (data-dependent) loop over detections remains,
    and it skips detections whose best IoU can't reach the lowest threshold.
    The reference loop's rules, preserved exactly (fuzz-checked against a
    direct transcription in ``tests/test_coco_eval.py``):
      * gts sorted real-first / ignored-last; a det prefers ANY real match
        over a higher-IoU ignored match (the transcription's break),
      * equal-IoU ties go to the later gt index,
      * used non-crowd gts leave the candidate pool; crowd gts can absorb
        any number of detections.

    ``ious``: optional precomputed (D_sorted, G_unsorted) matrix (crowd
    semantics applied) — lets the caller share it across area ranges.
    Returns (det_scores (D,), det_matched (T, D), det_ignore (T, D),
    num_gt_not_ignored).
    """
    order = np.argsort(-dets[:, 4], kind="mergesort")[:max_dets]
    dets = dets[order]
    if len(gt_boxes) and len(dets) and ious is None:
        ious = _iou_xyxy(dets[:, :4], gt_boxes, iscrowd)
    elif ious is not None:
        ious = ious[:max_dets]
    matched, ignored = _match_image(ious, len(gt_boxes), gt_ignore, iscrowd,
                                    len(dets))
    return dets[:, 4], matched, ignored, int((~gt_ignore).sum())


def _match_image(ious, ngt: int, gt_ignore: np.ndarray, iscrowd: np.ndarray,
                 nd: int):
    """The matcher core for one image: ``ious`` is the (D_sorted, G) matrix
    over score-sorted capped detections and UNSORTED gts (None when either
    side is empty).  Returns (matched (T, D), ignored (T, D))."""
    t = len(IOU_THRS)
    matched = np.zeros((t, nd), bool)
    ignored = np.zeros((t, nd), bool)
    if ngt and nd:
        # sort gt: real first, ignored last (pycocotools order)
        gt_order = np.argsort(gt_ignore, kind="mergesort")
        gt_ignore_s = gt_ignore[gt_order]
        crowd_s = iscrowd[gt_order]
        ious = ious[:, gt_order]
        n_real = int((~gt_ignore_s).sum())
        thr_e = np.minimum(IOU_THRS, 1 - 1e-10)  # (T,)
        gt_used = np.zeros((t, ngt), bool)
        # a det whose best IoU is below the lowest threshold can never
        # match — skip it (matched/ignored stay False)
        for di in np.nonzero(ious.max(axis=1) >= thr_e[0])[0]:
            avail = ~gt_used | crowd_s[None, :]           # (T, G)
            vals = np.where(avail, ious[di][None, :], -1.0)
            if n_real:
                best_rv = vals[:, :n_real].max(axis=1)
                best_ri = _last_argmax(vals[:, :n_real])
            else:
                best_rv = np.full(t, -1.0)
                best_ri = np.zeros(t, np.intp)
            if ngt > n_real:
                best_iv = vals[:, n_real:].max(axis=1)
                best_ii = n_real + _last_argmax(vals[:, n_real:])
            else:
                best_iv = np.full(t, -1.0)
                best_ii = np.zeros(t, np.intp)
            has_r = best_rv >= thr_e
            has_i = ~has_r & (best_iv >= thr_e)
            chosen = np.where(has_r, best_ri,
                              np.where(has_i, best_ii, -1))
            sel = chosen >= 0
            gt_used[np.nonzero(sel)[0], chosen[sel]] = True
            matched[:, di] = sel
            ignored[:, di] = has_i
    return matched, ignored


def evaluate_bbox(
    dets_by_image_cat: Mapping[str, Mapping[int, np.ndarray]],
    gt_by_image_cat: Mapping[str, Mapping[int, Dict]],
    categories: Sequence[int],
    max_dets: int = 100,
) -> Dict[str, float]:
    """COCO bbox AP.

    Args:
      dets_by_image_cat: image id → {category → (k, 5) [x1 y1 x2 y2 score]}.
      gt_by_image_cat: image id → {category → dict(boxes (n, 4),
        iscrowd (n,) bool, area (n,))}; area defaults to box area.
      categories: category ids to evaluate.
    Returns dict with AP, AP50, AP75, AP_small/medium/large, AR_100.
    """
    def fetch(img, cat):
        gt = gt_by_image_cat[img].get(cat)
        if gt is None:
            gt_boxes = np.zeros((0, 4))
            iscrowd = np.zeros((0,), bool)
            areas = np.zeros((0,))
        else:
            gt_boxes = np.asarray(gt["boxes"]).reshape(-1, 4)
            iscrowd = np.asarray(
                gt.get("iscrowd", np.zeros(len(gt_boxes), bool)), bool)
            areas = np.asarray(gt.get(
                "area",
                (gt_boxes[:, 2] - gt_boxes[:, 0])
                * (gt_boxes[:, 3] - gt_boxes[:, 1])))
        dets = dets_by_image_cat.get(img, {}).get(cat)
        dets = (np.asarray(dets).reshape(-1, 5) if dets is not None
                else np.zeros((0, 5)))
        if len(dets) == 0 and len(gt_boxes) == 0:
            return None
        order = np.argsort(-dets[:, 4], kind="mergesort")[:max_dets]
        dets_s = dets[order]
        ious = (_iou_xyxy(dets_s[:, :4], gt_boxes, iscrowd)
                if len(gt_boxes) and len(dets_s) else None)
        d_area = (dets_s[:, 2] - dets_s[:, 0]) \
            * (dets_s[:, 3] - dets_s[:, 1])
        return dets_s[:, 4], d_area, ious, gt_boxes.shape[0], areas, iscrowd

    return _run_eval(list(gt_by_image_cat.keys()), categories, fetch)


def evaluate_segm(
    dets_by_image_cat: Mapping[str, Mapping[int, Sequence]],
    gt_by_image_cat: Mapping[str, Mapping[int, Dict]],
    categories: Sequence[int],
    max_dets: int = 100,
) -> Dict[str, float]:
    """COCO segmentation (mask) AP — the segm-mode counterpart of
    :func:`evaluate_bbox`, sharing its matcher and accumulation exactly
    (ref vendored ``pycocotools/cocoeval.py`` with iouType='segm', mask IoU
    from ``maskApi``).

    Args:
      dets_by_image_cat: image id → {category → list of (rle, score)
        pairs}, ``rle`` in the ``native`` RLE dict format
        (``mx_rcnn_tpu.native.encode``/``from_poly``).
      gt_by_image_cat: image id → {category → dict(rles (n,),
        iscrowd (n,) bool, area (n,) optional — defaults to mask area)}.
      categories: category ids to evaluate.
    Returns the same metric dict as :func:`evaluate_bbox`.
    """
    from mx_rcnn_tpu import native

    def fetch(img, cat):
        gt = gt_by_image_cat[img].get(cat)
        if gt is None:
            gt_rles, iscrowd, areas = [], np.zeros(0, bool), np.zeros(0)
        else:
            gt_rles = list(gt["rles"])
            iscrowd = np.asarray(
                gt.get("iscrowd", np.zeros(len(gt_rles), bool)), bool)
            areas = np.asarray(
                gt["area"] if "area" in gt
                else [native.area(r) for r in gt_rles], float)
        dets = dets_by_image_cat.get(img, {}).get(cat) or []
        if not dets and not gt_rles:
            return None
        scores = np.asarray([s for _, s in dets], float)
        order = np.argsort(-scores, kind="mergesort")[:max_dets]
        d_rles = [dets[i][0] for i in order]
        d_scores = scores[order]
        d_area = np.asarray([native.area(r) for r in d_rles], float)
        ious = None
        if d_rles and gt_rles:
            ious = native.iou_matrix(d_rles, gt_rles, iscrowd)
        return d_scores, d_area, ious, len(gt_rles), areas, iscrowd

    return _run_eval(list(gt_by_image_cat.keys()), categories, fetch)


def _run_eval(images, categories, fetch) -> Dict[str, float]:
    """Shared eval driver: per (image, cat) ``fetch`` returns
    (det_scores SORTED desc + capped, det_areas, ious (D, G)|None, n_gt,
    gt_areas, iscrowd) or None when the image has neither dets nor gts.
    Matching per area range + the 101-point accumulation are identical for
    bbox and segm modes (pycocotools ``evaluate``/``accumulate``)."""
    t = len(IOU_THRS)
    precisions = {k: [] for k in AREA_RANGES}  # per (cat): (T, 101) arrays
    recalls = {k: [] for k in AREA_RANGES}

    for cat in categories:
        # one pass over images: the IoU matrix is computed ONCE per
        # (image, cat) — gt sorting and matching differ per area range,
        # the IoUs do not (crowd semantics are area-independent)
        acc = {k: dict(scores=[], matched=[], ignored=[], npos=0)
               for k in AREA_RANGES}
        for img in images:
            got = fetch(img, cat)
            if got is None:
                continue
            scores, d_area, ious, n_gt, areas, iscrowd = got
            for area_name, (lo, hi) in AREA_RANGES.items():
                gt_ignore = iscrowd | (areas < lo) | (areas >= hi)
                m, ig = _match_image(ious, n_gt, gt_ignore, iscrowd,
                                     len(scores))
                # detections outside the area range that match nothing are
                # ignored too (pycocotools marks unmatched out-of-range dets)
                oor = (d_area < lo) | (d_area >= hi)
                ig = ig | (~m & oor[None, :])
                a = acc[area_name]
                a["scores"].append(scores)
                a["matched"].append(m)
                a["ignored"].append(ig)
                a["npos"] += int((~gt_ignore).sum())
        for area_name in AREA_RANGES:
            a = acc[area_name]
            npos = a["npos"]
            if npos == 0:
                continue
            scores = (np.concatenate(a["scores"]) if a["scores"]
                      else np.zeros(0))
            matched = (np.concatenate(a["matched"], axis=1) if a["matched"]
                       else np.zeros((t, 0), bool))
            ignored = (np.concatenate(a["ignored"], axis=1) if a["ignored"]
                       else np.zeros((t, 0), bool))
            order = np.argsort(-scores, kind="mergesort")
            matched = matched[:, order]
            ignored = ignored[:, order]
            prec_interp = np.zeros((t, len(RECALL_THRS)))
            rec_final = np.zeros(t)
            for ti in range(t):
                keep = ~ignored[ti]
                tps = np.cumsum(matched[ti][keep])
                fps = np.cumsum(~matched[ti][keep])
                rec = tps / npos
                prec = tps / np.maximum(tps + fps, 1e-12)
                # precision envelope: monotonically non-increasing, sampled
                # at the 101 recall points (pycocotools accumulate)
                prec = np.maximum.accumulate(prec[::-1])[::-1]
                idx = np.searchsorted(rec, RECALL_THRS, side="left")
                valid = idx < len(prec)
                prec_interp[ti, valid] = prec[idx[valid]]
                rec_final[ti] = rec[-1] if len(rec) else 0.0
            precisions[area_name].append(prec_interp)
            recalls[area_name].append(rec_final)

    def mean_ap(area: str, thr_idx=None) -> float:
        ps = precisions[area]
        if not ps:
            return float("nan")
        arr = np.stack(ps)  # (cats, T, 101)
        if thr_idx is not None:
            arr = arr[:, thr_idx:thr_idx + 1]
        return float(arr.mean())

    out = {
        "AP": mean_ap("all"),
        "AP50": mean_ap("all", 0),
        "AP75": mean_ap("all", 5),
        "AP_small": mean_ap("small"),
        "AP_medium": mean_ap("medium"),
        "AP_large": mean_ap("large"),
    }
    if recalls["all"]:
        out["AR_100"] = float(np.stack(recalls["all"]).mean())
    return out
