"""Data pipeline: datasets (roidb), image IO, static-shape batch loading.

Reference: layer L4 of SURVEY.md — ``rcnn/dataset/`` (IMDB/roidb, VOC, COCO),
``rcnn/io/`` (image transforms, batch assembly) and ``rcnn/core/loader.py``
(AnchorLoader / ROIIter / TestLoader DataIters).

TPU-native differences:
* images are padded into a small set of static shape buckets instead of the
  reference's per-batch max-shape padding + executor rebinding,
* RPN/RCNN target assignment moved on-device (``ops/targets.py``), so the
  loader only produces (images, im_info, gt) batches — the host does image
  decode + resize only (critical: this machine class has few host cores),
* a synthetic dataset provides download-free training/eval for tests, demos
  and benchmarks.
"""

from mx_rcnn_tpu.data.cache import DecodedImageCache  # noqa: F401
from mx_rcnn_tpu.data.image import load_and_transform, resize_to_bucket  # noqa: F401
from mx_rcnn_tpu.data.loader import (AnchorLoader, ROITestLoader,  # noqa: F401
                                     StreamLoader, StreamTestLoader,
                                     TestLoader, cache_from_config,
                                     decode_pool_from_config,
                                     stream_cache_budget)
from mx_rcnn_tpu.data.roidb import IMDB, filter_roidb, merge_roidbs  # noqa: F401
from mx_rcnn_tpu.data.pascal_voc import PascalVOC  # noqa: F401
from mx_rcnn_tpu.data.coco import COCODataset  # noqa: F401
from mx_rcnn_tpu.data.synthetic import (HardSyntheticDataset,  # noqa: F401
                                        StreamSyntheticDataset,
                                        SyntheticDataset)


def get_dataset(name: str, image_set: str, root_path: str, dataset_path: str,
                **kw):
    """Dataset factory (ref ``rcnn/utils/load_data.py — load_gt_roidb``'s
    eval-by-name)."""
    table = {
        "PascalVOC": PascalVOC,
        "coco": COCODataset,
        "synthetic": SyntheticDataset,
        "synthetic_hard": HardSyntheticDataset,
        "synthetic_stream": StreamSyntheticDataset,
    }
    if name not in table:
        raise KeyError(f"unknown dataset {name!r}; have {sorted(table)}")
    return table[name](image_set, root_path, dataset_path, **kw)


def load_gt_roidb(cfg, image_set: str = None, training: bool = True,
                  flip: bool = None, **kw):
    """Config → (imdb, roidb): the full roidb assembly used by the entry
    points (ref ``rcnn/utils/load_data.py — load_gt_roidb`` + ``merge_roidb``
    + ``filter_roidb``; VOC07+12 is expressed as a '+'-joined image_set,
    e.g. ``2007_trainval+2012_trainval``, exactly like the reference CLI).

    Training mode appends flipped copies (ref TRAIN.FLIP) and filters
    images without gt; eval mode does neither.  Returns the FIRST imdb
    (the evaluator) and the merged roidb.
    """
    ds = cfg.dataset
    if image_set is None:
        image_set = ds.image_set if training else ds.test_image_set
    if not training and "+" in image_set:
        # pred_eval hands all detections to the FIRST imdb's evaluator,
        # which only scores its own images — a merged eval set would
        # silently drop the later sets from the reported mAP
        raise ValueError(
            f"'+'-joined image sets are train-only; got {image_set!r}")
    if ds.name in ("synthetic", "synthetic_hard", "synthetic_stream"):
        kw.setdefault("num_classes", ds.num_classes)
    imdbs, roidbs = [], []
    for sset in image_set.split("+"):
        imdb = get_dataset(ds.name, sset, ds.root_path, ds.dataset_path, **kw)
        r = imdb.gt_roidb()
        if training:
            r = filter_roidb(r)
            do_flip = cfg.train.flip if flip is None else flip
            if do_flip:
                r = IMDB.append_flipped_images(r)
        imdbs.append(imdb)
        roidbs.append(r)
    return imdbs[0], merge_roidbs(roidbs)
