"""Data pipeline: datasets (roidb), image IO, static-shape batch loading.

Reference: layer L4 of SURVEY.md — ``rcnn/dataset/`` (IMDB/roidb, VOC, COCO),
``rcnn/io/`` (image transforms, batch assembly) and ``rcnn/core/loader.py``
(AnchorLoader / ROIIter / TestLoader DataIters).

TPU-native differences:
* images are padded into a small set of static shape buckets instead of the
  reference's per-batch max-shape padding + executor rebinding,
* RPN/RCNN target assignment moved on-device (``ops/targets.py``), so the
  loader only produces (images, im_info, gt) batches — the host does image
  decode + resize only (critical: this machine class has few host cores),
* a synthetic dataset provides download-free training/eval for tests, demos
  and benchmarks.
"""

from mx_rcnn_tpu.data.image import load_and_transform, resize_to_bucket  # noqa: F401
from mx_rcnn_tpu.data.loader import AnchorLoader, TestLoader  # noqa: F401
from mx_rcnn_tpu.data.roidb import IMDB, filter_roidb, merge_roidbs  # noqa: F401
from mx_rcnn_tpu.data.pascal_voc import PascalVOC  # noqa: F401
from mx_rcnn_tpu.data.coco import COCODataset  # noqa: F401
from mx_rcnn_tpu.data.synthetic import SyntheticDataset  # noqa: F401


def get_dataset(name: str, image_set: str, root_path: str, dataset_path: str,
                **kw):
    """Dataset factory (ref ``rcnn/utils/load_data.py — load_gt_roidb``'s
    eval-by-name)."""
    table = {
        "PascalVOC": PascalVOC,
        "coco": COCODataset,
        "synthetic": SyntheticDataset,
    }
    if name not in table:
        raise KeyError(f"unknown dataset {name!r}; have {sorted(table)}")
    return table[name](image_set, root_path, dataset_path, **kw)
