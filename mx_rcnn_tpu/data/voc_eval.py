"""PASCAL VOC detection AP evaluation.

Reference: ``rcnn/dataset/pascal_voc_eval.py — voc_eval`` (the standard
implementation inherited from py-faster-rcnn): greedy matching of
score-ranked detections to ground truth at IoU>=0.5, difficult boxes
excluded from both matching penalties and the positive count, AP by the
07 11-point interpolation or the continuous metric.
"""

from __future__ import annotations

from typing import Dict, Mapping

import numpy as np


def voc_ap(rec: np.ndarray, prec: np.ndarray, use_07_metric: bool = False
           ) -> float:
    """AP from recall/precision curves (ref voc_ap)."""
    if use_07_metric:
        ap = 0.0
        for t in np.arange(0.0, 1.1, 0.1):
            p = np.max(prec[rec >= t]) if np.any(rec >= t) else 0.0
            ap += p / 11.0
        return float(ap)
    mrec = np.concatenate(([0.0], rec, [1.0]))
    mpre = np.concatenate(([0.0], prec, [0.0]))
    for i in range(mpre.size - 1, 0, -1):
        mpre[i - 1] = np.maximum(mpre[i - 1], mpre[i])
    idx = np.where(mrec[1:] != mrec[:-1])[0]
    return float(np.sum((mrec[idx + 1] - mrec[idx]) * mpre[idx + 1]))


def voc_eval(
    dets_by_image: Mapping[str, np.ndarray],
    gt_by_image: Mapping[str, Dict],
    class_id: int,
    ovthresh: float = 0.5,
    use_07_metric: bool = True,
) -> float:
    """AP for one class.

    Args:
      dets_by_image: image id → (k, 5) [x1 y1 x2 y2 score].
      gt_by_image: image id → dict(boxes (n,4), gt_classes (n,),
        difficult (n,) bool).
      class_id: evaluated class.
    Returns AP.
    """
    # collect per-image gt for this class
    class_gt = {}
    npos = 0
    for img, rec in gt_by_image.items():
        mask = rec["gt_classes"] == class_id
        boxes = rec["boxes"][mask]
        difficult = rec["difficult"][mask] if "difficult" in rec else np.zeros(
            mask.sum(), bool)
        det_flag = np.zeros(len(boxes), bool)
        npos += int((~difficult).sum())
        class_gt[img] = dict(boxes=boxes, difficult=difficult, det=det_flag)

    # flatten detections, sort by score desc
    image_ids, confidences, bbs = [], [], []
    for img, dets in dets_by_image.items():
        for d in np.asarray(dets).reshape(-1, 5):
            image_ids.append(img)
            confidences.append(d[4])
            bbs.append(d[:4])
    if not image_ids:
        return 0.0
    confidences = np.asarray(confidences)
    bbs = np.asarray(bbs)
    order = np.argsort(-confidences)
    image_ids = [image_ids[i] for i in order]
    bbs = bbs[order]

    nd = len(image_ids)
    tp = np.zeros(nd)
    fp = np.zeros(nd)
    for d in range(nd):
        rec = class_gt.get(image_ids[d])
        bb = bbs[d]
        ovmax = -np.inf
        jmax = -1
        if rec is not None and len(rec["boxes"]):
            bbgt = rec["boxes"]
            ixmin = np.maximum(bbgt[:, 0], bb[0])
            iymin = np.maximum(bbgt[:, 1], bb[1])
            ixmax = np.minimum(bbgt[:, 2], bb[2])
            iymax = np.minimum(bbgt[:, 3], bb[3])
            iw = np.maximum(ixmax - ixmin + 1.0, 0.0)
            ih = np.maximum(iymax - iymin + 1.0, 0.0)
            inters = iw * ih
            uni = ((bb[2] - bb[0] + 1.0) * (bb[3] - bb[1] + 1.0)
                   + (bbgt[:, 2] - bbgt[:, 0] + 1.0)
                   * (bbgt[:, 3] - bbgt[:, 1] + 1.0) - inters)
            overlaps = inters / uni
            ovmax = overlaps.max()
            jmax = int(overlaps.argmax())
        if ovmax > ovthresh:
            if not rec["difficult"][jmax]:
                if not rec["det"][jmax]:
                    tp[d] = 1.0
                    rec["det"][jmax] = True
                else:
                    fp[d] = 1.0
        else:
            fp[d] = 1.0

    fp = np.cumsum(fp)
    tp = np.cumsum(tp)
    recall = tp / max(npos, 1)
    precision = tp / np.maximum(tp + fp, np.finfo(np.float64).eps)
    return voc_ap(recall, precision, use_07_metric)
