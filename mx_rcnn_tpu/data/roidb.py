"""roidb abstraction: the per-image annotation records all loaders consume.

Reference: ``rcnn/dataset/imdb.py — IMDB`` (gt_roidb pkl cache,
``append_flipped_images``, ``merge_roidbs``) and ``rcnn/utils/load_data.py``
(``load_gt_roidb``, ``merge_roidb``, ``filter_roidb``).

A roidb entry is a plain dict (same keys as the reference where they
matter):
  ``image`` (path), ``height``, ``width``,
  ``boxes`` (n, 4) float32 gt boxes (x1, y1, x2, y2),
  ``gt_classes`` (n,) int32 class ids (1..C-1),
  ``flipped`` bool.
"""

from __future__ import annotations

import os
import pickle
from typing import Dict, List, Sequence

import numpy as np

Roidb = List[Dict]


class IMDB:
    """Image database base class (ref ``rcnn/dataset/imdb.py — IMDB``)."""

    def __init__(self, name: str, image_set: str, root_path: str,
                 dataset_path: str):
        self.name = f"{name}_{image_set}"
        self.image_set = image_set
        self.root_path = root_path
        self.data_path = dataset_path
        self.classes: Sequence[str] = []
        self.num_images = 0

    @property
    def num_classes(self) -> int:
        return len(self.classes)

    @property
    def cache_path(self) -> str:
        path = os.path.join(self.root_path, "cache")
        os.makedirs(path, exist_ok=True)
        return path

    def gt_roidb(self) -> Roidb:
        """Load ground-truth annotations, cached as a pkl (ref gt_roidb)."""
        cache_file = os.path.join(self.cache_path, self.name + "_gt_roidb.pkl")
        if os.path.exists(cache_file):
            with open(cache_file, "rb") as f:
                return pickle.load(f)
        roidb = self._load_annotations()
        with open(cache_file, "wb") as f:
            pickle.dump(roidb, f, pickle.HIGHEST_PROTOCOL)
        return roidb

    def _load_annotations(self) -> Roidb:
        raise NotImplementedError

    def evaluate_detections(self, all_boxes) -> Dict[str, float]:
        """all_boxes[class][image] = (k, 5) array of [x1 y1 x2 y2 score]."""
        raise NotImplementedError

    @staticmethod
    def append_flipped_images(roidb: Roidb) -> Roidb:
        """Double the roidb with horizontally flipped copies
        (ref ``append_flipped_images``; boxes mirrored as x' = W-1-x)."""
        flipped = []
        for rec in roidb:
            boxes = rec["boxes"].copy()
            if boxes.size:
                x1 = boxes[:, 0].copy()
                x2 = boxes[:, 2].copy()
                boxes[:, 0] = rec["width"] - x2 - 1
                boxes[:, 2] = rec["width"] - x1 - 1
                assert (boxes[:, 2] >= boxes[:, 0]).all()
            new = dict(rec)
            new["boxes"] = boxes
            new["flipped"] = True
            flipped.append(new)
        return list(roidb) + flipped


def merge_roidbs(roidbs: Sequence[Roidb]) -> Roidb:
    """Concatenate roidbs of multiple image sets (ref merge_roidb; used for
    VOC07+12 training)."""
    out: Roidb = []
    for r in roidbs:
        out.extend(r)
    return out


def filter_roidb(roidb: Roidb) -> Roidb:
    """Drop images without any gt box (ref filter_roidb drops entries with
    neither fg nor bg ROIs; with on-device sampling the only fatal case is
    an image with zero annotations)."""
    keep = [r for r in roidb if len(r["boxes"]) > 0]
    return keep
