"""Black-box flight recorder: last-N-seconds postmortem dumps.

No reference equivalent.  Postmortems of the kill-mid-burst and
preemption-storm runs (docs/FT.md, docs/SERVING.md) have so far
depended on stdout scrollback and whatever the run record happened to
flush — neither holds the metric HISTORY right before the event, and a
SIGKILLed scrollback holds nothing.  The flight recorder keeps the
recent past IN MEMORY (bounded, off the hot path) and writes it out
only when something goes wrong:

* **samples** — the ``obs/timeseries.py`` ring IS the in-memory window;
  the dump takes its trailing ``window_s`` (histogram bucket counts
  serialize as lists);
* **events**  — a bounded deque fed by ``RunRecord.add_listener`` (every
  runrec event — ejects, elastic transitions, health transitions — with
  zero extra instrumentation at the emit sites);
* **spans**   — the tail of the ``obs/trace.py`` buffer, when tracing is
  on;
* **context** — live callables registered by the planes
  (``tools/fleet.py`` registers ``router.healthz`` — the dump of a
  kill-mid-burst run names the ejected replica and its state), each
  invoked fail-soft at dump time.

Triggers (:meth:`FlightRecorder.arm` wires all four):

* **crash**       — a chained ``sys.excepthook``;
* **SIGTERM**     — the handler only flips a flag (the TL401 rule: the
  SIGUSR2 profiler deadlock taught this repo what handlers may do); a
  daemon worker does the dump, and an ``atexit`` backstop catches the
  case where the interpreter unwinds before the worker runs;
* **lock-watchdog trip** — ``analysis/sanitizer.py`` invokes trip
  listeners when a lock acquire stalls past the budget;
* **health CRITICAL** — ``CliObs`` points the health engine's
  transition callback here.

Dumps land in ``runs/<id>/flight/<seq>-<reason>/flight.json`` via
``utils/checkpoint._atomic_write`` (tmp → fsync → rename → dir-fsync:
a crash DURING the dump leaves no torn record), rate-limited to one per
reason per ``min_gap_s`` so a flapping health rule cannot fill the
disk.  Like every obs layer, failures log and degrade — a dump that
cannot be written never takes down the run it is recording.
"""

from __future__ import annotations

import atexit
import json
import logging
import os
import signal
import sys
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from mx_rcnn_tpu.obs.timeseries import TimeSeriesStore

logger = logging.getLogger("mx_rcnn_tpu")


def _sample_jsonable(smp: Dict) -> Dict:
    """A ring sample with ndarray bucket state → plain JSON types."""
    out = {"ts": smp["ts"], "counters": smp["counters"],
           "gauges": smp["gauges"]}
    hists = {}
    for name, h in smp.get("hists", {}).items():
        if "counts" in h:
            hists[name] = {"counts": h["counts"].tolist(),
                           "bounds": [float(b) for b in h["bounds"]],
                           "total": h["total"], "sum": h["sum"],
                           "max": h["max"]}
        else:
            hists[name] = dict(h)
    out["hists"] = hists
    if "labels" in smp:
        out["labels"] = smp["labels"]
    return out


class FlightRecorder:
    """Bounded in-memory black box with fail-soft durable dumps."""

    def __init__(self, store: TimeSeriesStore, run_dir: str,
                 window_s: float = 120.0, max_events: int = 512,
                 span_tail: int = 500, min_gap_s: float = 5.0,
                 trace_tree_tail: int = 32):
        self.store = store
        self.flight_dir = os.path.join(run_dir, "flight")
        self.window_s = float(window_s)
        self.span_tail = int(span_tail)
        # schema /2: the distributed trace ring's last-N kept span
        # trees ride the dump (a postmortem names the slow/failed
        # requests, not just the aggregate window)
        self.trace_tree_tail = int(trace_tree_tail)
        self.min_gap_s = float(min_gap_s)
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=int(max_events))
        self._context: Dict[str, Callable[[], Dict]] = {}
        self._last_dump: Dict[str, float] = {}
        self._seq = 0
        self.dumps: List[str] = []
        # SIGTERM trigger state: handler flips, worker dumps
        self._term = threading.Event()
        self._term_worker: Optional[threading.Thread] = None
        self._prev_excepthook = None
        self._prev_sigterm = None
        self._armed = False

    # ------------------------------------------------------------------
    # feeds
    # ------------------------------------------------------------------

    def note_event(self, event: Dict) -> None:
        """Ring an event into the black box (the ``RunRecord`` listener
        target — also callable directly by planes with no record)."""
        with self._lock:
            self._events.append(dict(event))

    def add_context(self, name: str, fn: Callable[[], Dict]) -> None:
        """Register a live context provider invoked at dump time (e.g.
        the fleet router's ``healthz`` — per-replica states name the
        ejected replica in the record)."""
        with self._lock:
            self._context[name] = fn

    # ------------------------------------------------------------------
    # the dump
    # ------------------------------------------------------------------

    def dump(self, reason: str, force: bool = False, **extra
             ) -> Optional[str]:
        """Write one flight record; returns its path (None when
        rate-limited or the write failed).  Never raises."""
        now = time.monotonic()
        with self._lock:
            if not force:
                last = self._last_dump.get(reason)
                if last is not None and now - last < self.min_gap_s:
                    return None
            self._last_dump[reason] = now
            self._seq += 1
            seq = self._seq
            events = list(self._events)
            context_fns = dict(self._context)
        record: Dict = {
            # /2: adds "trace_trees" — the distributed trace ring's last
            # N kept span trees (absent when the plane is unarmed)
            "schema": "mx_rcnn_tpu.flight/2",
            "reason": reason,
            "ts": round(time.time(), 6),
            "pid": os.getpid(),
            "window_s": self.window_s,
            "samples": [_sample_jsonable(s)
                        for s in self.store.window(self.window_s)],
            "events": events,
        }
        try:
            from mx_rcnn_tpu.obs import trace as obs_trace

            if obs_trace.enabled():
                record["spans"] = obs_trace.events()[-self.span_tail:]
        except Exception:
            logger.exception("obs flight: span capture failed")
        try:
            from mx_rcnn_tpu.obs import trace as obs_trace

            if obs_trace.ring() is not None:
                record["trace_trees"] = obs_trace.kept_trees(
                    limit=self.trace_tree_tail)
        except Exception:
            logger.exception("obs flight: trace-tree capture failed")
        ctx: Dict = {}
        for name, fn in context_fns.items():
            try:
                ctx[name] = fn()
            except Exception as e:
                ctx[name] = {"error": repr(e)}
        record["context"] = ctx
        if extra:
            record["extra"] = {k: _best_effort_jsonable(v)
                               for k, v in extra.items()}
        path = os.path.join(self.flight_dir, f"{seq:03d}-{reason}",
                            "flight.json")
        try:
            from mx_rcnn_tpu.utils.checkpoint import _atomic_write

            os.makedirs(os.path.dirname(path), exist_ok=True)
            _atomic_write(path, json.dumps(
                record, default=repr).encode())
        except Exception:
            logger.exception("obs flight: dump write failed (%s)", path)
            return None
        with self._lock:
            self.dumps.append(path)
        logger.warning("obs flight: %s record -> %s", reason, path)
        return path

    # ------------------------------------------------------------------
    # triggers
    # ------------------------------------------------------------------

    def arm(self, signals: bool = True, excepthook: bool = True,
            watchdog: bool = True) -> None:
        """Install the trigger paths (idempotent).  Signal arming only
        works on the main thread — callers off it (tests) pass
        ``signals=False``."""
        if self._armed:
            return
        self._armed = True
        if excepthook:
            self._prev_excepthook = sys.excepthook
            sys.excepthook = self._on_crash
        if watchdog:
            try:
                from mx_rcnn_tpu.analysis import sanitizer

                sanitizer.add_trip_listener(self._on_watchdog_trip)
            except Exception:
                logger.exception("obs flight: watchdog hook failed")
        if signals:
            try:
                self._term_worker = threading.Thread(
                    target=self._term_loop, name="obs-flight-sigterm",
                    daemon=True)
                self._term_worker.start()
                self._prev_sigterm = signal.signal(signal.SIGTERM,
                                                   self._on_sigterm)
                atexit.register(self._atexit_backstop)
            except (ValueError, OSError) as e:
                # not the main thread / unsupported platform
                logger.warning("obs flight: SIGTERM trigger not armed "
                               "(%s)", e)

    def disarm(self) -> None:
        """Undo the process-global hooks (tests; long-lived sessions
        building successive recorders)."""
        if not self._armed:
            return
        self._armed = False
        if self._prev_excepthook is not None:
            sys.excepthook = self._prev_excepthook
            self._prev_excepthook = None
        try:
            from mx_rcnn_tpu.analysis import sanitizer

            sanitizer.remove_trip_listener(self._on_watchdog_trip)
        except Exception:
            logger.debug("obs flight: watchdog unhook failed")
        if self._prev_sigterm is not None:
            try:
                signal.signal(signal.SIGTERM, self._prev_sigterm)
            except (ValueError, OSError):
                pass
            self._prev_sigterm = None
        self._term.set()  # release the worker

    # -- crash ----------------------------------------------------------

    def _on_crash(self, exc_type, exc, tb) -> None:
        self.dump("crash", error=f"{exc_type.__name__}: {exc}")
        prev = self._prev_excepthook or sys.__excepthook__
        prev(exc_type, exc, tb)

    # -- SIGTERM --------------------------------------------------------

    def _on_sigterm(self, signum, frame) -> None:
        # signal handler: FLIP STATE ONLY (TL401 — the worker thread
        # does the dump; doing I/O or taking the store lock here could
        # deadlock against whatever the main thread was holding)
        self._term.set()
        prev = self._prev_sigterm
        if callable(prev):
            prev(signum, frame)

    def _term_loop(self) -> None:
        self._term.wait()
        if self._armed:
            self.dump("sigterm")

    def _atexit_backstop(self) -> None:
        # the interpreter can unwind before the worker wakes; atexit
        # runs on the main thread after the handler returned, so a
        # pending flag with no dump yet gets one here
        if self._term.is_set() and self._armed:
            with self._lock:
                dumped = any("sigterm" in p for p in self.dumps)
            if not dumped:
                self.dump("sigterm")

    # -- watchdog / health ---------------------------------------------

    def _on_watchdog_trip(self, trip: Dict) -> None:
        self.note_event({"event": "watchdog_trip", **trip})
        self.dump("watchdog", trip=trip)

    def on_health_transition(self, prev: str, new: str,
                             verdict: Dict) -> None:
        """The health engine's transition callback: a CRITICAL entry
        dumps the black box (recoveries and WARNs only ring an
        event)."""
        self.note_event({"event": "health_transition", "prev": prev,
                         "verdict": new,
                         "firing": verdict.get("firing", [])})
        if new == "CRITICAL":
            self.dump("health-critical", firing=verdict.get("firing"))


def _best_effort_jsonable(v):
    try:
        json.dumps(v)
        return v
    except (TypeError, ValueError):
        return repr(v)


# ---------------------------------------------------------------------------
# active-recorder registration (planes trigger without plumbing)
# ---------------------------------------------------------------------------

_active_lock = threading.Lock()
_ACTIVE: Optional[FlightRecorder] = None


def set_active(rec: Optional[FlightRecorder]) -> None:
    global _ACTIVE
    with _active_lock:
        _ACTIVE = rec


def active() -> Optional[FlightRecorder]:
    with _active_lock:
        return _ACTIVE


def trigger(reason: str, **extra) -> Optional[str]:
    """Dump the ACTIVE recorder, if any — how planes without a handle
    (``ft/elastic.py`` peer-failure exit, ``serve/bulk.py`` abort)
    request a black-box record with one fail-soft call."""
    rec = active()
    if rec is None:
        return None
    return rec.dump(reason, **extra)
