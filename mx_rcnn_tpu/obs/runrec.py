"""Structured run records: ``runs/<id>/events.jsonl`` + BENCH summary.

No reference equivalent — the reference's only run artifact is the stdout
log.  Every ``tools/train.py`` / ``tools/serve.py`` invocation with
``obs.enabled`` writes:

* ``runs/<id>/events.jsonl`` — one JSON object per line, appended live,
  schema::

      {"ts": <unix seconds>, "event": "<kind>", ...payload}

  Crash contract (LINE-GRANULAR, verified by the crashsim regression in
  ``tests/test_persistlint.py``): each line is flushed to the kernel as
  it is written (line buffering), so a PROCESS crash loses nothing; a
  HOST crash may lose an un-fsynced tail and may tear the last line at
  a byte boundary — readers must treat ``events.jsonl`` as "every fully
  parseable line is real, a torn tail line is the crash point", never
  as an atomic document.  ``close()`` fsyncs the stream so a finished
  run's final flush is durable.  Per-event fsync (or an atomic rewrite
  per event) would serialize the training/serving hot path behind disk
  latency, which the line-granular contract exists to avoid.
* ``runs/<id>/summary.json`` — ONE final BENCH-compatible record
  (``{"metric": ..., "value": ..., "measured": ...}`` like ``bench.py``
  and ``tools/loadgen.py`` emit) plus the closing snapshot of the
  process metrics registry, so a finished run is analyzable without
  re-parsing the event stream.  Unlike the event stream this IS an
  atomic document (one shot, read as a whole), so it goes through
  ``utils/checkpoint._atomic_write`` — a crash during the final write
  leaves the previous state, never a torn half-summary that parses as
  a finished run.
* ``runs/<id>/trace.json`` / ``runs/<id>/profile/`` — chrome trace and
  profiler windows, when those subsystems are enabled (written by the
  CLIs, not by this class).

The record never throws into the training path: write failures log and
disable the record (observability must not kill the run it observes).
"""

from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time
from typing import Dict, Optional

logger = logging.getLogger("mx_rcnn_tpu")


def _jsonable(obj):
    """Fallback serializer: numpy scalars/arrays and anything else that
    sneaks into an event payload degrade to plain types, never a crash."""
    for attr in ("item",):
        if hasattr(obj, attr):
            try:
                return obj.item()
            except Exception:
                pass
    if hasattr(obj, "tolist"):
        try:
            return obj.tolist()
        except Exception:
            pass
    return repr(obj)


class RunRecord:
    """One run directory under ``base_dir`` with a live event stream.

    ``run_id`` defaults to ``<kind>-<utc timestamp>-<pid>`` — unique per
    process without coordination.  Thread-safe: the fit loop, snapshot
    writer and HTTP threads may all emit events.
    """

    def __init__(self, kind: str, base_dir: str = "runs",
                 run_id: Optional[str] = None):
        self.kind = kind
        self.run_id = run_id or "{}-{}-{}".format(
            kind, time.strftime("%Y%m%d-%H%M%S", time.gmtime()),
            os.getpid())
        self.dir = os.path.join(base_dir, self.run_id)
        self.events_path = os.path.join(self.dir, "events.jsonl")
        self.summary_path = os.path.join(self.dir, "summary.json")
        self._lock = threading.Lock()
        self._n = 0
        self._dead = False
        self._listeners = []
        try:
            os.makedirs(self.dir, exist_ok=True)
            # persistlint: disable=PL101 append-only event stream with a LINE-GRANULAR crash contract (module docstring): each line is kernel-flushed, readers tolerate a torn tail line, close() fsyncs; an atomic rewrite per event would put disk latency on the hot path
            self._f = open(self.events_path, "a", buffering=1)
        except OSError as e:
            logger.warning("obs runrec: cannot open %s (%s) — run record "
                           "disabled", self.events_path, e)
            self._f, self._dead = None, True
        self.event("run_start", kind=kind, pid=os.getpid(),
                   argv=list(sys.argv))

    def add_listener(self, fn) -> None:
        """Register ``fn(event_dict)`` to observe every event as it is
        emitted — the flight recorder (``obs/flightrec.py``) rings the
        run's events into its black box this way, with zero extra
        instrumentation at the emit sites.  Listeners run on the
        emitting thread and must be cheap; exceptions log and never
        propagate into the emitter."""
        with self._lock:
            if fn not in self._listeners:
                self._listeners.append(fn)

    def remove_listener(self, fn) -> None:
        with self._lock:
            if fn in self._listeners:
                self._listeners.remove(fn)

    def event(self, event: str, **payload) -> None:
        """Append one event line (flushed immediately); never raises."""
        if self._dead:
            return
        rec = {"ts": round(time.time(), 6), "event": event, **payload}
        try:
            line = json.dumps(rec, default=_jsonable)
        except (TypeError, ValueError) as e:
            logger.warning("obs runrec: unserializable event %r: %s",
                           event, e)
            return
        with self._lock:
            listeners = list(self._listeners)
        for fn in listeners:
            try:
                fn(rec)
            except Exception:
                logger.exception("obs runrec: event listener failed")
        with self._lock:
            if self._dead:
                return
            try:
                self._f.write(line + "\n")
                self._n += 1
            except OSError as e:
                logger.warning("obs runrec: write failed (%s) — run "
                               "record disabled", e)
                self._dead = True

    @property
    def num_events(self) -> int:
        return self._n

    def finish(self, metric: Optional[str] = None, value=None,
               unit: Optional[str] = None, registry=None,
               **extra) -> Dict:
        """Write the final BENCH-compatible ``summary.json`` (and a
        closing ``run_finish`` event).  ``registry`` (default: the
        process registry) snapshots into the summary under
        ``"metrics"``."""
        if registry is None:
            from mx_rcnn_tpu.obs.metrics import registry as _registry

            registry = _registry()
        self.event("run_finish", metric=metric, value=value)
        summary = {
            "metric": metric or f"{self.kind}_run",
            "value": value,
            "unit": unit,
            "measured": value is not None,
            "run_id": self.run_id,
            "kind": self.kind,
            "events": self._n,
            **extra,
            "metrics": registry.snapshot(),
        }
        try:
            from mx_rcnn_tpu.utils.checkpoint import _atomic_write

            _atomic_write(self.summary_path,
                          json.dumps(summary, indent=1,
                                     default=_jsonable).encode())
        except OSError as e:
            logger.warning("obs runrec: summary write failed: %s", e)
        return summary

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                try:
                    # the final flush is durable: a run that reported
                    # success must not lose its closing events to a host
                    # crash (the line-granular contract's one fsync)
                    self._f.flush()
                    os.fsync(self._f.fileno())
                except (OSError, ValueError) as e:
                    logger.warning("obs runrec: final event-stream "
                                   "fsync failed (%s) — a host crash "
                                   "may lose the closing events", e)
                try:
                    self._f.close()
                except OSError:
                    pass
                self._f = None
            self._dead = True


class CliObs:
    """The shared ``tools/train.py`` / ``tools/serve.py`` obs wiring:
    run record + optional span trace + optional ``/metrics`` exporter +
    optional SIGUSR2 profiler toggle, with a FAIL-SOFT teardown — one
    place to keep the two CLIs in sync, and nothing in setup-after-record
    or teardown may mask the run's own exception or fail a successful
    run (the runrec invariant: observability never throws into the path
    it observes).

    Build with :func:`cli_obs` (returns None when ``cfg.obs`` is off);
    read ``.record`` for the RunRecord to thread into the run; call
    :meth:`close` exactly once from a ``finally``.
    """

    def __init__(self, cfg, kind: str):
        self.cfg = cfg
        self.record = RunRecord(kind, base_dir=cfg.obs.run_dir)
        logger.info("obs: run record -> %s", self.record.dir)
        self._metrics_srv = None
        self.store = None
        self.sampler = None
        self.health = None
        self.flight = None
        try:
            from mx_rcnn_tpu.obs import trace as obs_trace

            if cfg.obs.trace:
                obs_trace.enable(cfg.obs.trace_cap)
            if cfg.obs.metrics_port:
                from mx_rcnn_tpu.obs.metrics import start_metrics_server

                self._metrics_srv = start_metrics_server(
                    port=cfg.obs.metrics_port)
            if cfg.obs.sigusr2:
                from mx_rcnn_tpu.obs.profiler import install_sigusr2

                install_sigusr2(self.record.dir)
        except Exception:
            logger.exception("obs: CLI wiring failed — continuing "
                             "without the failed piece")
        # time-series plane (obs/timeseries.py + health.py + flightrec
        # .py — docs/OBSERVABILITY.md "Time-series plane"): the sampler
        # drives the ring store AND the health engine on one daemon
        # thread; the flight recorder rings runrec events and arms the
        # crash/SIGTERM/watchdog triggers.  Same fail-soft posture as
        # the block above.
        try:
            if (cfg.obs.timeseries or cfg.obs.health
                    or cfg.obs.flight):
                from mx_rcnn_tpu.obs import timeseries as obs_ts
                from mx_rcnn_tpu.obs.metrics import registry

                self.store = obs_ts.TimeSeriesStore(cfg.obs.ts_capacity)
                obs_ts.set_active(self.store)
                if cfg.obs.flight:
                    from mx_rcnn_tpu.obs import flightrec

                    self.flight = flightrec.FlightRecorder(
                        self.store, self.record.dir,
                        window_s=cfg.obs.flight_window_s,
                        max_events=cfg.obs.flight_events)
                    self.record.add_listener(self.flight.note_event)
                    flightrec.set_active(self.flight)
                    self.flight.arm(
                        signals=threading.current_thread()
                        is threading.main_thread())
                if cfg.obs.health:
                    from mx_rcnn_tpu.obs import health as obs_health

                    self.health = obs_health.HealthEngine(
                        obs_health.default_rules(cfg), self.store,
                        registry=registry(), record=self.record,
                        on_transition=(self.flight.on_health_transition
                                       if self.flight else None))
                    obs_health.set_active_engine(self.health)
                self.sampler = obs_ts.Sampler(
                    self.store, interval_s=cfg.obs.sample_interval_s,
                    after_sample=(self.health.evaluate_sample
                                  if self.health else None))
                self.sampler.start()
        except Exception:
            logger.exception("obs: time-series wiring failed — "
                             "continuing without the failed piece")

    def close(self, metric: Optional[str] = None, value=None,
              unit: Optional[str] = None, **extra) -> None:
        """Export the chrome trace (if spans were collected), write the
        BENCH summary, stop the exporter.  Never raises."""
        try:
            if self.sampler is not None:
                # one last sample (and health pass) so the ring's tail
                # reflects shutdown state before the summary snapshots
                self.sampler.stop(final_sample=True)
        except Exception:
            logger.exception("obs: sampler stop failed")
        try:
            from mx_rcnn_tpu.obs import trace as obs_trace

            if obs_trace.enabled():
                obs_trace.export_chrome_trace(
                    os.path.join(self.record.dir, "trace.json"))
        except Exception:
            logger.exception("obs: chrome-trace export failed")
        try:
            self.record.finish(metric=metric, value=value, unit=unit,
                               **extra)
        except Exception:
            logger.exception("obs: run summary write failed")
        self.record.close()
        try:
            if self.flight is not None:
                from mx_rcnn_tpu.obs import flightrec

                self.flight.disarm()
                flightrec.set_active(None)
            if self.health is not None:
                from mx_rcnn_tpu.obs import health as obs_health

                obs_health.set_active_engine(None)
            if self.store is not None:
                from mx_rcnn_tpu.obs import timeseries as obs_ts

                obs_ts.set_active(None)
        except Exception:
            logger.exception("obs: time-series teardown failed")
        if self._metrics_srv is not None:
            try:
                self._metrics_srv.shutdown()
                self._metrics_srv.server_close()
            except Exception:
                logger.exception("obs: metrics exporter shutdown failed")


def cli_obs(cfg, kind: str) -> Optional[CliObs]:
    """:class:`CliObs` when ``cfg.obs.enabled``, else None — so callers
    write ``obs_sess = cli_obs(cfg, "train")`` and guard on None."""
    if getattr(cfg, "obs", None) is not None and cfg.obs.enabled:
        return CliObs(cfg, kind)
    return None
