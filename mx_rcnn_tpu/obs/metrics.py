"""Process-wide metrics registry: counters, gauges, log-bucket histograms.

Promoted from ``serve/metrics.py`` (which is now a thin back-compat shim
over this module) so ONE registry can carry training, data-loading,
checkpointing AND serving metrics, and a single ``/metrics`` scrape shows
the whole process.  Design constraints carried over unchanged: recording
must be cheap and lock-bounded (it runs on every request/step), and a
snapshot must be computable without storing per-sample history — so
latencies land in log-spaced fixed-bound histograms (40 buckets spanning
0.1 ms .. ~28 s at ×1.37 steps, ~±16% percentile resolution) and
percentiles are read off the cumulative counts.

Metric naming scheme (the full catalog: docs/OBSERVABILITY.md):

    <subsystem>.<metric>[_<unit>]

    train.step_ms / train.data_wait_ms / train.samples_per_sec / ...
    loader.decode_ms / loader.assemble_ms / loader.queue_depth / ...
    snapshot.stall_ms / snapshot.commit_ms / snapshot.bytes / ...
    serve.queue_wait_ms / serve.submitted / serve.batches / ...

Also here: :class:`LoweringCounter` — counts ``jax.monitoring`` lowering
events so tests/loadgen/obs-smoke can assert that a warmed program serves
steady-state traffic with ZERO new compiles — and
:func:`start_metrics_server`, the stdlib JSON ``/metrics`` exporter used
by ``tools/train.py`` (``obs.metrics_port``) and ``make obs-smoke``.
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Tuple

import numpy as np

logger = logging.getLogger("mx_rcnn_tpu")


class Histogram:
    """Fixed log-spaced-bucket histogram with percentile readout.

    ``percentile`` returns the UPPER bound of the bucket holding the
    rank — a conservative (never-understated) latency estimate.
    """

    def __init__(self, lo: float = 0.1, hi: float = 30_000.0,
                 buckets: int = 40):
        # bounds[i] is the inclusive upper edge of bucket i; the last
        # bucket is open-ended (+inf) so no sample is ever dropped
        self.bounds = np.geomspace(lo, hi, buckets)
        self.counts = np.zeros(buckets + 1, np.int64)
        self.total = 0
        self.sum = 0.0
        self.max = 0.0

    def record(self, value: float) -> None:
        # Histogram is Registry-internal: every record AND every read
        # (snapshot/percentile) runs under Registry.lock — the lock just
        # lives one object up (the per-line waivers document that)
        i = int(np.searchsorted(self.bounds, value))
        self.counts[i] += 1  # threadlint: disable=TL201 guarded by Registry.lock at every call site (observe/observe_batch)
        self.total += 1      # threadlint: disable=TL201 guarded by Registry.lock at every call site (observe/observe_batch)
        self.sum += value    # threadlint: disable=TL201 guarded by Registry.lock at every call site (observe/observe_batch)
        self.max = max(self.max, value)  # threadlint: disable=TL201 guarded by Registry.lock at every call site (observe/observe_batch)

    def percentile(self, p: float) -> Optional[float]:
        """p in [0, 100]; None when empty.  Bucket-upper-bound estimate;
        the overflow bucket reports the observed max."""
        if self.total == 0:
            return None
        rank = int(np.ceil(p / 100.0 * self.total))
        rank = min(max(rank, 1), self.total)
        cum = np.cumsum(self.counts)
        i = int(np.searchsorted(cum, rank))
        if i >= len(self.bounds):
            return float(self.max)
        return float(self.bounds[i])

    @property
    def mean(self) -> Optional[float]:
        return self.sum / self.total if self.total else None

    def summary(self) -> Dict:
        """The standard readout dict (same shape/rounding the serving
        snapshot has always used)."""
        pct = {p: self.percentile(p) for p in (50, 90, 99)}
        return {
            "count": self.total,
            "mean": None if self.mean is None else round(self.mean, 3),
            **{f"p{p}": None if v is None else round(v, 3)
               for p, v in pct.items()},
            "max": round(self.max, 3) if self.total else None,
        }


class Registry:
    """Thread-safe named counters, gauges and histograms.

    One shared lock bounds every record (a dict lookup + a few float ops
    under it — the same cost profile the serving metrics always had);
    :meth:`snapshot` reads everything consistently under the same lock.
    Metrics are created lazily on first record, so wiring a subsystem
    costs nothing until it actually records.
    """

    def __init__(self):
        self.lock = threading.RLock()
        self._counters: Dict[str, int] = {}
        self._gauges: Dict[str, float] = {}
        self._hists: Dict[str, Histogram] = {}

    # -- record -------------------------------------------------------------
    def inc(self, name: str, n: int = 1) -> None:
        with self.lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def set_gauge(self, name: str, value: float) -> None:
        with self.lock:
            self._gauges[name] = float(value)

    def observe(self, name: str, value: float, lo: float = 0.1,
                hi: float = 30_000.0, buckets: int = 40) -> None:
        """Record ``value`` into the named histogram (created on first
        use with the given bucket geometry)."""
        with self.lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram(lo, hi, buckets)
            h.record(value)

    # -- read ---------------------------------------------------------------
    def counter(self, name: str) -> int:
        with self.lock:
            return self._counters.get(name, 0)

    def gauge(self, name: str) -> Optional[float]:
        with self.lock:
            return self._gauges.get(name)

    def hist(self, name: str) -> Optional[Histogram]:
        with self.lock:
            return self._hists.get(name)

    def names(self) -> Tuple[str, ...]:
        with self.lock:
            return tuple(sorted(set(self._counters) | set(self._gauges)
                                | set(self._hists)))

    def snapshot(self) -> Dict:
        """One consistent dict over every metric — the unified
        ``/metrics`` response body."""
        with self.lock:
            return {
                "counters": dict(sorted(self._counters.items())),
                "gauges": {k: round(v, 6) for k, v in
                           sorted(self._gauges.items())},
                "hists": {name: h.summary()
                          for name, h in sorted(self._hists.items())},
            }

    def reset(self, prefix: str = "") -> None:
        """REMOVE counters/gauges/histograms whose name starts with
        ``prefix`` (default: everything); they recreate lazily at zero on
        the next record.  Not atomic w.r.t. concurrent recorders — call
        it only between phases."""
        with self.lock:
            for d in (self._counters, self._gauges, self._hists):
                for k in [k for k in d if k.startswith(prefix)]:
                    del d[k]


# The process-wide default registry: train, loader, snapshot and (when
# wired by the CLIs) serve all record here, so one scrape sees them all.
_GLOBAL = Registry()


def registry() -> Registry:
    """The process-wide registry (see module docstring for naming)."""
    return _GLOBAL


_COUNTERS = ("submitted", "served", "shed", "expired", "failed",
             "batches", "padded_rows")


class ServeMetrics:
    """Thread-safe counters + histograms for the serving engine — now a
    facade over a :class:`Registry` (names prefixed ``serve.``) with the
    ORIGINAL snapshot format preserved bit for bit (pinned by
    ``tests/test_obs.py`` so ``tools/loadgen.py`` and the
    ``docs/serve_bench_*.json`` comparisons stay valid).

    Counters: every request increments ``submitted`` and exactly one of
    ``served`` / ``shed`` / ``expired`` / ``failed`` — the zero-lost
    accounting invariant (``submitted == sum of terminals`` once traffic
    drains).  ``batches`` counts dispatches; ``padded_rows`` counts dead
    rows shipped to keep the batch shape static.

    Histograms (milliseconds): ``queue_wait_ms`` (admission → dispatch),
    ``model_ms`` (per-batch forward+postprocess wall), ``total_ms``
    (admission → response).

    ``registry=None`` (the default) gives the engine a PRIVATE registry —
    engines stay isolated, exactly the old behavior.  Pass the process
    registry (``obs.metrics.registry()``) to publish serving metrics into
    the unified ``/metrics`` scrape (``tools/serve.py`` does when
    ``cfg.obs.enabled``).
    """

    PREFIX = "serve."

    def __init__(self, registry: Registry = None):
        self.registry = registry if registry is not None else Registry()
        self.reset()

    def reset(self) -> None:
        """Zero everything (loadgen excludes warmup from the measured
        window this way).  Not atomic w.r.t. concurrent recorders — call
        it only between traffic phases."""
        p = self.PREFIX
        with self.registry.lock:
            for k in _COUNTERS + ("rows",):
                self.registry._counters[p + k] = 0
            for h in ("queue_wait_ms", "model_ms", "total_ms"):
                self.registry._hists[p + h] = Histogram()

    # NOTE every accessor below tolerates missing keys (setdefault/get):
    # Registry.reset REMOVES entries, and a ServeMetrics sharing the
    # process registry must survive someone resetting it mid-traffic
    # instead of KeyError-ing the dispatcher thread.

    # live views kept for back-compat with pre-registry callers
    @property
    def counters(self) -> Dict[str, int]:
        with self.registry.lock:
            return {k: self.registry._counters.get(self.PREFIX + k, 0)
                    for k in _COUNTERS}

    @property
    def hists(self) -> Dict[str, Histogram]:
        with self.registry.lock:
            return {h: self.registry._hists.setdefault(self.PREFIX + h,
                                                       Histogram())
                    for h in ("queue_wait_ms", "model_ms", "total_ms")}

    def count(self, name: str, n: int = 1) -> None:
        self.registry.inc(self.PREFIX + name, n)

    def observe(self, name: str, value_ms: float) -> None:
        self.registry.observe(self.PREFIX + name, value_ms)

    def observe_batch(self, rows: int, batch_size: int,
                      model_ms: float) -> None:
        p = self.PREFIX
        with self.registry.lock:
            c = self.registry._counters
            c[p + "batches"] = c.get(p + "batches", 0) + 1
            c[p + "padded_rows"] = (c.get(p + "padded_rows", 0)
                                    + batch_size - rows)
            c[p + "rows"] = c.get(p + "rows", 0) + rows
            self.registry._hists.setdefault(p + "model_ms",
                                            Histogram()).record(model_ms)

    def in_flight(self) -> int:
        """Admitted-but-not-terminal request count — O(1) under one lock
        (five counter reads), cheap enough for the fleet router's
        per-request join-shortest-queue decision (``serve/fleet.py``),
        where a full :meth:`snapshot` per routing choice would not be."""
        p = self.PREFIX
        with self.registry.lock:
            c = self.registry._counters
            return c.get(p + "submitted", 0) - (
                c.get(p + "served", 0) + c.get(p + "shed", 0)
                + c.get(p + "expired", 0) + c.get(p + "failed", 0))

    def snapshot(self) -> Dict:
        """One consistent dict: counters, percentiles, occupancy — the
        serving ``/metrics`` response body and the loadgen record source.
        Format identical to the pre-registry ``serve/metrics.py``."""
        p = self.PREFIX
        with self.registry.lock:
            cnt = {k: self.registry._counters.get(p + k, 0)
                   for k in _COUNTERS}
            out: Dict = {"counters": cnt}
            for name in ("queue_wait_ms", "model_ms", "total_ms"):
                out[name] = self.registry._hists.setdefault(
                    p + name, Histogram()).summary()
            b = cnt["batches"]
            rows = self.registry._counters.get(p + "rows", 0)
            out["batch_occupancy"] = {
                "batches": b,
                "mean_rows": round(rows / b, 3) if b else None,
                "padded_rows": cnt["padded_rows"],
            }
            out["terminated"] = (cnt["served"] + cnt["shed"]
                                 + cnt["expired"] + cnt["failed"])
            out["in_flight"] = cnt["submitted"] - out["terminated"]
            return out


class LoweringCounter:
    """Counts pjit lowering events (jit cache misses) inside a ``with``
    block via ``jax.monitoring`` — fired on every trace+lower regardless
    of the persistent XLA compile cache, so "zero new compiles on a
    warmed program" is assertable across cold and warm processes.

    Import-light: registering the listener touches jax only on first use.
    """

    _events = {"lowerings": 0}
    _registered = False

    @classmethod
    def _ensure_listener(cls) -> None:
        if cls._registered:
            return
        import jax

        def on_event(event, duration, **kw):
            if event == "/jax/core/compile/jaxpr_to_mlir_module_duration":
                cls._events["lowerings"] += 1

        jax.monitoring.register_event_duration_secs_listener(on_event)
        cls._registered = True

    def __enter__(self) -> "LoweringCounter":
        self._ensure_listener()
        self._start = self._events["lowerings"]
        return self

    def __exit__(self, *exc) -> bool:
        return False

    @property
    def n(self) -> int:
        return self._events["lowerings"] - self._start


# ---------------------------------------------------------------------------
# stdlib /metrics exporter
# ---------------------------------------------------------------------------

class _MetricsHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def _reply(self, status: int, payload: Dict) -> None:
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):  # route to the repo logger
        logger.debug("obs metrics http: " + fmt, *args)

    def do_GET(self):
        if self.path == "/metrics":
            snap = self.server.registry.snapshot()
            # when the time-series plane is armed (cfg.obs.timeseries →
            # CliObs sets the active store) one scrape also answers
            # "what moved lately" — pure host-side work, no lowerings
            # (asserted by make obs-smoke)
            from mx_rcnn_tpu.obs.timeseries import active

            store = active()
            if store is not None:
                snap["timeseries"] = store.scrape_section()
            self._reply(200, snap)
        elif self.path == "/healthz":
            from mx_rcnn_tpu.obs.health import active_verdict

            payload = {"ok": True}
            verdict = active_verdict()
            if verdict is not None:
                payload["ok"] = verdict["verdict"] != "CRITICAL"
                payload["health"] = verdict
            # a CRITICAL verdict fails the probe (matches the serving
            # plane's /healthz: load balancers key on the status code)
            self._reply(200 if payload["ok"] else 503, payload)
        else:
            self._reply(404, {"error": f"no such path {self.path!r}"})


def start_metrics_server(reg: Registry = None, host: str = "127.0.0.1",
                         port: int = 0) -> ThreadingHTTPServer:
    """Start a daemon-threaded JSON ``GET /metrics`` server over ``reg``
    (default: the process registry).  ``port=0`` picks a free port (read
    it back from ``server.server_address``).  Call ``shutdown()`` +
    ``server_close()`` to stop."""
    srv = ThreadingHTTPServer((host, port), _MetricsHandler)
    srv.registry = reg if reg is not None else registry()
    t = threading.Thread(target=srv.serve_forever,
                         name="obs-metrics-http", daemon=True)
    t.start()
    logger.info("obs: /metrics on http://%s:%d", *srv.server_address[:2])
    return srv
