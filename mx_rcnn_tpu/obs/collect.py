"""Cross-process metric collection: N registries → one labeled fleet view.

No reference equivalent.  Every plane built since PR 4 runs more than
one registry: fleet replicas keep PRIVATE engine registries (so their
``serve.*`` counters never double-count into the router's process
registry — ``serve/fleet.py``), elastic workers are separate PROCESSES
each with its own ``/metrics`` exporter, and the bulk driver is a third
party again.  Nothing merged them; ``tools/obs.py watch``/``check`` and
ROADMAP item 2's scheduler need exactly that merge.

Two source kinds, one scrape contract — ``scrape() -> (snapshot,
labels) | None``:

* :class:`RegistrySource` — an IN-PROCESS registry, resolved through a
  callable on every scrape so the source tracks object churn: a fleet
  replica that ejects and relaunches gets a NEW engine (new registry,
  bumped generation) and the next scrape simply follows it
  (:func:`collector_for_fleet` wires this off ``router.manager``);
* :class:`HttpSource` — a remote ``/metrics`` JSON endpoint (elastic
  workers via ``cfg.obs.metrics_port``, any ``tools/serve.py`` front
  end; ``cfg.obs.collect_urls`` is the CLI-facing list).

A failed scrape marks the source ``up: false`` for that collection and
nothing else — a mid-relaunch replica or a resized-away elastic worker
degrades the view, never breaks the collector (the same
survive-the-churn posture the router takes).

:meth:`Collector.collect` returns the merged view::

    {"ts": ..., "up": <n live>, "sources": {
         "replica-0": {"up": true, "labels": {"source": "replica-0",
                       "generation": 2, ...}, "counters": ..., "gauges":
                       ..., "hists": ...},
         ...},
     "agg": {"counters": <summed across live sources>,
             "gauges": {name: {source: value}}}}

Counters SUM across sources (each source is a distinct registry, so the
sum is the fleet total and can never double-count); gauges stay
per-source labeled (summing ``fleet.replica0.depth`` across replicas
would be nonsense) — consumers pick ``agg.counters`` for totals and
``sources[...]`` for placement decisions.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import urllib.request
from typing import Callable, Dict, List, Optional, Tuple

from mx_rcnn_tpu.netio import read_limited

logger = logging.getLogger("mx_rcnn_tpu")

ScrapeResult = Optional[Tuple[Dict, Dict]]


class RegistrySource:
    """An in-process registry behind a per-scrape resolver.

    ``resolve() -> (registry, labels) | None`` runs on EVERY scrape:
    returning None means "down right now" (e.g. the replica is mid-
    relaunch); returning a different registry object next time is the
    expected relaunch behavior, not an error.  A bare registry is
    accepted for the static case (tests, the train process's own
    registry).
    """

    def __init__(self, name: str, registry_or_resolve,
                 labels: Optional[Dict] = None):
        self.name = name
        self._static_labels = dict(labels or {})
        if callable(registry_or_resolve):
            self._resolve = registry_or_resolve
        else:
            reg = registry_or_resolve
            self._resolve = lambda: (reg, {})

    def scrape(self) -> ScrapeResult:
        try:
            resolved = self._resolve()
        except Exception:
            logger.exception("obs collect: source %s resolver failed",
                             self.name)
            return None
        if resolved is None:
            return None
        reg, labels = resolved
        if reg is None:
            return None
        snap = reg.snapshot()
        merged = {"source": self.name, **self._static_labels,
                  **(labels or {})}
        return snap, merged


class HttpSource:
    """A remote ``/metrics`` JSON endpoint (the stdlib exporter's or the
    serve front end's response body is ``Registry.snapshot`` shaped).

    Every request carries a hard per-request timeout, and consecutive
    failures open an exponential backoff window during which
    :meth:`scrape` reports down WITHOUT touching the socket.  Together
    they bound what one wedged endpoint can cost the collection loop: a
    host that accepts connections but never answers (half-open after a
    SIGKILL, a hung agent) stalls ONE scrape for ``timeout_s``, then
    costs nothing until its backoff expires — it cannot turn every
    sampler tick into a fleet-wide ``timeout_s`` stall while the other
    sources' data ages (tests/test_remote.py pins this with a
    deliberately hung server).
    """

    def __init__(self, name: str, url: str, timeout_s: float = 2.0,
                 labels: Optional[Dict] = None,
                 backoff_base_s: float = 1.0,
                 backoff_cap_s: float = 30.0,
                 max_bytes: int = 8 << 20):
        self.name = name
        if url.isdigit():  # bare port ("9101") = this host's exporter
            url = f"127.0.0.1:{url}"
        self.url = url if "://" in url else f"http://{url}"
        if not self.url.rstrip("/").endswith("/metrics"):
            self.url = self.url.rstrip("/") + "/metrics"
        self.timeout_s = float(timeout_s)
        self.max_bytes = int(max_bytes)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self._static_labels = dict(labels or {})
        self._lock = threading.Lock()
        self._failures = 0           # consecutive, reset on success
        self._skip_until = 0.0       # monotonic deadline of the window

    def failures(self) -> int:
        with self._lock:
            return self._failures

    def scrape(self) -> ScrapeResult:
        with self._lock:
            if time.monotonic() < self._skip_until:
                return None  # backing off: down, and no socket touched
        try:
            with urllib.request.urlopen(self.url,
                                        timeout=self.timeout_s) as r:
                # capped read: a malicious/broken exporter streaming an
                # unbounded body is a typed failure (ResponseTooLarge is
                # a ValueError), counted and backed off like any other
                # capped AND wall-clock bounded: a trickling exporter
                # (one byte per tick never trips the socket timeout)
                # is cut off as ResponseTooSlow, another ValueError
                snap = json.loads(
                    read_limited(r, self.max_bytes, "metrics body",
                                 deadline_s=self.timeout_s * 4.0
                                 ).decode())
        except Exception as e:  # refused / timeout / bad JSON / too big
            with self._lock:
                self._failures += 1
                delay = min(self.backoff_cap_s,
                            self.backoff_base_s
                            * (2.0 ** (self._failures - 1)))
                self._skip_until = time.monotonic() + delay
            logger.debug("obs collect: source %s (%s) down: %s",
                         self.name, self.url, e)
            return None
        with self._lock:
            self._failures = 0
            self._skip_until = 0.0
        if not isinstance(snap, dict):
            return None
        # the serve front end nests the registry under "registry";
        # normalize both shapes to Registry.snapshot
        if "registry" in snap and "counters" not in snap:
            snap = snap["registry"]
        return snap, {"source": self.name, "url": self.url,
                      **self._static_labels}


class Collector:
    """Merge N sources into one labeled view, churn-tolerant.

    Sources add/remove under a lock (the fleet helper re-derives them
    per collect); :meth:`collect` scrapes every source and never raises
    — a down source is data (``up: false``), not an exception.
    """

    def __init__(self, sources: Optional[List] = None,
                 clock: Callable[[], float] = time.time):
        self._lock = threading.Lock()
        self._sources: Dict[str, object] = {}
        # head-local gauge callables (no registry of their own): each
        # returns {name: value} folded into agg.gauges under the "head"
        # source on every collect — how the skew estimator's
        # obs.skew_ms.* gauges reach the timeseries store without a
        # dedicated registry (serve/remote.py — RemoteBacklogFeed)
        self._gauge_fns: List[Callable[[], Dict[str, float]]] = []
        # view-timestamp clock: wall time by default, virtual under sim/
        self._clock = clock
        for s in sources or []:
            self._sources[s.name] = s

    def add(self, source) -> None:
        with self._lock:
            self._sources[source.name] = source

    def add_gauge_fn(self, fn: Callable[[], Dict[str, float]]) -> None:
        with self._lock:
            self._gauge_fns.append(fn)

    def remove(self, name: str) -> None:
        with self._lock:
            self._sources.pop(name, None)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._sources)

    def collect(self) -> Dict:
        with self._lock:
            sources = list(self._sources.values())
            gauge_fns = list(self._gauge_fns)
        view: Dict = {"ts": round(self._clock(), 6), "sources": {}}
        agg_counters: Dict[str, float] = {}
        agg_gauges: Dict[str, Dict[str, float]] = {}
        up = 0
        for src in sources:
            res = src.scrape()
            if res is None:
                view["sources"][src.name] = {"up": False}
                continue
            snap, labels = res
            up += 1
            view["sources"][src.name] = {
                "up": True, "labels": labels,
                "counters": snap.get("counters", {}),
                "gauges": snap.get("gauges", {}),
                "hists": snap.get("hists", {}),
            }
            for k, v in snap.get("counters", {}).items():
                agg_counters[k] = agg_counters.get(k, 0) + v
            for k, v in snap.get("gauges", {}).items():
                agg_gauges.setdefault(k, {})[src.name] = v
        for fn in gauge_fns:
            try:
                extra = fn()
            except Exception:
                logger.exception("obs collect: gauge fn failed")
                continue
            for k, v in (extra or {}).items():
                agg_gauges.setdefault(k, {})["head"] = float(v)
        view["up"] = up
        view["agg"] = {"counters": agg_counters, "gauges": agg_gauges}
        return view


def collector_for_fleet(router, extra_sources: Optional[List] = None
                        ) -> Collector:
    """One source per managed replica, resolved through the replica
    object each scrape — an eject reads down, a relaunch reads the NEW
    engine's registry with the bumped generation label, a world resize
    (different replica count after rebuild) is just a different source
    set.  Plus the router's own process registry as ``router`` (the
    fleet.* gauges live there, published by ``export_gauges``)."""
    from mx_rcnn_tpu.obs.metrics import registry as process_registry

    def replica_resolve(r):
        with r._lock:
            eng, gen, state = r.engine, r.generation, r.state
        if eng is None:
            return None
        return eng.metrics.registry, {"generation": gen, "state": state}

    sources: List = [
        RegistrySource(f"replica-{r.id}",
                       (lambda r=r: replica_resolve(r)))
        for r in router.manager.replicas
    ]
    sources.append(RegistrySource("router", router.manager.registry
                                  if router.manager.registry is not None
                                  else process_registry()))
    for s in extra_sources or []:
        sources.append(s)
    return Collector(sources)


def view_to_snapshot(view: Dict) -> Dict:
    """Collapse one collected view into a ``Registry.snapshot``-shaped
    dict so windowed judgment can run over a FLEET the same way it runs
    over a process (``tools/obs.py check`` appends these to a local
    :class:`~mx_rcnn_tpu.obs.timeseries.TimeSeriesStore`).

    Merge semantics, chosen conservative for SLO rules:

    * counters — the agg SUM (fleet totals; sources are distinct
      registries, so summing cannot double-count);
    * gauges   — the bare name keeps the MIN across sources (a
      readiness gauge judged fleet-wide must reflect the worst source)
      and every per-source value survives as ``name@source``;
    * hist summaries — counts sum; p50/p90/p99/max take the MAX across
      sources (the fleet's tail is its worst source's tail).
    """
    gauges: Dict[str, float] = {}
    for name, by_src in view["agg"]["gauges"].items():
        gauges[name] = min(by_src.values())
        for src, v in by_src.items():
            gauges[f"{name}@{src}"] = v
    hists: Dict[str, Dict] = {}
    for src in view["sources"].values():
        if not src.get("up"):
            continue
        for name, s in src.get("hists", {}).items():
            if name not in hists:
                hists[name] = dict(s)
                continue
            m = hists[name]
            m["count"] = (m.get("count") or 0) + (s.get("count") or 0)
            for k in ("p50", "p90", "p99", "max", "mean"):
                a, b = m.get(k), s.get(k)
                m[k] = b if a is None else (a if b is None else max(a, b))
    return {"counters": dict(view["agg"]["counters"]),
            "gauges": gauges, "hists": hists}


def sources_from_urls(urls: str) -> List[HttpSource]:
    """``cfg.obs.collect_urls`` / ``--url`` parsing: a comma-separated
    list of ``host:port`` or full URLs, optionally ``name=url``."""
    out: List[HttpSource] = []
    for i, item in enumerate(s.strip() for s in urls.split(",")):
        if not item:
            continue
        if "=" in item and "://" not in item.split("=", 1)[0]:
            name, url = item.split("=", 1)
        else:
            name, url = f"source-{i}", item
        out.append(HttpSource(name.strip(), url.strip()))
    return out
