"""Bounded ring-buffer time-series store over the metrics registry.

No reference equivalent.  PR 4's registry (``obs/metrics.py``) is
point-in-time: a scrape answers "what are the totals NOW", never "what
was the shed rate over the last 30 s" — which is the question every
consumer built since actually asks (the health rules in ``obs/health.py``,
ROADMAP item 2's scheduler, item 3's canary comparison, the flight
recorder's last-N-seconds postmortem window).  This module closes the
gap with the same discipline PR 4 set: OFF by default, nothing on the
hot path (sampling runs on its own daemon thread; recorders never see
it), bounded memory (a ring of ``capacity`` samples), <2% measured
overhead with sampling armed (``tools/obs_smoke.py --overhead_out``).

One **sample** is a consistent copy of the registry taken under
``Registry.lock``: counter totals, gauge values, and per-histogram
cumulative state (bucket counts + total/sum/max — the counts COPY is
what makes exact windowed percentiles possible).  Windowed queries
difference two cumulative samples:

* ``delta(name, window_s)``   — counter increase over the window;
* ``rate(name, window_s)``    — delta / actual elapsed span;
* ``gauge(name)``             — latest value; ``gauge_max``/``gauge_min``
  scan the window;
* ``pctl(name, p, window_s)`` — EXACT windowed percentile from the
  bucket-count difference (same bucket-upper-bound readout as
  ``Histogram.percentile``, applied to only the window's samples);
* ``hist_window(name, window_s)`` — count/p50/p99 over the window.

Samples ingested from a remote ``/metrics`` scrape
(:meth:`TimeSeriesStore.append_snapshot`, used by ``tools/obs.py
check`` over HTTP sources) carry histogram SUMMARIES instead of bucket
counts — there ``pctl`` degrades to the latest scraped percentile,
which is the best a summary-only wire format admits.

The module-level **active store** (:func:`set_active` / :func:`active`)
is how the ``/metrics`` exporters (``obs/metrics.py`` and
``serve/server.py``) discover whether to include a ``"timeseries"``
section in the scrape — set by ``CliObs`` when ``cfg.obs.timeseries``
is on, never implicitly.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

logger = logging.getLogger("mx_rcnn_tpu")


class TimeSeriesStore:
    """Ring buffer of registry samples with windowed queries.

    Thread-safe: the sampler thread appends while query threads (health
    engine, HTTP scrape, flight dump) read; one lock bounds both.  At
    the default 1 s interval and 600-sample capacity the ring holds ten
    minutes; memory is ~(hists × 41 int64 + counters + gauges) per
    sample — a few KB for this repo's metric surface.
    """

    def __init__(self, capacity: int = 600,
                 clock: Callable[[], float] = time.time):
        self.capacity = int(capacity)
        self._clock = clock
        self._lock = threading.Lock()
        self._buf: deque = deque(maxlen=self.capacity)
        self._dropped = 0

    # ------------------------------------------------------------------
    # ingest
    # ------------------------------------------------------------------

    def sample(self, reg=None, ts: Optional[float] = None) -> Dict:
        """Take one consistent sample of ``reg`` (default: the process
        registry) and append it.  Runs on the sampler thread — never on
        a recording hot path."""
        if reg is None:
            from mx_rcnn_tpu.obs.metrics import registry as _registry

            reg = _registry()
        ts = self._clock() if ts is None else ts
        with reg.lock:
            counters = dict(reg._counters)
            gauges = dict(reg._gauges)
            # counts.copy() under the registry lock: the cumulative
            # bucket vector is the windowed-percentile substrate
            hists = {name: {"bounds": h.bounds,
                            "counts": h.counts.copy(),
                            "total": int(h.total),
                            "sum": float(h.sum),
                            "max": float(h.max)}
                     for name, h in reg._hists.items()}
        smp = {"ts": ts, "counters": counters, "gauges": gauges,
               "hists": hists}
        self._append(smp)
        return smp

    def append_snapshot(self, snap: Dict, ts: Optional[float] = None,
                        gauge_labels: Optional[Dict[str, Dict]] = None
                        ) -> Dict:
        """Ingest a remote ``/metrics`` snapshot (``Registry.snapshot``
        shape) as one sample — the cross-process path ``tools/obs.py``
        uses.  Histograms arrive as summaries (no bucket counts), so
        windowed percentiles over these samples fall back to the latest
        summary value."""
        smp = {"ts": self._clock() if ts is None else ts,
               "counters": dict(snap.get("counters", {})),
               "gauges": dict(snap.get("gauges", {})),
               "hists": {name: {"summary": dict(s)}
                         for name, s in snap.get("hists", {}).items()}}
        if gauge_labels:
            smp["labels"] = gauge_labels
        self._append(smp)
        return smp

    def _append(self, smp: Dict) -> None:
        with self._lock:
            if len(self._buf) == self.capacity:
                self._dropped += 1
            self._buf.append(smp)

    # ------------------------------------------------------------------
    # windowed reads
    # ------------------------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def window(self, window_s: Optional[float] = None) -> List[Dict]:
        """Samples within the trailing window (all, when None), oldest
        first.  Returns the sample dicts themselves — treat as
        read-only (samples are immutable once appended)."""
        with self._lock:
            out = list(self._buf)
        if window_s is None or not out:
            return out
        cut = out[-1]["ts"] - float(window_s)
        return [s for s in out if s["ts"] >= cut]

    def _edges(self, window_s: Optional[float]
               ) -> Optional[Tuple[Dict, Dict]]:
        w = self.window(window_s)
        if len(w) < 2:
            return None
        return w[0], w[-1]

    def delta(self, name: str, window_s: Optional[float] = None
              ) -> Optional[float]:
        """Counter increase across the window (None: not enough
        samples or the counter never appeared)."""
        e = self._edges(window_s)
        if e is None:
            return None
        a, b = e
        if name not in b["counters"]:
            return None
        return float(b["counters"][name] - a["counters"].get(name, 0))

    def rate(self, name: str, window_s: Optional[float] = None
             ) -> Optional[float]:
        """Counter rate (per second) over the ACTUAL sampled span —
        the span between the window's edge samples, not the nominal
        window length."""
        e = self._edges(window_s)
        if e is None:
            return None
        d = self.delta(name, window_s)
        if d is None:
            return None
        span = e[1]["ts"] - e[0]["ts"]
        if span <= 0:
            return None
        return d / span

    def gauge(self, name: str,
              window_s: Optional[float] = None) -> Optional[float]:
        """Most recent value of the gauge (scanning back for the last
        sample that carried it).  ``window_s`` bounds the scan: a gauge
        whose source stopped reporting longer than the window ago reads
        None — exactly like a source that NEVER reported.  Unbounded
        (the default) preserves the original latest-ever semantics."""
        for smp in reversed(self.window(window_s)):
            if name in smp["gauges"]:
                return float(smp["gauges"][name])
        return None

    def gauge_max(self, name: str, window_s: Optional[float] = None
                  ) -> Optional[float]:
        vals = [s["gauges"][name] for s in self.window(window_s)
                if name in s["gauges"]]
        return max(vals) if vals else None

    def gauge_min(self, name: str, window_s: Optional[float] = None
                  ) -> Optional[float]:
        vals = [s["gauges"][name] for s in self.window(window_s)
                if name in s["gauges"]]
        return min(vals) if vals else None

    def series(self, name: str, window_s: Optional[float] = None
               ) -> List[Tuple[float, float]]:
        """(ts, value) pairs for a gauge or counter over the window —
        the canary-comparison / plotting readout."""
        out = []
        for s in self.window(window_s):
            if name in s["gauges"]:
                out.append((s["ts"], float(s["gauges"][name])))
            elif name in s["counters"]:
                out.append((s["ts"], float(s["counters"][name])))
        return out

    def pctl(self, name: str, p: float,
             window_s: Optional[float] = None) -> Optional[float]:
        """Windowed histogram percentile.  With bucket-count samples the
        readout is EXACT for the window (cumulative-count difference,
        bucket-upper-bound estimate); with summary-only samples (remote
        scrapes) it degrades to the latest scraped ``p<P>``."""
        e = self._edges(window_s)
        if e is None:
            w = self.window(window_s)
            e = (w[-1], w[-1]) if w else None
        if e is None:
            return None
        a, b = e
        hb = b["hists"].get(name)
        if hb is None:
            return None
        if "counts" not in hb:  # summary-only (cross-process) sample
            return hb["summary"].get(f"p{int(p)}")
        ha = a["hists"].get(name)
        counts = hb["counts"] - (ha["counts"] if ha is not None
                                 and "counts" in ha else 0)
        total = int(counts.sum())
        if total <= 0:
            return None
        rank = int(np.ceil(p / 100.0 * total))
        rank = min(max(rank, 1), total)
        cum = np.cumsum(counts)
        i = int(np.searchsorted(cum, rank))
        bounds = hb["bounds"]
        if i >= len(bounds):
            return float(hb["max"])  # overflow bucket: observed max
        return float(bounds[i])

    def hist_window(self, name: str,
                    window_s: Optional[float] = None) -> Optional[Dict]:
        """Windowed histogram readout: sample count + p50/p99 over the
        window (summary-shaped, like ``Histogram.summary`` minus
        mean)."""
        e = self._edges(window_s)
        if e is None:
            return None
        a, b = e
        hb = b["hists"].get(name)
        if hb is None:
            return None
        if "counts" not in hb:
            s = dict(hb["summary"])
            s["windowed"] = False
            return s
        ha = a["hists"].get(name)
        prev_total = (ha["total"] if ha is not None
                      and "counts" in ha else 0)
        return {"count": int(hb["total"]) - int(prev_total),
                "p50": self.pctl(name, 50, window_s),
                "p99": self.pctl(name, 99, window_s),
                "max": float(hb["max"]),
                "windowed": True}

    # ------------------------------------------------------------------
    # scrape section
    # ------------------------------------------------------------------

    def scrape_section(self, window_s: float = 60.0) -> Dict:
        """The compact ``"timeseries"`` block the ``/metrics`` exporters
        attach when this store is active: ring occupancy plus windowed
        rates and p99s for everything the window saw move."""
        w = self.window(window_s)
        out: Dict = {"samples": len(self), "capacity": self.capacity,
                     "window_s": float(window_s), "dropped": self.dropped}
        if len(w) >= 2:
            a, b = w[0], w[-1]
            out["span_s"] = round(b["ts"] - a["ts"], 3)
            rates = {}
            for name in b["counters"]:
                r = self.rate(name, window_s)
                if r:
                    rates[name] = round(r, 3)
            out["rates_per_s"] = rates
            p99 = {}
            for name in b["hists"]:
                v = self.pctl(name, 99, window_s)
                if v is not None:
                    p99[name] = round(v, 3)
            out["p99"] = p99
        return out


class Sampler:
    """Daemon thread sampling a registry into a store on an interval.

    ``after_sample`` (optional) runs on the sampler thread after every
    tick — the health engine evaluates there, so sampling + judging is
    ONE thread and the hot paths stay untouched.  Fail-soft: a hook
    exception logs and disables the hook, never kills the sampler (the
    runrec invariant: observability must not take down what it
    observes).
    """

    def __init__(self, store: TimeSeriesStore, interval_s: float = 1.0,
                 reg=None,
                 after_sample: Optional[Callable[[Dict], None]] = None,
                 clock: Optional[Callable[[], float]] = None):
        self.store = store
        self.interval_s = max(float(interval_s), 0.01)
        self._reg = reg
        self._after = after_sample
        # sample timestamp source; None defers to the store's clock
        # (wall time by default, virtual time under the simulator)
        self._clock = clock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def tick(self) -> Dict:
        """One sample + hook pass (public so tests drive the cadence
        deterministically without the wall-clock loop — the same
        pattern as ``ReplicaManager.tick``)."""
        ts = None if self._clock is None else self._clock()
        smp = self.store.sample(self._reg, ts=ts)
        if self._after is not None:
            try:
                self._after(smp)
            except Exception:
                logger.exception("obs timeseries: after_sample hook "
                                 "failed — hook disabled")
                self._after = None
        return smp

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.tick()

    def start(self) -> "Sampler":
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop,
                                            name="obs-ts-sampler",
                                            daemon=True)
            self._thread.start()
        return self

    def stop(self, final_sample: bool = True) -> None:
        """Stop the loop (bounded join) and by default take one last
        sample so the ring's tail reflects shutdown state."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=max(self.interval_s * 2, 1.0))
            self._thread = None
        if final_sample:
            self.tick()


# ---------------------------------------------------------------------------
# active-store registration (the exporter hook)
# ---------------------------------------------------------------------------

_active_lock = threading.Lock()
_ACTIVE: Optional[TimeSeriesStore] = None


def set_active(store: Optional[TimeSeriesStore]) -> None:
    """Publish ``store`` as THE process time-series store: the
    ``/metrics`` exporters include its scrape section, the flight
    recorder dumps its window.  Pass None to clear."""
    global _ACTIVE
    with _active_lock:
        _ACTIVE = store


def active() -> Optional[TimeSeriesStore]:
    with _active_lock:
        return _ACTIVE
