"""Cheap host-side span tracing with chrome-trace export.

No reference equivalent.  The XLA device timeline (``jax.profiler`` →
``utils/xplane.py``) explains where DEVICE time goes; this module is the
host half: ``with span("h2d")`` wraps any host-side region (data wait,
dispatch, snapshot stall, serve queue wait) with ~µs overhead, and a
trace-context id ties the pieces of one logical operation together
across threads — e.g. a serve request's enqueue (caller thread) →
coalesce/dispatch (dispatcher thread) → respond hops all carry the same
``trace_id``.

Disabled (the default) the whole API is a no-op: ``span`` returns a
shared null context manager after ONE module-flag read, so leaving the
calls in hot paths costs a branch (pinned near zero by
``tests/test_obs.py``).

Export is the chrome trace event format (load in Perfetto /
``chrome://tracing``):

* spans → ``ph:"X"`` duration events (ts/dur in µs) on their real thread;
* request lifecycles → ``ph:"b"/"e"`` async events keyed by trace id;
* :func:`device_trace_events` decodes an ``*.xplane.pb`` (via
  ``utils/xplane.py``) into the same format so host + device merge into
  ONE timeline: host timestamps use ``time.time_ns()`` (unix epoch) and
  xplane ``XLine.timestamp_ns`` is the same epoch clock, so the two
  align without offset surgery (``merge_device_trace``).

IMPORTANT: never call ``span`` (or any host clock) INSIDE jitted code —
host clocks in traced code measure tracing, not compute.  graphlint rule
GL105 enforces this.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from typing import Dict, List, Optional

_lock = threading.Lock()
_events: List[dict] = []
_enabled = False
_cap = 100_000
_dropped = 0
_tls = threading.local()
_ids = itertools.count(1)


def enable(cap: int = 100_000) -> None:
    """Start collecting spans (bounded buffer of ``cap`` events; overflow
    is counted, never grows memory)."""
    global _enabled, _cap
    with _lock:
        _cap = int(cap)
        _enabled = True


def disable() -> None:
    global _enabled
    with _lock:
        _enabled = False


def enabled() -> bool:
    return _enabled


def reset() -> None:
    """Drop collected events (keeps the enabled flag)."""
    global _dropped
    with _lock:
        _events.clear()
        _dropped = 0


def dropped() -> int:
    return _dropped


def events() -> List[dict]:
    with _lock:
        return list(_events)


def _emit(ev: dict) -> None:
    global _dropped
    with _lock:
        if len(_events) >= _cap:
            _dropped += 1
            return
        _events.append(ev)


def _now_us() -> float:
    # wall clock, not perf_counter: xplane device lines timestamp in
    # ns-since-epoch, so host events on the same clock merge cleanly
    return time.time_ns() / 1e3


# ---------------------------------------------------------------------------
# trace-context ids
# ---------------------------------------------------------------------------

def new_trace_id() -> str:
    """Process-unique id tying the spans of one logical operation
    together (e.g. one serve request across caller + dispatcher
    threads)."""
    return f"{os.getpid():x}.{next(_ids):x}"


def set_trace_id(trace_id: Optional[str]) -> None:
    """Bind a trace id to the CURRENT thread: spans opened on it attach
    the id automatically until cleared (pass None to clear)."""
    _tls.trace_id = trace_id


def get_trace_id() -> Optional[str]:
    return getattr(_tls, "trace_id", None)


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------

class _Null:
    """Reusable no-op context manager (the disabled fast path)."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL = _Null()


class _Span:
    __slots__ = ("name", "args", "t0", "depth")

    def __init__(self, name: str, args: Dict):
        self.name = name
        self.args = args

    def __enter__(self) -> "_Span":
        self.depth = getattr(_tls, "depth", 0)
        _tls.depth = self.depth + 1
        self.t0 = _now_us()
        return self

    def __exit__(self, *exc) -> bool:
        t1 = _now_us()
        _tls.depth = self.depth
        args = {"depth": self.depth}
        tid = self.args.pop("trace_id", None) or get_trace_id()
        if tid is not None:
            args["trace_id"] = tid
        args.update(self.args)
        _emit({"name": self.name, "ph": "X", "ts": self.t0,
               "dur": t1 - self.t0, "pid": os.getpid(),
               "tid": threading.get_ident(), "args": args})
        return False


def span(name: str, **args):
    """``with span("h2d"): ...`` — record a duration event on this
    thread.  Attaches the thread's bound trace id (or an explicit
    ``trace_id=`` kwarg).  Near-free when tracing is disabled."""
    if not _enabled:
        return _NULL
    return _Span(name, args)


def complete(name: str, dur_ms: float, **args) -> None:
    """Record a duration event that ENDED now and lasted ``dur_ms`` —
    for intervals measured with other clocks (e.g. a request's
    ``monotonic`` queue wait) whose endpoints span threads."""
    if not _enabled:
        return
    t1 = _now_us()
    tid = args.pop("trace_id", None) or get_trace_id()
    a = dict(args)
    if tid is not None:
        a["trace_id"] = tid
    _emit({"name": name, "ph": "X", "ts": t1 - dur_ms * 1e3,
           "dur": dur_ms * 1e3, "pid": os.getpid(),
           "tid": threading.get_ident(), "args": a})


def instant(name: str, **args) -> None:
    if not _enabled:
        return
    tid = args.pop("trace_id", None) or get_trace_id()
    a = dict(args)
    if tid is not None:
        a["trace_id"] = tid
    _emit({"name": name, "ph": "i", "s": "t", "ts": _now_us(),
           "pid": os.getpid(), "tid": threading.get_ident(), "args": a})


def async_begin(name: str, trace_id: str, **args) -> None:
    """Open an async (cross-thread) interval keyed by ``trace_id`` —
    chrome ``ph:"b"``.  Close it with :func:`async_end` from ANY
    thread."""
    if not _enabled:
        return
    _emit({"name": name, "ph": "b", "cat": "request", "id": trace_id,
           "ts": _now_us(), "pid": os.getpid(),
           "tid": threading.get_ident(),
           "args": {"trace_id": trace_id, **args}})


def async_end(name: str, trace_id: str, **args) -> None:
    if not _enabled:
        return
    _emit({"name": name, "ph": "e", "cat": "request", "id": trace_id,
           "ts": _now_us(), "pid": os.getpid(),
           "tid": threading.get_ident(),
           "args": {"trace_id": trace_id, **args}})


# ---------------------------------------------------------------------------
# export + device-trace merge
# ---------------------------------------------------------------------------

def export_chrome_trace(path: str, extra_events: List[dict] = None) -> str:
    """Write collected events (plus ``extra_events``, e.g. the decoded
    device timeline) as chrome trace JSON."""
    evs = events() + list(extra_events or [])
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "w") as f:
        json.dump({"traceEvents": evs, "displayTimeUnit": "ms",
                   "metadata": {"dropped_host_events": _dropped}}, f)
    return path


def device_trace_events(source) -> List[dict]:
    """Decode an ``*.xplane.pb`` path (or pre-parsed planes) into chrome
    duration events, one pid per device plane, one tid per XLine.  Event
    start = ``XLine.timestamp_ns + offset_ps`` — the same unix-epoch ns
    clock host spans use, so the merged file lines up."""
    from mx_rcnn_tpu.utils.xplane import device_planes, parse_xspace

    planes = parse_xspace(source) if isinstance(source, str) else source
    out: List[dict] = []
    for plane in device_planes(planes):
        pid = f"device:{plane.get('name', '?')}"
        emd = plane.get("event_metadata", {})
        for line in plane["lines"]:
            base_us = line.get("timestamp_ns", 0) / 1e3
            tid = line.get("display_name") or line.get("name", "")
            for ev in line["events"]:
                md = emd.get(ev.get("metadata_id"), {})
                out.append({
                    "name": md.get("display_name") or md.get("name", "?"),
                    "ph": "X",
                    "ts": base_us + ev.get("offset_ps", 0) / 1e6,
                    "dur": ev.get("duration_ps", 0) / 1e6,
                    "pid": pid, "tid": tid,
                })
    return out


def merge_device_trace(path: str, trace_dir: str) -> str:
    """Export host spans merged with the newest device trace under
    ``trace_dir`` (a ``jax.profiler`` output directory) into one
    chrome-trace file."""
    from mx_rcnn_tpu.obs.profiler import newest_xplane

    pb = newest_xplane(trace_dir)
    extra = device_trace_events(pb) if pb else []
    return export_chrome_trace(path, extra_events=extra)
