"""Cheap host-side span tracing with chrome-trace export.

No reference equivalent.  The XLA device timeline (``jax.profiler`` →
``utils/xplane.py``) explains where DEVICE time goes; this module is the
host half: ``with span("h2d")`` wraps any host-side region (data wait,
dispatch, snapshot stall, serve queue wait) with ~µs overhead, and a
trace-context id ties the pieces of one logical operation together
across threads — e.g. a serve request's enqueue (caller thread) →
coalesce/dispatch (dispatcher thread) → respond hops all carry the same
``trace_id``.

Disabled (the default) the whole API is a no-op: ``span`` returns a
shared null context manager after ONE module-flag read, so leaving the
calls in hot paths costs a branch (pinned near zero by
``tests/test_obs.py``).

Export is the chrome trace event format (load in Perfetto /
``chrome://tracing``):

* spans → ``ph:"X"`` duration events (ts/dur in µs) on their real thread;
* request lifecycles → ``ph:"b"/"e"`` async events keyed by trace id;
* :func:`device_trace_events` decodes an ``*.xplane.pb`` (via
  ``utils/xplane.py``) into the same format so host + device merge into
  ONE timeline: host timestamps use ``time.time_ns()`` (unix epoch) and
  xplane ``XLine.timestamp_ns`` is the same epoch clock, so the two
  align without offset surgery (``merge_device_trace``).

IMPORTANT: never call ``span`` (or any host clock) INSIDE jitted code —
host clocks in traced code measure tracing, not compute.  graphlint rule
GL105 enforces this.
"""

from __future__ import annotations

import itertools
import json
import os
import struct
import threading
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional

_lock = threading.Lock()
_events: List[dict] = []
_enabled = False
_cap = 100_000
_dropped = 0
_tls = threading.local()
_ids = itertools.count(1)


def enable(cap: int = 100_000) -> None:
    """Start collecting spans (bounded buffer of ``cap`` events; overflow
    is counted, never grows memory)."""
    global _enabled, _cap
    with _lock:
        _cap = int(cap)
        _enabled = True


def disable() -> None:
    global _enabled
    with _lock:
        _enabled = False


def enabled() -> bool:
    return _enabled


def reset() -> None:
    """Drop collected events (keeps the enabled flag)."""
    global _dropped
    with _lock:
        _events.clear()
        _dropped = 0


def dropped() -> int:
    return _dropped


def events() -> List[dict]:
    with _lock:
        return list(_events)


def _emit(ev: dict) -> None:
    global _dropped
    with _lock:
        if len(_events) >= _cap:
            _dropped += 1
            return
        _events.append(ev)


def _now_us() -> float:
    # wall clock, not perf_counter: xplane device lines timestamp in
    # ns-since-epoch, so host events on the same clock merge cleanly
    return time.time_ns() / 1e3


def epoch_us() -> int:
    """Integer epoch-µs stamp — the cross-host span/skew clock (the
    wire skew extension ships these, so both ends must agree on units
    and epoch; monotonic clocks are per-host and cannot be compared)."""
    return time.time_ns() // 1000


# ---------------------------------------------------------------------------
# trace-context ids
# ---------------------------------------------------------------------------

def new_trace_id() -> str:
    """Process-unique id tying the spans of one logical operation
    together (e.g. one serve request across caller + dispatcher
    threads)."""
    return f"{os.getpid():x}.{next(_ids):x}"


def set_trace_id(trace_id: Optional[str]) -> None:
    """Bind a trace id to the CURRENT thread: spans opened on it attach
    the id automatically until cleared (pass None to clear)."""
    _tls.trace_id = trace_id


def get_trace_id() -> Optional[str]:
    return getattr(_tls, "trace_id", None)


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------

class _Null:
    """Reusable no-op context manager (the disabled fast path)."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL = _Null()


class _Span:
    __slots__ = ("name", "args", "t0", "depth")

    def __init__(self, name: str, args: Dict):
        self.name = name
        self.args = args

    def __enter__(self) -> "_Span":
        self.depth = getattr(_tls, "depth", 0)
        _tls.depth = self.depth + 1
        self.t0 = _now_us()
        return self

    def __exit__(self, *exc) -> bool:
        t1 = _now_us()
        _tls.depth = self.depth
        args = {"depth": self.depth}
        tid = self.args.pop("trace_id", None) or get_trace_id()
        if tid is not None:
            args["trace_id"] = tid
        args.update(self.args)
        _emit({"name": self.name, "ph": "X", "ts": self.t0,
               "dur": t1 - self.t0, "pid": os.getpid(),
               "tid": threading.get_ident(), "args": args})
        return False


def span(name: str, **args):
    """``with span("h2d"): ...`` — record a duration event on this
    thread.  Attaches the thread's bound trace id (or an explicit
    ``trace_id=`` kwarg).  Near-free when tracing is disabled."""
    if not _enabled:
        return _NULL
    return _Span(name, args)


def complete(name: str, dur_ms: float, **args) -> None:
    """Record a duration event that ENDED now and lasted ``dur_ms`` —
    for intervals measured with other clocks (e.g. a request's
    ``monotonic`` queue wait) whose endpoints span threads."""
    if not _enabled:
        return
    t1 = _now_us()
    tid = args.pop("trace_id", None) or get_trace_id()
    a = dict(args)
    if tid is not None:
        a["trace_id"] = tid
    _emit({"name": name, "ph": "X", "ts": t1 - dur_ms * 1e3,
           "dur": dur_ms * 1e3, "pid": os.getpid(),
           "tid": threading.get_ident(), "args": a})


def instant(name: str, **args) -> None:
    if not _enabled:
        return
    tid = args.pop("trace_id", None) or get_trace_id()
    a = dict(args)
    if tid is not None:
        a["trace_id"] = tid
    _emit({"name": name, "ph": "i", "s": "t", "ts": _now_us(),
           "pid": os.getpid(), "tid": threading.get_ident(), "args": a})


def async_begin(name: str, trace_id: str, **args) -> None:
    """Open an async (cross-thread) interval keyed by ``trace_id`` —
    chrome ``ph:"b"``.  Close it with :func:`async_end` from ANY
    thread."""
    if not _enabled:
        return
    _emit({"name": name, "ph": "b", "cat": "request", "id": trace_id,
           "ts": _now_us(), "pid": os.getpid(),
           "tid": threading.get_ident(),
           "args": {"trace_id": trace_id, **args}})


def async_end(name: str, trace_id: str, **args) -> None:
    if not _enabled:
        return
    _emit({"name": name, "ph": "e", "cat": "request", "id": trace_id,
           "ts": _now_us(), "pid": os.getpid(),
           "tid": threading.get_ident(),
           "args": {"trace_id": trace_id, **args}})


# ---------------------------------------------------------------------------
# export + device-trace merge
# ---------------------------------------------------------------------------

def export_chrome_trace(path: str, extra_events: List[dict] = None) -> str:
    """Write collected events (plus ``extra_events``, e.g. the decoded
    device timeline) as chrome trace JSON."""
    evs = events() + list(extra_events or [])
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "w") as f:
        json.dump({"traceEvents": evs, "displayTimeUnit": "ms",
                   "metadata": {"dropped_host_events": _dropped}}, f)
    return path


def device_trace_events(source) -> List[dict]:
    """Decode an ``*.xplane.pb`` path (or pre-parsed planes) into chrome
    duration events, one pid per device plane, one tid per XLine.  Event
    start = ``XLine.timestamp_ns + offset_ps`` — the same unix-epoch ns
    clock host spans use, so the merged file lines up."""
    from mx_rcnn_tpu.utils.xplane import device_planes, parse_xspace

    planes = parse_xspace(source) if isinstance(source, str) else source
    out: List[dict] = []
    for plane in device_planes(planes):
        pid = f"device:{plane.get('name', '?')}"
        emd = plane.get("event_metadata", {})
        for line in plane["lines"]:
            base_us = line.get("timestamp_ns", 0) / 1e3
            tid = line.get("display_name") or line.get("name", "")
            for ev in line["events"]:
                md = emd.get(ev.get("metadata_id"), {})
                out.append({
                    "name": md.get("display_name") or md.get("name", "?"),
                    "ph": "X",
                    "ts": base_us + ev.get("offset_ps", 0) / 1e6,
                    "dur": ev.get("duration_ps", 0) / 1e6,
                    "pid": pid, "tid": tid,
                })
    return out


def merge_device_trace(path: str, trace_dir: str) -> str:
    """Export host spans merged with the newest device trace under
    ``trace_dir`` (a ``jax.profiler`` output directory) into one
    chrome-trace file."""
    from mx_rcnn_tpu.obs.profiler import newest_xplane

    pb = newest_xplane(trace_dir)
    extra = device_trace_events(pb) if pb else []
    return export_chrome_trace(path, extra_events=extra)


# ---------------------------------------------------------------------------
# distributed tracing (docs/OBSERVABILITY.md "Distributed tracing")
#
# Everything below extends the in-process plane across hosts: a compact
# trace CONTEXT (trace id, parent span id, hop depth, sampling bit)
# rides the MXR1/MXD1 wire frames and an ``X-MXR-Trace`` header, agents
# record per-hop spans into a bounded SpanRing served by ``/trace``, the
# head estimates per-agent clock offset NTP-style, and
# :func:`merge_fleet_trace` stitches it all into one skew-corrected
# timeline.  Dapper-style propagation with tail-based sampling
# (Sigelman et al. 2010).
# ---------------------------------------------------------------------------

#: header name carried on JSON verbs (/detect, agent admin/rollout)
TRACE_HEADER = "X-MXR-Trace"

CTX_VERSION = 1
# version, flags (bit0 = sampled), hop depth, parent span id, id length
_CTX_HEAD = struct.Struct("<BBHQB")
_MAX_CTX_ID = 64                     # trace-id byte bound (wire + header)
_CTX_ID_CHARS = frozenset("0123456789abcdefABCDEF.-_:")


class TraceContext:
    """One request's propagated trace context — immutable value object.

    ``parent`` is the SPAN id (64-bit int) the next hop's spans must
    nest under; ``hop`` counts process boundaries crossed (head = 0).
    """

    __slots__ = ("trace_id", "parent", "hop", "sampled")

    def __init__(self, trace_id: str, parent: int = 0, hop: int = 0,
                 sampled: bool = True):
        self.trace_id = trace_id
        self.parent = int(parent)
        self.hop = int(hop)
        self.sampled = bool(sampled)

    def child(self, parent_span: int) -> "TraceContext":
        """The context the NEXT hop receives: same trace, one hop
        deeper, nesting under ``parent_span``."""
        return TraceContext(self.trace_id, parent_span, self.hop + 1,
                            self.sampled)

    def __eq__(self, other) -> bool:
        return (isinstance(other, TraceContext)
                and self.trace_id == other.trace_id
                and self.parent == other.parent
                and self.hop == other.hop
                and self.sampled == other.sampled)

    def __repr__(self) -> str:
        return (f"TraceContext({self.trace_id!r}, parent={self.parent:#x},"
                f" hop={self.hop}, sampled={self.sampled})")


def _check_ctx_id(trace_id: str) -> bytes:
    raw = trace_id.encode("ascii", "strict") if isinstance(
        trace_id, str) else bytes(trace_id)
    if not raw or len(raw) > _MAX_CTX_ID:
        raise ValueError(
            f"trace id length {len(raw)} outside [1, {_MAX_CTX_ID}]")
    if not set(trace_id) <= _CTX_ID_CHARS:
        raise ValueError(f"trace id {trace_id!r} has invalid characters")
    return raw


def encode_ctx(ctx: TraceContext) -> bytes:
    """Trace context → the compact wire extension blob."""
    raw = _check_ctx_id(ctx.trace_id)
    if not 0 <= ctx.parent < (1 << 64):
        raise ValueError(f"parent span id {ctx.parent} outside u64")
    if not 0 <= ctx.hop < (1 << 16):
        raise ValueError(f"hop depth {ctx.hop} outside u16")
    return _CTX_HEAD.pack(CTX_VERSION, 1 if ctx.sampled else 0,
                          ctx.hop, ctx.parent, len(raw)) + raw


def decode_ctx(buf: bytes) -> TraceContext:
    """Wire extension blob → trace context.  Malformed input is a
    typed ``ValueError`` (the netio rejection contract) — NEVER a
    zero-filled default."""
    if len(buf) < _CTX_HEAD.size:
        raise ValueError(
            f"trace extension truncated: {len(buf)} < {_CTX_HEAD.size}")
    ver, flags, hop, parent, idlen = _CTX_HEAD.unpack_from(buf)
    if ver != CTX_VERSION:
        raise ValueError(f"trace extension version {ver} unsupported")
    if idlen == 0 or idlen > _MAX_CTX_ID:
        raise ValueError(
            f"trace id length {idlen} outside [1, {_MAX_CTX_ID}]")
    if len(buf) != _CTX_HEAD.size + idlen:
        raise ValueError(
            f"trace extension length {len(buf)} != "
            f"{_CTX_HEAD.size + idlen} declared")
    raw = buf[_CTX_HEAD.size:]
    try:
        trace_id = raw.decode("ascii")
    except UnicodeDecodeError:
        raise ValueError("trace id is not ascii")
    if not set(trace_id) <= _CTX_ID_CHARS:
        raise ValueError(f"trace id {trace_id!r} has invalid characters")
    # unknown FLAG bits are ignored (forward-compat: a newer head may
    # set bits this build does not know), unknown VERSIONS are rejected
    return TraceContext(trace_id, parent, hop, bool(flags & 1))


def format_header(ctx: TraceContext) -> str:
    """Trace context → the ``X-MXR-Trace`` header value."""
    _check_ctx_id(ctx.trace_id)
    return (f"v{CTX_VERSION};id={ctx.trace_id};parent={ctx.parent:x};"
            f"hop={ctx.hop};s={1 if ctx.sampled else 0}")


def parse_header(value: str) -> TraceContext:
    """``X-MXR-Trace`` header value → trace context (ValueError on any
    malformation — a bad header is a 400, never a silent default)."""
    if not isinstance(value, str) or len(value) > 256:
        raise ValueError("trace header missing or oversized")
    parts = value.strip().split(";")
    if parts[0] != f"v{CTX_VERSION}":
        raise ValueError(f"trace header version {parts[0]!r} unsupported")
    kv: Dict[str, str] = {}
    for p in parts[1:]:
        if "=" not in p:
            raise ValueError(f"trace header field {p!r} malformed")
        k, v = p.split("=", 1)
        kv[k] = v
    try:
        trace_id = kv["id"]
        parent = int(kv["parent"], 16)
        hop = int(kv["hop"])
        s = kv["s"]
    except KeyError as e:
        raise ValueError(f"trace header missing field {e.args[0]!r}")
    except ValueError:
        raise ValueError("trace header numeric field malformed")
    if s not in ("0", "1"):
        raise ValueError(f"trace header sampling bit {s!r} malformed")
    if not 0 <= parent < (1 << 64) or not 0 <= hop < (1 << 16):
        raise ValueError("trace header field out of range")
    _check_ctx_id(trace_id)
    return TraceContext(trace_id, parent, hop, s == "1")


# -- span ids ---------------------------------------------------------------

_span_ids = itertools.count(1)


def new_span_id() -> int:
    """Fleet-unique-enough 64-bit span id: pid in the high bits, a
    process counter in the low — no randomness, so traces stay
    byte-reproducible under the simulator's virtual clock."""
    return ((os.getpid() & 0xFFFF) << 48) | (next(_span_ids) & 0xFFFFFFFFFFFF)


# -- distributed configuration ---------------------------------------------

_dist_lock = threading.Lock()
_dist_sample = 0.0        # head sampling probability (cfg.obs.trace_sample)
_dist_acc = 0.0           # deterministic fraction accumulator
_dist_slow_pct = 99.0     # slowest-percentile forced retention
_dist_host = f"pid-{os.getpid()}"
_dist_ring: Optional["SpanRing"] = None
_dist_durs: deque = deque(maxlen=512)   # recent SERVED totals (tail window)


def configure_distributed(sample: float = None, ring: int = None,
                          slow_pct: float = None, host: str = None) -> None:
    """Arm (or retune) the distributed plane.  ``sample`` is the head
    sampling probability (0 disables head-side trace creation; agents
    obey the inbound sampled bit regardless); ``ring`` bounds the kept
    span trees; ``host`` labels this process's spans in merged views."""
    global _dist_sample, _dist_slow_pct, _dist_host, _dist_ring, _dist_acc
    with _dist_lock:
        if sample is not None:
            _dist_sample = max(0.0, min(1.0, float(sample)))
            _dist_acc = 0.0
        if slow_pct is not None:
            _dist_slow_pct = max(0.0, min(100.0, float(slow_pct)))
        if host is not None:
            _dist_host = str(host)
        if ring is not None and ring > 0 and (
                _dist_ring is None or _dist_ring.cap != int(ring)):
            _dist_ring = SpanRing(int(ring))


def reset_distributed() -> None:
    """Drop all distributed state (tests)."""
    global _dist_sample, _dist_ring, _dist_acc
    with _dist_lock:
        _dist_sample = 0.0
        _dist_acc = 0.0
        _dist_ring = None
        _dist_durs.clear()
        _skew.reset()


def host_label() -> str:
    return _dist_host


def ring() -> Optional["SpanRing"]:
    return _dist_ring


def sample_trace() -> Optional[TraceContext]:
    """The head's admission-time sampling decision: a new root context
    for every sampled request, None otherwise.  Deterministic fraction
    accumulator (the canary-lane idiom), not a coin flip — request k is
    sampled iff ``floor(k*p) > floor((k-1)*p)``, so a 25% sample is
    exactly 1-in-4 and byte-reproducible."""
    global _dist_acc
    if _dist_ring is None or _dist_sample <= 0.0:
        return None
    with _dist_lock:
        _dist_acc += _dist_sample
        take = _dist_acc >= 1.0
        if take:
            _dist_acc -= 1.0
    if not take:
        return None
    return TraceContext(new_trace_id(), parent=0, hop=0, sampled=True)


def correlation_id(sample_ts: float) -> str:
    """The decision-log correlation id: derived from the TRIGGERING
    health sample's timestamp (``w`` + epoch-ms hex), so every action a
    window caused carries the same id and ``tools/trace.py --decision``
    can join scheduler actions, rollout phases and the sample window
    they reacted to.  Purely a function of the sample clock — under the
    simulator's virtual clock the id is deterministic, preserving
    byte-reproducible decision logs."""
    return f"w{int(round(float(sample_ts) * 1000)):x}"


def admin_trace() -> Optional[TraceContext]:
    """An ALWAYS-sampled root context for control-plane verbs (resize,
    rollout) — rare enough that probabilistic sampling would lose most
    of them, important enough that every one should be reconstructible.
    None (no header, byte-identical admin RPC) unless the distributed
    plane is armed with a non-zero sample rate."""
    if _dist_ring is None or _dist_sample <= 0.0:
        return None
    return TraceContext(new_trace_id(), parent=0, hop=0, sampled=True)


def retain_trace(state: str, total_ms: float = None,
                 attempts: int = 1) -> bool:
    """Tail retention: forced for every non-SERVED terminal and every
    rerouted request; SERVED requests are kept when they land in the
    slowest ``obs.trace_slow_pct`` percentile of the recent window
    (warmup keeps everything until the window has 32 samples)."""
    if state != "SERVED" or attempts > 1:
        return True
    if total_ms is None:
        return True
    with _dist_lock:
        _dist_durs.append(float(total_ms))
        n = len(_dist_durs)
        if n < 32:
            return True
        cut = sorted(_dist_durs)[min(n - 1,
                                     int(n * _dist_slow_pct / 100.0))]
    return total_ms >= cut


class SpanRing:
    """Bounded per-trace span store: spans accumulate under their trace
    id while the request is in flight, then :meth:`close` either KEEPS
    the finished tree (bounded deque — oldest kept tree falls off) or
    drops it.  Overflowing open traces evict oldest-first, counted."""

    def __init__(self, cap: int = 256, cap_spans: int = 128):
        self.cap = int(cap)
        self.cap_spans = int(cap_spans)
        self._lock = threading.Lock()
        self._open: "OrderedDict[str, List[dict]]" = OrderedDict()
        self._kept: deque = deque(maxlen=self.cap)
        self.dropped = 0

    def record(self, trace_id: str, span: dict) -> None:
        with self._lock:
            spans = self._open.get(trace_id)
            if spans is None:
                if len(self._open) >= 2 * self.cap:
                    self._open.popitem(last=False)
                    self.dropped += 1
                spans = self._open[trace_id] = []
            if len(spans) < self.cap_spans:
                spans.append(span)
            else:
                self.dropped += 1

    def close(self, trace_id: str, keep: bool, **meta) -> None:
        with self._lock:
            spans = self._open.pop(trace_id, None)
            if spans is None or not keep:
                return
            self._kept.append({"trace": trace_id, "host": _dist_host,
                               "spans": spans, **meta})

    def trees(self, limit: int = None) -> List[dict]:
        with self._lock:
            out = list(self._kept)
        return out[-limit:] if limit else out

    def open_count(self) -> int:
        with self._lock:
            return len(self._open)


def record_span(ctx: TraceContext, name: str, dur_ms: float,
                span_id: int = None, parent: int = None,
                t1_us: float = None, **args) -> int:
    """Record one completed span under ``ctx`` into the ring.  The span
    ENDED at ``t1_us`` (epoch µs; default now) and lasted ``dur_ms``.
    Returns the span id so callers can parent later spans under it.
    Callers gate on ``ctx is not None`` — that None-check is the whole
    untraced hot-path cost."""
    r = _dist_ring
    sid = span_id if span_id is not None else new_span_id()
    if r is None or not ctx.sampled:
        return sid
    end = _now_us() if t1_us is None else t1_us
    span = {"name": name, "span": sid,
            "parent": ctx.parent if parent is None else parent,
            "ts": end - float(dur_ms) * 1e3, "dur": float(dur_ms) * 1e3,
            "host": _dist_host, "hop": ctx.hop}
    if args:
        span["args"] = args
    r.record(ctx.trace_id, span)
    return sid


def close_trace(ctx: TraceContext, keep: bool = True, **meta) -> None:
    r = _dist_ring
    if r is not None and ctx.sampled:
        r.close(ctx.trace_id, keep, **meta)


def kept_trees(limit: int = None) -> List[dict]:
    """The retained span trees (flight-recorder + /trace surface)."""
    r = _dist_ring
    return r.trees(limit) if r is not None else []


# -- clock-skew estimation --------------------------------------------------

class SkewEstimator:
    """NTP-style per-source clock-offset estimation from request/
    response timestamp pairs: for each exchange the head records its
    send (t0) and receive (t3) epoch-µs stamps and the agent returns
    its receive (t1) and send (t2); offset = ((t1-t0)+(t2-t3))/2, rtt =
    (t3-t0)-(t2-t1).  The estimate is the median offset of the
    lowest-rtt half of a bounded sample window — queueing delay inflates
    rtt symmetrically, so low-rtt exchanges bound the skew tightest."""

    def __init__(self, window: int = 64):
        self._lock = threading.Lock()
        self._window = int(window)
        self._samples: Dict[str, deque] = {}

    def note(self, source: str, t0_us: float, t1_us: float,
             t2_us: float, t3_us: float) -> None:
        off = ((t1_us - t0_us) + (t2_us - t3_us)) / 2.0 / 1e3
        rtt = max(((t3_us - t0_us) - (t2_us - t1_us)) / 1e3, 0.0)
        with self._lock:
            dq = self._samples.setdefault(
                source, deque(maxlen=self._window))
            dq.append((off, rtt))

    def offset_ms(self, source: str) -> Optional[float]:
        """Estimated ``source_clock - head_clock`` in ms (None until a
        sample lands)."""
        with self._lock:
            dq = self._samples.get(source)
            if not dq:
                return None
            samples = list(dq)
        best = sorted(samples, key=lambda s: s[1])
        best = best[:max(1, len(best) // 2)]
        offs = sorted(s[0] for s in best)
        return offs[len(offs) // 2]

    def sources(self) -> List[str]:
        with self._lock:
            return sorted(self._samples)

    def gauges(self) -> Dict[str, float]:
        """``obs.skew_ms.<source>`` per-agent offsets plus
        ``obs.skew_ms.max`` (the worst |offset|) — the drift-alarm
        rule's input (obs/health.py skew_rules)."""
        out: Dict[str, float] = {}
        worst = 0.0
        for src in self.sources():
            off = self.offset_ms(src)
            if off is None:
                continue
            out[f"obs.skew_ms.{src}"] = round(off, 3)
            worst = max(worst, abs(off))
        if out:
            out["obs.skew_ms.max"] = round(worst, 3)
        return out

    def reset(self) -> None:
        with self._lock:
            self._samples.clear()


_skew = SkewEstimator()


def skew() -> SkewEstimator:
    return _skew


def skew_gauges() -> Dict[str, float]:
    return _skew.gauges()


# -- skew-corrected fleet merge --------------------------------------------

def correct_tree_spans(spans: List[dict], offset_ms: float) -> int:
    """Shift one host's spans onto the head clock (subtract its
    estimated offset), IN PLACE.  Returns the span count."""
    if offset_ms:
        for s in spans:
            s["ts"] = s["ts"] - offset_ms * 1e3
    return len(spans)


def _clamp_children(spans: List[dict]) -> int:
    """Post-correction monotonicity: no child span may start before its
    parent.  Residual estimation error (sub-ms) can still invert an
    edge; the clamp pins child start to parent start and COUNTS it, so
    the doctor reports correction quality honestly."""
    by_id = {s["span"]: s for s in spans}
    clamped = 0
    for _ in range(8):               # tree depth bound; converges fast
        changed = False
        for s in spans:
            p = by_id.get(s.get("parent"))
            if p is not None and s["ts"] < p["ts"]:
                s["ts"] = p["ts"]
                clamped += 1
                changed = True
        if not changed:
            break
    return clamped


def merge_fleet_trace(local_trees: List[dict],
                      remote_by_source: Dict[str, List[dict]],
                      offsets_ms: Dict[str, float],
                      path: str = None) -> Dict:
    """Merge head + remote span trees into one skew-corrected view.

    ``local_trees`` are the head's kept trees; ``remote_by_source``
    maps agent source name → its ``/trace`` trees; ``offsets_ms`` the
    per-source skew estimates (missing sources merge uncorrected).
    Returns ``{"traces": {trace_id: [spans]}, "traceEvents": [...],
    "metadata": {...}}`` — the traceEvents list loads in Perfetto, with
    one pid per host.  When ``path`` is given the chrome-trace JSON is
    also written there."""
    traces: Dict[str, List[dict]] = {}

    def _fold(trees: List[dict], offset: float) -> None:
        for t in trees:
            spans = [dict(s) for s in t.get("spans", [])]
            correct_tree_spans(spans, offset)
            traces.setdefault(t["trace"], []).extend(spans)

    _fold(local_trees, 0.0)
    for src, trees in remote_by_source.items():
        _fold(trees, float(offsets_ms.get(src) or 0.0))
    clamped = 0
    for spans in traces.values():
        spans.sort(key=lambda s: s["ts"])
        clamped += _clamp_children(spans)
    events_out: List[dict] = []
    for tid, spans in traces.items():
        for s in spans:
            events_out.append({
                "name": s["name"], "ph": "X", "ts": s["ts"],
                "dur": s["dur"], "pid": s.get("host", "?"),
                "tid": f"hop-{s.get('hop', 0)}",
                "args": {"trace_id": tid, "span": f"{s['span']:x}",
                         "parent": f"{s.get('parent', 0):x}",
                         **s.get("args", {})}})
    doc = {"traces": traces, "traceEvents": events_out,
           "metadata": {"clamped": clamped,
                        "offsets_ms": {k: round(float(v), 3)
                                       for k, v in offsets_ms.items()},
                        "n_traces": len(traces)}}
    if path:
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "w") as f:
            json.dump({"traceEvents": events_out, "displayTimeUnit": "ms",
                       "metadata": doc["metadata"]}, f)
    return doc


def tree_complete(spans: List[dict]) -> bool:
    """A span tree is COMPLETE when every non-root parent pointer
    resolves to a span in the tree (root spans carry parent 0)."""
    ids = {s["span"] for s in spans}
    return all(s.get("parent", 0) == 0 or s["parent"] in ids
               for s in spans)


def tree_monotonic(spans: List[dict]) -> bool:
    """No child starts before its parent (the skew-correction check)."""
    by_id = {s["span"]: s for s in spans}
    for s in spans:
        p = by_id.get(s.get("parent"))
        if p is not None and s["ts"] < p["ts"] - 1e-3:
            return False
    return True
