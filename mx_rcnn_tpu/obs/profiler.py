"""On-demand ``jax.profiler`` windows with automatic xplane rollup.

No reference equivalent (the reference has no profiler wiring at all —
SURVEY.md §5.1).  Two triggers, one mechanism:

* **config** — ``obs.profile_at_step=N`` makes the fit loop capture a
  ``obs.profile_steps``-step window starting at global step N
  (:class:`StepProfiler`, wired in ``core/fit.py``);
* **signal** — :func:`install_sigusr2` arms a live process: the first
  ``SIGUSR2`` starts a window, the second stops it — profile a
  production run mid-flight without restarting it
  (``kill -USR2 <pid>`` twice; runbook in docs/OBSERVABILITY.md).

Either way the captured trace is auto-rolled-up by
``utils/xplane.py — summarize_device_time`` into per-scope and
per-op-class device-time tables written next to the trace
(``rollup.json``) — the answer a human wants, without opening
TensorBoard.

Only ONE window can be open at a time (module-level guard shared with
nothing else; ``core/fit.py``'s legacy ``profile_dir`` early-step trace
uses ``jax.profiler`` directly, so don't combine both in one run —
``start_window`` fails soft with a log line if the profiler is busy).
"""

from __future__ import annotations

import glob
import json
import logging
import os
import signal
import threading
from typing import Callable, Dict, Optional

logger = logging.getLogger("mx_rcnn_tpu")

_lock = threading.Lock()
_active_dir: Optional[str] = None


def newest_xplane(trace_dir: str) -> Optional[str]:
    """The newest ``*.xplane.pb`` under a ``jax.profiler`` output dir."""
    pbs = glob.glob(os.path.join(trace_dir, "plugins", "profile",
                                 "*", "*.xplane.pb"))
    return max(pbs, key=os.path.getmtime) if pbs else None


# inline-rollup trace-size cap: the pure-Python protobuf walk is
# ~seconds per 10 MB; a long-open window over a fast loop can write
# hundreds of MB (observed live: a ~60 s window -> 432 MB xplane whose
# parse starved a 1-core box for minutes).  Bigger traces skip the
# inline parse with a pointer to the offline path.
MAX_ROLLUP_BYTES = 64 << 20


def rollup(trace_dir: str, depth: int = 1) -> Dict:
    """Roll the newest xplane under ``trace_dir`` up into device-time
    tables: ``{"by_scope": {plane: {scope: ms}}, "by_op_class": ...}``.
    Empty dict when no trace was captured; for traces over
    :data:`MAX_ROLLUP_BYTES` the parse is SKIPPED (a ``"skipped"`` note
    replaces the tables) — summarize offline with
    ``tools/profile_step.summarize_trace``."""
    pb = newest_xplane(trace_dir)
    if pb is None:
        return {}
    size = os.path.getsize(pb)
    if size > MAX_ROLLUP_BYTES:
        note = (f"trace is {size >> 20} MB (> {MAX_ROLLUP_BYTES >> 20} MB "
                "inline cap) — keep profile windows short; summarize "
                "offline: python -c \"from mx_rcnn_tpu.tools.profile_step "
                f"import summarize_trace; summarize_trace('{trace_dir}')\"")
        logger.warning("obs profiler: %s", note)
        return {"xplane": pb, "skipped": note,
                "by_scope": {}, "by_op_class": {}}
    from mx_rcnn_tpu.utils.xplane import (category_of, parse_xspace,
                                          summarize_device_time)

    planes = parse_xspace(pb)  # parse once, summarize twice
    return {
        "xplane": pb,
        "by_scope": summarize_device_time(planes, depth=depth),
        "by_op_class": summarize_device_time(planes, key=category_of),
    }


def start_window(out_dir: str) -> bool:
    """Open a profiler window into ``out_dir``.  Fails SOFT (False + log)
    when a window is already open or the profiler is busy — a profiling
    hiccup must never kill a training/serving process."""
    global _active_dir
    with _lock:
        if _active_dir is not None:
            logger.warning("obs profiler: window already open (%s)",
                           _active_dir)
            return False
        try:
            import jax.profiler

            os.makedirs(out_dir, exist_ok=True)
            jax.profiler.start_trace(out_dir)
        except Exception as e:  # profiler busy / backend quirk
            logger.warning("obs profiler: could not start window: %s", e)
            return False
        _active_dir = out_dir
    logger.info("obs profiler: window started -> %s", out_dir)
    return True


def stop_window(sync: Callable[[], None] = None) -> Dict:
    """Close the open window, write ``rollup.json`` next to the trace and
    return the rollup dict.  ``sync`` (e.g. ``jax.block_until_ready`` on
    the last step's outputs) runs first so in-flight device work lands
    inside the window."""
    global _active_dir
    with _lock:
        if _active_dir is None:
            return {}
        out_dir, _active_dir = _active_dir, None
        try:
            import jax.profiler

            if sync is not None:
                sync()
            jax.profiler.stop_trace()
        except Exception as e:
            logger.warning("obs profiler: could not stop window: %s", e)
            return {}
    roll = rollup(out_dir)
    path = os.path.join(out_dir, "rollup.json")
    try:
        with open(path, "w") as f:
            json.dump(roll, f, indent=1)
    except OSError as e:
        logger.warning("obs profiler: rollup write failed: %s", e)
    _log_rollup(roll)
    logger.info("obs profiler: window closed -> %s (rollup.json)", out_dir)
    return roll


def _log_rollup(roll: Dict, top: int = 8) -> None:
    for plane, groups in roll.get("by_op_class", {}).items():
        total = sum(groups.values())
        if not total:
            continue
        logger.info("obs profiler: %s device time by op class "
                    "(total %.2f ms):", plane, total)
        for g, ms in list(groups.items())[:top]:
            logger.info("  %-36s %9.3f ms  %5.1f%%", g, ms,
                        100 * ms / total)


class StepProfiler:
    """Config-triggered window for the fit loop: starts at global step
    ``at_step``, captures ``steps`` steps, rolls up, stays inert
    otherwise.  ``on_step`` is called once per executed step with the
    global step index and a ``sync`` thunk."""

    def __init__(self, out_dir: str, at_step: int, steps: int = 3):
        self.out_dir = out_dir
        self.at_step = at_step
        self.steps = max(int(steps), 1)
        self._started = False
        self._done = False
        self._stop_at = None
        self.result: Dict = {}

    def on_step(self, step: int, sync: Callable[[], None] = None) -> None:
        if self._done or self.at_step <= 0:
            return
        if not self._started and step >= self.at_step:
            self._started = start_window(self.out_dir)
            self._done = not self._started
            self._stop_at = step + self.steps
        elif self._started and step >= self._stop_at:
            self.result = stop_window(sync)
            self._done = True

    def close(self, sync: Callable[[], None] = None) -> None:
        """Epoch/loop ended with the window still open (e.g. the run was
        shorter than ``at_step + steps``): close it now."""
        if self._started and not self._done:
            self.result = stop_window(sync)
            self._done = True


def install_sigusr2(out_dir: str) -> Callable:
    """Arm SIGUSR2 as a profiler toggle: first signal starts a window
    under ``out_dir/sigusr2-<n>``, the next stops it and writes the
    rollup.  Returns the installed handler (the tests drive it via
    ``signal.raise_signal``).

    The handler itself only flips state and spawns a daemon worker
    thread — it must NOT call ``jax.profiler`` inline: a signal handler
    runs on the main thread at an arbitrary bytecode boundary, possibly
    with the interrupted frame holding jax runtime locks, and
    ``stop_trace`` from inside it deadlocks (observed live: a training
    process wedged in 'Sl' until SIGKILL).  The worker serializes
    toggles through a queue-less chain so start/stop cannot race each
    other, and everything fails soft — a profiling problem must never
    take down the process it is observing."""
    state = {"open": False, "n": 0, "worker": None}

    def toggle():
        try:
            if not state["open"]:
                d = os.path.join(out_dir, f"sigusr2-{state['n']}")
                state["open"] = start_window(d)
            else:
                stop_window()
                state["open"] = False
                state["n"] += 1
        except Exception:  # pragma: no cover - belt and braces
            logger.exception("obs profiler: SIGUSR2 toggle failed")

    def handler(signum, frame):
        prev = state["worker"]
        if prev is not None and prev.is_alive():
            # a toggle is still in flight (e.g. a large trace being
            # rolled up) — drop this signal instead of racing it
            logger.warning("obs profiler: SIGUSR2 ignored, previous "
                           "toggle still running")
            return
        t = threading.Thread(target=toggle, name="obs-sigusr2-toggle",
                             daemon=True)
        state["worker"] = t
        t.start()

    signal.signal(signal.SIGUSR2, handler)
    logger.info("obs profiler: SIGUSR2 armed (kill -USR2 %d to toggle a "
                "profile window under %s)", os.getpid(), out_dir)
    return handler
