"""Unified observability (docs/OBSERVABILITY.md) — one answer to "where
did this step/request spend its time?" across training, data loading,
checkpointing and serving (ISSUE 4).

Before this package the repo had three disjoint fragments: the serving
histograms (``serve/metrics.py``), the XSpace decoder reachable only via
``tools/profile_step.py`` (``utils/xplane.py``), and the ``Speedometer``
stdout line in ``core/fit.py`` — none of which could see each other or
the ``ft/`` snapshot path.  Layers, bottom-up:

* ``metrics.py``  — process-wide :class:`Registry` (counters, gauges,
  log-bucket histograms) + the promoted ``Histogram`` /
  ``LoweringCounter`` / ``ServeMetrics`` (``serve.metrics`` is now a
  back-compat shim over this module) + the stdlib ``/metrics`` HTTP
  exporter;
* ``trace.py``    — cheap host-side spans (``with span("h2d")``) with a
  trace-context id propagated through the serve request lifecycle and
  the train loop, exported as chrome-trace JSON that merges with the
  XLA device timeline via ``utils/xplane.py`` timestamps;
* ``profiler.py`` — on-demand ``jax.profiler`` windows (config
  ``obs.profile_at_step`` or SIGUSR2 on a live process), auto-rolled-up
  by ``utils/xplane.py — summarize_device_time``;
* ``runrec.py``   — structured run records: every ``tools/train.py`` /
  ``tools/serve.py`` run writes ``runs/<id>/events.jsonl`` plus a final
  BENCH-compatible ``summary.json``.

Everything is DISABLED by default (``cfg.obs.enabled``); the disabled
hot-path cost is pinned near zero by ``tests/test_obs.py``.
"""

from mx_rcnn_tpu.obs.metrics import (Histogram, LoweringCounter,  # noqa: F401
                                     Registry, ServeMetrics, registry,
                                     start_metrics_server)
from mx_rcnn_tpu.obs.runrec import RunRecord  # noqa: F401
