"""Unified observability (docs/OBSERVABILITY.md) — one answer to "where
did this step/request spend its time?" across training, data loading,
checkpointing and serving (ISSUE 4).

Before this package the repo had three disjoint fragments: the serving
histograms (``serve/metrics.py``), the XSpace decoder reachable only via
``tools/profile_step.py`` (``utils/xplane.py``), and the ``Speedometer``
stdout line in ``core/fit.py`` — none of which could see each other or
the ``ft/`` snapshot path.  Layers, bottom-up:

* ``metrics.py``  — process-wide :class:`Registry` (counters, gauges,
  log-bucket histograms) + the promoted ``Histogram`` /
  ``LoweringCounter`` / ``ServeMetrics`` (``serve.metrics`` is now a
  back-compat shim over this module) + the stdlib ``/metrics`` HTTP
  exporter;
* ``trace.py``    — cheap host-side spans (``with span("h2d")``) with a
  trace-context id propagated through the serve request lifecycle and
  the train loop, exported as chrome-trace JSON that merges with the
  XLA device timeline via ``utils/xplane.py`` timestamps;
* ``profiler.py`` — on-demand ``jax.profiler`` windows (config
  ``obs.profile_at_step`` or SIGUSR2 on a live process), auto-rolled-up
  by ``utils/xplane.py — summarize_device_time``;
* ``runrec.py``   — structured run records: every ``tools/train.py`` /
  ``tools/serve.py`` run writes ``runs/<id>/events.jsonl`` plus a final
  BENCH-compatible ``summary.json``.

The fleet-wide time-series plane (ISSUE 14) stacks on top:

* ``timeseries.py`` — bounded ring-buffer store sampling the registry
  (counters→windowed rates, gauges, exact windowed histogram
  percentiles) on a daemon-thread cadence;
* ``collect.py``    — cross-process aggregation: N replica/worker
  registries (in-process or scraped over ``/metrics``) merged into one
  source-labeled, generation-tagged fleet view;
* ``health.py``     — declarative SLO rules over the time-series
  windows → OK/WARN/CRITICAL verdict as gauges + runrec events +
  enriched ``/healthz`` + exit codes (``tools/obs.py check``);
* ``flightrec.py``  — black-box flight recorder: last-N-seconds of
  samples + spans + events dumped to ``runs/<id>/flight/`` on crash,
  SIGTERM, lock-watchdog trip, or a health-critical transition.

Everything is DISABLED by default (``cfg.obs.enabled``); the disabled
hot-path cost is pinned near zero by ``tests/test_obs.py`` and the
sampling-enabled overhead by ``tools/obs_smoke.py --overhead_out``
(<2% acceptance bar, docs/obs_overhead.json).
"""

from mx_rcnn_tpu.obs.metrics import (Histogram, LoweringCounter,  # noqa: F401
                                     Registry, ServeMetrics, registry,
                                     start_metrics_server)
from mx_rcnn_tpu.obs.runrec import RunRecord  # noqa: F401
from mx_rcnn_tpu.obs.timeseries import (Sampler,  # noqa: F401
                                        TimeSeriesStore)
