"""Declarative SLO rule engine over the time-series windows.

No reference equivalent.  Everything before this judged health ad hoc:
the fleet router's ``/healthz`` lists replica states but draws no
conclusion, loadgen asserts SLOs only inside its own bench legs, and
the gauges the elastic/fleet/bulk planes export (``docs/OBSERVABILITY.md``
catalog) had no consumer.  This module is the judgment layer ROADMAP
item 2's scheduler and item 3's canary gate will read: declarative
rules over ``obs/timeseries.py`` windows, a machine-readable verdict,
and exit-code semantics (``tools/obs.py check``).

A :class:`Rule` names a query (``kind`` picks the store readout), a
comparison and a severity::

    Rule("serve-p99", metric="serve.total_ms", kind="p99",
         op=">", threshold=1800.0, window_s=30, severity="critical")

Kinds: ``gauge`` (latest value), ``gauge_min``/``gauge_max`` (window
scan), ``rate``/``delta`` (counter windows), ``p99``/``p50`` (windowed
histogram percentiles), ``ratio`` (``metric="a/b"`` — windowed counter
delta ratio, e.g. shed fraction).  A metric the window never saw is NOT
a breach (``missing_ok``): rules describe subsystems that may simply be
off (no elastic world → no ``elastic.*`` gauges), and judging absence
as failure would make every partial deployment CRITICAL.

**Hysteresis** is per-rule: ``for_samples`` consecutive breaching
evaluations fire the rule (a single bad sample — one slow scrape, one
GC pause — never flaps the verdict), ``clear_samples`` consecutive
clean ones clear it.  The asymmetry is deliberate: fire slow, clear
slow, report instantly.

Each evaluation publishes the verdict as gauges (``health.verdict`` 0/1/2,
``health.rule.<name>`` 0/1 — scheduler-scrapeable like every other
gauge), appends a ``health_transition`` runrec event on every verdict
change, and invokes the transition callback — ``CliObs`` wires that to
the flight recorder, so a CRITICAL transition dumps the black box
(``obs/flightrec.py``).  The ACTIVE engine (:func:`set_active_engine`)
enriches ``/healthz`` on both HTTP front ends.

Verdict → exit code: OK=0, WARN=1, CRITICAL=2 (:data:`EXIT_BY_VERDICT`).
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from mx_rcnn_tpu.obs.timeseries import TimeSeriesStore

logger = logging.getLogger("mx_rcnn_tpu")

OK, WARN, CRITICAL = "OK", "WARN", "CRITICAL"
_LEVEL = {OK: 0, WARN: 1, CRITICAL: 2}
EXIT_BY_VERDICT = dict(_LEVEL)

_OPS: Dict[str, Callable[[float, float], bool]] = {
    ">": lambda v, t: v > t,
    ">=": lambda v, t: v >= t,
    "<": lambda v, t: v < t,
    "<=": lambda v, t: v <= t,
}


@dataclass(frozen=True)
class Rule:
    """One SLO: fire ``severity`` when ``<kind>(metric, window_s) <op>
    threshold`` holds for ``for_samples`` consecutive evaluations."""

    name: str
    metric: str           # for kind="ratio": "numerator/denominator"
    kind: str             # gauge|gauge_min|gauge_max|rate|delta|p99|p50|ratio
    op: str               # > >= < <=
    threshold: float
    window_s: float = 30.0
    severity: str = WARN  # WARN or CRITICAL when firing
    for_samples: int = 2  # consecutive breaches to fire (hysteresis)
    clear_samples: int = 2  # consecutive cleans to clear
    missing_ok: bool = True  # absent metric = no judgment, not a breach

    def value(self, store: TimeSeriesStore) -> Optional[float]:
        k = self.kind
        if k == "gauge":
            # Bounded by the rule's window: a gauge whose source died
            # longer than window_s ago reads None (absent), the same
            # judgment as a source that never reported at all.  The
            # unbounded scan used to return the dead source's stale
            # last value forever — a down host read as healthy.
            return store.gauge(self.metric, self.window_s)
        if k == "gauge_min":
            return store.gauge_min(self.metric, self.window_s)
        if k == "gauge_max":
            return store.gauge_max(self.metric, self.window_s)
        if k == "rate":
            return store.rate(self.metric, self.window_s)
        if k == "delta":
            return store.delta(self.metric, self.window_s)
        if k in ("p99", "p50"):
            return store.pctl(self.metric, float(k[1:]), self.window_s)
        if k == "ratio":
            num, den = self.metric.split("/", 1)
            dn = store.delta(num.strip(), self.window_s)
            dd = store.delta(den.strip(), self.window_s)
            if dn is None or dd is None or dd <= 0:
                return None
            return dn / dd
        raise ValueError(f"unknown rule kind {k!r}")


class _RuleState:
    __slots__ = ("breaches", "cleans", "firing")

    def __init__(self):
        self.breaches = 0
        self.cleans = 0
        self.firing = False


class HealthEngine:
    """Evaluate the rule set against a store; publish + notify.

    Driven by the sampler thread (``Sampler(after_sample=engine.
    evaluate_sample)``) or explicitly (:meth:`evaluate` — tests and
    ``tools/obs.py check``).  All rule state lives under one lock; the
    transition callback and runrec write run OUTSIDE it (they do I/O).
    """

    def __init__(self, rules: List[Rule], store: TimeSeriesStore,
                 registry=None, record=None,
                 on_transition: Optional[Callable[[str, str, Dict],
                                                  None]] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.rules = list(rules)
        self.store = store
        self._reg = registry
        self._record = record
        self._on_transition = on_transition
        # engine clock: monotonic by default, virtual under the
        # simulator; stamps every verdict (SLO-minute attribution)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = {r.name: _RuleState() for r in self.rules}
        self._verdict = OK
        self._last: Optional[Dict] = None

    # ------------------------------------------------------------------

    def evaluate(self) -> Dict:
        """One pass over every rule; returns (and retains) the verdict
        document.  Never raises: a rule whose query blows up reads as
        value None (missing), logged once per pass."""
        rule_out: List[Dict] = []
        fired: List[str] = []
        worst = OK
        with self._lock:
            for r in self.rules:
                try:
                    v = r.value(self.store)
                except Exception:
                    logger.exception("obs health: rule %s query failed",
                                     r.name)
                    v = None
                st = self._state[r.name]
                if v is None:
                    # absent metric: hold state, judge nothing
                    breach = None
                else:
                    breach = _OPS[r.op](float(v), r.threshold)
                    if breach:
                        st.breaches += 1
                        st.cleans = 0
                        if st.breaches >= r.for_samples:
                            st.firing = True
                    else:
                        st.cleans += 1
                        st.breaches = 0
                        if st.cleans >= r.clear_samples:
                            st.firing = False
                if st.firing:
                    fired.append(r.name)
                    if _LEVEL[r.severity] > _LEVEL[worst]:
                        worst = r.severity
                rule_out.append({
                    "name": r.name, "metric": r.metric, "kind": r.kind,
                    "op": r.op, "threshold": r.threshold,
                    "window_s": r.window_s, "severity": r.severity,
                    "value": None if v is None else round(float(v), 4),
                    "breaching": breach, "firing": st.firing,
                })
            prev = self._verdict
            self._verdict = worst
            verdict = {"verdict": worst, "code": _LEVEL[worst],
                       "previous": prev, "changed": worst != prev,
                       "ts": round(float(self._clock()), 6),
                       "firing": fired, "rules": rule_out}
            self._last = verdict
        self._publish(verdict)
        if verdict["changed"]:
            self._notify(prev, worst, verdict)
        return verdict

    def evaluate_sample(self, _sample: Dict) -> None:
        """``Sampler(after_sample=...)`` adapter."""
        self.evaluate()

    # ------------------------------------------------------------------

    def _publish(self, verdict: Dict) -> None:
        if self._reg is None:
            return
        try:
            self._reg.set_gauge("health.verdict", verdict["code"])
            for r in verdict["rules"]:
                self._reg.set_gauge(f"health.rule.{r['name']}",
                                    1.0 if r["firing"] else 0.0)
        except Exception:
            logger.exception("obs health: gauge publish failed")

    def _notify(self, prev: str, new: str, verdict: Dict) -> None:
        logger.log(logging.WARNING if _LEVEL[new] else logging.INFO,
                   "health verdict %s -> %s (firing: %s)", prev, new,
                   ",".join(verdict["firing"]) or "-")
        if self._record is not None:
            try:
                self._record.event("health_transition", prev=prev,
                                   verdict=new, firing=verdict["firing"])
            except Exception:
                logger.exception("obs health: transition event failed")
        if self._on_transition is not None:
            try:
                self._on_transition(prev, new, verdict)
            except Exception:
                logger.exception("obs health: transition callback "
                                 "failed")

    # ------------------------------------------------------------------

    @property
    def verdict(self) -> str:
        with self._lock:
            return self._verdict

    def last(self) -> Optional[Dict]:
        """The most recent verdict document (None before the first
        evaluation) — the ``/healthz`` enrichment body."""
        with self._lock:
            return self._last

    def exit_code(self) -> int:
        return _LEVEL[self.verdict]


def default_rules(cfg) -> List[Rule]:
    """The stock SLO set over the gauges the planes already export
    (thresholds from the config the run actually used).  Every rule is
    ``missing_ok`` — a train-only run simply never resolves the fleet
    rules and vice versa."""
    deadline = cfg.serve.default_timeout_ms or 2000.0
    w = cfg.obs.health_window_s
    return [
        # serve p99 vs the request-deadline budget: p99 at 90% of the
        # deadline means the tail is about to start expiring
        Rule("serve-p99-budget", "serve.total_ms", "p99", ">",
             0.9 * deadline, window_s=w, severity=CRITICAL),
        # shed fraction over the window (terminated-by-shedding / all
        # submitted); sustained shedding = capacity, not noise
        Rule("serve-shed-frac", "serve.shed/serve.submitted", "ratio",
             ">", 0.05, window_s=w, severity=WARN),
        # fleet capacity: ready below the CONFIGURED size is CRITICAL —
        # the router masks a dead replica by rerouting, so judged
        # health must not (a scheduler that waits for total blackout
        # acts one failure too late).  Reads the latest gauge with
        # 1-sample hysteresis: replicas_ready is a state readout, not a
        # noisy measurement, and the relaunch clears it the same way
        # (the CRITICAL→OK transition make health-smoke pins)
        Rule("fleet-degraded", "fleet.replicas_ready", "gauge", "<",
             float(cfg.fleet.replicas), window_s=15.0,
             severity=CRITICAL, for_samples=1, clear_samples=1),
        # elastic recovery: resumes should land within the snapshot
        # cadence budget
        Rule("elastic-recovery", "elastic.recovery_ms", "p99", ">",
             60_000.0, window_s=300.0, severity=WARN),
        # input-bound training: the step loop waiting on data more than
        # half the time means the loader, not the model, sets the speed
        Rule("train-data-wait", "train.data_wait_frac", "gauge", ">",
             0.5, window_s=60.0, severity=WARN),
        # snapshot stalls: the async snapshotter should never hold the
        # step loop for a macroscopic pause
        Rule("snapshot-stall", "snapshot.stall_ms", "p99", ">",
             1000.0, window_s=120.0, severity=WARN),
        # clock-skew drift: the head's NTP-style per-agent offset
        # estimate (obs/trace.py — SkewEstimator, exported through the
        # crosshost feed as obs.skew_ms.*).  Past the alarm bound the
        # merged fleet timelines stop being trustworthy — the doctor
        # still clamps children into parents, but attribution across
        # hosts degrades from measurement to estimate
        Rule("trace-skew-drift", "obs.skew_ms.max", "gauge_max", ">",
             float(cfg.obs.skew_alarm_ms), window_s=w, severity=WARN),
    ]


# ---------------------------------------------------------------------------
# active-engine registration (the /healthz enrichment hook)
# ---------------------------------------------------------------------------

_active_lock = threading.Lock()
_ACTIVE: Optional[HealthEngine] = None


def set_active_engine(engine: Optional[HealthEngine]) -> None:
    global _ACTIVE
    with _active_lock:
        _ACTIVE = engine


def active_engine() -> Optional[HealthEngine]:
    with _active_lock:
        return _ACTIVE


def active_verdict() -> Optional[Dict]:
    """The current verdict document, if a health engine is live in this
    process — what ``serve/server.py`` and the ``/metrics`` exporter
    attach to ``/healthz``."""
    eng = active_engine()
    return None if eng is None else eng.last()
