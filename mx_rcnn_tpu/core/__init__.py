"""Training/eval core — the TPU-native replacement for ``rcnn/core/``
(MutableModule, metrics, callbacks, Predictor) and the optimizer wiring of
``train_end2end.py``."""
