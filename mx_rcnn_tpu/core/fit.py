"""The training driver: epochs of the jitted step + logging + checkpoints.

Reference: ``rcnn/core/module.py — MutableModule.fit`` (the train loop:
forward_backward → update → metric update → batch_end_callback →
epoch_end_callback) plus ``rcnn/core/callback.py — Speedometer`` (imgs/sec
every ``frequent`` batches) and ``do_checkpoint`` (per-epoch save).

TPU-native: the loop body is ONE jitted XLA program (single device) or one
SPMD program over a mesh (``parallel/dp.py``); metrics come back as device
scalars and are only synced to host at log time so the async dispatch
pipeline stays full between logs.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Callable, Dict, List, Optional

import jax
import numpy as np

from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.core.train import Batch, TrainState, make_train_step
from mx_rcnn_tpu.models.faster_rcnn import FasterRCNN
from mx_rcnn_tpu.obs import trace as obs_trace

logger = logging.getLogger("mx_rcnn_tpu")


class Speedometer:
    """imgs/sec + running metric means every ``frequent`` batches
    (ref ``rcnn/core/callback.py — Speedometer``).

    Call once per batch; pass the averaged metrics on log batches (the fit
    loop aligns those with its metric window) and it prints samples/sec over
    the batches elapsed since the previous log line.

    ``registry`` (an ``obs/metrics.py`` Registry): each log window also
    publishes ``train.samples_per_sec`` and the windowed metric means
    (``train.metric.<name>`` gauges) into the shared registry — the
    stdout line itself stays byte-identical to the reference port
    (pinned by ``tests/test_obs.py``).
    """

    def __init__(self, batch_size: int, frequent: int = 20,
                 log: Callable[[str], None] = None, registry=None):
        self.batch_size = batch_size
        self.frequent = frequent
        self.log = log or logger.info
        self.registry = registry
        self._tic = time.perf_counter()
        self._since = 0

    def reset(self) -> None:
        """Call at epoch start so the first window excludes checkpoint-save
        and summary time from the previous epoch."""
        self._tic = time.perf_counter()
        self._since = 0

    def __call__(self, epoch: int, nbatch: int,
                 metrics: Dict[str, float]) -> None:
        self._since += 1
        if not metrics:
            return
        elapsed = time.perf_counter() - self._tic
        speed = self._since * self.batch_size / max(elapsed, 1e-9)
        if self.registry is not None:
            self.registry.set_gauge("train.samples_per_sec", speed)
            for k, v in metrics.items():
                self.registry.set_gauge(f"train.metric.{k}", float(v))
        parts = ", ".join(f"{k}={v:.4f}" for k, v in metrics.items())
        self.log(f"Epoch[{epoch}] Batch [{nbatch}] "
                 f"Speed: {speed:.2f} samples/sec, {parts}")
        self._tic = time.perf_counter()
        self._since = 0


# unique loop sentinel: the device-cache path legitimately yields None
# batches, so exhaustion cannot be signalled with None
_END = object()


def _accum_iter(batch_iter, grad_accum: int):
    """Group a loader iterator into accumulation batches: every yield
    stacks ``grad_accum`` consecutive loader batches into leaves shaped
    ``(grad_accum, N, ...)``; a partial trailing group is dropped (the
    epoch holds ``len(loader) // grad_accum`` optimizer steps)."""
    from mx_rcnn_tpu.parallel.dp import stack_microbatches

    while True:
        group = []
        for _ in range(grad_accum):
            b = next(batch_iter, _END)
            if b is _END:
                return
            group.append(b)
        yield stack_microbatches(group)


def _mean_metrics(window: List[Dict]) -> Dict[str, float]:
    """Host-side mean of a window of device metric dicts (one sync)."""
    if not window:
        return {}
    window = jax.device_get(window)
    keys = window[0].keys()
    return {k: float(np.mean([m[k] for m in window])) for k in keys}


def fit(
    model: FasterRCNN,
    cfg: Config,
    state: TrainState,
    tx,
    train_loader,
    num_epochs: int,
    key: jax.Array,
    begin_epoch: int = 0,
    prefix: Optional[str] = None,
    frequent: Optional[int] = None,
    mesh=None,
    mode: str = "e2e",
    epoch_end_callback: Optional[Callable[[int, TrainState], None]] = None,
    profile_dir: Optional[str] = None,
    stop_flag: Optional[Callable[[], bool]] = None,
    device_cache: bool = False,
    step_callback: Optional[Callable[[int], None]] = None,
    run_record=None,
    grad_accum: int = 1,
    multiproc: bool = False,
    data_cursor: Optional[Dict] = None,
) -> TrainState:
    """Run ``begin_epoch .. num_epochs`` epochs; checkpoint per epoch.

    ``mesh``: a ``jax.sharding.Mesh`` (1-D ``('data',)`` or hierarchical
    ``('dcn', 'ici')`` — see ``parallel.dp.device_mesh``) enables
    data-parallel SPMD (the kvstore='device' replacement); None =
    single-device jit.
    ``mode``: 'e2e' | 'rpn' | 'rcnn' (alternate-training stages).
    ``key`` is the base RNG; the step folds in ``state.step`` so resuming
    from a checkpoint replays the identical sample stream.
    ``profile_dir``: capture a ``jax.profiler`` trace of a few early steps
    (after compile warm-up) into this directory for tensorboard inspection;
    the coarse per-stage breakdown lives in ``tools/profile_step.py``.
    ``stop_flag``: polled after every step; when it returns True the loop
    saves a mid-epoch interrupt checkpoint (``<prefix>-interrupt.ckpt``)
    and returns — the preemption path (SIGTERM on preemptible TPUs).
    ``step_callback``: host-side hook called with the global step after
    every executed step (fault injection — ``ft/faults.py`` — and test
    instrumentation; adds no device sync).
    Checkpoints go through the ``ft/snapshot.py`` snapshotter: the
    training thread pays only the ``jax.device_get``; serialization +
    durable write + manifest commit + retention GC happen on a background
    writer thread (``cfg.ft.async_snapshots=false`` restores inline
    writes).  The interrupt save is flushed before the loop returns.
    ``run_record``: an ``obs/runrec.py`` RunRecord — the loop appends
    epoch/log/snapshot events to its ``events.jsonl`` (None = no record).
    With ``cfg.obs.enabled`` the loop also records step time, data-wait
    fraction, loss EMA and lowering counts into the process metrics
    registry, and ``cfg.obs.profile_at_step`` opens an on-demand
    profiler window (``obs/profiler.py``); all of it is absent from the
    hot path when disabled (the default — overhead pinned by
    ``tests/test_obs.py``).
    ``device_cache``: stage the loader's epoch in HBM once and gather each
    step's batch on device (``data/device_cache.py``) — for RAM/HBM-scale
    datasets on hosts or links too slow to stream per step.  Shuffling is
    IMAGE-granular (r5): each epoch re-groups images into new batches via
    an on-device permutation, matching the streaming loader's in-bucket
    semantics (deterministic from ``key`` and the epoch number, so resume
    stays step-exact; ``shuffle=False`` loaders run bit-identical to
    streaming).  Composes with a mesh: the epoch shards over the data
    axes and each device regroups within its own shard (disclosed
    residual: images don't migrate across devices between epochs —
    ``parallel.dp.make_dp_cached_step``).  Limit: requires a
    single-bucket dataset.
    Mid-epoch RESUME is driven by ``state.step`` alone: if the incoming
    state is ``skip`` steps past ``begin_epoch``'s start, the first epoch
    skips its first ``skip`` batches; the deterministic per-epoch shuffle
    (``set_epoch``) plus the step-folded RNG make the continued run
    bit-identical to an uninterrupted one.
    ``grad_accum``: microbatches accumulated per optimizer step (the
    elastic shrink lever — ft/elastic.py): each step consumes
    ``grad_accum`` consecutive loader batches, ``steps_per_epoch`` and
    ``state.step`` count OPTIMIZER steps, so the LR schedule, the
    step↔epoch mapping and the resume math are accumulation-invariant.
    Not composable with ``device_cache`` (the HBM epoch cache gathers one
    batch per step by construction).
    ``multiproc``: the mesh spans multiple ``jax.distributed`` processes
    (``parallel/multihost.py``): state replication and batch assembly go
    through ``multihost_utils`` (every process feeds only its local image
    slice of the deterministic global batch — decoded by the loader's own
    row shard when one is set, sliced host-side otherwise), and only
    process 0 writes checkpoints (state is replicated, so host 0 holds
    the full values).
    ``data_cursor``: the checkpoint manifest's data-shard cursor (PR 6
    recorded it; ``tools/train.py --resume auto`` now consumes it) —
    ``{"loader_batch_images": N}`` names the batch size of the run that
    WROTE the checkpoint, so a loader with ``resume_at`` (the streaming
    loader) can replay that run's plan and continue the epoch
    exactly-once even across a topology change.
    With ``cfg.data.staging`` (the default), batches are double-buffered
    host→device by a background thread (``data/staging.py``): the next
    batch's assembly + ``device_put`` overlap the in-flight step, so
    ``train.data_wait_frac`` goes to ~0 without requiring the dataset to
    fit in HBM.  Skipped automatically for the device-cache path (no
    host batches) and multiproc (global-array assembly is collective and
    stays on the step thread).
    """
    frequent = cfg.default.frequent if frequent is None else frequent
    # -- observability wiring (cfg.obs.enabled; docs/OBSERVABILITY.md) --
    # rec stays None when disabled, and every obs touch below hides
    # behind a `rec is None` branch — the disabled hot path is a local
    # None-check (cost pinned by tests/test_obs.py)
    rec = None
    prof = None
    lowerings = None
    loss_ema = None
    if getattr(cfg, "obs", None) is not None and cfg.obs.enabled:
        from mx_rcnn_tpu.obs.metrics import LoweringCounter, registry

        rec = registry()
        lowerings = LoweringCounter()
        lowerings.__enter__()
        if cfg.obs.profile_at_step > 0:
            from mx_rcnn_tpu.obs.profiler import StepProfiler

            pdir = cfg.obs.profile_dir or os.path.join(
                run_record.dir if run_record is not None else "obs_trace",
                "profile")
            prof = StepProfiler(pdir, cfg.obs.profile_at_step,
                                cfg.obs.profile_steps)
    grad_accum = max(int(grad_accum), 1)
    if device_cache and (grad_accum > 1 or multiproc):
        raise ValueError(
            "device_cache composes with neither grad_accum nor multiproc "
            "(the HBM epoch cache gathers exactly one batch per step, "
            "single process) — use the streaming loader for elastic runs")
    cache = None
    # host→device staging placement (data/staging.py): set by the two
    # single-process branches below; stays None for the device-cache
    # path (no host batches to stage) and multiproc (collective global
    # assembly must run on the step thread)
    stage_place = None
    if device_cache:
        import jax.numpy as jnp

        from mx_rcnn_tpu.data.device_cache import (build_caches,
                                                   make_cached_step)

        on_mesh = mesh is not None and mesh.size > 1
        caches = build_caches(train_loader,
                              mesh=mesh if on_mesh else None)
        if len(caches) != 1:
            raise ValueError(
                f"device_cache needs a single-bucket dataset "
                f"(got {len(caches)} buckets); use the streaming loader")
        cache = caches[0]
        shuffle = getattr(train_loader, "shuffle", True)
        logger.info("device cache: %d batches staged in HBM (%.0f MB%s)",
                    cache.num_batches, cache.nbytes / 1e6,
                    f", sharded over {mesh.size} devices" if on_mesh else "")
        if on_mesh:
            from mx_rcnn_tpu.parallel.dp import (make_dp_cached_step,
                                                 replicate)

            cstep = make_dp_cached_step(model, cfg, tx, mesh,
                                        cache.num_batches, shuffle=shuffle,
                                        mode=mode)
            state = replicate(state, mesh)
        else:
            cstep = jax.jit(
                make_cached_step(
                    make_train_step(model, cfg, tx, mode=mode),
                    cache.num_batches, shuffle=shuffle),
                donate_argnums=(0, 2))
        # the gather index IS the global step: restores (incl. mid-epoch
        # interrupts) resume the exact batch sequence with no bookkeeping.
        # int() is LOAD-BEARING: on CPU, device_get returns a zero-copy
        # view of the step buffer and jnp.asarray keeps sharing it — the
        # idx would alias state.step, and cstep donates BOTH (argnums 0
        # and 2), double-donating one buffer → nondeterministic training
        # (found by the ft crashloop; pinned by
        # test_cached_fit_is_deterministic in tests/test_ft.py)
        idx_box = [jnp.asarray(int(jax.device_get(state.step)), jnp.int32)]

        def run_step(state, batch: Batch):
            state, idx_box[0], metrics = cstep(state, cache.data,
                                               idx_box[0], key)
            return state, metrics
    elif mesh is not None and mesh.size > 1:
        from mx_rcnn_tpu.parallel.dp import (
            make_dp_train_step, replicate, shard_accum_batch, shard_batch)

        step_fn = make_dp_train_step(model, cfg, tx, mesh, mode=mode,
                                     grad_accum=grad_accum)
        if multiproc:
            # the mesh spans processes: device_put cannot address remote
            # devices, so replication and batch assembly go through
            # multihost_utils (parallel/multihost.py).  Every process
            # contributes only its own image slice (rows [pid*per,
            # (pid+1)*per) of the image axis) — identical math to
            # single-process DP.  With a loader row shard (the r7
            # sharded input plane — tools/train.py sets it from the
            # process topology) the batch IS the local slice already
            # and each process decoded only 1/N of the epoch; without
            # one, every process decodes the full batch and slices it
            # host-side (the pre-r7 fallback).
            from mx_rcnn_tpu.parallel import multihost

            state = multihost.replicate_global(jax.device_get(state), mesh)

            def run_step(state, batch: Batch):
                # read the shard LIVE (set_shard may remap between
                # epochs): a sharded loader already yields local rows
                local = (batch
                         if getattr(train_loader, "shard", None) is not None
                         else multihost.local_image_slice(
                             batch, accum=grad_accum > 1))
                gbatch = multihost.global_batch(local, mesh,
                                                accum=grad_accum > 1)
                return step_fn(state, gbatch, key)
        else:
            state = replicate(state, mesh)
            place = (shard_batch if grad_accum <= 1 else shard_accum_batch)
            stage_place = lambda b: place(b, mesh)  # noqa: E731

            def run_step(state, batch: Batch):
                # a staged batch is already mesh-placed; device_put with
                # an identical sharding is a no-op, so one place() serves
                # both the staged and unstaged paths
                return step_fn(state, place(batch, mesh), key)
    else:
        from mx_rcnn_tpu.parallel.dp import own_leaves

        base = jax.jit(make_train_step(model, cfg, tx, mode=mode,
                                       grad_accum=grad_accum),
                       donate_argnums=(0,))
        # a restored state arrives with numpy leaves (views of one
        # msgpack buffer); the jitted step DONATES arg 0 — force
        # private jax-owned copies first (parallel/dp.py — own_leaves)
        state = own_leaves(state)
        stage_place = jax.device_put

        def run_step(state, batch: Batch):
            return base(state, batch, key)

    n_dev = mesh.size if mesh is not None else 1
    speedo = Speedometer(cfg.train.batch_images * n_dev * grad_accum,
                         frequent, registry=rec)
    # OPTIMIZER steps per epoch: with accumulation each step consumes
    # grad_accum loader batches (a partial trailing group is dropped —
    # the effective batch of every optimizer step stays on-recipe)
    if grad_accum > 1 and len(train_loader) < grad_accum:
        raise ValueError(
            f"grad_accum={grad_accum} exceeds the loader's "
            f"{len(train_loader)} batches/epoch — every epoch would run "
            f"ZERO optimizer steps (and 'complete' without training); "
            f"the dataset is too small for this topology")
    steps_per_epoch = (len(train_loader) // grad_accum if grad_accum > 1
                       else len(train_loader))
    done_steps = int(jax.device_get(state.step))
    data_cfg = getattr(cfg, "data", None)
    if data_cfg is None or not data_cfg.staging:
        stage_place = None
    stager = None
    snap = None
    if prefix is not None and not (multiproc and jax.process_index() != 0):
        from mx_rcnn_tpu.ft.snapshot import make_snapshotter
        from mx_rcnn_tpu.utils.checkpoint import make_topology

        topo = make_topology(
            n_dev, num_processes=jax.process_count() if multiproc else 1,
            grad_accum=grad_accum, batch_images=cfg.train.batch_images)
        snap = make_snapshotter(prefix, cfg, steps_per_epoch, topology=topo)
    try:
        for epoch in range(begin_epoch, num_epochs):
            if hasattr(train_loader, "set_epoch"):
                train_loader.set_epoch(epoch)  # resume-exact shuffle order
            # mid-epoch (preemption) resume: skip batches the restored state
            # already consumed; the deterministic shuffle replays the same
            # order
            skip = 0
            if epoch == begin_epoch and steps_per_epoch:
                skip = min(max(done_steps - epoch * steps_per_epoch, 0),
                           steps_per_epoch)
                if skip:
                    logger.info("Epoch[%d] resuming mid-epoch: skipping %d "
                                "consumed batches", epoch, skip)
            speedo.reset()
            window: List[Dict] = []
            epoch_metrics: List[Dict] = []
            t0 = time.perf_counter()
            nbatch = skip
            tracing = False
            stop_requested = False
            if cache is not None:
                # batches gather on device from the staged epoch; the
                # resumed idx (== state.step) already accounts for the
                # skipped prefix
                batch_iter = iter([None] * (steps_per_epoch - skip))
            else:
                skip_b = skip * grad_accum  # loader batches, not opt steps
                loader_skips = hasattr(train_loader, "skip_next_batches")
                if skip_b and hasattr(train_loader, "resume_at"):
                    # streaming loader: position by the data cursor —
                    # the recording run's batch size lets the loader
                    # replay ITS plan, so the continued epoch is
                    # exactly-once even across an elastic topology
                    # change (docs/DATA.md; plain skip trims the
                    # CURRENT plan, which is only identical for the
                    # same topology).  images_consumed_in_epoch comes
                    # from state.step under the OLD topology
                    # (tools/train.py); the new-topology product below
                    # is the fallback for direct fit() callers and is
                    # only equal when the global batch is unchanged.
                    cur = data_cursor or {}
                    images = cur.get("images_consumed_in_epoch")
                    if images is None:
                        images = skip_b * train_loader.batch_images
                    train_loader.resume_at(
                        images, cur.get("loader_batch_images"))
                elif skip_b and loader_skips:
                    train_loader.skip_next_batches(skip_b)  # trims the order
                batch_iter = iter(train_loader)
                if skip_b and not loader_skips:
                    for _ in range(skip_b):  # fallback: decode-and-discard
                        next(batch_iter, None)
                if grad_accum > 1:
                    batch_iter = _accum_iter(batch_iter, grad_accum)
                if stage_place is not None:
                    from mx_rcnn_tpu.data.staging import DeviceStager

                    # double-buffer host→device: assembly + device_put of
                    # batch k+1 overlap step k (docs/DATA.md)
                    stager = DeviceStager(batch_iter, stage_place,
                                          depth=data_cfg.stage_depth,
                                          rec=rec)
                    batch_iter = iter(stager)
            if run_record is not None:
                run_record.event("epoch_start", epoch=epoch, skip=skip,
                                 steps_per_epoch=steps_per_epoch)
            while True:
                if rec is None:
                    batch = next(batch_iter, _END)
                else:
                    t_wait = time.perf_counter()
                    with obs_trace.span("train.data_wait"):
                        batch = next(batch_iter, _END)
                    wait_s = time.perf_counter() - t_wait
                if batch is _END:
                    break
                # trace steps [skip+2, skip+5) of the first epoch: the first
                # two executed steps carry compile
                if (profile_dir is not None and epoch == begin_epoch
                        and nbatch == skip + 2):
                    jax.profiler.start_trace(profile_dir)
                    tracing = True
                    logger.info("profiler trace started -> %s", profile_dir)
                if rec is None:
                    state, metrics = run_step(state, batch)
                else:
                    with obs_trace.span("train.dispatch"):
                        state, metrics = run_step(state, batch)
                    step_s = time.perf_counter() - t_wait
                    rec.inc("train.steps")
                    rec.observe("train.step_ms", step_s * 1e3)
                    rec.observe("train.data_wait_ms", wait_s * 1e3)
                    frac = wait_s / max(step_s, 1e-9)
                    rec.set_gauge("train.data_wait_frac", frac)
                    # the PER-STEP fraction as a distribution (percent
                    # scale for the log-bucket range): p50 of this is
                    # the honest data_wait_frac statistic — a ratio of
                    # independent wait/step percentiles is not
                    rec.observe("train.data_wait_frac_pct", 100.0 * frac,
                                lo=0.01, hi=1000.0)
                window.append(metrics)
                nbatch += 1
                if tracing and nbatch >= skip + 5:
                    jax.block_until_ready(metrics)
                    jax.profiler.stop_trace()
                    tracing = False
                    logger.info("profiler trace written to %s", profile_dir)
                if prof is not None:
                    m = metrics  # bind: the lambda must sync THIS step
                    prof.on_step(epoch * steps_per_epoch + nbatch,
                                 sync=lambda: jax.block_until_ready(m))
                if step_callback is not None:
                    step_callback(epoch * steps_per_epoch + nbatch)
                if stop_flag is not None and stop_flag():
                    stop_requested = True
                    # mid-epoch: save the step-exact interrupt state and
                    # leave.  On the epoch's LAST batch, fall through
                    # instead — the normal epoch end writes the
                    # (superseding) epoch checkpoint and the run stops
                    # cleanly at the boundary.
                    if nbatch < steps_per_epoch:
                        if tracing:
                            jax.profiler.stop_trace()
                        if snap is not None:
                            with obs_trace.span("train.snapshot",
                                                kind="interrupt"):
                                path = snap.save_interrupt(state)
                            if run_record is not None:
                                run_record.event(
                                    "interrupt", epoch=epoch, nbatch=nbatch,
                                    path=path)
                            logger.info(
                                "stop requested: saved interrupt checkpoint "
                                'to "%s" (step %d) — rerun with --resume to '
                                "continue", path,
                                int(jax.device_get(state.step)))
                        else:
                            logger.info(
                                "stop requested: no prefix, state not saved")
                        return state
                if nbatch % frequent == 0:
                    with obs_trace.span("train.sync"):
                        avg = _mean_metrics(window)
                    epoch_metrics.append(avg)
                    window = []
                    speedo(epoch, nbatch, avg)
                    if rec is not None:
                        loss = avg.get("loss")
                        if loss is not None:
                            a = cfg.obs.loss_ema
                            loss_ema = (loss if loss_ema is None
                                        else a * loss_ema + (1 - a) * loss)
                            rec.set_gauge("train.loss_ema", loss_ema)
                        rec.set_gauge("train.lowerings_total", lowerings.n)
                    if run_record is not None:
                        run_record.event(
                            "log", epoch=epoch, nbatch=nbatch,
                            samples_per_sec=(
                                None if rec is None
                                else rec.gauge("train.samples_per_sec")),
                            **avg)
                else:
                    speedo(epoch, nbatch, {})
            if stager is not None:  # epoch drained: join the stage thread
                stager.close()
                stager = None
            if tracing:  # epoch shorter than the trace window
                jax.block_until_ready(metrics)
                jax.profiler.stop_trace()
                logger.info("profiler trace written to %s", profile_dir)
            if window:
                epoch_metrics.append(_mean_metrics(window))
            epoch_s = time.perf_counter() - t0
            if epoch_metrics:
                keys = epoch_metrics[0].keys()
                summary = ", ".join(
                    f"{k}={np.mean([m[k] for m in epoch_metrics]):.4f}"
                    for k in keys)
                logger.info("Epoch[%d] Train summary: %s  (%.1fs)", epoch,
                            summary, epoch_s)
            if rec is not None:
                rec.inc("train.epochs")
                rec.set_gauge("train.epoch_s", epoch_s)
            if run_record is not None:
                run_record.event(
                    "epoch_end", epoch=epoch, nbatch=nbatch,
                    epoch_s=round(epoch_s, 3),
                    **{k: float(np.mean([m[k] for m in epoch_metrics]))
                       for k in (epoch_metrics[0].keys()
                                 if epoch_metrics else ())})
            if snap is not None:
                # device_get here, serialize+write+manifest+GC in the
                # background; the interrupt file is cleared by the writer
                # only after this epoch checkpoint commits
                with obs_trace.span("train.snapshot", kind="epoch"):
                    path = snap.save_epoch(epoch + 1, state)
                if run_record is not None:
                    run_record.event("snapshot", epoch=epoch, path=path)
                logger.info('Epoch[%d] Snapshotting checkpoint to "%s"',
                            epoch, path)
            if epoch_end_callback is not None:
                if snap is not None:
                    snap.flush()  # callbacks may read the checkpoint file
                epoch_end_callback(epoch, state)
            if stop_requested:
                logger.info("stop requested at epoch boundary — stopping "
                            "after epoch %d", epoch)
                return state
        return state
    finally:
        if stager is not None:
            stager.close()  # early return/error: release the stage thread
        if prof is not None:
            prof.close()  # run shorter than the window: close it cleanly
        if snap is not None:
            snap.close()  # flush pending writes before the process moves on
