"""Prediction + detection evaluation.

Reference: ``rcnn/core/tester.py`` — ``Predictor`` (binds the test symbol
once per input shape), ``im_detect`` (forward + bbox decode + clip),
``pred_eval`` (per-class threshold → NMS → cap max_per_image →
``imdb.evaluate_detections``) and ``generate_proposals`` (RPN-only dump for
alternate training).

Normalization invariant (SURVEY.md §5.4): the reference trains bbox_pred
against mean/std-normalized targets and **un-normalizes the weights at
checkpoint time** (``do_checkpoint``), so saved models emit raw deltas.
Here weights always stay in normalized space and the predictor applies
``delta * std + mean`` at decode time — one convention everywhere, no
weight rewriting; checkpoints are therefore directly resumable.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.models.faster_rcnn import FasterRCNN
from mx_rcnn_tpu.ops.boxes import bbox_pred, clip_boxes
from mx_rcnn_tpu.ops.nms import nms_mask


class Predictor:
    """Jit-compiled test-mode forward, cached per input shape
    (the XLA analog of MutableModule's rebinding-on-shape-change)."""

    def __init__(self, model: FasterRCNN, variables, cfg: Config):
        self.model = model
        self.variables = variables
        self.cfg = cfg
        self._fns: Dict[Tuple[int, ...], callable] = {}

    def __call__(self, images: np.ndarray, im_info: np.ndarray):
        shape = tuple(images.shape)
        if shape not in self._fns:
            model = self.model

            @jax.jit
            def fn(variables, images, im_info):
                return model.apply(variables, images, im_info)

            self._fns[shape] = fn
        rois, roi_valid, cls_prob, deltas = self._fns[shape](
            self.variables, jnp.asarray(images), jnp.asarray(im_info))
        return (np.asarray(rois), np.asarray(roi_valid),
                np.asarray(cls_prob), np.asarray(deltas))


@functools.partial(jax.jit, static_argnames=("nms_thresh",))
def _per_class_nms(boxes: jnp.ndarray, scores: jnp.ndarray, valid: jnp.ndarray,
                   nms_thresh: float) -> jnp.ndarray:
    return nms_mask(boxes, scores, nms_thresh, valid=valid)


def im_detect_batch(
    rois: np.ndarray,
    roi_valid: np.ndarray,
    cls_prob: np.ndarray,
    deltas: np.ndarray,
    im_info: np.ndarray,
    scales: np.ndarray,
    cfg: Config,
) -> List[Tuple[np.ndarray, np.ndarray]]:
    """Decode one forward batch into per-image (boxes_per_class, scores).

    Applies the de-normalization invariant, decodes class-specific deltas,
    clips to the image, and un-scales to raw image coordinates
    (ref ``im_detect``).
    Returns a list of (boxes (R, 4C), scores (R, C)) per image.
    """
    n, r, c4 = deltas.shape
    num_classes = c4 // 4
    stds = np.tile(np.asarray(cfg.train.bbox_stds, np.float32), num_classes)
    means = np.tile(np.asarray(cfg.train.bbox_means, np.float32), num_classes)
    out = []
    for i in range(n):
        d = deltas[i] * stds + means
        boxes = np.asarray(bbox_pred(jnp.asarray(rois[i]), jnp.asarray(d)))
        boxes = np.asarray(clip_boxes(jnp.asarray(boxes),
                                      (im_info[i, 0], im_info[i, 1])))
        boxes = boxes / scales[i]  # back to raw image coordinates
        scores = cls_prob[i] * roi_valid[i][:, None]  # padded slots → 0
        out.append((boxes, scores))
    return out


def pred_eval(predictor: Predictor, test_loader, imdb, cfg: Config,
              out_dir: str = None, verbose: bool = True) -> Dict[str, float]:
    """Full evaluation loop (ref ``pred_eval``): forward every image,
    per-class score threshold + NMS, cap ``max_per_image``, then
    ``imdb.evaluate_detections``."""
    num_classes = imdb.num_classes
    num_images = len(test_loader.roidb)
    all_boxes: List[List[np.ndarray]] = [
        [np.zeros((0, 5), np.float32) for _ in range(num_images)]
        for _ in range(num_classes)
    ]
    thresh = cfg.test.score_thresh
    done = 0
    for batch, indices, scales in test_loader:
        rois, roi_valid, cls_prob, deltas = predictor(batch.images,
                                                      batch.im_info)
        decoded = im_detect_batch(rois, roi_valid, cls_prob, deltas,
                                  batch.im_info, scales, cfg)
        for j, i in enumerate(indices):
            boxes, scores = decoded[j]
            kept_all = []
            for c in range(1, num_classes):
                inds = scores[:, c] > thresh
                if not inds.any():
                    continue
                cls_boxes = boxes[inds, 4 * c:4 * c + 4]
                cls_scores = scores[inds, c]
                keep = np.asarray(_per_class_nms(
                    jnp.asarray(cls_boxes), jnp.asarray(cls_scores),
                    jnp.ones(len(cls_scores), bool), cfg.test.nms))
                dets = np.hstack([cls_boxes[keep],
                                  cls_scores[keep, None]]).astype(np.float32)
                all_boxes[c][i] = dets
                kept_all.append(dets[:, 4])
            # cap detections per image by score (ref max_per_image=100)
            if kept_all:
                all_scores = np.concatenate(kept_all)
                if len(all_scores) > cfg.test.max_per_image:
                    score_thresh = np.sort(all_scores)[
                        -cfg.test.max_per_image]
                    for c in range(1, num_classes):
                        keep = all_boxes[c][i][:, 4] >= score_thresh
                        all_boxes[c][i] = all_boxes[c][i][keep]
        done += len(indices)
        if verbose:
            print(f"eval: {done}/{num_images} images")
    results = imdb.evaluate_detections(all_boxes, out_dir) if out_dir \
        else imdb.evaluate_detections(all_boxes)
    return results


def generate_proposals(model: FasterRCNN, variables, test_loader, cfg: Config
                       ) -> List[np.ndarray]:
    """RPN-only proposal dump for alternate training
    (ref ``generate_proposals`` writes rpn_data/*.pkl; here the (R, 5)
    [x1 y1 x2 y2 score] arrays are returned in roidb order and the caller
    persists them)."""
    num_images = len(test_loader.roidb)
    proposals: List[np.ndarray] = [None] * num_images
    fns: Dict[Tuple[int, ...], callable] = {}
    pre = cfg.test.proposal_pre_nms_top_n
    post = cfg.test.proposal_post_nms_top_n
    for batch, indices, scales in test_loader:
        shape = tuple(batch.images.shape)
        if shape not in fns:
            @jax.jit
            def fn(variables, images, im_info):
                return model.apply(variables, images, im_info, pre, post,
                                   method=model.rpn_proposals)

            fns[shape] = fn
        rois, scores, roi_valid = map(np.asarray, fns[shape](
            variables, jnp.asarray(batch.images), jnp.asarray(batch.im_info)))
        for j, i in enumerate(indices):
            valid = roi_valid[j]
            boxes = rois[j][valid] / scales[j]
            proposals[i] = np.hstack(
                [boxes, scores[j][valid][:, None]]).astype(np.float32)
    return proposals
