"""Prediction + detection evaluation.

Reference: ``rcnn/core/tester.py`` — ``Predictor`` (binds the test symbol
once per input shape), ``im_detect`` (forward + bbox decode + clip),
``pred_eval`` (per-class threshold → NMS → cap max_per_image →
``imdb.evaluate_detections``) and ``generate_proposals`` (RPN-only dump for
alternate training).

Normalization invariant (SURVEY.md §5.4): the reference trains bbox_pred
against mean/std-normalized targets and **un-normalizes the weights at
checkpoint time** (``do_checkpoint``), so saved models emit raw deltas.
Here weights always stay in normalized space and the predictor applies
``delta * std + mean`` at decode time — one convention everywhere, no
weight rewriting; checkpoints are therefore directly resumable.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.models.faster_rcnn import FasterRCNN
from mx_rcnn_tpu.ops.boxes import bbox_pred, clip_boxes
from mx_rcnn_tpu.ops.nms import nms_mask_batch


class Predictor:
    """Jit-compiled test-mode forward, cached per input shape
    (the XLA analog of MutableModule's rebinding-on-shape-change).

    ``mesh``: optional 1-D data mesh for multi-chip evaluation (beyond the
    reference, whose eval is single-GPU): the batch axis is sharded over
    the mesh and GSPMD parallelizes the whole test-mode forward; short
    batches pad to a mesh multiple and the pad rows are dropped on fetch.
    """

    def __init__(self, model: FasterRCNN, variables, cfg: Config, mesh=None):
        self.model = model
        self.variables = variables
        self.cfg = cfg
        self.mesh = mesh
        self._fns: Dict[Tuple[int, ...], callable] = {}
        # quant mode (docs/PERF.md "Quantized inference"): a quantized
        # Predictor tags every program key with the quant recipe + the
        # calibration fingerprint, so quantized and fp programs can
        # never share a cache slot (or an export-store slot) unnoticed
        self.quant_fingerprint: str = None
        self._kind_tag = ""
        if cfg.quant.enabled:
            from mx_rcnn_tpu.ops.quant import (calibration_fingerprint,
                                               quant_program_tag)

            if "quant" not in variables:
                raise ValueError(
                    "cfg.quant.enabled but variables carry no 'quant' "
                    "collection — calibrate first (core/tester.py — "
                    "quant_predictor)")
            self.quant_fingerprint = calibration_fingerprint(
                variables["quant"], cfg.quant)
            self._kind_tag = quant_program_tag(
                cfg.quant, self.quant_fingerprint) + ":"
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from mx_rcnn_tpu.parallel.dp import data_axes, replicate

            self._batch_sharding = NamedSharding(mesh, P(data_axes(mesh)))
            self.variables = replicate(variables, mesh)

    def _forward(self, kind: str, arrays, pad_fills, make_fn):
        """Shared forward scaffolding for every predictor mode: mesh
        padding (``pad_fills`` per array — im_info pads with 1 so padded
        rows never divide by zero), the per-(mode, shape, dtype) jit cache,
        sharded placement, and pad-row trimming.  ``make_fn()`` builds the
        jitted function on first use for a new shape."""
        n = arrays[0].shape[0]
        if self.mesh is not None:
            pad = (-n) % self.mesh.size
            if pad:
                arrays = tuple(
                    np.concatenate([a, np.full((pad,) + a.shape[1:], fill,
                                               a.dtype)])
                    for a, fill in zip(arrays, pad_fills))
        shape = self.program_key(kind, arrays)
        if shape not in self._fns:
            # threadlint: disable=TL201 warmup pre-traces every bucket before dispatchers serve; a post-warmup miss re-installs an identical program and dict assignment is atomic under the GIL (zero post-warmup compiles is pinned by the recompile guard)
            self._fns[shape] = make_fn()
        if self.mesh is not None:
            # device_put the host arrays straight into their shards — going
            # through jnp.asarray first would commit the whole batch to
            # device 0 and transfer it twice
            arrays = tuple(jax.device_put(np.asarray(a),
                                          self._batch_sharding)
                           for a in arrays)
        else:
            arrays = tuple(jnp.asarray(a) for a in arrays)
        out = self._fns[shape](self.variables, *arrays)
        if self.mesh is not None and out[0].shape[0] != n:
            out = tuple(o[:n] for o in out)
        return out

    def program_key(self, kind: str, arrays) -> Tuple:
        """The per-(mode, shape, dtype) program-cache key ``_forward``
        caches jitted functions under — keyed by mode AND shape AND
        dtype: uint8 raw batches and fp32 host-normalized batches compile
        to different programs.  In quant mode the kind is additionally
        tagged with the quant recipe + calibration fingerprint, so a
        quantized program can never collide with (or shadow) an fp one.
        Public so the AOT export store (``serve/export.py``) can address
        the same slots."""
        return (self._kind_tag + kind,) + tuple(
            (tuple(a.shape), np.dtype(a.dtype).name) for a in arrays)

    def install_program(self, key: Tuple, fn) -> None:
        """Pre-populate one program-cache slot with an ahead-of-time
        program (a deserialized ``jax.export`` call wrapped in
        ``jax.jit`` — ``serve/export.py — ExportStore.load``).  The slot
        is called exactly like a live-traced one:
        ``fn(variables, *arrays)``.  Installing over an existing slot is
        refused — silently shadowing a live-traced program would make
        "which program served this?" unanswerable."""
        if key in self._fns:
            raise ValueError(f"program slot {key!r} already resident — "
                             "install exports before the first forward")
        self._fns[key] = fn

    def raw(self, images: np.ndarray, im_info: np.ndarray):
        """Forward pass returning DEVICE arrays (no host sync) — the eval
        loop feeds these straight into the jitted postprocess.  Outputs
        cover exactly the input rows (mesh padding is stripped)."""
        model = self.model

        def make_fn():
            @jax.jit
            def fn(variables, images, im_info):
                return model.apply(variables, images, im_info)

            return fn

        return self._forward("rpn", (images, im_info), (0, 1), make_fn)

    def raw_rois(self, images: np.ndarray, im_info: np.ndarray,
                 rois: np.ndarray, rois_valid: np.ndarray):
        """RCNN-only forward on precomputed proposals (ref the
        HAS_RPN=False predictor used by ``rcnn/tools/test_rcnn.py``):
        same contract as :meth:`raw` but the ROIs come from the loader, so
        the model's ``detect_rois`` path runs instead of the RPN."""
        model = self.model

        def make_fn():
            @jax.jit
            def fn(variables, images, im_info, rois, rois_valid):
                return model.apply(variables, images, im_info, rois,
                                   rois_valid, method=model.detect_rois)

            return fn

        return self._forward("rois", (images, im_info, rois, rois_valid),
                             (0, 1, 0, 0), make_fn)

    def rpn(self, images: np.ndarray, im_info: np.ndarray):
        """RPN-only proposal forward (ref ``generate_proposals`` — the
        alternate-training stage 1/3 inference): returns device arrays
        (rois (N, R, 4), scores (N, R), valid (N, R)).  Mesh-sharded like
        every other predictor mode, which the reference's single-GPU
        proposal dump never was."""
        model = self.model
        pre = self.cfg.test.proposal_pre_nms_top_n
        post = self.cfg.test.proposal_post_nms_top_n

        def make_fn():
            @jax.jit
            def fn(variables, images, im_info):
                return model.apply(variables, images, im_info, pre, post,
                                   method=model.rpn_proposals)

            return fn

        return self._forward("rpn_only", (images, im_info), (0, 1), make_fn)

    def raw_batch(self, batch):
        """Dispatch a loader batch: an RCNNBatch (carries ``rois`` from
        precomputed proposals) runs the RCNN-only path; a plain Batch runs
        the full RPN+RCNN test forward."""
        if hasattr(batch, "rois"):
            return self.raw_rois(batch.images, batch.im_info, batch.rois,
                                 batch.rois_valid)
        return self.raw(batch.images, batch.im_info)

    def __call__(self, images: np.ndarray, im_info: np.ndarray):
        rois, roi_valid, cls_prob, deltas = self.raw(images, im_info)
        return (np.asarray(rois), np.asarray(roi_valid),
                np.asarray(cls_prob), np.asarray(deltas))


def tiled_bbox_stats(cfg: Config, num_classes: int):
    """(stds, means) tiled per class for delta de-normalization — THE
    decode-time half of the one-convention invariant (module docstring);
    every eval/demo/dryrun caller must use this single helper so the
    convention cannot fork."""
    stds = jnp.tile(jnp.asarray(cfg.train.bbox_stds, jnp.float32),
                    num_classes)
    means = jnp.tile(jnp.asarray(cfg.train.bbox_means, jnp.float32),
                     num_classes)
    return stds, means


@jax.jit
def _decode_batch(rois, roi_valid, cls_prob, deltas, im_info, scales,
                  stds, means):
    """De-normalize deltas, decode, clip, unscale — the shared decode step
    of eval (ref ``im_detect``).  Returns (boxes (N, R, 4C) in raw-image
    coordinates, scores (N, R, C) with padded ROI slots zeroed)."""

    def one(rois_i, valid_i, prob_i, d_i, info_i, scale_i):
        d = d_i * stds + means  # de-normalization invariant (see docstring)
        boxes = bbox_pred(rois_i, d)
        boxes = clip_boxes(boxes, (info_i[0], info_i[1]))
        boxes = boxes / scale_i  # back to raw image coordinates
        scores = prob_i * valid_i[:, None]  # padded ROI slots → 0
        return boxes, scores

    return jax.vmap(one)(rois, roi_valid, cls_prob, deltas, im_info, scales)


@functools.partial(jax.jit, static_argnames=("nms_thresh", "score_thresh"))
def _postprocess_batch(rois, roi_valid, cls_prob, deltas, im_info, scales,
                       stds, means, *, nms_thresh: float, score_thresh: float):
    """Decode + clip + unscale + per-class masked NMS for a whole batch in
    ONE fixed-shape XLA program.

    The reference runs per-class NMS on host with variable-length candidate
    lists (``pred_eval``); a naive port jits per candidate-count and
    recompiles hundreds of times.  Here every class runs masked NMS over the
    full fixed ROI buffer (valid = score>thresh), vmapped over classes and
    images, so eval compiles once per batch shape.

    Returns (boxes (N, R, 4C) raw-image coords, scores (N, R, C),
    keep (N, C, R) bool — the post-NMS per-class detection mask).
    """
    n, r, c4 = deltas.shape
    c = cls_prob.shape[-1]
    boxes_b, scores_b = _decode_batch(rois, roi_valid, cls_prob, deltas,
                                      im_info, scales, stds, means)

    def prep(boxes, scores, valid_i):
        boxes_c = boxes.reshape(r, c, 4).transpose(1, 0, 2)  # (C, R, 4)
        scores_c = scores.T  # (C, R)
        cand = (scores_c > score_thresh) & valid_i[None, :]
        return boxes_c, scores_c, cand

    boxes_c, scores_c, cand = jax.vmap(prep)(boxes_b, scores_b, roi_valid)
    # every (image, class) NMS in ONE cross-image batched sweep (r6) —
    # decision-exact vs the former vmap(vmap(nms_mask)) composition.
    # backend pinned to jnp: at eval sizes (a few hundred boxes per class)
    # the Pallas kernel has no advantage, and the (N·C)-row batch would
    # multiply its per-image VMEM blocks under vmap
    keep_flat = nms_mask_batch(
        boxes_c.reshape(n * c, r, 4), scores_c.reshape(n * c, r),
        nms_thresh, valid=cand.reshape(n * c, r), backend="jnp")
    keep_b = keep_flat.reshape(n, c, r) & cand
    return boxes_b, scores_b, keep_b


def detections_from_keep(boxes_b: np.ndarray, scores_b: np.ndarray,
                         keep_b: np.ndarray, j: int) -> Dict[int, np.ndarray]:
    """Per-image detection dict from :func:`_postprocess_batch` outputs:
    row ``j`` → ``{class_id: (k, 5) [x1 y1 x2 y2 score]}`` over the
    foreground classes.  ONE implementation shared by the demo
    (``tools/demo.py``) and the serving engine (``serve/engine.py``) so a
    response can never disagree with the eval-path postprocess on how the
    keep mask demultiplexes into detections."""
    r = boxes_b.shape[1]
    num_classes = scores_b.shape[-1]
    boxes = boxes_b[j].reshape(r, num_classes, 4)
    out: Dict[int, np.ndarray] = {}
    for c in range(1, num_classes):
        keep = keep_b[j, c]
        if keep.any():
            out[c] = np.hstack([boxes[keep, c],
                                scores_b[j][keep, c, None]]
                               ).astype(np.float32)
    return out


def im_detect_batch(
    rois: np.ndarray,
    roi_valid: np.ndarray,
    cls_prob: np.ndarray,
    deltas: np.ndarray,
    im_info: np.ndarray,
    scales: np.ndarray,
    cfg: Config,
) -> List[Tuple[np.ndarray, np.ndarray]]:
    """Decode one forward batch into per-image (boxes_per_class, scores).

    Applies the de-normalization invariant, decodes class-specific deltas,
    clips to the image, and un-scales to raw image coordinates
    (ref ``im_detect``).
    Returns a list of (boxes (R, 4C), scores (R, C)) per image.
    """
    n, r, c4 = deltas.shape
    num_classes = c4 // 4
    stds, means = tiled_bbox_stats(cfg, num_classes)
    boxes_b, scores_b = map(np.asarray, _decode_batch(
        jnp.asarray(rois), jnp.asarray(roi_valid), jnp.asarray(cls_prob),
        jnp.asarray(deltas), jnp.asarray(im_info), jnp.asarray(scales),
        stds, means))
    return [(boxes_b[i], scores_b[i]) for i in range(n)]


def pred_eval(predictor: Predictor, test_loader, imdb, cfg: Config,
              out_dir: str = None, verbose: bool = True,
              save_dets: str = None) -> Dict[str, float]:
    """Full evaluation loop (ref ``pred_eval``): forward every image,
    per-class score threshold + NMS, cap ``max_per_image``, then
    ``imdb.evaluate_detections``.

    ``save_dets``: pickle the raw ``all_boxes`` detections here before
    evaluating (ref ``pred_eval`` caches ``detections.pkl``), enabling
    ``tools/reeval.py`` to re-score without re-running the model.
    """
    num_classes = imdb.num_classes
    num_images = len(test_loader.roidb)
    all_boxes: List[List[np.ndarray]] = [
        [np.zeros((0, 5), np.float32) for _ in range(num_images)]
        for _ in range(num_classes)
    ]
    thresh = cfg.test.score_thresh
    stds, means = tiled_bbox_stats(cfg, num_classes)
    done = 0
    for batch, indices, scales in test_loader:
        # device arrays stay on device between forward and postprocess;
        # raw_batch dispatches RPN-generated vs precomputed-ROI batches
        # (duck-typed so fabricated test predictors exposing only .raw work)
        if hasattr(predictor, "raw_batch"):
            rois, roi_valid, cls_prob, deltas = predictor.raw_batch(batch)
        else:
            rois, roi_valid, cls_prob, deltas = predictor.raw(batch.images,
                                                              batch.im_info)
        boxes_b, scores_b, keep_b = map(np.asarray, _postprocess_batch(
            rois, roi_valid, cls_prob, deltas, jnp.asarray(batch.im_info),
            jnp.asarray(scales), stds, means,
            nms_thresh=cfg.test.nms, score_thresh=thresh))
        r = boxes_b.shape[1]
        for j, i in enumerate(indices):
            boxes = boxes_b[j].reshape(r, num_classes, 4)
            scores = scores_b[j]
            kept_all = []
            for c in range(1, num_classes):
                keep = keep_b[j, c]
                if not keep.any():
                    continue
                dets = np.hstack([boxes[keep, c],
                                  scores[keep, c, None]]).astype(np.float32)
                all_boxes[c][i] = dets
                kept_all.append(dets[:, 4])
            # cap detections per image by score (ref max_per_image=100)
            if kept_all:
                all_scores = np.concatenate(kept_all)
                if len(all_scores) > cfg.test.max_per_image:
                    score_thresh = np.sort(all_scores)[
                        -cfg.test.max_per_image]
                    for c in range(1, num_classes):
                        keep = all_boxes[c][i][:, 4] >= score_thresh
                        all_boxes[c][i] = all_boxes[c][i][keep]
        done += len(indices)
        if verbose:
            print(f"eval: {done}/{num_images} images")
    if save_dets:
        import os
        import pickle

        os.makedirs(os.path.dirname(save_dets) or ".", exist_ok=True)
        with open(save_dets, "wb") as f:
            pickle.dump({"all_boxes": all_boxes, "classes": imdb.classes},
                        f, protocol=pickle.HIGHEST_PROTOCOL)
    results = imdb.evaluate_detections(all_boxes, out_dir) if out_dir \
        else imdb.evaluate_detections(all_boxes)
    return results


def calibration_batches(cfg: Config, dataset_kw: dict = None):
    """The held-out calibration sweep for quantized inference
    (docs/PERF.md "Quantized inference"): ``cfg.quant.calibration_batches``
    test-mode batches drawn from a DETERMINISTIC
    ``cfg.quant.calibration_seed`` subsample of the TRAINING split —
    never the eval set (the accuracy gate evaluates on it), and never
    order-dependent on loader workers (the subsample is materialized
    before the loader, and batches are consumed in roidb order).
    Yields ``(images, im_info)`` pairs."""
    from mx_rcnn_tpu.data import TestLoader, load_gt_roidb

    q = cfg.quant
    _, roidb = load_gt_roidb(cfg, training=True, **(dataset_kw or {}))
    per_batch = max(1, cfg.test.batch_images)
    want = max(1, q.calibration_batches) * per_batch
    order = np.random.RandomState(q.calibration_seed).permutation(len(roidb))
    roidb = [roidb[i] for i in order[:want]]
    loader = TestLoader(roidb, cfg, batch_images=per_batch, num_workers=0)
    out = []
    for batch, _, _ in loader:
        out.append((np.asarray(batch.images), np.asarray(batch.im_info)))
        if len(out) >= q.calibration_batches:
            break
    return out


def calibrate_quant(cfg: Config, params, batch_stats, *,
                    dataset_kw: dict = None, batches=None):
    """Run the calibration sweep and return the ``quant`` variables
    collection (per-layer activation scales).  ``batches`` overrides the
    default held-out sweep (bench rigs pass synthetic batches).
    Deterministic: the same batches in the same order produce
    bit-identical scales (pinned by ``tests/test_quant.py``)."""
    import jax

    from mx_rcnn_tpu.models import build_model
    from mx_rcnn_tpu.ops.quant import finalize_calibration

    if not cfg.quant.enabled:
        raise ValueError("calibrate_quant needs cfg.quant.enabled")
    calib_model = build_model(cfg, quant_phase="calib")
    variables = {"params": params, "batch_stats": batch_stats}

    @jax.jit
    def sweep_one(variables, stats, images, im_info):
        v = dict(variables)
        if stats is not None:
            v["quant_stats"] = stats
        _, mut = calib_model.apply(v, images, im_info,
                                   mutable=["quant_stats"])
        return mut["quant_stats"]

    stats = None
    for images, im_info in (batches if batches is not None
                            else calibration_batches(cfg, dataset_kw)):
        stats = sweep_one(variables, stats, jnp.asarray(images),
                          jnp.asarray(im_info))
    if stats is None:
        raise ValueError("calibration sweep saw zero batches")
    return finalize_calibration(stats, cfg.quant)


def quant_predictor(cfg: Config, params, batch_stats, mesh=None, *,
                    dataset_kw: dict = None, batches=None) -> Predictor:
    """Build the quantized-inference Predictor: calibration sweep →
    ``quant`` scales collection → quantized model → Predictor (whose
    program keys carry the quant tag + calibration fingerprint).  The
    drop-in quant mode for eval (``tools/test.py``), serving
    (``tools/serve.py`` / ``tools/fleet.py``) and the export store."""
    from mx_rcnn_tpu.models import build_model

    quant_col = calibrate_quant(cfg, params, batch_stats,
                                dataset_kw=dataset_kw, batches=batches)
    model = build_model(cfg)
    return Predictor(model, {"params": params, "batch_stats": batch_stats,
                             "quant": quant_col}, cfg, mesh=mesh)


def generate_proposals(model: FasterRCNN, variables, test_loader, cfg: Config,
                       mesh=None) -> List[np.ndarray]:
    """RPN-only proposal dump for alternate training
    (ref ``generate_proposals`` writes rpn_data/*.pkl; here the (R, 5)
    [x1 y1 x2 y2 score] arrays are returned in roidb order and the caller
    persists them).  ``mesh``: optional data mesh — proposal generation
    shards over chips exactly like eval (:class:`Predictor`)."""
    num_images = len(test_loader.roidb)
    proposals: List[np.ndarray] = [None] * num_images
    predictor = Predictor(model, variables, cfg, mesh=mesh)
    for batch, indices, scales in test_loader:
        rois, scores, roi_valid = map(np.asarray, predictor.rpn(
            batch.images, batch.im_info))
        for j, i in enumerate(indices):
            valid = roi_valid[j]
            boxes = rois[j][valid] / scales[j]
            proposals[i] = np.hstack(
                [boxes, scores[j][valid][:, None]]).astype(np.float32)
    return proposals
