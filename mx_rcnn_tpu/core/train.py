"""The end-to-end training step: ONE XLA program per iteration.

Reference hot loop (SURVEY.md §3.1): ``MutableModule.fit`` →
``forward_backward`` with two device→host→device CustomOp bounces
(proposal, proposal_target) and host-side anchor assignment in
``AnchorLoader``; gradients synced per-array through KVStore.

TPU-native: everything from the (image, gt) batch onward — anchor targets,
RPN losses, proposal NMS, ROI sampling, ROIAlign, RCNN losses, backward,
SGD update — is traced into a single jitted function.  Data parallelism
wraps this same function (see ``mx_rcnn_tpu/parallel``), with gradient
``psum`` over ICI fused into the step.

Loss layout matches the reference train symbol (§3.5):
  rpn_cls:  softmax CE, ignore -1, normalized by valid anchors,
  rpn_bbox: smooth_l1(sigma=3) · weights / RPN_BATCH_SIZE,
  rcnn_cls: softmax CE over sampled ROIs (normalization='batch'),
  rcnn_bbox: smooth_l1(sigma=1) · weights / BATCH_ROIS,
all summed; the six training metrics of ``rcnn/core/metric.py`` are
returned per step from the same activations.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, NamedTuple, Tuple

import flax
import jax
import jax.numpy as jnp
import optax

from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.models.faster_rcnn import FasterRCNN
from mx_rcnn_tpu.ops.losses import (
    accuracy_with_ignore,
    softmax_cross_entropy_with_ignore,
    weighted_smooth_l1,
)
from mx_rcnn_tpu.ops.proposal import propose_batch
from mx_rcnn_tpu.ops.roi_pool import roi_align_batched
from mx_rcnn_tpu.ops.targets import anchor_target, proposal_target


class TrainState(NamedTuple):
    """Weights + optimizer slots (the analog of the Module's arg/aux params +
    optimizer state; see ref ``rcnn/core/module.py``)."""

    step: jnp.ndarray
    params: Any
    batch_stats: Any
    opt_state: Any


class Batch(NamedTuple):
    """Static-shape training batch (built host-side by the loader).

    images: (N, H, W, 3) padded into the bucket — uint8 raw RGB (the
      TPU-native default; normalized on device, see ops/normalize.py) or
      fp32 mean-subtracted (host-normalized path).
    im_info: (N, 3) — (real_h, real_w, scale) of the resized image.
    gt_boxes: (N, G, 4) padded gt boxes in input coordinates.
    gt_classes: (N, G) int32 class ids (1..C-1; 0 is background).
    gt_valid: (N, G) bool.
    """

    images: jnp.ndarray
    im_info: jnp.ndarray
    gt_boxes: jnp.ndarray
    gt_classes: jnp.ndarray
    gt_valid: jnp.ndarray


class RCNNBatch(NamedTuple):
    """Batch for RCNN-only training from PRECOMPUTED proposals (alternate
    training stages 2/4; ref ``ROIIter`` feeds ``get_rcnn_batch``).

    Same fields as :class:`Batch` plus the proposal buffer:
    rois: (N, R, 4) proposal boxes in input (scaled) coordinates.
    rois_valid: (N, R) bool.
    """

    images: jnp.ndarray
    im_info: jnp.ndarray
    gt_boxes: jnp.ndarray
    gt_classes: jnp.ndarray
    gt_valid: jnp.ndarray
    rois: jnp.ndarray
    rois_valid: jnp.ndarray


def _rpn_losses(model: FasterRCNN, rpn_cls, rpn_box, anchors, batch,
                key: jax.Array, cfg: Config):
    """Anchor targets + the two RPN losses (shared by e2e and RPN-only
    training so the objectives cannot drift apart).

    Returns (cls_loss, bbox_loss, metrics dict).
    """
    tr = cfg.train
    n = batch.images.shape[0]
    at = jax.vmap(
        functools.partial(
            anchor_target,
            rpn_batch_size=tr.rpn_batch_size,
            rpn_fg_fraction=tr.rpn_fg_fraction,
            positive_overlap=tr.rpn_positive_overlap,
            negative_overlap=tr.rpn_negative_overlap,
            clobber_positives=tr.rpn_clobber_positives,
            allowed_border=tr.rpn_allowed_border,
            bbox_weights=tr.rpn_bbox_weights,
        ),
        in_axes=(None, 0, 0, 0, 0),
    )(anchors, batch.gt_boxes, batch.gt_valid, batch.im_info,
      jax.random.split(key, n))

    rpn_cls32 = rpn_cls.astype(jnp.float32)
    cls_loss = softmax_cross_entropy_with_ignore(
        rpn_cls32.reshape(-1, 2), at.labels.reshape(-1), -1, "valid")
    bbox_loss = weighted_smooth_l1(
        rpn_box.astype(jnp.float32), at.bbox_targets, at.bbox_weights,
        sigma=3.0, grad_norm=tr.rpn_batch_size * n)
    metrics = {
        "rpn_acc": accuracy_with_ignore(rpn_cls32.reshape(-1, 2),
                                        at.labels.reshape(-1)),
        "rpn_logloss": cls_loss,
        "rpn_l1loss": bbox_loss,
    }
    return cls_loss, bbox_loss, metrics


def _rcnn_losses(model: FasterRCNN, variables, feat, rois, rois_valid,
                 batch, key: jax.Array, cfg: Config):
    """ROI sampling + pooled head + the two RCNN losses (shared by e2e and
    RCNN-only training).  ``rois`` come either from the in-graph proposal
    op (e2e) or from a precomputed buffer (alternate stages 2/4).

    Returns (cls_loss, bbox_loss, metrics dict).
    """
    tr = cfg.train
    n = batch.images.shape[0]
    k_prop, k_drop = jax.random.split(key)

    def one_img(rois_i, valid_i, gt_b, gt_c, gt_v, key_i):
        return proposal_target(
            rois_i, valid_i, gt_b, gt_c, gt_v, key_i,
            num_classes=model.num_classes,
            batch_rois=tr.batch_rois,
            fg_fraction=tr.fg_fraction,
            fg_thresh=tr.fg_thresh,
            bg_thresh_hi=tr.bg_thresh_hi,
            bg_thresh_lo=tr.bg_thresh_lo,
            bbox_means=tr.bbox_means,
            bbox_stds=tr.bbox_stds,
            gt_append=tr.gt_append)

    pt = jax.vmap(one_img)(
        rois, rois_valid, batch.gt_boxes, batch.gt_classes, batch.gt_valid,
        jax.random.split(k_prop, n))

    # 'auto' resolves to the einsum pair — the fused Pallas kernel wins
    # isolated but loses ~13 ms to custom-call boundary costs in the full
    # step (see ops/roi_pool.py roi_align_batched); 'blocked' runs the
    # same pair ROI-chunked (bit-equal forward, intermediate shrunk by
    # roi_align_chunk/R); 'pallas' opts into the kernel
    backend = None if tr.roi_align_backend == "auto" else tr.roi_align_backend
    pooled = roi_align_batched(feat, pt.rois, model.pooled_size,
                               1.0 / model.feat_stride,
                               backend=backend,
                               chunk=tr.roi_align_chunk)  # (N, B, ph, pw, C)
    flat = pooled.reshape((-1,) + pooled.shape[2:])
    cls_logits, bbox_deltas = model.apply(
        variables, flat, True, method=model.roi_head,
        rngs={"dropout": k_drop})
    cls_logits = cls_logits.astype(jnp.float32)
    bbox_deltas = bbox_deltas.astype(jnp.float32)

    labels = pt.labels.reshape(-1)
    # ref RCNN loss is normalization='batch', but the reference never emits
    # filler ROIs (sample_rois fills all BATCH_ROIS slots), so its batch
    # denominator always equals the valid count; 'valid' is the faithful
    # generalization when the proposal pool is too small to fill every slot
    cls_loss = softmax_cross_entropy_with_ignore(
        cls_logits, labels, -1, "valid")
    bbox_loss = weighted_smooth_l1(
        bbox_deltas, pt.bbox_targets.reshape(bbox_deltas.shape),
        pt.bbox_weights.reshape(bbox_deltas.shape),
        sigma=1.0, grad_norm=tr.batch_rois * n)
    metrics = {
        "rcnn_acc": accuracy_with_ignore(cls_logits, labels),
        "rcnn_logloss": cls_loss,
        "rcnn_l1loss": bbox_loss,
        "num_fg": pt.fg_mask.sum().astype(jnp.float32),
    }
    return cls_loss, bbox_loss, metrics


def _backbone_features(model: FasterRCNN, variables, batch, cfg: Config):
    """Backbone forward shared by the three training objectives; with
    ``cfg.train.remat_backbone`` the activations are rematerialized in the
    backward pass (jax.checkpoint) — numerically identical gradients,
    HBM for FLOPs (pinned equal by test)."""

    def f(v, images):
        return model.apply(v, images, batch.im_info, method=model.features)

    if cfg.train.remat_backbone:
        f = jax.checkpoint(f)
    return f(variables, batch.images)


def loss_and_metrics(  # graphlint: jit (traced via LOSS_FNS inside the step)
    model: FasterRCNN,
    params,
    batch_stats,
    batch: Batch,
    key: jax.Array,
    cfg: Config,
) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """Full train-mode forward; returns (total_loss, metrics)."""
    tr = cfg.train
    variables = {"params": params, "batch_stats": batch_stats}
    k_anchor, k_rcnn = jax.random.split(key)

    # named_scope on each stage: jax.profiler traces then attribute device
    # time per stage (tools/profile_step.py --trace_summary), the loop-free
    # fallback to the unrolled-chain timing
    with jax.named_scope("backbone"):
        feat = _backbone_features(model, variables, batch, cfg)
    with jax.named_scope("rpn_head"):
        rpn_cls, rpn_box = model.apply(variables, feat,
                                       method=model.rpn_raw)
    _, fh, fw, _ = feat.shape
    anchors = model.anchors_for(fh, fw)

    with jax.named_scope("rpn_losses"):
        rpn_cls_loss, rpn_bbox_loss, rpn_metrics = _rpn_losses(
            model, rpn_cls, rpn_box, anchors, batch, k_anchor, cfg)

    # ---- proposals (no gradient; ref Proposal/proposal_target CustomOps
    # define no backward) ---------------------------------------------------
    rpn_cls32 = jax.lax.stop_gradient(rpn_cls.astype(jnp.float32))
    fg_scores = jax.nn.softmax(rpn_cls32, axis=-1)[..., 1]
    rpn_box_sg = jax.lax.stop_gradient(rpn_box.astype(jnp.float32))

    with jax.named_scope("proposal"):
        # cross-image batched NMS sweep (r6): one tile-sweep loop nest for
        # the whole batch instead of B serialized chains under vmap —
        # decision-exact vs vmap(propose), pinned by tests/test_proposal.py
        rois, _, rois_valid = propose_batch(
            fg_scores, rpn_box_sg, anchors, batch.im_info,
            batched_nms=tr.nms_batched,
            pre_nms_top_n=tr.rpn_pre_nms_top_n,
            post_nms_top_n=tr.rpn_post_nms_top_n,
            nms_thresh=tr.rpn_nms_thresh,
            min_size=tr.rpn_min_size)
    with jax.named_scope("rcnn_losses"):
        rcnn_cls_loss, rcnn_bbox_loss, rcnn_metrics = _rcnn_losses(
            model, variables, feat, rois, rois_valid, batch, k_rcnn, cfg)

    total = rpn_cls_loss + rpn_bbox_loss + rcnn_cls_loss + rcnn_bbox_loss
    # the six reference metrics (rcnn/core/metric.py)
    metrics = {**rpn_metrics, **rcnn_metrics, "loss": total}
    return total, metrics


def loss_and_metrics_rpn(  # graphlint: jit (traced via LOSS_FNS)
    model: FasterRCNN,
    params,
    batch_stats,
    batch: Batch,
    key: jax.Array,
    cfg: Config,
) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """RPN-only training loss (alternate stages 1/3; ref ``get_vgg_rpn`` /
    ``train_rpn.py``): backbone → RPN heads → anchor targets → two losses.
    Shares ``_rpn_losses`` with the e2e objective."""
    variables = {"params": params, "batch_stats": batch_stats}
    feat = _backbone_features(model, variables, batch, cfg)
    rpn_cls, rpn_box = model.apply(variables, feat, method=model.rpn_raw)
    _, fh, fw, _ = feat.shape
    anchors = model.anchors_for(fh, fw)
    cls_loss, bbox_loss, metrics = _rpn_losses(
        model, rpn_cls, rpn_box, anchors, batch, key, cfg)
    total = cls_loss + bbox_loss
    return total, {**metrics, "loss": total}


def loss_and_metrics_rcnn(  # graphlint: jit (traced via LOSS_FNS)
    model: FasterRCNN,
    params,
    batch_stats,
    batch: RCNNBatch,
    key: jax.Array,
    cfg: Config,
) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """RCNN-only training loss from precomputed proposals (alternate stages
    2/4; ref ``train_rcnn.py`` + host-side ``sample_rois``).  Shares
    ``_rcnn_losses`` with the e2e objective."""
    variables = {"params": params, "batch_stats": batch_stats}
    feat = _backbone_features(model, variables, batch, cfg)
    cls_loss, bbox_loss, metrics = _rcnn_losses(
        model, variables, feat, batch.rois, batch.rois_valid, batch, key,
        cfg)
    total = cls_loss + bbox_loss
    return total, {**metrics, "loss": total}


LOSS_FNS = {
    "e2e": loss_and_metrics,
    "rpn": loss_and_metrics_rpn,
    "rcnn": loss_and_metrics_rcnn,
}


def init_variables(
    model: FasterRCNN,
    key: jax.Array,
    image_shape: Tuple[int, int, int, int],
):
    """Initialize all model variables in ONE compiled program.

    Returns (params, batch_stats).  (Ref analog: ``load_param`` + the
    Normal-init of new layers in ``train_end2end.py``; pretrained weights
    are grafted on top via ``utils/pretrained.py``.)"""
    def _init(key):
        images = jnp.zeros(image_shape, jnp.float32)
        variables = model.init(key, images, method=model.features)
        # also materialize RPN + head params with dummy shapes
        feat = model.apply(variables, images, method=model.features)
        k1, k2 = jax.random.split(key)
        v_rpn = model.init(k1, feat, method=model.rpn_raw)
        pooled = jnp.zeros((8,) + model.pooled_size + (feat.shape[-1],),
                           jnp.float32)
        v_head = model.init(k2, pooled, False, method=model.roi_head)
        params = {**variables["params"], **v_rpn["params"], **v_head["params"]}
        batch_stats = {
            **variables.get("batch_stats", {}),
            **v_rpn.get("batch_stats", {}),
            **v_head.get("batch_stats", {}),
        }
        return flax.core.freeze(params).unfreeze(), batch_stats

    # one compiled program instead of thousands of tunneled eager ops;
    # init runs once per process so discarding the jit cache is the point
    return jax.jit(_init)(key)  # graphlint: disable=GL302 one-shot init program


def init_state(
    model: FasterRCNN,
    key: jax.Array,
    tx: optax.GradientTransformation,
    image_shape: Tuple[int, int, int, int],
) -> TrainState:
    """init_variables + optimizer slots as a TrainState."""
    params, batch_stats = init_variables(model, key, image_shape)
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        batch_stats=batch_stats,
        opt_state=tx.init(params),
    )


def setup_training(
    model: FasterRCNN,
    cfg: Config,
    key: jax.Array,
    image_shape: Tuple[int, int, int, int],
    steps_per_epoch: int,
    **optimizer_kw,
):
    """One-stop builder: init variables ONCE, build the optimizer from the
    resulting param tree (no throwaway second init), assemble the state.

    Returns (state, tx).
    """
    from mx_rcnn_tpu.core.optim import make_optimizer

    params, batch_stats = init_variables(model, key, image_shape)
    tx = make_optimizer(cfg, params, steps_per_epoch, **optimizer_kw)
    state = TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        batch_stats=batch_stats,
        opt_state=tx.init(params),
    )
    return state, tx


def make_train_step(model: FasterRCNN, cfg: Config,
                    tx: optax.GradientTransformation,
                    axis_name: str | None = None, mode: str = "e2e",
                    grad_accum: int = 1):
    """Build the jittable train step.  When ``axis_name`` is set the step is
    meant to run under shard_map/pmap-style SPMD and gradients/metrics are
    psum-averaged over that mesh axis (the TPU replacement for MXNet
    ``kvstore='device'``).

    ``mode`` selects the loss: 'e2e' (full Faster R-CNN), 'rpn' (alternate
    stages 1/3, expects :class:`Batch`), 'rcnn' (stages 2/4, expects
    :class:`RCNNBatch` with precomputed proposals).

    ``grad_accum > 1`` builds the ACCUMULATING step the elastic controller
    (ft/elastic.py) uses to keep the effective global batch on-recipe on a
    shrunken mesh: the batch arrives with a leading microbatch axis
    (leaves shaped ``(grad_accum, N, ...)``), gradients and metrics are
    computed per microbatch under ``lax.map`` (serialized — peak
    activation memory stays that of ONE microbatch) and averaged, then
    ONE optimizer update applies.  ``state.step`` counts optimizer steps
    in both paths, so the LR schedule and step↔epoch mapping are
    accumulation-invariant by construction.  The per-microbatch RNG folds
    in the microbatch index on top of the step fold, so microbatches
    sample independently (``grad_accum=1`` keeps the exact pre-elastic
    key derivation — resume streams stay bit-identical).
    """
    loss_and_metrics_fn = LOSS_FNS[mode]

    # graphlint: jit (jitted by fit/parallel.dp after construction)
    def step(state: TrainState, batch, key: jax.Array
             ) -> Tuple[TrainState, Dict[str, jnp.ndarray]]:
        key = jax.random.fold_in(key, state.step)

        if grad_accum <= 1:
            def loss_fn(params):
                return loss_and_metrics_fn(model, params, state.batch_stats,
                                           batch, key, cfg)

            (_, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                state.params)
        else:
            def micro(idx_and_batch):
                idx, mb = idx_and_batch
                mkey = jax.random.fold_in(key, idx)

                def loss_fn(params):
                    return loss_and_metrics_fn(
                        model, params, state.batch_stats, mb, mkey, cfg)

                (_, m), g = jax.value_and_grad(loss_fn, has_aux=True)(
                    state.params)
                return g, m

            grads, metrics = jax.lax.map(
                micro, (jnp.arange(grad_accum, dtype=jnp.int32), batch))
            # mean over microbatches = the gradient of the mean loss over
            # the full effective batch (each microbatch is equal-sized)
            grads = jax.tree.map(lambda g: g.mean(axis=0), grads)
            metrics = jax.tree.map(lambda m: m.mean(axis=0), metrics)
        if axis_name is not None:
            grads = jax.lax.pmean(grads, axis_name)
            metrics = jax.lax.pmean(metrics, axis_name)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        new_state = TrainState(state.step + 1, params, state.batch_stats,
                               opt_state)
        return new_state, metrics

    return step
