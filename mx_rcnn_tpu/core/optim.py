"""Optimizer construction: SGD + momentum + weight decay + step-decay LR,
with reference ``FIXED_PARAMS`` freezing.

Reference: ``train_end2end.py — train_net`` configures
``optimizer='sgd'`` with ``momentum=0.9``, ``wd=0.0005``,
``lr`` warm-from-config with an ``MultiFactorScheduler`` stepping ×0.1 at
``lr_step`` epoch boundaries, and ``rcnn/core/module.py — MutableModule``
excludes parameters whose name starts with any ``fixed_param_prefix`` from
the update.

TPU-native: one ``optax`` chain; freezing is an explicit gradient mask over
the param tree (prefix match on the top-level module scope names, e.g.
``backbone/conv1_*`` for VGG, ``backbone/stage1_*``/``backbone/bn*`` for
ResNet).  MXNet applies weight decay to every parameter, so we do too.
"""

from __future__ import annotations

from typing import Iterable, Sequence, Tuple

import jax
import jax.numpy as jnp
import optax

from mx_rcnn_tpu.config import Config


def lr_schedule(base_lr: float, lr_step_epochs: Sequence[int],
                steps_per_epoch: int, factor: float = 0.1,
                warmup_step: int = 0, warmup_lr: float = 0.0
                ) -> optax.Schedule:
    """Step-decay schedule with optional linear warmup.

    Step decay follows ref MultiFactorScheduler semantics (multiply lr by
    ``factor`` when crossing each epoch boundary in ``lr_step``); warmup
    follows the upstream lineage's WarmupMultiFactorScheduler
    (``warmup='linear'``: ramp from ``warmup_lr`` to ``base_lr`` over
    ``warmup_step`` steps) — off by default, matters at large DP batch.
    """
    boundaries = {
        int(e) * steps_per_epoch: factor for e in lr_step_epochs if int(e) > 0
    }
    decay = optax.piecewise_constant_schedule(base_lr, boundaries)
    if warmup_step <= 0:
        return decay

    def schedule(count):
        # decay boundaries count from global step 0 (the ref scheduler also
        # counts warmup steps against the decay boundaries)
        frac = jnp.minimum(count / warmup_step, 1.0)
        warm = warmup_lr + (base_lr - warmup_lr) * frac
        return jnp.where(count < warmup_step, warm, decay(count))

    return schedule


def parse_lr_step(lr_step: str) -> Tuple[int, ...]:
    """'7' or '5,7' → (7,) / (5, 7) (ref: comma-separated epoch list)."""
    return tuple(int(s) for s in str(lr_step).split(",") if s.strip())


def frozen_mask(params, fixed_prefixes: Iterable[str]):
    """True = trainable, False = frozen.

    A parameter is frozen when any path component starts with one of the
    reference's FIXED_PARAMS prefixes (ref MutableModule fixed_param_prefix
    matching by substring of the MXNet param name).

    The reference ResNet FIXED_PARAMS also lists ``'gamma'``/``'beta'`` —
    MXNet names every BN affine ``*_gamma``/``*_beta``, so those two tokens
    freeze the affine of EVERY BatchNorm network-wide (statistics are frozen
    anyway; training an affine against frozen stats with weight decay is the
    divergence ADVICE r1 flagged).  Here the equivalent leaves are
    ``scale``/``bias`` directly under a ``bn*`` scope.
    """
    prefixes = tuple(fixed_prefixes)
    freeze_gamma = "gamma" in prefixes
    freeze_beta = "beta" in prefixes

    def trainable(path: Tuple, _leaf) -> bool:
        names = [getattr(k, "key", str(k)) for k in path]
        for name in names:
            if any(name.startswith(p) for p in prefixes):
                return False
        leaf = names[-1] if names else ""
        parent = names[-2] if len(names) > 1 else ""
        if parent.startswith("bn"):
            if freeze_gamma and leaf == "scale":
                return False
            if freeze_beta and leaf == "bias":
                return False
        return True

    return jax.tree_util.tree_map_with_path(trainable, params)


def make_optimizer(
    cfg: Config,
    params,
    steps_per_epoch: int,
    base_lr: float | None = None,
    lr_step: str | None = None,
    frozen_prefixes: Sequence[str] | None = None,
) -> optax.GradientTransformation:
    """SGD(momentum, wd) with step decay and FIXED_PARAMS freezing.

    ``params`` is only used to build the freeze mask pytree.
    """
    base_lr = cfg.default.e2e_lr if base_lr is None else base_lr
    lr_step = cfg.default.e2e_lr_step if lr_step is None else lr_step
    if frozen_prefixes is None:
        frozen_prefixes = cfg.network.fixed_params
    sched = lr_schedule(base_lr, parse_lr_step(lr_step), steps_per_epoch,
                        cfg.default.lr_factor,
                        warmup_step=cfg.default.warmup_step,
                        warmup_lr=cfg.default.warmup_lr)
    # momentum accumulator dtype: bfloat16 halves optimizer-state HBM and
    # bandwidth (config.default.momentum_dtype — TPU addition; float32 =
    # exact reference semantics); unknown spellings raise
    from mx_rcnn_tpu.config import validate_dtype_string

    md = validate_dtype_string(cfg.default.momentum_dtype,
                               "default__momentum_dtype")
    acc_dtype = jnp.bfloat16 if md == "bfloat16" else None
    sgd = optax.chain(
        # ref optimizer_params: elementwise clip_gradient=5 before update
        optax.clip(cfg.default.clip_gradient),
        optax.add_decayed_weights(cfg.default.wd),
        optax.sgd(learning_rate=sched, momentum=cfg.default.momentum,
                  accumulator_dtype=acc_dtype),
    )
    mask = frozen_mask(params, frozen_prefixes)
    return optax.chain(
        optax.masked(sgd, mask),
        optax.masked(optax.set_to_zero(), jax.tree.map(lambda t: not t, mask)),
    )
