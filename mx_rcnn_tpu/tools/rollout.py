"""Rollout-plane measurement protocol: ROLLOUT_r18
(docs/SERVING.md "Rollout tier").

Drives the REAL :class:`~mx_rcnn_tpu.serve.rollout.RolloutController`
over real ``tools/agent.py`` subprocesses on loopback ports — the same
rig (and the same honesty caveat: shared CPU core(s), so the numbers
validate the PLANE, not silicon) as the cross-host bench.  Legs:

1. **lineage** — the admission truth table over real exported stores:
   a v2 child admits against its recorded parent, an unknown parent and
   an unrooted version refuse, a ``train_fingerprint`` mismatch
   refuses, and a legacy version-less store still admits (back-compat);
2. **live swap** — two REAL tiny-model agents booted from a v1 store,
   a v2 store (same weights — an equivalence rollout) rolled out
   MID-BURST through pull → canary (online paired gate) → per-host
   rolling swap → finalize: every request terminates exactly once
   (0 lost), a post-swap mixed-bucket burst lowers ZERO new programs
   (0 unexpected recompiles — v2 serves from exported programs; the
   engine warm's own wrapper lowerings are recorded, not judged), each
   host pulled v2 exactly ONCE, and both hosts finish all-v2;
3. **red-team refusal** — a v2d store whose BUNDLED WEIGHTS are
   damaged (large additive noise): it passes lineage admission (the
   lineage is genuine — only behavior is wrong), the online paired
   gate refuses it on shadow-scored deltas, the controller
   auto-rollbacks, and every host ends base-only with 0 lost; a second
   rollback on top must be a recorded no-op (idempotence);
4. **kill mid-rollout** (full tier) — one agent SIGKILLed while its
   rolling swap is in flight: the controller defers it, finishes the
   fleet, and FINALIZE re-converges the relaunched (clean-disk) host —
   re-pull, re-swap — ending DONE with every host on v2;
5. **sim 100-host** (full tier) — the virtual-time canary-rollout
   scenario over the same controller at fleet scale, shipped and
   damaged-model arms (the gauntlet's rubric, summarized here so one
   JSON carries the whole protocol).

``--smoke`` runs legs 1-3 at 2 hosts (`make rollout-smoke`, ~2-3 min);
the full battery adds legs 4-5 and writes ``ROLLOUT_r18.json``.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
import tempfile
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from mx_rcnn_tpu.config import Config, generate_config
from mx_rcnn_tpu.tools.crosshost import (AgentProc, _free_ports,
                                         _prepared_set,
                                         _run_prepared_closed, _scrape)
from mx_rcnn_tpu.tools.loadgen import _drain, _smoke_overrides

logger = logging.getLogger("mx_rcnn_tpu")


def _damaged_variables(variables, scale: float, seed: int = 1):
    """The red-team arm's weights: every matrix/conv leaf gains
    additive noise ``scale`` x its own mean magnitude.  The lineage
    stays genuine (the damaged store records the true parent and its
    own true fingerprint) — only the model's BEHAVIOR is wrong, so
    nothing but the online paired gate can catch it."""
    rng = np.random.RandomState(seed)

    def walk(x):
        if isinstance(x, dict):
            return {k: walk(v) for k, v in x.items()}
        a = np.asarray(x)
        if a.ndim >= 2:
            noise = rng.standard_normal(a.shape).astype(a.dtype)
            return a + scale * (np.abs(a).mean() + 1e-3) * noise
        return a

    return walk(variables)


def _store_server(root: str):
    from mx_rcnn_tpu.serve.agent import make_store_server

    srv = make_store_server(root)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, f"http://127.0.0.1:{srv.server_address[1]}"


def _lineage_leg(cfg: Config, predictor, workdir: str, v1_root: str,
                 v2_root: str, problems: List[str]) -> Dict:
    """Leg 1: the admission truth table (satellite: lineage manifest
    fields + refusals + version-less back-compat)."""
    from mx_rcnn_tpu.serve.export import (ExportMismatch, ExportStore,
                                          export_serve_programs,
                                          manifest_sha,
                                          variables_fingerprint)

    legacy_root = os.path.join(workdir, "store_legacy")
    export_serve_programs(predictor, cfg, legacy_root, verify=False)

    sha1 = manifest_sha(v1_root)
    v1s, v2s = ExportStore(v1_root), ExportStore(v2_root)
    table: Dict[str, Dict] = {}

    def case(name: str, fn, expect_refused: bool):
        try:
            lineage = fn()
            table[name] = {"refused": False, "lineage": lineage}
        except ExportMismatch as e:
            table[name] = {"refused": True, "error": str(e)[:160]}
        if table[name]["refused"] != expect_refused:
            problems.append(
                f"lineage case {name}: expected refused="
                f"{expect_refused}, got {table[name]}")

    case("child_admits",
         lambda: v2s.check_lineage(known_parents={sha1}), False)
    case("unknown_parent_refused",
         lambda: v2s.check_lineage(known_parents={"0" * 64}), True)
    case("unrooted_refused",
         lambda: v1s.check_lineage(known_parents={sha1}), True)
    case("fingerprint_mismatch_refused",
         lambda: v2s.check_lineage(
             known_parents={sha1},
             expect_train_fingerprint="deadbeef"), True)
    case("fingerprint_match_admits",
         lambda: v2s.check_lineage(
             known_parents={sha1},
             expect_train_fingerprint=variables_fingerprint(
                 predictor.variables)), False)
    case("legacy_versionless_admits",
         lambda: ExportStore(legacy_root).check_lineage(
             known_parents={sha1}), False)
    return {"parent_sha": sha1[:16], "cases": table}


def _ver_counters(url: str) -> Dict[str, float]:
    """The per-version accounting series one agent exports
    (``fleet.ver.<label>.*`` — the canary health rules' inputs)."""
    try:
        snap = _scrape(url)
    except OSError:
        return {}
    return {k: v for k, v in (snap.get("counters") or {}).items()
            if k.startswith("fleet.ver.")}


def _host_state(port, admin, urls: List[str]) -> Dict[str, Dict]:
    out = {}
    for i, source in enumerate(sorted(admin.by_source)):
        versions = port.versions(source)
        rec = {"versions": versions}
        try:
            snap = _scrape(admin.by_source[source])
            rec["recompiles_after_warm"] = (
                snap["gauges"].get("agent.lowered_after_warm"))
        except OSError:
            rec["recompiles_after_warm"] = None
        out[source] = rec
    return out


def _burst(router, prepared, duration_s: float, concurrency: int,
           timeout_ms: float) -> Dict:
    box: Dict = {}

    def run():
        box["run"] = _run_prepared_closed(router, prepared, duration_s,
                                          concurrency, timeout_ms)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    box["thread"] = t
    return box


def _controller(port, cfg: Config, version: str, store_url: str):
    from mx_rcnn_tpu.serve.rollout import RolloutController

    return RolloutController(port, cfg, version=version,
                             store_url=store_url)


def _swap_leg_record(ctrl, run: Dict, snap: Dict, hosts: Dict) -> Dict:
    c = snap["counters"]
    return {
        "phase": ctrl.phase,
        "submitted": c["submitted"], "served": c["served"],
        "shed": c["shed"], "expired": c["expired"],
        "failed": c["failed"],
        "lost": c["submitted"] - snap["terminated"],
        "client": run["client"],
        "gate": ctrl.gate.verdict(),
        "events": [e["kind"] for e in ctrl.events],
        "hosts": hosts,
    }


def _check_exactly_once(name: str, leg: Dict,
                        problems: List[str]) -> None:
    if leg["lost"]:
        problems.append(f"{name}: lost {leg['lost']} requests")
    if leg["failed"] or leg["expired"]:
        problems.append(f"{name}: {leg['failed']} failed / "
                        f"{leg['expired']} expired mid-rollout — the "
                        "graceful drain path dropped work")
    if leg["served"] <= 0:
        problems.append(f"{name}: burst served nothing")


def run_rollout_bench(args) -> int:
    from mx_rcnn_tpu.analysis import sanitizer
    from mx_rcnn_tpu.serve.export import (CACHE_SUBDIR,
                                          enable_compile_cache,
                                          export_serve_programs)
    from mx_rcnn_tpu.serve.remote import build_crosshost_router
    from mx_rcnn_tpu.serve.rollout import (DONE, ROLLED_BACK,
                                           AgentRolloutPort)
    from mx_rcnn_tpu.serve.scheduler import AgentAdmin
    from mx_rcnn_tpu.tools.loadgen import init_predictor
    from mx_rcnn_tpu.tools.train import parse_set_overrides

    smoke = args.smoke
    overrides = dict(_smoke_overrides())  # tiny rig on both tiers: all
    # "hosts" share one box; the full tier differs in legs, not canvas
    overrides.update(parse_set_overrides(args))
    cfg = generate_config(args.network, args.dataset, **overrides)
    agent_overrides = dict(overrides)
    workdir = args.workdir or tempfile.mkdtemp(prefix="rollout_")
    os.makedirs(workdir, exist_ok=True)
    timeout_ms = 20_000.0 if args.timeout_ms is None else args.timeout_ms
    batch = cfg.serve.batch_size
    ch_over = {"connections": 2, "pipeline_depth": 4 * batch,
               "scrape_interval_s": 0.2, "io_timeout_s": 30.0,
               "admin_timeout_s": 30.0}  # a pull RPC blocks while the
    # agent downloads the store; refused sockets still fail instantly
    # controller cadence for a 2-host wall-clock rig: sample every tick,
    # judge after 6 pairs, 2s bake; one step timeout covers a clean-disk
    # agent relaunch (leg 4) so FINALIZE re-converges instead of
    # abandoning
    rcfg = cfg.replace_in("rollout", gate_min_pairs=6,
                          gate_sample_every=1, bake_s=2.0,
                          settle_s=0.25, step_timeout_s=45.0)
    rcfg = rcfg.replace_in("crosshost", **ch_over)
    rec: Dict = {
        "metric": "rollout_live_swap_exactly_once",
        "unit": "invariant",
        "measured": True,
        "smoke": smoke,
        "network": args.network,
        "bucket_shapes": [list(b) for b in cfg.bucket.shapes],
        "batch_size": batch,
        "host": {"physical_cores": os.cpu_count()},
        "note": "every 'host' is a separate agent process sharing this "
                "box's core(s): the invariants (exactly-once, 0 "
                "recompiles, gate refusal, re-convergence) validate "
                "the rollout PLANE, not multi-machine silicon",
    }
    problems: List[str] = []
    prepared = _prepared_set(cfg, args.images, args.seed)
    dur = min(args.duration, 4.0) if smoke else max(args.duration, 8.0)

    # -- stores: v1 (boot), v2 (same weights: equivalence), v2d (damaged)
    v1_root = os.path.join(workdir, "store_v1")
    v2_root = os.path.join(workdir, "store_v2")
    v2d_root = os.path.join(workdir, "store_v2d")
    logger.info("[rollout] exporting v1/v2/v2d stores -> %s", workdir)
    enable_compile_cache(os.path.join(v1_root, CACHE_SUBDIR))
    predictor = init_predictor(cfg, args.prefix, args.epoch, args.seed)
    export_serve_programs(predictor, cfg, v1_root, version="v1",
                          bundle_variables=True)
    export_serve_programs(predictor, cfg, v2_root, version="v2",
                          parent=v1_root, bundle_variables=True)
    damaged = type(predictor)(predictor.model,
                              _damaged_variables(predictor.variables,
                                                 scale=10.0), cfg)
    export_serve_programs(damaged, cfg, v2d_root, version="v2d",
                          parent=v1_root, verify=False,
                          bundle_variables=True)

    # -- 1. lineage admission truth table -------------------------------
    logger.info("[rollout] lineage leg ...")
    rec["lineage"] = _lineage_leg(cfg, predictor, workdir, v1_root,
                                  v2_root, problems)

    srv1, url1 = _store_server(v1_root)
    srv2, url2 = _store_server(v2_root)
    srv2d, url2d = _store_server(v2d_root)

    ports = _free_ports(4)
    logger.info("[rollout] launching 2 real agents ...")
    agents = [AgentProc(workdir, f"roll-{i}", ports[i], agent_overrides,
                        network=args.network, dataset=args.dataset,
                        replicas=1, store_url=url1,
                        export_dir=os.path.join(workdir,
                                                f"agent{i}_store"))
              for i in range(2)]
    try:
        for a in agents:
            a.wait_ready()
        urls = [a.url for a in agents]
        admin = AgentAdmin.from_config(urls, rcfg)
        port = AgentRolloutPort(admin)
        router, feed = build_crosshost_router(rcfg, urls)
        try:
            # -- 2. live v1 -> v2 swap mid-burst ------------------------
            logger.info("[rollout] live swap leg (v2 mid-burst) ...")
            ctrl = _controller(port, rcfg, "v2", url2)
            router.metrics.reset()  # per-leg accounting (bulk idiom)
            box = _burst(router, prepared, max(dur * 3, 12.0),
                         concurrency=2 * batch * 2,
                         timeout_ms=timeout_ms)
            phase = ctrl.run(timeout_s=300.0)
            box["thread"].join()
            _drain(router)
            hosts = _host_state(port, admin, urls)
            # the 0-unexpected-recompiles bar: v2 replicas warm from
            # EXPORTED programs, so a post-swap mixed-bucket burst must
            # lower NOTHING new (the warm itself pays the same few
            # wrapper lowerings any post-boot replica add pays — that
            # cost is recorded per host above, not judged)
            post = _run_prepared_closed(router, prepared,
                                        max(dur, 3.0),
                                        concurrency=2 * batch * 2,
                                        timeout_ms=timeout_ms)
            _drain(router)
            hosts_after = _host_state(port, admin, urls)
            leg = _swap_leg_record(ctrl, box["run"],
                                   router.metrics.snapshot(), hosts)
            leg["post_swap_client"] = post["client"]
            leg["recompiles_during_post_swap_burst"] = {
                s: (None
                    if hosts_after[s]["recompiles_after_warm"] is None
                    or hosts[s]["recompiles_after_warm"] is None
                    else hosts_after[s]["recompiles_after_warm"]
                    - hosts[s]["recompiles_after_warm"])
                for s in hosts}
            # one-transfer-per-host: a re-pull must be a recorded no-op
            leg["repull_already"] = [
                bool((port.pull(s, url2, "v2") or {}).get("already"))
                for s in sorted(admin.by_source)]
            leg["per_version_counters"] = {
                a.name: _ver_counters(a.url) for a in agents}
            rec["live_swap"] = leg
            if phase != DONE:
                problems.append(f"live swap ended {phase}, not done "
                                f"(events: {leg['events']})")
            _check_exactly_once("live swap", leg, problems)
            for src, h in hosts.items():
                if h["versions"] != {"v2": 1}:
                    problems.append(f"live swap: {src} ended "
                                    f"{h['versions']}, not all-v2")
            for src, delta in (
                    leg["recompiles_during_post_swap_burst"].items()):
                if delta != 0:
                    problems.append(
                        f"live swap: {src} lowered {delta} program(s) "
                        "during the post-swap burst — v2 is not serving "
                        "from its exported/warmed programs")
            if not (post["client"].get("ok", 0) > 0):
                problems.append("live swap: post-swap burst served "
                                "nothing — recompile delta is vacuous")
            if not all(leg["repull_already"]):
                problems.append("live swap: a re-pull was not a no-op "
                                "— one-transfer-per-host broken")
            if not leg["gate"]["judged"] or leg["gate"]["refused"]:
                problems.append(f"live swap gate did not pass: "
                                f"{leg['gate']}")
            if not any(k.endswith(".dispatched") for k in
                       {c for d in leg["per_version_counters"].values()
                        for c in d}):
                problems.append("no fleet.ver.* counters appeared — "
                                "per-version accounting never engaged")

            # -- 3. red-team: damaged weights, gate refusal -------------
            logger.info("[rollout] red-team leg (damaged v2d) ...")
            ctrl2 = _controller(port, rcfg, "v2d", url2d)
            router.metrics.reset()
            box = _burst(router, prepared, max(dur * 3, 12.0),
                         concurrency=2 * batch * 2,
                         timeout_ms=timeout_ms)
            phase = ctrl2.run(timeout_s=300.0)
            box["thread"].join()
            _drain(router)
            hosts = _host_state(port, admin, urls)
            leg = _swap_leg_record(ctrl2, box["run"],
                                   router.metrics.snapshot(), hosts)
            leg["rollback_reason"] = ctrl2.status()["rollback_reason"]
            leg["rollback_s"] = ctrl2.rollback_s
            leg["rollback_noop"] = ctrl2.rollback("operator")
            rec["redteam"] = leg
            if phase != ROLLED_BACK:
                problems.append(f"red-team ended {phase}, not "
                                f"rolled_back ({leg['events']})")
            if leg["rollback_reason"] != "gate_refused":
                problems.append(f"red-team rollback reason "
                                f"{leg['rollback_reason']!r}, not the "
                                "gate")
            if not leg["gate"]["refused"]:
                problems.append(f"gate did not refuse the damaged "
                                f"model: {leg['gate']}")
            _check_exactly_once("red-team", leg, problems)
            for src, h in hosts.items():
                if h["versions"] != {"base": 1}:
                    problems.append(f"red-team: {src} ended "
                                    f"{h['versions']}, not base-only")
            if not leg["rollback_noop"].get("noop"):
                problems.append("second rollback was not a no-op — "
                                "rollback is not idempotent")

            # -- 4. SIGKILL mid-rollout, relaunch, re-converge ----------
            if not smoke:
                logger.info("[rollout] kill-mid-rollout leg ...")
                rec["kill_rollout"] = _kill_leg(
                    args, agent_overrides, workdir, agents, ports,
                    port, admin, rcfg, url1, url2, problems)
        finally:
            feed.close()
            router.close()
    finally:
        for a in agents:
            a.kill()
        for srv in (srv1, srv2, srv2d):
            srv.shutdown()

    # -- 5. fleet-scale virtual-time arm --------------------------------
    if not smoke:
        logger.info("[rollout] sim 100-host leg ...")
        rec["sim_100h"] = _sim_leg(args.seed, problems)

    print(json.dumps(rec))
    if args.out:
        from mx_rcnn_tpu.tools.sim import _atomic_json

        _atomic_json(args.out, rec)
    if args.check:
        problems += sanitizer.check_problems()
        for msg in problems:
            logger.error("CHECK FAILED: %s", msg)
        return 1 if problems else 0
    return 0


def _kill_leg(args, agent_overrides: Dict, workdir: str,
              agents: List[AgentProc], ports: List[int], port, admin,
              rcfg: Config, url1: str, url2: str,
              problems: List[str]) -> Dict:
    """Leg 4: SIGKILL one agent while its rolling swap is in flight.
    The relaunch gets a CLEAN export dir (a replaced host, not a
    rebooted one): FINALIZE must re-pull v2 onto it and re-swap."""
    from mx_rcnn_tpu.serve.rollout import DONE, ROLLING, _TERMINAL

    ctrl = _controller(port, rcfg, "v2", url2)
    ctrl.start()
    killed_source = sorted(admin.by_source)[1]
    killed = False
    deadline = time.monotonic() + 420.0
    while ctrl.phase not in _TERMINAL and time.monotonic() < deadline:
        ctrl.step()
        if (not killed and ctrl.phase == ROLLING
                and any(e["kind"] == "host_rolling"
                        and e.get("source") == killed_source
                        for e in ctrl.events)):
            agents[1].sigkill()
            killed = True
            # immediate replacement on the same port, fresh disk; it
            # warms concurrently with the rest of the rollout
            agents[1] = AgentProc(
                workdir, "roll-1b", ports[1], agent_overrides,
                network=args.network, dataset=args.dataset, replicas=1,
                store_url=url1,
                export_dir=os.path.join(workdir, "agent1b_store"))
            threading.Thread(target=agents[1].wait_ready,
                             daemon=True).start()
        time.sleep(rcfg.rollout.settle_s)
    hosts = _host_state(port, admin, [a.url for a in agents])
    leg = {
        "phase": ctrl.phase,
        "killed": killed,
        "killed_source": killed_source,
        "events": [e["kind"] for e in ctrl.events],
        "deferred": ctrl.status()["deferred"],
        "hosts": hosts,
    }
    if not killed:
        problems.append("kill leg: never reached the kill point")
    if ctrl.phase != DONE:
        problems.append(f"kill leg ended {ctrl.phase}, not done "
                        f"({leg['events']})")
    if "host_deferred" not in leg["events"]:
        problems.append("kill leg: the killed host was never deferred "
                        "— the kill did not land mid-swap")
    for src, h in hosts.items():
        if h["versions"] != {"v2": 1}:
            problems.append(f"kill leg: {src} ended {h['versions']} — "
                            "FINALIZE did not re-converge the fleet")
    return leg


def _sim_leg(seed: int, problems: List[str]) -> Dict:
    """Leg 5: the 100-host virtual-time canary rollout, both arms —
    the same rubric the sim gauntlet pins (tools/sim.py)."""
    from mx_rcnn_tpu.sim.traffic import generate
    from mx_rcnn_tpu.tools.sim import MISTUNED_BY_SCENARIO, _arm

    cfg = generate_config("tiny", "synthetic")
    trace = generate("canary_rollout", cfg, 100, max(seed, 0))
    shipped = _arm(trace, cfg, "shipped")
    mistuned = _arm(trace, cfg, "mistuned",
                    MISTUNED_BY_SCENARIO["canary_rollout"])

    def summary(s: Dict) -> Dict:
        r = s.get("rollout") or {}
        return {"lost": s["lost"], "served": s["served"],
                "phase": r.get("phase"), "reason": r.get("reason"),
                "final_versions": r.get("final_versions"),
                "gate": r.get("gate"), "wall_s": s["wall_s"]}

    leg = {"hosts": trace["hosts"], "seed": trace["seed"],
           "shipped": summary(shipped), "mistuned": summary(mistuned)}
    sh, mi = leg["shipped"], leg["mistuned"]
    if sh["phase"] != "done" or sh["final_versions"] != {"v2": 100}:
        problems.append(f"sim shipped arm: {sh}")
    if sh["lost"] or mi["lost"]:
        problems.append(f"sim lost requests: shipped {sh['lost']}, "
                        f"mistuned {mi['lost']}")
    if (mi["phase"] != "rolled_back" or mi["reason"] != "gate_refused"
            or set(mi["final_versions"] or {}) != {"base"}):
        problems.append(f"sim mistuned arm not refused+rolled back: "
                        f"{mi}")
    return leg


def main(argv=None) -> int:
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(message)s")
    from mx_rcnn_tpu.analysis import sanitizer
    from mx_rcnn_tpu.tools.train import add_set_arg

    sanitizer.maybe_install_from_env()
    p = argparse.ArgumentParser(
        description="Rollout-plane bench: ROLLOUT_r18 protocol "
                    "(docs/SERVING.md 'Rollout tier')")
    p.add_argument("--network", default="tiny",
                   choices=["vgg", "resnet50", "resnet101", "tiny"])
    p.add_argument("--dataset", default="synthetic",
                   choices=["PascalVOC", "coco", "synthetic",
                            "synthetic_hard"])
    p.add_argument("--prefix", default=None,
                   help="checkpoint prefix (default: random init — "
                        "the invariants do not depend on weights)")
    p.add_argument("--epoch", type=int, default=0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--duration", type=float, default=4.0,
                   help="per-leg burst window, seconds")
    p.add_argument("--timeout_ms", type=float, default=None)
    p.add_argument("--images", type=int, default=16)
    p.add_argument("--workdir", default=None,
                   help="scratch dir for stores/agent logs (default: "
                        "mkdtemp)")
    p.add_argument("--out", default=None,
                   help="write the protocol record here "
                        "(ROLLOUT_r18.json)")
    p.add_argument("--smoke", action="store_true",
                   help="gate scale: legs 1-3 at 2 hosts, ~2-3 min "
                        "(make rollout-smoke)")
    p.add_argument("--check", action="store_true",
                   help="exit nonzero on any violated invariant")
    add_set_arg(p)
    args = p.parse_args(argv)
    return run_rollout_bench(args)


if __name__ == "__main__":
    sys.exit(main())
