"""Fleet observability CLI: live view, SLO check, black-box dump, smoke.

No reference equivalent.  The consumer end of the time-series plane
(``obs/timeseries.py`` + ``collect.py`` + ``health.py`` +
``flightrec.py`` — docs/OBSERVABILITY.md "Time-series plane"):

* ``watch`` — a live fleet top: scrape the given ``/metrics`` sources
  every interval and print the merged, source-labeled view (rates,
  gauges, verdict) as one line-oriented table per tick;
* ``check`` — the exit-code surface ROADMAP item 2's scheduler and
  item 3's canary gate consume: scrape the sources ``--samples`` times,
  fold each merged view into a local time-series window
  (``collect.view_to_snapshot``), evaluate the default SLO rules, print
  the verdict document as JSON and exit ``0`` OK / ``1`` WARN / ``2``
  CRITICAL (``3`` = no source reachable — unknown is not healthy);
* ``dump`` — write a flight-style record of the current merged view to
  a file (the manual black-box pull for a live incident);
* ``smoke`` — ``make health-smoke``: an observed 2-replica fleet burst
  with a mid-burst replica kill, asserting the merged labeled view
  (replica + elastic sources), the CRITICAL→OK verdict transition
  around the relaunch, and a parseable flight record that names the
  ejected replica.  Runs the stub-model fleet by default (CPU tier —
  the router/obs path is what is under test); ``--export`` runs the
  AOT export-warmed fleet instead (the full measured deliverable, at
  the cost of the export build).

Sources: ``--url host:port`` (repeatable, optionally ``name=url``) or
``cfg.obs.collect_urls``; every elastic worker / train / serve process
with ``obs.metrics_port`` set is scrapeable, and the serving front
end's ``/metrics`` is accepted in both shapes.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
import time

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.obs.collect import (Collector, HttpSource,
                                     collector_for_fleet,
                                     sources_from_urls, view_to_snapshot)
from mx_rcnn_tpu.obs.health import (EXIT_BY_VERDICT, HealthEngine,
                                    default_rules)
from mx_rcnn_tpu.obs.timeseries import TimeSeriesStore
from mx_rcnn_tpu.tools.train import add_set_arg, parse_set_overrides

logger = logging.getLogger("mx_rcnn_tpu")

EXIT_NO_SOURCE = 3


def _collector_from_args(args, cfg) -> Collector:
    urls = list(args.url or [])
    if not urls and cfg.obs.collect_urls:
        urls = [cfg.obs.collect_urls]
    sources = []
    for u in urls:
        sources.extend(sources_from_urls(u))
    return Collector(sources)


def _fmt_view(view) -> str:
    """One human block per collection: per-source status line + the
    headline aggregate numbers."""
    lines = []
    for name, src in sorted(view["sources"].items()):
        if not src.get("up"):
            lines.append(f"  {name:<14} DOWN")
            continue
        lab = src.get("labels", {})
        gen = lab.get("generation")
        extras = f" gen={gen}" if gen is not None else ""
        c = src.get("counters", {})
        served = c.get("serve.served", c.get("served"))
        lines.append(f"  {name:<14} up{extras}"
                     + (f" served={served}" if served is not None else ""))
    agg = view["agg"]["counters"]
    head = {k: v for k, v in sorted(agg.items())
            if k.startswith(("serve.", "bulk.", "train."))}
    lines.append(f"  agg: {json.dumps(head)[:160]}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# watch / check / dump
# ---------------------------------------------------------------------------

def cmd_watch(args) -> int:
    cfg = generate_config(args.network, args.dataset,
                          **parse_set_overrides(args))
    collector = _collector_from_args(args, cfg)
    if not collector.names():
        print("obs watch: no sources (pass --url or set "
              "obs.collect_urls)", file=sys.stderr)
        return EXIT_NO_SOURCE
    n = 0
    try:
        while args.iterations <= 0 or n < args.iterations:
            view = collector.collect()
            print(f"-- {time.strftime('%H:%M:%S')}  "
                  f"{view['up']}/{len(view['sources'])} sources up")
            print(_fmt_view(view), flush=True)
            n += 1
            if args.iterations <= 0 or n < args.iterations:
                time.sleep(args.interval_s)
    except KeyboardInterrupt:
        pass
    return 0


def run_check(collector: Collector, cfg, samples: int,
              interval_s: float) -> dict:
    """The check protocol, callable in-process (the smoke reuses it
    against a live fleet's collector): scrape ``samples`` times into a
    local window, judge the default rules over it, return the verdict
    document (plus the final merged view under ``"view"``)."""
    store = TimeSeriesStore(capacity=max(samples + 2, 16))
    engine = HealthEngine(default_rules(cfg), store)
    view = None
    up = 0
    ever_up: set = set()
    for i in range(samples):
        view = collector.collect()
        up = view["up"]
        ever_up.update(name for name, src in view["sources"].items()
                       if src.get("up"))
        store.append_snapshot(view_to_snapshot(view), ts=view["ts"])
        engine.evaluate()
        if i < samples - 1:
            time.sleep(interval_s)
    verdict = dict(engine.last() or {"verdict": "OK", "code": 0,
                                     "rules": [], "firing": []})
    verdict["sources_up"] = up
    verdict["samples"] = samples
    # a configured source that answered NO scrape the whole check: it
    # contributed zero samples, so every gauge-backed judgment treats
    # it exactly like a downed one (absent, not stale) — but the
    # operator should see the distinction spelled out
    verdict["never_up"] = sorted(set(collector.names()) - ever_up)
    if view is not None:
        verdict["view"] = {
            name: ({"up": src.get("up", False),
                    **({"labels": src["labels"]} if src.get("up")
                       else {})})
            for name, src in view["sources"].items()}
    return verdict


def cmd_check(args) -> int:
    cfg = generate_config(args.network, args.dataset,
                          **parse_set_overrides(args))
    collector = _collector_from_args(args, cfg)
    if not collector.names():
        print("obs check: no sources (pass --url or set "
              "obs.collect_urls)", file=sys.stderr)
        return EXIT_NO_SOURCE
    verdict = run_check(collector, cfg, args.samples, args.interval_s)
    print(json.dumps(verdict, indent=1))
    if verdict["sources_up"] == 0:
        return EXIT_NO_SOURCE  # unknown is not healthy
    return EXIT_BY_VERDICT[verdict["verdict"]]


def cmd_dump(args) -> int:
    cfg = generate_config(args.network, args.dataset,
                          **parse_set_overrides(args))
    collector = _collector_from_args(args, cfg)
    if not collector.names():
        print("obs dump: no sources (pass --url or set "
              "obs.collect_urls)", file=sys.stderr)
        return EXIT_NO_SOURCE
    view = collector.collect()
    record = {"schema": "mx_rcnn_tpu.flight/2", "reason": "manual",
              "ts": view["ts"], "pid": os.getpid(), "view": view}
    from mx_rcnn_tpu.utils.checkpoint import _atomic_write

    out = args.out or f"flight-manual-{int(view['ts'])}.json"
    os.makedirs(os.path.dirname(os.path.abspath(out)), exist_ok=True)
    _atomic_write(out, json.dumps(record, indent=1).encode())
    print(f"obs dump: {view['up']}/{len(view['sources'])} sources "
          f"-> {out}")
    return 0 if view["up"] > 0 else EXIT_NO_SOURCE


# ---------------------------------------------------------------------------
# smoke (make health-smoke)
# ---------------------------------------------------------------------------

def run_smoke(args) -> dict:
    """Observed 2-replica fleet burst with a mid-burst replica kill.

    The whole plane runs as production wires it — ``cli_obs`` builds
    the store/sampler/health/flight from cfg, the fleet publishes its
    gauges through ``ReplicaManager.export_gauges`` and its
    eject/rejoin events through the run record, and an elastic-shaped
    registry is scraped over REAL HTTP so the merged view crosses a
    process boundary the way a live elastic world does.  Assertions
    (folded into ``ev["problems"]``):

    * merged view: both replicas up with ``source``/``generation``
      labels, the elastic HTTP source up, counters aggregated;
    * kill-mid-burst: verdict transitions to CRITICAL on the eject and
      back to OK after the relaunch (`fleet-degraded` rule);
    * flight: a ``health-critical`` record written, parseable, schema-
      tagged, and naming the ejected replica (the ``fleet_eject``
      event + the router's healthz context);
    * ``run_check`` over the live collector returns OK with exit-code
      semantics once the fleet has healed.
    """
    import tempfile
    import threading

    import numpy as np

    from mx_rcnn_tpu.models import build_model
    from mx_rcnn_tpu.obs.metrics import Registry, registry, \
        start_metrics_server
    from mx_rcnn_tpu.obs.runrec import cli_obs
    from mx_rcnn_tpu.serve.fleet import build_fleet
    from mx_rcnn_tpu.serve.queue import (DeadlineExceeded, RequestFailed,
                                         ShedError)
    from mx_rcnn_tpu.tools.loadgen import (_smoke_overrides,
                                           make_stub_run_fn,
                                           synthetic_images)

    workdir = args.workdir or tempfile.mkdtemp(prefix="health_smoke_")
    os.makedirs(workdir, exist_ok=True)
    overrides = dict(_smoke_overrides())
    overrides.update({
        "fleet__replicas": 2, "fleet__health_interval_s": 0.2,
        "obs__enabled": True, "obs__run_dir": os.path.join(workdir,
                                                           "runs"),
        "obs__timeseries": True, "obs__sample_interval_s": 0.1,
        "obs__health": True, "obs__flight": True,
        "obs__flight_window_s": 60.0,
    })
    overrides.update(parse_set_overrides(args))
    cfg = generate_config(args.network, args.dataset, **overrides)
    registry().reset()

    ev: dict = {"workdir": workdir, "problems": []}
    problems = ev["problems"]

    # model/variables once (stub run_fn replaces the forward; --export
    # runs the real AOT-warmed path instead)
    import jax

    from mx_rcnn_tpu.core.train import init_variables

    model = build_model(cfg)
    params, batch_stats = init_variables(
        model, jax.random.PRNGKey(0),
        (1,) + tuple(cfg.bucket.shapes[0]) + (3,))
    variables = {"params": params, "batch_stats": batch_stats}

    export_root = None
    factory = (lambda rid: make_stub_run_fn(cfg, args.stub_ms,
                                            seed=rid))
    if args.export:
        from mx_rcnn_tpu.serve.export import export_serve_programs

        export_root = os.path.join(workdir, "store")
        export_serve_programs(cfg, model, variables, export_root)
        factory = None

    obs_sess = cli_obs(cfg, "health_smoke")
    assert obs_sess is not None and obs_sess.health is not None

    # the elastic-shaped peer: its own registry behind a REAL HTTP
    # exporter, so the collector demonstrably crosses a process-style
    # boundary (the gauges mirror what ft/elastic.py publishes)
    elastic_reg = Registry()
    elastic_reg.set_gauge("elastic.generation", 1)
    elastic_reg.set_gauge("elastic.num_devices", 1)
    elastic_reg.observe("elastic.recovery_ms", 1500.0, lo=1.0,
                        hi=600_000.0)
    elastic_srv = start_metrics_server(elastic_reg, port=0)
    elastic_url = "http://%s:%d/metrics" % elastic_srv.server_address[:2]

    router = build_fleet(cfg, model, variables, export_root=export_root,
                         run_fn_factory=factory,
                         record=obs_sess.record)
    verdicts = []
    seen_lock = threading.Lock()

    def on_verdict(_smp):
        obs_sess.health.evaluate()
        v = obs_sess.health.verdict
        with seen_lock:
            if not verdicts or verdicts[-1] != v:
                verdicts.append(v)

    # rebind the sampler hook so the smoke records the verdict SEQUENCE
    obs_sess.sampler._after = on_verdict
    obs_sess.flight.add_context("fleet", router.healthz)

    try:
        collector = collector_for_fleet(
            router, extra_sources=[HttpSource("elastic-0", elastic_url)])
        view = collector.collect()
        for want in ("replica-0", "replica-1", "elastic-0", "router"):
            src = view["sources"].get(want)
            if not (src and src.get("up")):
                problems.append(f"source {want} not up in merged view: "
                                f"{src}")
        for rid in (0, 1):
            lab = view["sources"].get(f"replica-{rid}", {}).get(
                "labels", {})
            if lab.get("source") != f"replica-{rid}" \
                    or lab.get("generation") != 1:
                problems.append(f"replica-{rid} labels wrong: {lab}")
        if "elastic.generation" not in view["agg"]["gauges"]:
            problems.append("elastic gauges missing from merged view")

        # closed-loop burst with a mid-burst kill (the
        # loadgen._kill_mid_burst_leg pattern, obs-instrumented)
        images = synthetic_images(cfg, 8)
        concurrency = 2 * cfg.serve.batch_size * 2
        duration_s = args.duration_s
        stop = time.monotonic() + duration_s
        kill_at = time.monotonic() + duration_s / 3.0
        outcomes = {"ok": 0, "shed": 0, "expired": 0, "failed": 0}
        olock = threading.Lock()

        def worker(wid: int):
            i = wid
            while time.monotonic() < stop:
                try:
                    router.detect(images[i % len(images)],
                                  timeout_ms=5000.0)
                    key = "ok"
                except ShedError:
                    key = "shed"
                except DeadlineExceeded:
                    key = "expired"
                except (RequestFailed, TimeoutError):
                    key = "failed"
                i += concurrency
                with olock:
                    outcomes[key] += 1

        threads = [threading.Thread(target=worker, args=(w,),
                                    daemon=True)
                   for w in range(concurrency)]
        for t in threads:
            t.start()
        while time.monotonic() < kill_at:
            time.sleep(0.02)
        victim = router.manager.replicas[0]
        victim.engine.kill()
        kill_t = time.monotonic()
        for t in threads:
            t.join()
        # wait out the relaunch
        rejoin_s = None
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            if victim.ready() and victim.generation >= 2:
                rejoin_s = round(victim.joins[-1]["ready_t"] - kill_t, 2)
                break
            time.sleep(0.05)
        if rejoin_s is None:
            problems.append("victim replica never relaunched")
        # let the sampler observe the healed fleet
        ok_deadline = time.monotonic() + 10.0
        while (obs_sess.health.verdict != "OK"
               and time.monotonic() < ok_deadline):
            time.sleep(0.1)
        with seen_lock:
            seq = list(verdicts)
        ev["verdict_sequence"] = seq
        ev["outcomes"] = outcomes
        ev["rejoin_s"] = rejoin_s
        if "CRITICAL" not in seq:
            problems.append(f"no CRITICAL transition on kill: {seq}")
        if not seq or seq[-1] != "OK":
            problems.append(f"verdict did not recover to OK: {seq}")

        # the check surface against the (healed) live fleet
        check = run_check(collector, cfg, samples=3, interval_s=0.2)
        ev["check"] = {k: check[k] for k in ("verdict", "code",
                                             "sources_up")}
        if check["verdict"] != "OK":
            firing = [r for r in check["rules"] if r["firing"]]
            problems.append(f"post-heal check not OK: {firing}")
        if check["sources_up"] < 4:
            problems.append(f"check saw {check['sources_up']} sources, "
                            "wanted 4")

        # flight record: written, parseable, names the ejected replica
        dumps = [p for p in obs_sess.flight.dumps
                 if "health-critical" in p]
        ev["flight_dumps"] = list(obs_sess.flight.dumps)
        if not dumps:
            problems.append("no health-critical flight record written")
        else:
            with open(dumps[0]) as f:
                rec = json.load(f)
            if rec.get("schema") != "mx_rcnn_tpu.flight/2":
                problems.append(f"flight schema wrong: "
                                f"{rec.get('schema')}")
            if not rec.get("samples"):
                problems.append("flight record has no samples")
            ejected = [e for e in rec.get("events", [])
                       if e.get("event") == "fleet_eject"]
            if not any(e.get("replica") == victim.id for e in ejected):
                problems.append(f"flight record does not name ejected "
                                f"replica {victim.id}: {ejected}")
            ctx = rec.get("context", {}).get("fleet", {})
            if not any(r.get("id") == victim.id
                       for r in ctx.get("replicas", [])):
                problems.append("fleet context missing from flight "
                                "record")
        # scrape shape: the serving /metrics carries the timeseries
        # section while the store is active (the obs-smoke twin assert,
        # here over the fleet front end's snapshot path)
        snap = router.metrics.snapshot()
        snap["timeseries"] = (obs_sess.store.scrape_section()
                              if obs_sess.store else None)
        if not snap["timeseries"] or snap["timeseries"]["samples"] < 5:
            problems.append(f"timeseries store thin: "
                            f"{snap['timeseries']}")
    finally:
        router.close()
        elastic_srv.shutdown()
        elastic_srv.server_close()
        obs_sess.close(metric="health_smoke_requests",
                       value=None, unit="requests")
    ev["ok"] = not problems
    return ev


def cmd_smoke(args) -> int:
    ev = run_smoke(args)
    print("HEALTH_SMOKE " + json.dumps(
        {k: v for k, v in ev.items() if k != "check"} | {
            "check": ev.get("check")}, default=repr))
    if args.check:
        for p in ev["problems"]:
            print(f"HEALTH_SMOKE_PROBLEM {p}", file=sys.stderr)
        return 0 if ev["ok"] else 1
    return 0


# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(message)s")
    from mx_rcnn_tpu.analysis import sanitizer

    sanitizer.maybe_install_from_env()
    p = argparse.ArgumentParser(
        description="Fleet observability: watch / check / dump / smoke "
                    "(docs/OBSERVABILITY.md)")
    sub = p.add_subparsers(dest="cmd", required=True)

    def common(sp):
        sp.add_argument("--network", default="tiny",
                        choices=["vgg", "resnet50", "resnet101", "tiny"])
        sp.add_argument("--dataset", default="synthetic",
                        choices=["PascalVOC", "coco", "synthetic",
                                 "synthetic_hard"])
        sp.add_argument("--url", action="append", default=[],
                        help="/metrics source (host:port, URL, or "
                             "name=url; repeatable, comma-lists ok)")
        add_set_arg(sp)

    w = sub.add_parser("watch", help="live merged fleet view")
    common(w)
    w.add_argument("--interval_s", type=float, default=2.0)
    w.add_argument("--iterations", type=int, default=0,
                   help="stop after N collections (0 = forever)")
    w.set_defaults(fn=cmd_watch)

    c = sub.add_parser("check", help="SLO verdict with exit code "
                                     "(0 OK / 1 WARN / 2 CRITICAL / "
                                     "3 no source)")
    common(c)
    c.add_argument("--samples", type=int, default=5)
    c.add_argument("--interval_s", type=float, default=1.0)
    c.set_defaults(fn=cmd_check)

    d = sub.add_parser("dump", help="manual flight-style dump of the "
                                    "merged view")
    common(d)
    d.add_argument("--out", default=None)
    d.set_defaults(fn=cmd_dump)

    s = sub.add_parser("smoke", help="make health-smoke: kill-mid-burst "
                                     "verdict + flight-record assertions")
    common(s)
    s.add_argument("--workdir", default=None)
    s.add_argument("--duration_s", type=float, default=6.0)
    s.add_argument("--stub_ms", type=float, default=15.0,
                   help="stub model time per batch (stub fleet mode)")
    s.add_argument("--export", action="store_true",
                   help="run the AOT export-warmed fleet instead of "
                        "the stub (slower; the full deliverable)")
    s.add_argument("--check", action="store_true",
                   help="exit nonzero when any assertion fails")
    s.set_defaults(fn=cmd_smoke)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
