"""Dataset-scale input-plane benchmark: the COCO-cardinality rehearsal.

Reference: none — the reference assumes a downloaded COCO and a loader
fast enough for one GPU.  This tool is the measurement half of the r7
streaming input plane (docs/DATA.md): every pre-r7 loader/cache/pool
claim was made on <=400-image sets that fit in HBM, while the reference
trained 118k-image epochs.  It drives a 10-50k-image, 80-class synthetic
set (``data/synthetic.py — StreamSyntheticDataset``) through the REAL
input path end to end and records a BENCH-style JSON with ``--check``
invariants:

* **shard rig** — N real worker PROCESSES each own a row shard of the
  topology-invariant streaming plan and consume one full epoch;
  invariant: the union of decoded (image, flip) identities is the epoch
  EXACTLY ONCE, and each process decoded ~total/N (the multi-process
  decode-1/N claim, measured not asserted).
* **streaming epoch** — one full epoch through StreamLoader → bounded
  decoded-image cache (budget derived under ``data.ram_ceiling_mb``) →
  double-buffered host→device staging (``data/staging.py``) → a jitted
  device consumer; invariants: exactly-once, ZERO lowerings in the
  timed pass, peak RSS under the configured ceiling, sustained imgs/s
  against ``--min_rate`` (the 2x PR-5 38.2 imgs/s bar for the full
  rehearsal), plus a per-stage ms table for the host-bound argument.
* **eval leg** — the test split through the real eval loader
  (``TestLoader``), the input half of ``pred_eval``.
* **small-set control** — a REAL ``train_net`` run (tiny model) with
  streaming + staging + obs on; invariant: ``data_wait_frac ~ 0`` (the
  double-buffering claim) and a non-zero stage-overlap counter.

``--smoke`` shrinks every leg to gate scale (``make data-smoke``,
wired into ``make test-gate``); ``--worker`` is the internal shard-rig
entry (one process, one shard, ids out to a file).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time


def _vm_peak_mb() -> float:
    """Peak RSS (VmHWM) of this process in MiB, from /proc."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmHWM:"):
                    return int(line.split()[1]) / 1024.0
    except OSError:
        pass
    return 0.0


def _vm_rss_mb() -> float:
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) / 1024.0
    except OSError:
        pass
    return 0.0


def _canvas_overrides(args) -> dict:
    """``--coco_canvas``: run the pipeline at the PRODUCTION bucket
    canvas (PR 5's sublane-friendly 640x1024 / ref scale 600 capped at
    1000) instead of the 240x320 rehearsal canvas — the honest per-byte
    pixel-rate leg docs/DATA.md records (a 240x320 rate says nothing
    about decoding COCO-resolution pixels)."""
    if not getattr(args, "coco_canvas", False):
        return {}
    return {"bucket__scale": 600, "bucket__max_size": 1000,
            "bucket__shapes": ((640, 1024), (1024, 640))}


def _source_size_kw(args) -> dict:
    """Synthetic source-image size for the materialized set:
    ``--image_size HxW`` explicit, or 480x640 (the modal COCO source
    size) under ``--coco_canvas``."""
    if getattr(args, "image_size", None):
        h, w = (int(x) for x in args.image_size.split("x"))
        return {"image_size": (h, w)}
    if getattr(args, "coco_canvas", False):
        return {"image_size": (480, 640)}
    return {}


def _build(args, shard=None):
    """(cfg, roidb, loader) for the train split streaming epoch."""
    from mx_rcnn_tpu.config import generate_config
    from mx_rcnn_tpu.data import load_gt_roidb
    from mx_rcnn_tpu.data.loader import StreamLoader, cache_from_config

    over = ({"dataset__dataset_path": args.dataset_path}
            if args.dataset_path else {})
    over.update(_canvas_overrides(args))
    cfg = generate_config(
        args.network, args.dataset,
        dataset__root_path=args.root_path,
        train__flip=False,  # epoch cardinality = unique images, exactly
        data__ram_ceiling_mb=args.ram_ceiling_mb,
        data__streaming=True,
        default__num_workers=args.num_workers,
        default__decode_procs=args.decode_procs,
        obs__enabled=False, **over)
    kw = {"num_images": args.num_images}
    kw.update(_source_size_kw(args))
    _, roidb = load_gt_roidb(cfg, training=True, **kw)
    bh, bw = cfg.bucket.shapes[0]
    cache = cache_from_config(cfg, n_images=len(roidb),
                              image_bytes=bh * bw * 3,
                              batch_bytes=args.batch_images * bh * bw * 3)
    loader = StreamLoader(roidb, cfg, batch_images=args.batch_images,
                          shuffle=True, seed=args.seed, cache=cache,
                          shard=shard)
    loader.record_decodes()
    loader.set_epoch(0)
    return cfg, roidb, loader


def run_worker(args) -> int:
    """Shard-rig worker: consume one epoch of shard (shard_id/num_shards),
    dump decoded identities + stats to --ids_out."""
    _, roidb, loader = _build(args, shard=(args.shard_id, args.num_shards))
    t0 = time.perf_counter()
    batches = sum(1 for _ in loader)
    wall = time.perf_counter() - t0
    with open(args.ids_out, "w") as f:
        json.dump({"shard_id": args.shard_id,
                   "num_shards": args.num_shards,
                   "images_decoded": loader.images_decoded,
                   "batches": batches,
                   "wall_s": round(wall, 3),
                   "peak_rss_mb": round(_vm_peak_mb(), 1),
                   "ids": sorted(loader.decoded_ids)}, f)
    return 0


def _spawn_shard_rig(args):
    """N real processes, each owning one shard of the same epoch plan."""
    outs = []
    procs = []
    tmp = tempfile.mkdtemp(prefix="data_bench_rig_")
    for s in range(args.num_shards):
        ids_out = os.path.join(tmp, f"shard{s}.json")
        outs.append(ids_out)
        cmd = [sys.executable, "-m", "mx_rcnn_tpu.tools.data_bench",
               "--worker", "--shard_id", str(s),
               "--num_shards", str(args.num_shards),
               "--ids_out", ids_out,
               "--dataset", args.dataset, "--network", args.network,
               "--root_path", args.root_path,
               *(["--dataset_path", args.dataset_path]
                 if args.dataset_path else []),
               "--num_images", str(args.num_images),
               "--batch_images", str(args.batch_images),
               "--num_workers", str(args.num_workers),
               "--ram_ceiling_mb", str(args.ram_ceiling_mb),
               "--seed", str(args.seed),
               *(["--coco_canvas"] if args.coco_canvas else []),
               *(["--image_size", args.image_size]
                 if args.image_size else [])]
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        procs.append(subprocess.Popen(cmd, env=env,
                                      stdout=subprocess.PIPE,
                                      stderr=subprocess.STDOUT, text=True))
    logs = [p.communicate()[0] for p in procs]
    for p, log in zip(procs, logs):
        if p.returncode:
            raise RuntimeError(f"shard worker failed rc={p.returncode}:\n"
                               + log[-2000:])
    return [json.load(open(o)) for o in outs]


def _expected_epoch_ids(args):
    """The exactly-once reference: every (index, flipped=False) of the
    train split that the epoch's full batches cover."""
    _, roidb, loader = _build(args)
    plan = loader._plan(0, args.batch_images)
    ids = sorted((int(roidb[i].get("index", -1)), False)
                 for _, idx in plan for i in idx)
    return ids, len(roidb)


def run_stream_epoch(args, record, expected):
    """One full epoch: StreamLoader → bounded cache → staging → jitted
    device consumer.  Timed pass must lower ZERO new programs.
    ``expected`` is the precomputed exactly-once reference
    (:func:`_expected_epoch_ids`)."""
    import jax
    import jax.numpy as jnp

    from mx_rcnn_tpu.data.staging import DeviceStager
    from mx_rcnn_tpu.obs.metrics import LoweringCounter, registry

    cfg, roidb, loader = _build(args)
    rec = registry()
    loader._rec = rec  # decode/assemble stage gauges without obs config

    @jax.jit
    def consume(images, gt_boxes):
        # touch every input byte on device: the staging path's device
        # consumer (stands in for the train step; the REAL train step
        # runs in the control leg and the elastic/ft suites)
        return (jnp.sum(images, dtype=jnp.int32)
                + jnp.sum(gt_boxes).astype(jnp.int32))

    # warm the one program outside the timed pass (one bucket, one shape)
    bh, bw = cfg.bucket.shapes[0]
    import numpy as np
    consume(jnp.zeros((args.batch_images, bh, bw, 3), jnp.uint8),
            jnp.zeros((args.batch_images,
                       cfg.train.max_gt_boxes, 4))).block_until_ready()

    stager = DeviceStager(iter(loader), jax.device_put, depth=2, rec=rec)
    out = None
    n_img = 0
    waits = []
    peak_rss = 0.0
    t0 = time.perf_counter()
    with LoweringCounter() as lc:
        it = iter(stager)
        while True:
            tw = time.perf_counter()
            batch = next(it, None)
            waits.append(time.perf_counter() - tw)
            if batch is None:
                break
            out = consume(batch.images, batch.gt_boxes)
            if args.step_ms:
                time.sleep(args.step_ms / 1e3)  # simulated device step
            n_img += batch.images.shape[0]
            if n_img % 512 < args.batch_images:
                peak_rss = max(peak_rss, _vm_rss_mb())
        if out is None:
            raise SystemExit(
                f"streaming epoch yielded ZERO batches — num_images="
                f"{args.num_images} is below batch_images="
                f"{args.batch_images} per bucket")
        sink = int(out)
    wall = time.perf_counter() - t0
    stager.close()
    peak_rss = max(peak_rss, _vm_rss_mb())
    ids = sorted(loader.decoded_ids)
    hits = rec.counter("loader.stage_hits")
    misses = rec.counter("loader.stage_misses")
    decode_h = rec.hist("loader.decode_ms")
    assemble_h = rec.hist("loader.assemble_ms")
    record["stream_epoch"] = {
        "images": n_img,
        "roidb_images": len(roidb),
        "wall_s": round(wall, 3),
        "imgs_per_sec": round(n_img / wall, 2),
        "exactly_once": ids == expected,
        "timed_pass_lowerings": lc.n,
        "peak_rss_mb": round(max(peak_rss, _vm_peak_mb()), 1),
        "vm_hwm_mb": round(_vm_peak_mb(), 1),
        "ram_ceiling_mb": args.ram_ceiling_mb,
        "cache": ({"hits": loader.cache.hits, "misses": loader.cache.misses,
                   "ram_budget_mb": loader.cache.ram_bytes >> 20}
                  if loader.cache is not None else None),
        "stage": {
            "hits": hits, "misses": misses,
            "hit_rate": round(hits / max(hits + misses, 1), 3),
            "consumer_wait_ms_total": round(sum(waits) * 1e3, 1),
            "decode_ms_per_batch_p50": (
                round(decode_h.percentile(50), 3) if decode_h else None),
            "assemble_ms_per_batch_p50": (
                round(assemble_h.percentile(50), 3) if assemble_h else None),
        },
        "simulated_step_ms": args.step_ms,
        "consumer_checksum": sink,
    }


def run_eval_leg(args, record):
    """The test split through the real eval input path (TestLoader)."""
    from mx_rcnn_tpu.config import generate_config
    from mx_rcnn_tpu.data import load_gt_roidb
    from mx_rcnn_tpu.data.loader import TestLoader

    over = ({"dataset__dataset_path": args.dataset_path}
            if args.dataset_path else {})
    over.update(_canvas_overrides(args))
    cfg = generate_config(args.network, args.dataset,
                          dataset__root_path=args.root_path,
                          data__ram_ceiling_mb=args.ram_ceiling_mb, **over)
    _, roidb = load_gt_roidb(cfg, training=False,
                             num_images=args.test_images,
                             **_source_size_kw(args))
    loader = TestLoader(roidb, cfg, batch_images=args.batch_images,
                        num_workers=args.num_workers)
    t0 = time.perf_counter()
    n = sum(b.images.shape[0] for b, _, _ in loader)
    wall = time.perf_counter() - t0
    record["eval_leg"] = {
        "images": n, "expected": len(roidb),
        "wall_s": round(wall, 3),
        "imgs_per_sec": round(n / wall, 2),
        "decoded": loader.images_decoded,
    }


def run_control(args, record):
    """Small-set control: REAL training (tiny model) with streaming +
    staging + obs — the data_wait_frac ~ 0 claim, measured on the path
    production uses."""
    import tempfile as tf

    from mx_rcnn_tpu.config import generate_config
    from mx_rcnn_tpu.obs.metrics import registry
    from mx_rcnn_tpu.tools.train import train_net

    registry().reset("train.")
    registry().reset("loader.")
    root = tf.mkdtemp(prefix="data_bench_ctrl_")
    cfg = generate_config(
        "tiny", "synthetic",
        dataset__root_path=root,
        dataset__dataset_path=os.path.join(root, "synthetic"),
        train__flip=False, train__rpn_pre_nms_top_n=256,
        train__rpn_post_nms_top_n=64, train__max_gt_boxes=8,
        bucket__scale=128, bucket__max_size=160,
        bucket__shapes=((128, 160), (160, 128)),
        train__batch_images=2,
        data__streaming=True, data__staging=True, obs__enabled=True)
    train_net(cfg, prefix=os.path.join(root, "model", "e2e"),
              end_epoch=args.control_epochs, frequent=1000, seed=0,
              dataset_kw={"num_images": args.control_images,
                          "image_size": (128, 160), "max_objects": 3})
    rec = registry()
    wait_h = rec.hist("train.data_wait_ms")
    step_h = rec.hist("train.step_ms")
    frac_h = rec.hist("train.data_wait_frac_pct")
    wait_p50 = wait_h.percentile(50) if wait_h else None
    step_p50 = step_h.percentile(50) if step_h else None
    # p50 of the PER-STEP wait/step fraction (fit records it as a
    # distribution) — NOT a ratio of independent percentiles, which a
    # bimodal run could game
    frac_p50 = (frac_h.percentile(50) / 100.0 if frac_h else None)
    record["control"] = {
        "steps": rec.counter("train.steps"),
        "epochs": args.control_epochs,
        "images": args.control_images,
        "data_wait_ms_p50": round(wait_p50, 3) if wait_p50 else wait_p50,
        "step_ms_p50": round(step_p50, 3) if step_p50 else step_p50,
        "data_wait_frac_p50": (round(frac_p50, 4)
                               if frac_p50 is not None else None),
        "stage_hits": rec.counter("loader.stage_hits"),
        "staged_batches": rec.counter("loader.staged_batches"),
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Streaming input-plane benchmark (docs/DATA.md)")
    p.add_argument("--dataset", default="synthetic_stream",
                   choices=["synthetic", "synthetic_hard",
                            "synthetic_stream"])
    p.add_argument("--network", default="tiny")
    p.add_argument("--root_path", default="data")
    p.add_argument("--dataset_path", default=None,
                   help="dataset directory (default: the preset path; "
                        "--smoke defaults to a sibling *_smoke dir so "
                        "gate runs never invalidate the rehearsal set's "
                        "PNG cache stamp)")
    p.add_argument("--num_images", type=int, default=10_000)
    p.add_argument("--test_images", type=int, default=1_000)
    p.add_argument("--batch_images", type=int, default=2)
    p.add_argument("--num_workers", type=int, default=2)
    p.add_argument("--decode_procs", type=int, default=0,
                   help="decode-pool worker processes for the streaming "
                        "epoch leg (0 = in-thread decode; the ROADMAP "
                        "item-3 multi-core validation sweeps 1/2/4)")
    p.add_argument("--num_shards", type=int, default=2,
                   help="worker PROCESSES in the shard rig")
    p.add_argument("--ram_ceiling_mb", type=int, default=4096)
    p.add_argument("--min_rate", type=float, default=0.0,
                   help="imgs/s floor for the streaming epoch under "
                        "--check (the rehearsal uses 76.4 = 2x the PR-5 "
                        "38.2 single-core baseline)")
    p.add_argument("--step_ms", type=float, default=0.0,
                   help="simulated device step per batch in the "
                        "streaming epoch (0 = pure input-plane rate)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--coco_canvas", action="store_true",
                   help="run at the PRODUCTION 640x1024 bucket canvas "
                        "(scale 600/1000) over 480x640 source images — "
                        "the COCO-resolution pixel-rate rehearsal "
                        "(docs/DATA.md); defaults --dataset_path to a "
                        "sibling *_coco dir so the cardinality "
                        "rehearsal's PNG stamp survives")
    p.add_argument("--image_size", default=None, metavar="HxW",
                   help="synthetic source-image size override")
    p.add_argument("--control_images", type=int, default=64)
    p.add_argument("--control_epochs", type=int, default=2)
    p.add_argument("--skip_control", action="store_true")
    p.add_argument("--skip_rig", action="store_true")
    p.add_argument("--smoke", action="store_true",
                   help="gate scale: tiny set, every invariant")
    p.add_argument("--check", action="store_true")
    p.add_argument("--out", default=None, help="write the record here too")
    # internal worker mode
    p.add_argument("--worker", action="store_true")
    p.add_argument("--shard_id", type=int, default=0)
    p.add_argument("--ids_out", default=None)
    args = p.parse_args(argv)

    if args.smoke:
        args.num_images = min(args.num_images, 240)
        args.test_images = min(args.test_images, 60)
        args.control_epochs = min(args.control_epochs, 2)
        args.ram_ceiling_mb = min(args.ram_ceiling_mb, 3072)
        if args.dataset_path is None:
            # own directory: a 240-image smoke regenerating inside the
            # 10k rehearsal directory would invalidate its spec stamp
            # and force a full re-materialization on the next rehearsal
            args.dataset_path = os.path.join(
                args.root_path, f"{args.dataset}_smoke")

    if args.coco_canvas and args.dataset_path is None:
        # own directory: a different source-size spec regenerating
        # inside the 10k cardinality set's dir would invalidate its
        # PNG stamp (same rule as --smoke)
        args.dataset_path = os.path.join(args.root_path,
                                         f"{args.dataset}_coco")

    if args.worker:
        return run_worker(args)

    record = {"metric": "stream_input_plane_r7",
              "dataset": args.dataset,
              "num_images": args.num_images,
              "batch_images": args.batch_images,
              "coco_canvas": bool(args.coco_canvas),
              "smoke": bool(args.smoke)}
    t_all = time.perf_counter()

    # materialize the dataset (and the epoch-plan reference) in the
    # PARENT first: rig workers spawning concurrently must find the PNGs
    # already on disk, not race each other writing them
    expected, _ = _expected_epoch_ids(args)

    if not args.skip_rig:
        workers = _spawn_shard_rig(args)
        union = sorted(
            tuple(i) for w in workers for i in w["ids"])
        counts = [w["images_decoded"] for w in workers]
        total = sum(counts)
        wall = max(w["wall_s"] for w in workers)
        record["shard_rig"] = {
            "processes": args.num_shards,
            "per_process_decoded": counts,
            "total_decoded": total,
            "expected_images": len(expected),
            "union_exactly_once": union == expected,
            "per_process_share": [round(c / max(total, 1), 3)
                                  for c in counts],
            "wall_s": wall,
            "aggregate_imgs_per_sec": round(total / wall, 2),
            "per_process_peak_rss_mb": [w["peak_rss_mb"] for w in workers],
        }

    run_stream_epoch(args, record, expected)
    run_eval_leg(args, record)
    if not args.skip_control:
        run_control(args, record)
    record["wall_s_total"] = round(time.perf_counter() - t_all, 1)

    checks = {}
    if "shard_rig" in record:
        r = record["shard_rig"]
        checks["rig_union_exactly_once"] = r["union_exactly_once"]
        n = r["processes"]
        checks["rig_decode_split"] = all(
            abs(s - 1.0 / n) < 0.02 for s in r["per_process_share"])
    se = record["stream_epoch"]
    checks["stream_exactly_once"] = se["exactly_once"]
    checks["zero_timed_lowerings"] = se["timed_pass_lowerings"] == 0
    if args.ram_ceiling_mb > 0:  # 0 = unlimited (no ceiling to enforce)
        checks["rss_under_ceiling"] = (se["peak_rss_mb"]
                                       <= args.ram_ceiling_mb)
    checks["stage_overlap_nonzero"] = se["stage"]["hits"] > 0
    if args.min_rate > 0:
        checks["rate_floor"] = se["imgs_per_sec"] >= args.min_rate
    checks["eval_complete"] = (record["eval_leg"]["images"]
                               == record["eval_leg"]["expected"])
    if "control" in record:
        c = record["control"]
        frac = c["data_wait_frac_p50"]
        checks["control_data_wait_near_zero"] = (frac is not None
                                                 and frac < 0.15)
        checks["control_stage_overlap_nonzero"] = c["stage_hits"] > 0
    record["checks"] = checks
    record["ok"] = all(checks.values())

    out = json.dumps(record, indent=1)
    print(out, flush=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(out + "\n")
    if args.check and not record["ok"]:
        failed = [k for k, v in checks.items() if not v]
        print(f"CHECK FAILED: {failed}", file=sys.stderr)
        return 1
    if args.check:
        print("CHECK OK: " + ", ".join(checks), file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
