"""Load generator for the serving engine AND the serving fleet:
closed/open loop, BENCH JSON, the FLEET_r08 measurement protocol.

No reference equivalent.  Replays synthetic images against an IN-PROCESS
:class:`~mx_rcnn_tpu.serve.engine.ServingEngine` (no network in the
measurement path — the HTTP front end is exercised by its own tests) and
emits ONE BENCH-style JSON line so serving performance enters the
measured-evidence pipeline like `bench.py` does for training:

    {"metric": "serve_imgs_per_sec", "value": ..., "measured": true,
     "offline_imgs_per_sec": ..., "ratio_vs_offline": ...,
     "p50_ms"/"p90_ms"/"p99_ms": ..., "shed_rate": ..., "lost": 0,
     "recompiles_after_warmup": 0, ...}

Two load models:

* ``--mode closed`` — ``--concurrency`` workers each keep exactly one
  request in flight (submit → wait → repeat): measures sustainable
  throughput and in-system latency without overload.
* ``--mode open``   — requests are submitted on a fixed ``--qps``
  schedule regardless of completions (the arrival process real traffic
  has): driving QPS past capacity exercises deadline expiry and the shed
  watermark, and the emitted shed/expired rates show overload degrading
  gracefully instead of collapsing latency.

The offline baseline is the same Predictor forward + postprocess batch
loop WITHOUT the serving machinery (queues, threads, per-request demux),
at the identical bucket/batch size — ``ratio_vs_offline`` is the serving
overhead acceptance metric (ISSUE 2: >= 0.8).  ``--check`` turns the
invariants (zero lost requests, zero post-warmup recompiles, ratio
floor) into the exit code for ``make serve-smoke``.

Fleet tier (ISSUE 8, docs/SERVING.md "Fleet tier"): ``--fleet N`` runs
the same closed/open loops through an N-replica
:class:`~mx_rcnn_tpu.serve.fleet.FleetRouter`; ``--fleet_bench`` /
``--fleet_smoke`` run the full fleet measurement protocol and emit a
``FLEET_r08.json``-style record:

* **cold join** — one replica's time-to-serving in a FRESH process,
  trace-warm (today's path, persistent cache off) vs export-warm (AOT
  store + bundled cache), on the production-representative backbone;
* **export integrity** — every AOT program pinned bit-equal to the live
  trace, and zero post-join recompiles under mixed-bucket traffic;
* **router scaling** — closed-loop throughput at 1/2/4 replicas with a
  DEVICE-COMPUTE SIMULATOR (``--stub_ms`` sleep per dispatched batch,
  GIL released — exactly what an on-chip replica does to the host
  thread).  On this 1-core CPU box every real-model replica shares the
  same silicon, so real-model N-replica throughput is flat BY PHYSICS;
  the stub leg is the honest way to validate that the ROUTER (routing,
  queues, accounting, coalescing) sustains N-replica rates — the
  record carries both legs, labeled;
* **kill-mid-burst** — one replica killed under load: zero lost
  requests fleet-wide, stranded work rerouted, replica relaunched and
  rejoined.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import threading
import time
from typing import List

import numpy as np

from mx_rcnn_tpu.analysis import sanitizer
from mx_rcnn_tpu.config import Config, generate_config
from mx_rcnn_tpu.core.tester import Predictor
from mx_rcnn_tpu.models import build_model
from mx_rcnn_tpu.serve.engine import ServingEngine
from mx_rcnn_tpu.serve.metrics import LoweringCounter
from mx_rcnn_tpu.serve.queue import (DeadlineExceeded, RequestFailed,
                                     ShedError)
from mx_rcnn_tpu.tools.train import add_set_arg, parse_set_overrides

logger = logging.getLogger("mx_rcnn_tpu")


def synthetic_images(cfg: Config, n: int, seed: int = 0
                     ) -> List[np.ndarray]:
    """``n`` random uint8 RGB images alternating landscape/portrait at
    the bucket canvas sizes, so mixed traffic exercises EVERY shape
    bucket (the recompile guard is only meaningful over mixed shapes)."""
    rng = np.random.RandomState(seed)
    buckets = [tuple(b) for b in cfg.bucket.shapes]
    return [rng.randint(0, 256,
                        size=buckets[i % len(buckets)] + (3,),
                        dtype=np.uint8)
            for i in range(n)]


def init_predictor(cfg: Config, prefix: str = None, epoch: int = 0,
                   seed: int = 0) -> Predictor:
    """Predictor from a checkpoint when given one, else from random init
    — serving throughput does not depend on the weight values.  With
    ``cfg.quant.enabled`` the returned predictor is the quantized one
    (held-out calibration sweep + tagged program keys — docs/PERF.md
    "Quantized inference"), so every serving CLI gains the quant mode
    through one ``--set quant__enabled=true``."""
    import jax

    from mx_rcnn_tpu.core.train import init_variables

    model = build_model(cfg)
    if prefix:
        from mx_rcnn_tpu.utils.checkpoint import load_param

        params, batch_stats = load_param(prefix, epoch)
    else:
        params, batch_stats = init_variables(
            model, jax.random.PRNGKey(seed),
            (1,) + tuple(cfg.bucket.shapes[0]) + (3,))
    if cfg.quant.enabled:
        from mx_rcnn_tpu.core.tester import quant_predictor

        return quant_predictor(cfg, params, batch_stats)
    return Predictor(model, {"params": params, "batch_stats": batch_stats},
                     cfg)


def offline_rate(engine: ServingEngine, reps: int = 12) -> float:
    """The comparison bar: full-batch Predictor forward + postprocess in
    a plain loop, no serving machinery, same bucket/batch size.  Buckets
    alternate like the serving traffic does."""
    b = engine.cfg.serve.batch_size
    batches = []
    for bucket in engine.buckets:
        bh, bw = bucket
        images = np.zeros((b, bh, bw, 3), np.float32)
        im_info = np.tile(np.array([bh, bw, 1.0], np.float32), (b, 1))
        batches.append((images, im_info))
    engine._run(*batches[0])  # ensure warm before timing
    t0 = time.perf_counter()
    for i in range(reps):
        engine._run(*batches[i % len(batches)])
    dt = time.perf_counter() - t0
    return reps * b / dt


def run_closed_loop(engine: ServingEngine, images, duration_s: float,
                    concurrency: int, timeout_ms: float) -> dict:
    """``concurrency`` workers, one request in flight each."""
    stop = time.monotonic() + duration_s
    outcomes = {"ok": 0, "shed": 0, "expired": 0, "failed": 0}
    lock = threading.Lock()

    def worker(wid: int):
        i = wid
        while time.monotonic() < stop:
            img = images[i % len(images)]
            i += concurrency
            try:
                engine.detect(img, timeout_ms=timeout_ms)
                key = "ok"
            except ShedError:
                key = "shed"
            except DeadlineExceeded:
                key = "expired"
            except (RequestFailed, TimeoutError):
                key = "failed"
            with lock:
                outcomes[key] += 1

    threads = [threading.Thread(target=worker, args=(w,), daemon=True)
               for w in range(concurrency)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return {"wall_s": time.perf_counter() - t0, "client": outcomes}


def run_open_loop(engine: ServingEngine, images, duration_s: float,
                  qps: float, timeout_ms: float) -> dict:
    """Submit on a fixed schedule (no back-pressure from completions);
    collect every handle so no request outcome is dropped."""
    period = 1.0 / qps
    handles = []
    t0 = time.perf_counter()
    start = time.monotonic()
    k = 0
    while True:
        target = start + k * period
        now = time.monotonic()
        if now - start >= duration_s:
            break
        if now < target:
            time.sleep(target - now)
        handles.append(engine.submit(images[k % len(images)],
                                     timeout_ms=timeout_ms))
        k += 1
    outcomes = {"ok": 0, "shed": 0, "expired": 0, "failed": 0}
    for h in handles:
        try:
            h.wait(timeout=30.0)
            outcomes["ok"] += 1
        except ShedError:
            outcomes["shed"] += 1
        except DeadlineExceeded:
            outcomes["expired"] += 1
        except (RequestFailed, TimeoutError):
            outcomes["failed"] += 1
    return {"wall_s": time.perf_counter() - t0, "client": outcomes,
            "submitted": k}


# ---------------------------------------------------------------------------
# fleet tier (docs/SERVING.md "Fleet tier")
# ---------------------------------------------------------------------------

def make_stub_run_fn(cfg: Config, model_ms: float, seed: int = 0):
    """Device-compute simulator for the router-scaling legs: sleeps
    ``model_ms`` per dispatched batch with the GIL RELEASED (what an
    on-chip replica does to the host) and returns canned
    postprocess-shaped outputs, so the full engine path — preprocess,
    queues, coalescing, demux, accounting — runs for real while the
    model time parallelizes across replicas the way per-chip compute
    does.  Every use is labeled in the emitted record."""
    n = cfg.serve.batch_size
    r = cfg.test.rpn_post_nms_top_n
    c = cfg.num_classes
    rng = np.random.RandomState(seed)
    boxes = (rng.rand(n, r, 4 * c) * 100.0).astype(np.float32)
    scores = rng.rand(n, r, c).astype(np.float32)
    keep = np.zeros((n, c, r), bool)
    keep[:, 1:, :3] = True  # a few detections per class → real demux work

    def run_fn(images, im_info):
        time.sleep(model_ms / 1000.0)
        return boxes, scores, keep

    return run_fn


def make_content_stub_run_fn(cfg: Config, model_ms: float = 0.0):
    """Deterministic CONTENT-DEPENDENT stub (tests/test_bulk.py + the
    bulk sink's SIGKILL rig): every output row is a pure function of
    that row's pixels alone, so (a) identical images score identically
    regardless of micro-batch composition or replica — the property the
    bulk plane's byte-identity invariant rests on — and (b) two
    different images produce different lines, so a mis-ordered or
    mis-slotted sink cannot pass the bit-identity comparison."""
    r = cfg.test.rpn_post_nms_top_n
    c = cfg.num_classes

    def run_fn(images, im_info):
        if model_ms:
            time.sleep(model_ms / 1000.0)
        n = images.shape[0]
        boxes = np.zeros((n, r, 4 * c), np.float32)
        scores = np.zeros((n, r, c), np.float32)
        keep = np.zeros((n, c, r), bool)
        for j in range(n):
            m = np.float32(np.abs(images[j]).sum())
            x = np.float32(m % np.float32(37.0))
            boxes[j, 0, 4:8] = [x, x + 1.0, x + 5.0, x + 7.0]
            scores[j, 0, 1] = np.float32(0.5) + x / np.float32(100.0)
            keep[j, 1, 0] = True
        return boxes, scores, keep

    return run_fn


def _build_fleet(cfg: Config, replicas: int, model, variables, *,
                 export_root: str = None, stub_ms: float = None,
                 record=None):
    from mx_rcnn_tpu.serve.fleet import build_fleet

    fcfg = cfg.replace_in("fleet", replicas=replicas)
    factory = (None if stub_ms is None
               else (lambda rid: make_stub_run_fn(fcfg, stub_ms)))
    return build_fleet(fcfg, model, variables, export_root=export_root,
                       run_fn_factory=factory, record=record)


def _drain(target, timeout_s: float = 30.0) -> None:
    deadline = time.monotonic() + timeout_s
    while (target.metrics.snapshot()["in_flight"] > 0
           and time.monotonic() < deadline):
        time.sleep(0.05)


def _fleet_leg_record(run: dict, snap: dict) -> dict:
    c = snap["counters"]
    return {
        "imgs_per_sec": round(c["served"] / run["wall_s"], 2),
        "duration_s": round(run["wall_s"], 2),
        "p50_ms": snap["total_ms"]["p50"],
        "p99_ms": snap["total_ms"]["p99"],
        "served": c["served"], "shed": c["shed"],
        "expired": c["expired"], "failed": c["failed"],
        "submitted": c["submitted"],
        "shed_rate": round(c["shed"] / max(c["submitted"], 1), 4),
        "lost": c["submitted"] - snap["terminated"],
    }


def _run_join_bench(mode: str, network: str, dataset: str,
                    overrides: dict, export_dir: str = None,
                    timeout_s: float = 900.0) -> dict:
    """One cold-join measurement in a FRESH interpreter (imports and
    backend init excluded by the child's own timers; the record keeps
    ``total_s`` for the full picture)."""
    import subprocess
    import sys

    cmd = [sys.executable, "-m", "mx_rcnn_tpu.tools.fleet", "join_bench",
           "--mode", mode, "--network", network, "--dataset", dataset]
    for k, v in overrides.items():
        cmd += ["--set", f"{k}={v!r}" if isinstance(v, str) else
                f"{k}={v}"]
    if export_dir:
        cmd += ["--export_dir", export_dir]
    env = dict(os.environ)
    if mode == "trace":
        # the baseline must pay the full compile: strip any inherited
        # persistent-cache env (the child also clears the live config)
        for k in list(env):
            if k.startswith("JAX_COMPILATION_CACHE") \
                    or k.startswith("JAX_PERSISTENT_CACHE"):
                env.pop(k)
    out = subprocess.run(cmd, capture_output=True, text=True,
                         timeout=timeout_s, env=env)
    if out.returncode != 0:
        raise RuntimeError(f"join_bench {mode} failed rc={out.returncode}:"
                           f"\n{out.stdout[-2000:]}\n{out.stderr[-2000:]}")
    last = [ln for ln in out.stdout.strip().splitlines()
            if ln.startswith("{")][-1]
    return json.loads(last)


def _kill_mid_burst_leg(cfg: Config, model, variables, export_root: str,
                        duration_s: float, timeout_ms: float,
                        images) -> dict:
    """2-replica export-warm fleet, closed-loop burst, replica 0 killed
    mid-burst: the fleet-wide terminate-exactly-once + reroute +
    relaunch + rejoin leg."""
    kcfg = cfg.replace_in("fleet", health_interval_s=0.2)
    router = _build_fleet(kcfg, 2, model, variables,
                          export_root=export_root)
    try:
        concurrency = 2 * cfg.serve.batch_size * 2
        stop = time.monotonic() + duration_s
        kill_at = time.monotonic() + duration_s / 3.0
        outcomes = {"ok": 0, "shed": 0, "expired": 0, "failed": 0}
        lock = threading.Lock()

        def worker(wid: int):
            i = wid
            while time.monotonic() < stop:
                try:
                    router.detect(images[i % len(images)],
                                  timeout_ms=timeout_ms)
                    key = "ok"
                except ShedError:
                    key = "shed"
                except DeadlineExceeded:
                    key = "expired"
                except (RequestFailed, TimeoutError):
                    key = "failed"
                i += concurrency
                with lock:
                    outcomes[key] += 1

        threads = [threading.Thread(target=worker, args=(w,), daemon=True)
                   for w in range(concurrency)]
        for t in threads:
            t.start()
        while time.monotonic() < kill_at:
            time.sleep(0.02)
        victim = router.manager.replicas[0]
        served_before_kill = router.metrics.snapshot()["counters"]["served"]
        eng = victim.engine
        eng.kill()
        kill_t = time.monotonic()
        for t in threads:
            t.join()
        _drain(router)
        # wait for the relaunch to rejoin (RestartPolicy resets on
        # progress, so the delay is ~one health tick + the join itself)
        rejoin_s = None
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            if victim.ready() and victim.generation >= 2:
                # stamp from the replica's OWN ready transition: the
                # drain above may have finished long after the rejoin
                rejoin_s = round(victim.joins[-1]["ready_t"] - kill_t, 2)
                break
            time.sleep(0.05)
        snap = router.metrics.snapshot()
        c = snap["counters"]
        return {
            "submitted": c["submitted"], "served": c["served"],
            "shed": c["shed"], "expired": c["expired"],
            "failed": c["failed"],
            "lost": c["submitted"] - snap["terminated"],
            "served_after_kill": c["served"] - served_before_kill,
            "rerouted": router.rerouted(),
            "ejects": router.manager.ejects,
            "relaunched": victim.generation >= 2,
            "rejoin_s": rejoin_s,
            "client_outcomes": outcomes,
        }
    finally:
        router.close()


def run_fleet_bench(args) -> int:
    """The FLEET_r08 measurement protocol (module docstring); emits one
    BENCH-style record and, under ``--check``, turns the fleet
    acceptance invariants into the exit code for ``make fleet-smoke``."""
    import tempfile

    from mx_rcnn_tpu.serve.export import (CACHE_SUBDIR,
                                          enable_compile_cache,
                                          export_serve_programs)
    from mx_rcnn_tpu.serve.metrics import LoweringCounter

    smoke = args.fleet_smoke
    overrides = dict(_smoke_overrides()) if smoke else {}
    overrides.update(parse_set_overrides(args))
    cfg = generate_config(args.network, args.dataset, **overrides)
    workdir = args.workdir or tempfile.mkdtemp(prefix="fleet_bench_")
    os.makedirs(workdir, exist_ok=True)
    store_root = os.path.join(workdir, "store")
    # None → bounded 20 s bench default; explicit 0 keeps the engine
    # contract's no-deadline mode (same distinction as the single-engine
    # path below)
    timeout_ms = 20_000.0 if args.timeout_ms is None else args.timeout_ms
    dur = min(args.duration, 6.0) if smoke else args.duration
    rec: dict = {
        "metric": "fleet_scaling_x_at_2_replicas",
        "unit": "x",
        "measured": True,
        "smoke": smoke,
        "network": args.network,
        "bucket_shapes": [list(b) for b in cfg.bucket.shapes],
        "batch_size": cfg.serve.batch_size,
        "host": {"physical_cores": os.cpu_count()},
    }
    problems: List[str] = []

    # -- 1. export store (traffic model) + bit-equality pin -------------
    logger.info("[fleet] exporting serving programs → %s", store_root)
    enable_compile_cache(os.path.join(store_root, CACHE_SUBDIR))
    predictor = init_predictor(cfg, args.prefix, args.epoch, args.seed)
    t0 = time.perf_counter()
    report = export_serve_programs(predictor, cfg, store_root)
    rec["export"] = {"bit_equal": report["bit_equal"],
                     "programs": len(report["programs"]),
                     "bytes": report["bytes"],
                     "export_s": round(time.perf_counter() - t0, 2)}
    if not report["bit_equal"]:
        problems.append("exported programs not bit-equal to live trace")

    # -- 2. cold join: trace-warm vs export-warm, fresh processes -------
    # the production-representative backbone for the full bench (compile
    # cost is what the export machinery exists to skip); the smoke stays
    # on the traffic model to fit the gate budget
    join_net = args.join_network if not smoke else args.network
    join_overrides = dict(overrides)
    if join_net != args.network:
        join_overrides = {"serve__batch_size": cfg.serve.batch_size}
    join_store = store_root
    if join_net != args.network:
        import subprocess
        import sys

        join_store = os.path.join(workdir, f"store_{join_net}")
        logger.info("[fleet] exporting %s join store → %s", join_net,
                    join_store)
        cmd = [sys.executable, "-m", "mx_rcnn_tpu.tools.fleet", "export",
               "--network", join_net, "--dataset", args.dataset,
               "--out", join_store]
        for k, v in join_overrides.items():
            cmd += ["--set", f"{k}={v}"]
        out = subprocess.run(cmd, capture_output=True, text=True,
                             timeout=1800)
        if out.returncode != 0:
            raise RuntimeError(f"join-store export failed:\n"
                               f"{out.stdout[-2000:]}\n"
                               f"{out.stderr[-2000:]}")
    logger.info("[fleet] cold-join leg: trace-warm (fresh process, "
                "no cache) ...")
    trace_join = _run_join_bench("trace", join_net, args.dataset,
                                 join_overrides)
    logger.info("[fleet] cold-join leg: export-warm (fresh process, "
                "AOT store) ...")
    export_join = _run_join_bench("export", join_net, args.dataset,
                                  join_overrides, export_dir=join_store)
    # the ratio compares JOIN OVERHEAD (warm_s minus the pure execution
    # of the dummy warmup batches, measured by a second all-resident
    # warmup pass): trace+compile vs deserialize+cache-read.  The
    # execution term is identical in both modes and, on a CPU backbone,
    # dwarfs both overheads (~30 s/bucket of raw conv math a TPU does in
    # ms) — comparing raw warm_s would measure the backbone, not the
    # store
    ratio = (export_join["overhead_s"] / trace_join["overhead_s"]
             if trace_join.get("overhead_s") else None)
    rec["cold_join"] = {
        "network": join_net,
        "trace_warm_s": trace_join["warm_s"],
        "trace_exec_s": trace_join["exec_s"],
        "trace_overhead_s": trace_join["overhead_s"],
        "trace_total_s": trace_join["total_s"],
        "export_warm_s": export_join["warm_s"],
        "export_exec_s": export_join["exec_s"],
        "export_overhead_s": export_join["overhead_s"],
        "export_total_s": export_join["total_s"],
        "ratio": round(ratio, 4) if ratio is not None else None,
        "note": "overhead_s = warm_s - exec_s: the trace+compile "
                "(resp. deserialize+cache-read) stage the AOT store "
                "addresses; exec_s is the dummy-batch model execution, "
                "identical in both modes (ms on a TPU, dominant on "
                "this CPU box); total_s additionally includes model "
                "build",
    }
    if ratio is None or ratio > args.max_join_ratio:
        problems.append(f"export-warm/trace-warm join-overhead ratio "
                        f"{ratio} > {args.max_join_ratio}")

    model, variables = predictor.model, predictor.variables
    images = synthetic_images(cfg, args.images, args.seed)

    # -- 3. real-model fleet legs (export-warm) + zero-recompile pin ----
    real: dict = {}
    for n_rep in ([1, 2] if not smoke else [2]):
        router = _build_fleet(cfg, n_rep, model, variables,
                              export_root=store_root)
        try:
            with LoweringCounter() as lc:
                run = run_closed_loop(
                    router, images, dur,
                    concurrency=4 * cfg.serve.batch_size * n_rep,
                    timeout_ms=timeout_ms)
                _drain(router)
            leg = _fleet_leg_record(run, router.metrics.snapshot())
            leg["recompiles_after_join"] = lc.n
            real[str(n_rep)] = leg
            if leg["lost"]:
                problems.append(f"real {n_rep}-replica leg lost "
                                f"{leg['lost']} requests")
            if lc.n:
                problems.append(f"real {n_rep}-replica leg recompiled "
                                f"{lc.n}x after join")
        finally:
            router.close()
    if "1" in real and "2" in real and real["1"]["imgs_per_sec"]:
        real["scaling_2r"] = round(real["2"]["imgs_per_sec"]
                                   / real["1"]["imgs_per_sec"], 3)
    real["note"] = ("all replicas share this host's "
                    f"{os.cpu_count()} CPU core(s): real-model scaling "
                    "here validates fleet overhead, not silicon — "
                    "per-chip scaling is the stub leg's subject")
    rec["real_model"] = real

    # -- 4. router-scaling legs (device-compute simulator) --------------
    stub: dict = {"mode": "stub-device-compute",
                  "stub_model_ms": args.stub_ms,
                  "note": "per-batch device compute simulated by a "
                          "GIL-releasing sleep, so replica 'chips' run "
                          "concurrently like real device subsets; "
                          "everything else (preprocess, routing, "
                          "queues, coalescing, demux, accounting) is "
                          "the production path"}
    sweep = [int(s) for s in args.fleet_sweep.split(",")]
    thr: dict = {}
    for n_rep in sweep:
        router = _build_fleet(cfg, n_rep, model, variables,
                              stub_ms=args.stub_ms)
        try:
            # 4x batch-per-replica keeps every (replica, bucket) lane a
            # spare full batch deep — at 2x the closed loop runs with
            # zero slack and measures its own resubmit latency, not the
            # router (observed: 1.0-1.4x "scaling" at 2 replicas)
            run = run_closed_loop(
                router, images, dur,
                concurrency=4 * cfg.serve.batch_size * n_rep,
                timeout_ms=timeout_ms)
            _drain(router)
            leg = _fleet_leg_record(run, router.metrics.snapshot())
            thr[str(n_rep)] = leg
            if leg["lost"]:
                problems.append(f"stub {n_rep}-replica leg lost "
                                f"{leg['lost']} requests")
        finally:
            router.close()
    stub["replicas"] = thr
    base = thr[str(sweep[0])]["imgs_per_sec"]
    for n_rep in sweep[1:]:
        if base:
            stub[f"scaling_{n_rep}r"] = round(
                thr[str(n_rep)]["imgs_per_sec"] / base, 3)
    rec["router_scaling"] = stub
    scalings = [k for k in stub if k.startswith("scaling_")]
    rec["value"] = stub.get("scaling_2r") or (
        stub[scalings[0]] if scalings else None)
    if "scaling_2r" in stub:
        if stub["scaling_2r"] < args.min_scaling:
            problems.append(f"router scaling at 2 replicas "
                            f"{stub['scaling_2r']} < {args.min_scaling}")
    else:
        # scaling keys are relative to the sweep's first entry; a custom
        # --fleet_sweep without the 1→2 pair has no 2-replica claim to gate
        logger.warning("--fleet_sweep %s has no 1→2 pair — the "
                       "min-scaling gate is skipped", args.fleet_sweep)

    # -- 5. shed-rate curve (overdriven open loop, 2-replica stub) ------
    # per-replica capacity: each bucket's dispatcher pipelines its own
    # batches, so capacity = buckets x batch / stub_ms per replica
    capacity = (2 * len(cfg.bucket.shapes) * cfg.serve.batch_size
                / (args.stub_ms / 1000.0))
    curve = []
    # smoke overdrive is 2.5x: at 1.5x the short window ends before the
    # backlog (excess qps spread over 4 lanes) reaches the watermark
    for factor in ([0.6, 2.5] if smoke else [0.6, 1.0, 1.5, 2.5]):
        router = _build_fleet(cfg, 2, model, variables,
                              stub_ms=args.stub_ms)
        try:
            run = run_open_loop(router, images, max(dur / 2, 2.0),
                                qps=capacity * factor,
                                timeout_ms=timeout_ms)
            _drain(router)
            leg = _fleet_leg_record(run, router.metrics.snapshot())
            curve.append({"qps_target": round(capacity * factor, 1),
                          "load_factor": factor, **leg})
            if leg["lost"]:
                problems.append(f"shed-curve leg x{factor} lost "
                                f"{leg['lost']} requests")
        finally:
            router.close()
    rec["shed_curve"] = {"stub_capacity_imgs_per_sec": round(capacity, 1),
                         "legs": curve}
    over = [l for l in curve if l["load_factor"] > 1.0]
    if over and all(l["shed_rate"] == 0 for l in over):
        problems.append("overdriven legs shed nothing — watermark "
                        "shedding not composing at fleet level")

    # -- 6. kill-mid-burst: reroute + relaunch + exactly-once -----------
    logger.info("[fleet] kill-mid-burst leg ...")
    kill = _kill_mid_burst_leg(cfg, model, variables, store_root,
                               duration_s=max(dur, 4.0),
                               timeout_ms=timeout_ms, images=images)
    rec["kill_mid_burst"] = kill
    if kill["lost"]:
        problems.append(f"kill leg lost {kill['lost']} requests")
    if not kill["relaunched"]:
        problems.append("killed replica did not relaunch+rejoin")
    if kill["served_after_kill"] <= 0:
        problems.append("no requests served after the kill")

    print(json.dumps(rec))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(rec, f, indent=1)
    if args.check:
        problems += sanitizer.check_problems()
        for msg in problems:
            logger.error("CHECK FAILED: %s", msg)
        return 1 if problems else 0
    return 0


def _smoke_overrides() -> dict:
    """The `make serve-smoke` canvas: the quick-tier 128x160 tiny-model
    buckets (compiles in seconds on one CPU core) with eval-scale ROI
    counts shrunk to keep the smoke under a minute."""
    return {
        "bucket__scale": 128, "bucket__max_size": 160,
        "bucket__shapes": ((128, 160), (160, 128)),
        "test__rpn_pre_nms_top_n": 512, "test__rpn_post_nms_top_n": 64,
        "serve__batch_size": 2, "serve__max_delay_ms": 20.0,
    }


def main(argv=None) -> int:
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(message)s")
    # opt-in lock sanitizer (make threadlint-smoke runs the serve legs
    # with MXRCNN_THREAD_SANITIZER=strict; docs/ANALYSIS.md "threadlint")
    sanitizer.maybe_install_from_env()
    p = argparse.ArgumentParser(
        description="Serving load generator + BENCH JSON "
                    "(docs/SERVING.md)")
    p.add_argument("--network", default="tiny",
                   choices=["vgg", "resnet50", "resnet101", "tiny"])
    p.add_argument("--dataset", default="synthetic",
                   choices=["PascalVOC", "coco", "synthetic",
                            "synthetic_hard"])
    p.add_argument("--prefix", default=None,
                   help="checkpoint prefix (default: random init — "
                        "throughput does not depend on weights)")
    p.add_argument("--epoch", type=int, default=0)
    p.add_argument("--mode", default="closed", choices=["closed", "open"])
    p.add_argument("--duration", type=float, default=20.0,
                   help="measurement window, seconds")
    p.add_argument("--concurrency", type=int, default=None,
                   help="closed-loop workers (default: 2x batch_size — "
                        "enough to keep every micro-batch full)")
    p.add_argument("--qps", type=float, default=20.0,
                   help="open-loop arrival rate")
    p.add_argument("--timeout_ms", type=float, default=None,
                   help="per-request deadline (default: "
                        "cfg.serve.default_timeout_ms)")
    p.add_argument("--images", type=int, default=16,
                   help="distinct synthetic images to cycle through")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--out", default=None,
                   help="also write the JSON record to this path")
    p.add_argument("--check", action="store_true",
                   help="exit 1 unless: zero lost requests, zero "
                        "post-warmup recompiles, ratio_vs_offline >= "
                        "--min_ratio")
    p.add_argument("--min_ratio", type=float, default=0.5,
                   help="--check floor for serving/offline throughput "
                        "(0.5 for the contended-smoke gate; the "
                        "acceptance measurement in docs/SERVING.md "
                        "records the real ratio)")
    p.add_argument("--smoke", action="store_true",
                   help="small-canvas preset for `make serve-smoke` "
                        "(tiny net, 128x160 buckets, short window)")
    # fleet tier (docs/SERVING.md "Fleet tier")
    p.add_argument("--fleet", type=int, default=0,
                   help="run the closed/open loop through an N-replica "
                        "FleetRouter instead of a single engine")
    p.add_argument("--export_dir", default=None,
                   help="--fleet: warm replicas from this AOT export "
                        "store (default: trace-warm)")
    p.add_argument("--fleet_bench", action="store_true",
                   help="run the full FLEET_r08 measurement protocol "
                        "(cold join, bit-equality, router scaling, shed "
                        "curves, kill-mid-burst) and emit one record")
    p.add_argument("--fleet_smoke", action="store_true",
                   help="gate-scale --fleet_bench for `make fleet-smoke` "
                        "(tiny canvas, short windows, lenient join "
                        "ratio)")
    p.add_argument("--fleet_sweep", default="1,2,4",
                   help="replica counts for the router-scaling legs")
    p.add_argument("--stub_ms", type=float, default=150.0,
                   help="simulated per-batch device-compute time for "
                        "the router-scaling legs (GIL-releasing sleep)."
                        "  Sized so simulated device time dominates the "
                        "host-side per-request work this 1-core box "
                        "serializes — the leg measures the ROUTER, not "
                        "the GIL")
    p.add_argument("--join_network", default="resnet50",
                   help="backbone for the cold-join legs of the full "
                        "bench (compile cost is the quantity under "
                        "test; the smoke reuses --network)")
    p.add_argument("--max_join_ratio", type=float, default=None,
                   help="--check ceiling for export-warm/trace-warm "
                        "cold-join time (default 0.10 bench / 0.50 "
                        "smoke — tiny-model smoke programs compile in "
                        "seconds, so fixed per-program costs dominate)")
    p.add_argument("--min_scaling", type=float, default=1.8,
                   help="--check floor for router-leg throughput "
                        "scaling at 2 replicas")
    p.add_argument("--workdir", default=None,
                   help="fleet bench working directory (export stores; "
                        "default: a fresh temp dir)")
    p.add_argument("--crosshost_bench", action="store_true",
                   help="run the cross-host battery (subprocess agents "
                        "+ binary wire + store pull + live scheduler — "
                        "tools/crosshost.py) and emit CROSSHOST_r15-"
                        "style JSON")
    p.add_argument("--crosshost_smoke", action="store_true",
                   help="gate-scale --crosshost_bench for `make "
                        "crosshost-smoke` (2 hosts, short bursts)")
    p.add_argument("--crosshost_sweep", default="1,2,4",
                   help="host counts for the cross-host scaling legs")
    p.add_argument("--min_wire_ratio", type=float, default=1.05,
                   help="--check floor for binary/JSON prepared-wire "
                        "throughput (at CPU saturation the ratio is "
                        "total-cost-per-request, so the codec tax is "
                        "diluted by the shared HTTP/dispatch cost — "
                        "the per-request p50 gap in the record is the "
                        "sharper signal)")
    p.add_argument("--min_crosshost_scaling", type=float, default=1.9,
                   help="--check floor for 2-host stub scaling (the "
                        "4-host floor is 2x this)")
    p.add_argument("--wire_bench", action="store_true",
                   help="run the wire data-plane battery (v1-fp32 / "
                        "v2-u8 / +coalesce / +adaptive arms + SIGKILL-"
                        "mid-envelope — tools/wire_bench.py) and emit "
                        "WIRE_r20-style JSON")
    p.add_argument("--wire_smoke", action="store_true",
                   help="gate-scale --wire_bench for `make wire-smoke` "
                        "(short windows, same arms and kill leg)")
    p.add_argument("--max_wire_bytes_ratio", type=float, default=0.30,
                   help="--check ceiling for v2-u8/v1-fp32 bytes per "
                        "image (measured counters AND production-"
                        "bucket codec math)")
    p.add_argument("--min_wire_speedup", type=float, default=1.8,
                   help="--check floor for the coalesced-arm/v1-arm "
                        "wire-leg throughput ratio")
    add_set_arg(p)
    args = p.parse_args(argv)

    if args.wire_bench or args.wire_smoke:
        from mx_rcnn_tpu.tools.wire_bench import run_wire_bench

        return run_wire_bench(args)

    if args.crosshost_bench or args.crosshost_smoke:
        from mx_rcnn_tpu.tools.crosshost import run_crosshost_bench

        return run_crosshost_bench(args)

    if args.fleet_bench or args.fleet_smoke:
        if args.max_join_ratio is None:
            args.max_join_ratio = 0.5 if args.fleet_smoke else 0.10
        if args.fleet_smoke and args.fleet_sweep == "1,2,4":
            args.fleet_sweep = "1,2"  # gate budget: the scaling floor
        return run_fleet_bench(args)

    overrides = {}
    if args.smoke:
        overrides.update(_smoke_overrides())
        args.duration = min(args.duration, 12.0)
    overrides.update(parse_set_overrides(args))
    cfg = generate_config(args.network, args.dataset, **overrides)
    concurrency = args.concurrency or 2 * cfg.serve.batch_size
    timeout_ms = (cfg.serve.default_timeout_ms if args.timeout_ms is None
                  else args.timeout_ms)

    # obs (off by default): the loadgen is an entry point like any
    # other — a bench run with obs on gets a runs/<id>/ record and,
    # with the time-series plane on, sampled windows over the window
    # it measures (docs/OBSERVABILITY.md)
    from mx_rcnn_tpu.obs.runrec import cli_obs

    obs_sess = cli_obs(cfg, "loadgen")

    predictor = init_predictor(cfg, args.prefix, args.epoch, args.seed)
    images = synthetic_images(cfg, args.images, args.seed)

    if args.fleet:
        logger.info("building %d-replica fleet (%s) ...", args.fleet,
                    f"export-warm from {args.export_dir}"
                    if args.export_dir else "trace-warm")
        engine = _build_fleet(cfg, args.fleet, predictor.model,
                              predictor.variables,
                              export_root=args.export_dir,
                              record=obs_sess.record if obs_sess
                              else None)
        off = None  # offline baseline is a single-engine concept
    else:
        engine = ServingEngine(predictor, cfg)
        logger.info("warmup: compiling %d bucket program(s) at batch %d "
                    "...", len(engine.buckets), cfg.serve.batch_size)
        t0 = time.perf_counter()
        engine.warmup()
        logger.info("warmup done in %.1fs", time.perf_counter() - t0)
        logger.info("offline baseline (no serving machinery) ...")
        off = offline_rate(engine)
        logger.info("offline: %.2f imgs/s at batch %d", off,
                    cfg.serve.batch_size)

    # fresh metrics for the measured window (warmup batches excluded)
    engine.metrics.reset()
    logger.info("load: mode=%s duration=%.0fs %s", args.mode,
                args.duration,
                f"concurrency={concurrency}" if args.mode == "closed"
                else f"qps={args.qps}")
    with LoweringCounter() as lc:
        if args.mode == "closed":
            run = run_closed_loop(engine, images, args.duration,
                                  concurrency, timeout_ms)
        else:
            run = run_open_loop(engine, images, args.duration, args.qps,
                                timeout_ms)
        # drain: every submitted request must reach a terminal state
        deadline = time.monotonic() + 30.0
        while (engine.metrics.snapshot()["in_flight"] > 0
               and time.monotonic() < deadline):
            time.sleep(0.05)
    snap = engine.metrics.snapshot()
    engine.close()

    c = snap["counters"]
    lost = c["submitted"] - snap["terminated"]
    served_rate = c["served"] / run["wall_s"]
    rec = {
        "metric": "serve_imgs_per_sec",
        "value": round(served_rate, 2),
        "unit": "imgs/s",
        "measured": True,
        "mode": args.mode,
        "network": args.network,
        "bucket_shapes": [list(b) for b in cfg.bucket.shapes],
        "batch_size": cfg.serve.batch_size,
        "max_delay_ms": cfg.serve.max_delay_ms,
        "duration_s": round(run["wall_s"], 2),
        "concurrency": concurrency if args.mode == "closed" else None,
        "qps_target": args.qps if args.mode == "open" else None,
        "fleet_replicas": args.fleet or None,
        "offline_imgs_per_sec": round(off, 2) if off else None,
        "ratio_vs_offline": round(served_rate / off, 3) if off else None,
        "p50_ms": snap["total_ms"]["p50"],
        "p90_ms": snap["total_ms"]["p90"],
        "p99_ms": snap["total_ms"]["p99"],
        "queue_wait_p99_ms": snap["queue_wait_ms"]["p99"],
        "model_ms_p50": snap["model_ms"]["p50"],
        "batch_occupancy_mean": snap["batch_occupancy"]["mean_rows"],
        "served": c["served"], "shed": c["shed"],
        "expired": c["expired"], "failed": c["failed"],
        "submitted": c["submitted"],
        "shed_rate": round(c["shed"] / max(c["submitted"], 1), 4),
        "expired_rate": round(c["expired"] / max(c["submitted"], 1), 4),
        "lost": lost,
        "recompiles_after_warmup": lc.n,
        "client_outcomes": run["client"],
    }
    if obs_sess is not None:
        obs_sess.close(metric=rec["metric"], value=rec["value"],
                       unit=rec["unit"], mode=args.mode)
    print(json.dumps(rec))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(rec, f, indent=1)
    if args.check:
        problems = sanitizer.check_problems()
        if lost != 0:
            problems.append(f"{lost} requests lost (no terminal state)")
        if lc.n != 0:
            problems.append(f"{lc.n} recompiles after warmup")
        if rec["ratio_vs_offline"] is not None \
                and rec["ratio_vs_offline"] < args.min_ratio:
            problems.append(
                f"serving/offline ratio {rec['ratio_vs_offline']} < "
                f"{args.min_ratio}")
        if c["served"] == 0:
            problems.append("zero requests served")
        for msg in problems:
            logger.error("CHECK FAILED: %s", msg)
        return 1 if problems else 0
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
