"""Load generator for the serving engine: closed/open loop, BENCH JSON.

No reference equivalent.  Replays synthetic images against an IN-PROCESS
:class:`~mx_rcnn_tpu.serve.engine.ServingEngine` (no network in the
measurement path — the HTTP front end is exercised by its own tests) and
emits ONE BENCH-style JSON line so serving performance enters the
measured-evidence pipeline like `bench.py` does for training:

    {"metric": "serve_imgs_per_sec", "value": ..., "measured": true,
     "offline_imgs_per_sec": ..., "ratio_vs_offline": ...,
     "p50_ms"/"p90_ms"/"p99_ms": ..., "shed_rate": ..., "lost": 0,
     "recompiles_after_warmup": 0, ...}

Two load models:

* ``--mode closed`` — ``--concurrency`` workers each keep exactly one
  request in flight (submit → wait → repeat): measures sustainable
  throughput and in-system latency without overload.
* ``--mode open``   — requests are submitted on a fixed ``--qps``
  schedule regardless of completions (the arrival process real traffic
  has): driving QPS past capacity exercises deadline expiry and the shed
  watermark, and the emitted shed/expired rates show overload degrading
  gracefully instead of collapsing latency.

The offline baseline is the same Predictor forward + postprocess batch
loop WITHOUT the serving machinery (queues, threads, per-request demux),
at the identical bucket/batch size — ``ratio_vs_offline`` is the serving
overhead acceptance metric (ISSUE 2: >= 0.8).  ``--check`` turns the
invariants (zero lost requests, zero post-warmup recompiles, ratio
floor) into the exit code for ``make serve-smoke``.
"""

from __future__ import annotations

import argparse
import json
import logging
import threading
import time
from typing import List

import numpy as np

from mx_rcnn_tpu.config import Config, generate_config
from mx_rcnn_tpu.core.tester import Predictor
from mx_rcnn_tpu.models import build_model
from mx_rcnn_tpu.serve.engine import ServingEngine
from mx_rcnn_tpu.serve.metrics import LoweringCounter
from mx_rcnn_tpu.serve.queue import (DeadlineExceeded, RequestFailed,
                                     ShedError)
from mx_rcnn_tpu.tools.train import add_set_arg, parse_set_overrides

logger = logging.getLogger("mx_rcnn_tpu")


def synthetic_images(cfg: Config, n: int, seed: int = 0
                     ) -> List[np.ndarray]:
    """``n`` random uint8 RGB images alternating landscape/portrait at
    the bucket canvas sizes, so mixed traffic exercises EVERY shape
    bucket (the recompile guard is only meaningful over mixed shapes)."""
    rng = np.random.RandomState(seed)
    buckets = [tuple(b) for b in cfg.bucket.shapes]
    return [rng.randint(0, 256,
                        size=buckets[i % len(buckets)] + (3,),
                        dtype=np.uint8)
            for i in range(n)]


def init_predictor(cfg: Config, prefix: str = None, epoch: int = 0,
                   seed: int = 0) -> Predictor:
    """Predictor from a checkpoint when given one, else from random init
    — serving throughput does not depend on the weight values."""
    import jax

    from mx_rcnn_tpu.core.train import init_variables

    model = build_model(cfg)
    if prefix:
        from mx_rcnn_tpu.utils.checkpoint import load_param

        params, batch_stats = load_param(prefix, epoch)
    else:
        params, batch_stats = init_variables(
            model, jax.random.PRNGKey(seed),
            (1,) + tuple(cfg.bucket.shapes[0]) + (3,))
    return Predictor(model, {"params": params, "batch_stats": batch_stats},
                     cfg)


def offline_rate(engine: ServingEngine, reps: int = 12) -> float:
    """The comparison bar: full-batch Predictor forward + postprocess in
    a plain loop, no serving machinery, same bucket/batch size.  Buckets
    alternate like the serving traffic does."""
    b = engine.cfg.serve.batch_size
    batches = []
    for bucket in engine.buckets:
        bh, bw = bucket
        images = np.zeros((b, bh, bw, 3), np.float32)
        im_info = np.tile(np.array([bh, bw, 1.0], np.float32), (b, 1))
        batches.append((images, im_info))
    engine._run(*batches[0])  # ensure warm before timing
    t0 = time.perf_counter()
    for i in range(reps):
        engine._run(*batches[i % len(batches)])
    dt = time.perf_counter() - t0
    return reps * b / dt


def run_closed_loop(engine: ServingEngine, images, duration_s: float,
                    concurrency: int, timeout_ms: float) -> dict:
    """``concurrency`` workers, one request in flight each."""
    stop = time.monotonic() + duration_s
    outcomes = {"ok": 0, "shed": 0, "expired": 0, "failed": 0}
    lock = threading.Lock()

    def worker(wid: int):
        i = wid
        while time.monotonic() < stop:
            img = images[i % len(images)]
            i += concurrency
            try:
                engine.detect(img, timeout_ms=timeout_ms)
                key = "ok"
            except ShedError:
                key = "shed"
            except DeadlineExceeded:
                key = "expired"
            except (RequestFailed, TimeoutError):
                key = "failed"
            with lock:
                outcomes[key] += 1

    threads = [threading.Thread(target=worker, args=(w,), daemon=True)
               for w in range(concurrency)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return {"wall_s": time.perf_counter() - t0, "client": outcomes}


def run_open_loop(engine: ServingEngine, images, duration_s: float,
                  qps: float, timeout_ms: float) -> dict:
    """Submit on a fixed schedule (no back-pressure from completions);
    collect every handle so no request outcome is dropped."""
    period = 1.0 / qps
    handles = []
    t0 = time.perf_counter()
    start = time.monotonic()
    k = 0
    while True:
        target = start + k * period
        now = time.monotonic()
        if now - start >= duration_s:
            break
        if now < target:
            time.sleep(target - now)
        handles.append(engine.submit(images[k % len(images)],
                                     timeout_ms=timeout_ms))
        k += 1
    outcomes = {"ok": 0, "shed": 0, "expired": 0, "failed": 0}
    for h in handles:
        try:
            h.wait(timeout=30.0)
            outcomes["ok"] += 1
        except ShedError:
            outcomes["shed"] += 1
        except DeadlineExceeded:
            outcomes["expired"] += 1
        except (RequestFailed, TimeoutError):
            outcomes["failed"] += 1
    return {"wall_s": time.perf_counter() - t0, "client": outcomes,
            "submitted": k}


def _smoke_overrides() -> dict:
    """The `make serve-smoke` canvas: the quick-tier 128x160 tiny-model
    buckets (compiles in seconds on one CPU core) with eval-scale ROI
    counts shrunk to keep the smoke under a minute."""
    return {
        "bucket__scale": 128, "bucket__max_size": 160,
        "bucket__shapes": ((128, 160), (160, 128)),
        "test__rpn_pre_nms_top_n": 512, "test__rpn_post_nms_top_n": 64,
        "serve__batch_size": 2, "serve__max_delay_ms": 20.0,
    }


def main(argv=None) -> int:
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(message)s")
    p = argparse.ArgumentParser(
        description="Serving load generator + BENCH JSON "
                    "(docs/SERVING.md)")
    p.add_argument("--network", default="tiny",
                   choices=["vgg", "resnet50", "resnet101", "tiny"])
    p.add_argument("--dataset", default="synthetic",
                   choices=["PascalVOC", "coco", "synthetic",
                            "synthetic_hard"])
    p.add_argument("--prefix", default=None,
                   help="checkpoint prefix (default: random init — "
                        "throughput does not depend on weights)")
    p.add_argument("--epoch", type=int, default=0)
    p.add_argument("--mode", default="closed", choices=["closed", "open"])
    p.add_argument("--duration", type=float, default=20.0,
                   help="measurement window, seconds")
    p.add_argument("--concurrency", type=int, default=None,
                   help="closed-loop workers (default: 2x batch_size — "
                        "enough to keep every micro-batch full)")
    p.add_argument("--qps", type=float, default=20.0,
                   help="open-loop arrival rate")
    p.add_argument("--timeout_ms", type=float, default=None,
                   help="per-request deadline (default: "
                        "cfg.serve.default_timeout_ms)")
    p.add_argument("--images", type=int, default=16,
                   help="distinct synthetic images to cycle through")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--out", default=None,
                   help="also write the JSON record to this path")
    p.add_argument("--check", action="store_true",
                   help="exit 1 unless: zero lost requests, zero "
                        "post-warmup recompiles, ratio_vs_offline >= "
                        "--min_ratio")
    p.add_argument("--min_ratio", type=float, default=0.5,
                   help="--check floor for serving/offline throughput "
                        "(0.5 for the contended-smoke gate; the "
                        "acceptance measurement in docs/SERVING.md "
                        "records the real ratio)")
    p.add_argument("--smoke", action="store_true",
                   help="small-canvas preset for `make serve-smoke` "
                        "(tiny net, 128x160 buckets, short window)")
    add_set_arg(p)
    args = p.parse_args(argv)

    overrides = {}
    if args.smoke:
        overrides.update(_smoke_overrides())
        args.duration = min(args.duration, 12.0)
    overrides.update(parse_set_overrides(args))
    cfg = generate_config(args.network, args.dataset, **overrides)
    concurrency = args.concurrency or 2 * cfg.serve.batch_size
    timeout_ms = (cfg.serve.default_timeout_ms if args.timeout_ms is None
                  else args.timeout_ms)

    predictor = init_predictor(cfg, args.prefix, args.epoch, args.seed)
    engine = ServingEngine(predictor, cfg)
    images = synthetic_images(cfg, args.images, args.seed)

    logger.info("warmup: compiling %d bucket program(s) at batch %d ...",
                len(engine.buckets), cfg.serve.batch_size)
    t0 = time.perf_counter()
    engine.warmup()
    logger.info("warmup done in %.1fs", time.perf_counter() - t0)
    logger.info("offline baseline (no serving machinery) ...")
    off = offline_rate(engine)
    logger.info("offline: %.2f imgs/s at batch %d", off,
                cfg.serve.batch_size)

    # fresh metrics for the measured window (warmup batches excluded)
    engine.metrics.reset()
    logger.info("load: mode=%s duration=%.0fs %s", args.mode,
                args.duration,
                f"concurrency={concurrency}" if args.mode == "closed"
                else f"qps={args.qps}")
    with LoweringCounter() as lc:
        if args.mode == "closed":
            run = run_closed_loop(engine, images, args.duration,
                                  concurrency, timeout_ms)
        else:
            run = run_open_loop(engine, images, args.duration, args.qps,
                                timeout_ms)
        # drain: every submitted request must reach a terminal state
        deadline = time.monotonic() + 30.0
        while (engine.metrics.snapshot()["in_flight"] > 0
               and time.monotonic() < deadline):
            time.sleep(0.05)
    snap = engine.metrics.snapshot()
    engine.close()

    c = snap["counters"]
    lost = c["submitted"] - snap["terminated"]
    served_rate = c["served"] / run["wall_s"]
    rec = {
        "metric": "serve_imgs_per_sec",
        "value": round(served_rate, 2),
        "unit": "imgs/s",
        "measured": True,
        "mode": args.mode,
        "network": args.network,
        "bucket_shapes": [list(b) for b in cfg.bucket.shapes],
        "batch_size": cfg.serve.batch_size,
        "max_delay_ms": cfg.serve.max_delay_ms,
        "duration_s": round(run["wall_s"], 2),
        "concurrency": concurrency if args.mode == "closed" else None,
        "qps_target": args.qps if args.mode == "open" else None,
        "offline_imgs_per_sec": round(off, 2),
        "ratio_vs_offline": round(served_rate / off, 3) if off else None,
        "p50_ms": snap["total_ms"]["p50"],
        "p90_ms": snap["total_ms"]["p90"],
        "p99_ms": snap["total_ms"]["p99"],
        "queue_wait_p99_ms": snap["queue_wait_ms"]["p99"],
        "model_ms_p50": snap["model_ms"]["p50"],
        "batch_occupancy_mean": snap["batch_occupancy"]["mean_rows"],
        "served": c["served"], "shed": c["shed"],
        "expired": c["expired"], "failed": c["failed"],
        "submitted": c["submitted"],
        "shed_rate": round(c["shed"] / max(c["submitted"], 1), 4),
        "expired_rate": round(c["expired"] / max(c["submitted"], 1), 4),
        "lost": lost,
        "recompiles_after_warmup": lc.n,
        "client_outcomes": run["client"],
    }
    print(json.dumps(rec))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(rec, f, indent=1)
    if args.check:
        problems = []
        if lost != 0:
            problems.append(f"{lost} requests lost (no terminal state)")
        if lc.n != 0:
            problems.append(f"{lc.n} recompiles after warmup")
        if rec["ratio_vs_offline"] is not None \
                and rec["ratio_vs_offline"] < args.min_ratio:
            problems.append(
                f"serving/offline ratio {rec['ratio_vs_offline']} < "
                f"{args.min_ratio}")
        if c["served"] == 0:
            problems.append("zero requests served")
        for msg in problems:
            logger.error("CHECK FAILED: %s", msg)
        return 1 if problems else 0
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
