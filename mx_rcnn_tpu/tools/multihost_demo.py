"""Two-process multi-host training demonstration (and its launcher).

Reference: the reference's multi-machine story is MXNet
``kvstore='dist_sync'`` (a parameter server, present but unexercised —
SURVEY.md §5.8).  This tool actually RUNS the multi-host path: N processes
(one per simulated host, 2 CPU devices each by default) initialize
``jax.distributed``, build the global ``(dcn, ici)`` mesh, and train the
tiny Faster R-CNN end-to-end step with gradients pmean'd across processes
over Gloo — the same program a TPU pod runs with one process per host and
ICI/DCN in place of Gloo.

Worker mode (one per process):
  python -m mx_rcnn_tpu.tools.multihost_demo --process_id I \\
      --num_processes N [--coordinator HOST:PORT] [--steps K]

Launcher mode (spawns N local workers, checks their losses agree):
  python -m mx_rcnn_tpu.tools.multihost_demo --launch N
"""

from __future__ import annotations

import argparse
import os
import re
import subprocess
import sys

import numpy as np

LOSS_RE = re.compile(r"\[p(\d+)\] step (\d+) loss ([0-9.]+)")


def worker(args) -> None:
    # ORDER MATTERS: distributed init must precede ANY backend
    # initialization.  Importing mx_rcnn_tpu is safe (the package keeps no
    # module-level jnp constants for exactly this reason), but platform
    # pinning still comes first.
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    from mx_rcnn_tpu.parallel import multihost

    multihost.initialize(args.coordinator, args.num_processes,
                         args.process_id, local_devices=args.local_devices)

    from mx_rcnn_tpu.config import generate_config
    from mx_rcnn_tpu.core.train import setup_training
    from mx_rcnn_tpu.models import build_model
    from mx_rcnn_tpu.parallel.dp import make_dp_train_step
    from mx_rcnn_tpu.tools.profile_step import make_batch

    pid = jax.process_index()
    print(f"[p{pid}] devices: local={jax.local_device_count()} "
          f"global={jax.device_count()}", flush=True)

    size = 128
    cfg = generate_config("tiny", "PascalVOC")
    cfg = cfg.replace_in("train", rpn_pre_nms_top_n=256,
                         rpn_post_nms_top_n=64, batch_rois=32,
                         max_gt_boxes=8, rpn_min_size=2, batch_images=1)
    model = build_model(cfg)
    key = jax.random.PRNGKey(0)

    # identical seed on every host → bit-identical init; replicate_global
    # then lifts the host-local copies into one logically-shared tree
    state, tx = setup_training(model, cfg, key, (1, size, size, 3),
                               steps_per_epoch=1000)
    mesh = multihost.global_mesh()
    step = make_dp_train_step(model, cfg, tx, mesh)
    state = multihost.replicate_global(jax.device_get(state), mesh)

    # the GLOBAL batch is deterministic; each host materializes only its
    # local slice (local device count x batch_images images)
    n_global = mesh.size * cfg.train.batch_images
    full = make_batch(cfg, n_global, size, size, seed=7)
    per = n_global // args.num_processes
    lo, hi = pid * per, (pid + 1) * per
    local = jax.tree.map(lambda x: np.asarray(x)[lo:hi], full)
    batch = multihost.global_batch(local, mesh)

    for s in range(args.steps):
        state, metrics = step(state, batch, key)
        loss = float(np.asarray(jax.device_get(metrics["loss"])))
        print(f"[p{pid}] step {s} loss {loss:.6f}", flush=True)
    print(f"[p{pid}] done", flush=True)


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def launch(n: int, steps: int, local_devices: int = 2) -> int:
    """Spawn ``n`` local worker processes; verify every process reports the
    same per-step loss (the gradients were truly synchronized)."""
    port = _free_port()
    procs = []
    outs = []
    ok = True
    # no shared compilation cache for the workers: with jax.distributed,
    # ranks that HIT the cache race ahead of ranks that compile, and the
    # collective-init barrier can time the stragglers out (reproduced
    # when the test conftest exported JAX_COMPILATION_CACHE_DIR to
    # subprocesses — every rank compiles, or none)
    env = {k: v for k, v in os.environ.items()
           if k != "JAX_COMPILATION_CACHE_DIR"}
    try:
        for i in range(n):
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "mx_rcnn_tpu.tools.multihost_demo",
                 "--process_id", str(i), "--num_processes", str(n),
                 "--local_devices", str(local_devices),
                 "--coordinator", f"localhost:{port}",
                 "--steps", str(steps)],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, env=env))
        for p in procs:
            out, _ = p.communicate(timeout=900)
            outs.append(out)
            if p.returncode != 0:
                ok = False
    finally:
        # distributed init is a barrier: one wedged worker blocks the rest
        # forever — never leak them (or the coordinator port) on timeout
        for p in procs:
            if p.poll() is None:
                p.kill()
    losses = {}
    for out in outs:
        for pid, s, loss in LOSS_RE.findall(out):
            losses.setdefault(int(s), {})[int(pid)] = float(loss)
    for s, by_pid in sorted(losses.items()):
        vals = sorted(by_pid.values())
        agree = len(by_pid) == n and abs(vals[-1] - vals[0]) < 1e-5
        print(f"step {s}: losses {by_pid} "
              f"{'AGREE' if agree else 'MISMATCH'}")
        ok = ok and agree
    ok = ok and len(losses) == steps
    if not ok:
        for i, out in enumerate(outs):
            print(f"--- worker {i} output ---\n{out}")
    print("MULTIHOST DEMO:", "OK" if ok else "FAILED")
    return 0 if ok else 1


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--launch", type=int, default=None,
                   help="spawn N local workers and verify agreement")
    p.add_argument("--process_id", type=int, default=0)
    p.add_argument("--num_processes", type=int, default=2)
    p.add_argument("--coordinator", default="localhost:19876")
    p.add_argument("--local_devices", type=int, default=2)
    p.add_argument("--steps", type=int, default=3)
    args = p.parse_args(argv)
    if args.launch:
        return launch(args.launch, args.steps,
                      local_devices=args.local_devices)
    worker(args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
