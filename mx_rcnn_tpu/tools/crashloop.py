"""Crash-loop certification CLI: kill training M times, prove bit-exact
resume, emit the BENCH-style record.

The durability claim this drives (docs/FT.md): after mixed SIGTERM /
SIGKILL kills at planned and random steps plus on-disk faults (torn
write, bit rot, stale interrupt), auto-resume via the integrity scanner
recovers every time, zero work is lost beyond the last committed
snapshot, and the survivor's final TrainState is BIT-IDENTICAL to an
uninterrupted control run.

Usage:
  python -m mx_rcnn_tpu.tools.crashloop --out docs/ft_crashloop.json
  python -m mx_rcnn_tpu.tools.crashloop --smoke --check   # make ft-smoke

``--smoke`` runs the 2-kill fast variant (one SIGTERM, one torn-write +
SIGKILL); ``--check`` exits nonzero unless every invariant holds —
the CI shape, mirroring ``tools/loadgen.py --smoke --check``.
"""

from __future__ import annotations

import argparse
import json
import logging
import sys
import tempfile

from mx_rcnn_tpu.ft.supervisor import (DEFAULT_EVENTS, SMOKE_EVENTS,
                                       measure_snapshot_overhead,
                                       run_crashloop)

logger = logging.getLogger("mx_rcnn_tpu")


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--network", default="tiny")
    p.add_argument("--dataset", default="synthetic")
    p.add_argument("--end_epoch", type=int, default=None,
                   help="default: 5 (smoke: 3)")
    p.add_argument("--num_images", type=int, default=32)
    p.add_argument("--seed", type=int, default=0,
                   help="training seed (both arms)")
    p.add_argument("--rng_seed", type=int, default=0,
                   help="kill-step scheduling seed (the 'random steps')")
    p.add_argument("--workdir", default=None,
                   help="default: a fresh temp dir (kept on failure)")
    p.add_argument("--out", default=None, help="write the JSON record here")
    p.add_argument("--smoke", action="store_true",
                   help="2-kill fast variant (make ft-smoke)")
    p.add_argument("--check", action="store_true",
                   help="exit 1 unless all invariants hold (CI shape)")
    p.add_argument("--skip_overhead", action="store_true",
                   help="skip the in-process snapshot-overhead measurement")
    p.add_argument("--max_overhead_pct", type=float, default=5.0,
                   help="--check: async snapshot overhead ceiling")
    args = p.parse_args(argv)

    events = SMOKE_EVENTS if args.smoke else DEFAULT_EVENTS
    end_epoch = args.end_epoch or (3 if args.smoke else 5)
    auto_workdir = args.workdir is None
    workdir = args.workdir or tempfile.mkdtemp(prefix="ft_crashloop_")
    logger.info("crashloop workdir: %s", workdir)

    rec = run_crashloop(
        workdir, events=events, network=args.network, dataset=args.dataset,
        end_epoch=end_epoch, num_images=args.num_images, seed=args.seed,
        rng_seed=args.rng_seed)
    rec = {"metric": "ft_crashloop", "measured": True,
           "network": args.network, "dataset": args.dataset,
           "smoke": args.smoke, **rec}
    if not args.skip_overhead:
        rec["snapshot_overhead"] = measure_snapshot_overhead()

    print(json.dumps(rec, indent=1))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(rec, f, indent=1)
        logger.info("record written to %s", args.out)

    if args.check:
        problems = []
        if not rec["bit_identical"]:
            problems.append("survivor final TrainState is NOT bit-identical "
                            "to the control run")
        if rec["kills_survived"] < len(events):
            problems.append(f"only {rec['kills_survived']} of {len(events)} "
                            f"planned kills fired and were survived")
        ov = rec.get("snapshot_overhead")
        if ov and ov["async_stall_overhead_pct"] > args.max_overhead_pct:
            problems.append(
                f"async snapshot step-pipeline stall "
                f"{ov['async_stall_overhead_pct']}% > "
                f"{args.max_overhead_pct}% ceiling")
        for msg in problems:
            logger.error("CHECK FAILED: %s", msg)
        if problems:
            logger.error("checkpoint trees kept for triage: %s", workdir)
            sys.exit(1)
        logger.info("all crash-loop invariants hold (%d kills, "
                    "bit-identical survivor)", rec["kills_survived"])
    if auto_workdir:
        # success: drop the two training trees (a failure — exception or
        # check exit above — leaves them in place for triage)
        import shutil

        shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(message)s")
    main()
