"""Crash-loop certification CLI: kill training M times, prove bit-exact
resume, emit the BENCH-style record.

The durability claim this drives (docs/FT.md): after mixed SIGTERM /
SIGKILL kills at planned and random steps plus on-disk faults (torn
write, bit rot, stale interrupt), auto-resume via the integrity scanner
recovers every time, zero work is lost beyond the last committed
snapshot, and the survivor's final TrainState is BIT-IDENTICAL to an
uninterrupted control run.

Usage:
  python -m mx_rcnn_tpu.tools.crashloop --out docs/ft_crashloop.json
  python -m mx_rcnn_tpu.tools.crashloop --smoke --check   # make ft-smoke
  python -m mx_rcnn_tpu.tools.crashloop --elastic --out ELASTIC_r06.json
  python -m mx_rcnn_tpu.tools.crashloop --elastic --smoke --check
      # make elastic-smoke

``--smoke`` runs the 2-kill fast variant (one SIGTERM, one torn-write +
SIGKILL); ``--check`` exits nonzero unless every invariant holds —
the CI shape, mirroring ``tools/loadgen.py --smoke --check``.

``--elastic`` runs the multi-process PREEMPTION STORM instead
(``ft/supervisor.py — run_elastic_storm``; docs/FT.md "Elasticity"):
a 2-process ``jax.distributed`` world loses members to staggered
SIGTERM (grace window) and SIGKILL (none), shrinks onto the surviving
devices with grad-accum rescale, grows back live and by world
relaunch, and must finish the run — every restore proven bit-identical
to its checkpoint, recovery time measured detect→first-step per
transition, zero recompiles after any generation's first step.
"""

from __future__ import annotations

import argparse
import json
import logging
import sys
import tempfile

from mx_rcnn_tpu.ft.supervisor import (DEFAULT_EVENTS, SMOKE_EVENTS,
                                       measure_snapshot_overhead,
                                       run_crashloop, run_elastic_storm)

logger = logging.getLogger("mx_rcnn_tpu")


def _check_elastic(rec: dict, smoke: bool) -> list:
    """The elastic storm invariants (ISSUE 6 acceptance): mixed-signal
    preemptions survived, >=1 shrink and >=1 grow, every restore
    bit-identical, zero post-first-step recompiles, run completed."""
    problems = []
    want_kills = 1 if smoke else 4
    if rec["kills_total"] < want_kills:
        problems.append(f"only {rec['kills_total']} preemptions injected "
                        f"(need >= {want_kills})")
    if not smoke and (rec["kills"]["TERM"] < 1 or rec["kills"]["KILL"] < 1):
        problems.append(f"preemptions not mixed: {rec['kills']}")
    if rec["shrinks"] < 1:
        problems.append("no mesh shrink in the timeline")
    if rec["grows"] < 1:
        problems.append("no grow-back in the timeline")
    if rec["restores"] < 1:
        problems.append("no restore events (the storm never exercised "
                        "the state-surgery path)")
    if not rec["restores_bit_identical"]:
        problems.append("a restore was NOT bit-identical to its "
                        "checkpoint")
    if rec["unexpected_recompiles"]:
        problems.append(f"recompiles after a generation's first step: "
                        f"{rec['unexpected_recompiles']}")
    if not rec["completed"]:
        problems.append(f"run did not complete ({rec['final_step']} < "
                        f"{rec['total_steps']} steps)")
    if rec.get("locksan_dirty_workers"):
        problems.append(
            f"{rec['locksan_dirty_workers']} sanitizer-armed worker(s) "
            "reported lock-order inversions or watchdog trips "
            "(LOCKSAN_DIRTY)")
    return problems


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--network", default="tiny")
    p.add_argument("--dataset", default="synthetic")
    p.add_argument("--end_epoch", type=int, default=None,
                   help="default: 5 (smoke: 3)")
    p.add_argument("--num_images", type=int, default=None,
                   help="synthetic dataset size (default: 32 for the "
                        "crash loop, 24 for --elastic)")
    p.add_argument("--seed", type=int, default=0,
                   help="training seed (both arms)")
    p.add_argument("--rng_seed", type=int, default=0,
                   help="kill-step scheduling seed (the 'random steps')")
    p.add_argument("--workdir", default=None,
                   help="default: a fresh temp dir (kept on failure)")
    p.add_argument("--out", default=None, help="write the JSON record here")
    p.add_argument("--smoke", action="store_true",
                   help="2-kill fast variant (make ft-smoke)")
    p.add_argument("--check", action="store_true",
                   help="exit 1 unless all invariants hold (CI shape)")
    p.add_argument("--skip_overhead", action="store_true",
                   help="skip the in-process snapshot-overhead measurement")
    p.add_argument("--max_overhead_pct", type=float, default=5.0,
                   help="--check: async snapshot overhead ceiling")
    p.add_argument("--elastic", action="store_true",
                   help="run the multi-process elastic preemption storm "
                        "instead of the single-process crash loop")
    args = p.parse_args(argv)
    # opt-in lock sanitizer (make threadlint-smoke): arms THIS process;
    # training children inherit the env var and arm themselves in
    # tools/train.py, reporting LOCKSAN_REPORT/LOCKSAN_DIRTY lines the
    # storm harvest folds into the record
    from mx_rcnn_tpu.analysis import sanitizer

    sanitizer.maybe_install_from_env()

    if args.elastic:
        auto_workdir = args.workdir is None
        workdir = args.workdir or tempfile.mkdtemp(prefix="elastic_storm_")
        logger.info("elastic storm workdir: %s", workdir)
        rec = run_elastic_storm(
            workdir, smoke=args.smoke, network=args.network,
            dataset=args.dataset, end_epoch=args.end_epoch,
            num_images=args.num_images or 24, seed=args.seed)
        print(json.dumps(rec, indent=1))
        if args.out:
            with open(args.out, "w") as f:
                json.dump(rec, f, indent=1)
            logger.info("record written to %s", args.out)
        if args.check:
            problems = _check_elastic(rec, args.smoke) \
                + sanitizer.check_problems()
            for msg in problems:
                logger.error("CHECK FAILED: %s", msg)
            if problems:
                logger.error("storm tree kept for triage: %s", workdir)
                sys.exit(1)
            logger.info(
                "all elastic invariants hold (%d preemptions, %d shrinks, "
                "%d grows, %d bit-identical restores, recovery p50 "
                "%.0f ms)", rec["kills_total"], rec["shrinks"],
                rec["grows"], rec["restores"], rec["recovery_ms"]["p50"])
        if auto_workdir:
            import shutil

            shutil.rmtree(workdir, ignore_errors=True)
        return

    events = SMOKE_EVENTS if args.smoke else DEFAULT_EVENTS
    end_epoch = args.end_epoch or (3 if args.smoke else 5)
    auto_workdir = args.workdir is None
    workdir = args.workdir or tempfile.mkdtemp(prefix="ft_crashloop_")
    logger.info("crashloop workdir: %s", workdir)

    rec = run_crashloop(
        workdir, events=events, network=args.network, dataset=args.dataset,
        end_epoch=end_epoch, num_images=args.num_images or 32,
        seed=args.seed,
        rng_seed=args.rng_seed)
    rec = {"metric": "ft_crashloop", "measured": True,
           "network": args.network, "dataset": args.dataset,
           "smoke": args.smoke, **rec}
    if not args.skip_overhead:
        rec["snapshot_overhead"] = measure_snapshot_overhead()

    print(json.dumps(rec, indent=1))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(rec, f, indent=1)
        logger.info("record written to %s", args.out)

    if args.check:
        problems = sanitizer.check_problems()
        if not rec["bit_identical"]:
            problems.append("survivor final TrainState is NOT bit-identical "
                            "to the control run")
        if rec["kills_survived"] < len(events):
            problems.append(f"only {rec['kills_survived']} of {len(events)} "
                            f"planned kills fired and were survived")
        ov = rec.get("snapshot_overhead")
        if ov and ov["async_stall_overhead_pct"] > args.max_overhead_pct:
            problems.append(
                f"async snapshot step-pipeline stall "
                f"{ov['async_stall_overhead_pct']}% > "
                f"{args.max_overhead_pct}% ceiling")
        for msg in problems:
            logger.error("CHECK FAILED: %s", msg)
        if problems:
            logger.error("checkpoint trees kept for triage: %s", workdir)
            sys.exit(1)
        logger.info("all crash-loop invariants hold (%d kills, "
                    "bit-identical survivor)", rec["kills_survived"])
    if auto_workdir:
        # success: drop the two training trees (a failure — exception or
        # check exit above — leaves them in place for triage)
        import shutil

        shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(message)s")
    main()
