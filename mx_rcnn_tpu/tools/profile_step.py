"""Train-step time breakdown: where do the milliseconds go?

Reference analog: none — the reference has no profiler wiring beyond
``Speedometer`` (SURVEY.md §5.1).  On TPU the jitted step is one opaque XLA
program, so this tool attributes time by *ablation*: it compiles and times
each stage of the step (backbone, RPN losses, proposal NMS, targets,
ROIAlign, ROI head, full step) on the real device and reports per-stage
milliseconds.

Measurement methodology (important on tunneled devices): a host→device
round-trip can cost ~100 ms, so single-call timing drowns in RTT.  Each
stage is chained N times inside ONE XLA program with an unfoldable data
dependency (carry · 1e-30 injected into the stage input, carry re-derived
from the stage output), then timed with a single dispatch + fetch;
per-iteration time = (wall − RTT) / N.

The chain is UNROLLED at trace time, not a ``lax.fori_loop``: loop bodies
at ResNet-101 size hit a compile pathology on this stack (the loop-wrapped
program runs ~12× slower than the flat one — docs/PERF.md round 3, which
killed the round-2 fori_loop methodology).  Unrolling sidesteps the loop
op entirely at the cost of compile time linear in N — hence the default
N of 8; raise ``--iters`` on fast-compiling devices for tighter numbers.

r6 additions: the lever A/B switches (``--roi_backend blocked
--roi_chunk 64`` for the ROI-chunked blocked ROIAlign, ``--nms_mode
per_image`` for the pre-batched proposal sweep, ``--shape 640x1024`` for
the sublane-friendly bucket arm — `script/perf_r6.sh` drives the full
battery incl. the batch-8 stage table), per-stage gauges into the obs
registry (``profile/stage_ms/*`` — stage tables land in the unified
/metrics view and runrec summaries), and ``--check`` (the `make
perf-smoke` self-test: finite stages, ZERO timed-pass recompiles, chain
self-check).

Usage:
  python -m mx_rcnn_tpu.tools.profile_step --network resnet101 --iters 8
"""

from __future__ import annotations

import argparse
import functools
import sys
import time

import numpy as np


def make_batch(cfg, batch_images, h, w, seed=0, raw=False):
    """Synthetic training batch; ``raw=True`` emits the uint8 image layout
    the production loader ships (device-side normalization path)."""
    import jax.numpy as jnp

    from mx_rcnn_tpu.core.train import Batch

    rng = np.random.RandomState(seed)
    g = cfg.train.max_gt_boxes
    n_gt = 8
    gt_boxes = np.zeros((batch_images, g, 4), np.float32)
    gt_classes = np.zeros((batch_images, g), np.int32)
    gt_valid = np.zeros((batch_images, g), bool)
    for i in range(batch_images):
        xy = rng.uniform(0, [w * 0.8, h * 0.8], (n_gt, 2))
        wh = rng.uniform(0.05, 0.4, (n_gt, 2)) * [w, h]
        gt_boxes[i, :n_gt, :2] = xy
        gt_boxes[i, :n_gt, 2:] = np.minimum(xy + wh, [w - 1, h - 1])
        gt_classes[i, :n_gt] = rng.randint(1, cfg.dataset.num_classes, n_gt)
        gt_valid[i, :n_gt] = True
    if raw:
        images = jnp.asarray(
            rng.randint(0, 256, (batch_images, h, w, 3)), jnp.uint8)
    else:
        images = jnp.asarray(rng.randn(batch_images, h, w, 3), jnp.float32)
    return Batch(
        images=images,
        im_info=jnp.tile(jnp.array([[float(h), float(w), 1.0]]),
                         (batch_images, 1)),
        gt_boxes=jnp.asarray(gt_boxes),
        gt_classes=jnp.asarray(gt_classes),
        gt_valid=jnp.asarray(gt_valid),
    )


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--network", default="resnet101")
    p.add_argument("--dataset", default="coco")
    p.add_argument("--batch_images", type=int, default=2)
    p.add_argument("--shape", default="608x1024")
    p.add_argument("--iters", type=int, default=8,
                   help="unrolled chain length inside the timed XLA "
                        "program (compile time grows with it)")
    p.add_argument("--trace_dir", default=None,
                   help="also dump a jax.profiler trace here")
    p.add_argument("--trace_summary", action="store_true",
                   help="parse the dumped trace (utils/xplane.py) and "
                        "print device time by named scope and op class")
    p.add_argument("--prenms", type=int, default=None,
                   help="override TRAIN rpn_pre_nms_top_n (the adopted "
                        "recipe is 6000; the config ships the ref 12000)")
    p.add_argument("--roi_backend", default="auto",
                   choices=("auto", "jnp", "blocked", "pallas"),
                   help="ROIAlign backend for the roi_align stage AND the "
                        "full step (cfg.train.roi_align_backend) — the r6 "
                        "blocked-vs-einsum A/B arm switch")
    p.add_argument("--roi_chunk", type=int, default=64,
                   help="ROI block size for --roi_backend blocked")
    p.add_argument("--nms_mode", default="batched",
                   choices=("batched", "per_image"),
                   help="proposal-stage NMS composition: 'batched' (one "
                        "cross-image tile sweep when the jnp backend is "
                        "selected) or 'per_image' (vmap of per-image "
                        "sweeps — the pre-r6 composition)")
    p.add_argument("--nms_backend", default="auto",
                   choices=("auto", "jnp", "pallas"),
                   help="suppression-sweep backend (ops/nms.py "
                        "set_nms_backend).  NOTE on TPU 'auto' resolves "
                        "to the per-image Pallas kernel in BOTH "
                        "--nms_mode arms (lane/VMEM guards permitting), "
                        "so the batched-sweep A/B must force 'jnp' to "
                        "engage the cross-image sweep — script/perf_r6.sh "
                        "leg 3 runs the 3-arm comparison")
    p.add_argument("--check", action="store_true",
                   help="perf-smoke self-test: assert the chain "
                        "self-check (sum of stages ~ full step), zero "
                        "recompiles on every timed pass, and the stage "
                        "gauges landing in the obs registry; exits "
                        "non-zero on violation")
    p.add_argument("--quant", action="store_true",
                   help="ALSO time the quantized INFERENCE forward vs "
                        "the fp one per bucket (docs/PERF.md 'Quantized "
                        "inference'): calibrates on the synthetic batch, "
                        "then chains the full test-mode forward in both "
                        "arms — the r9 quant A/B switch")
    p.add_argument("--quant_dtype", default="int8",
                   choices=("int8", "fp8"))
    p.add_argument("--quant_mode", default="native",
                   choices=("native", "sim"))
    p.add_argument("--pad_stem", type=int, default=0,
                   help="backbone layout lever: zero-pad the stem's "
                        "input channels 3 -> N before conv0 "
                        "(cfg.network.stem_channel_pad; output pinned "
                        "bit-identical, param shapes change).  The A/B "
                        "is two invocations, 0 vs 4, like the other "
                        "lever switches")
    args = p.parse_args(argv)

    import jax
    import jax.numpy as jnp

    from mx_rcnn_tpu.config import generate_config
    from mx_rcnn_tpu.core.train import make_train_step, setup_training
    from mx_rcnn_tpu.models import build_model
    from mx_rcnn_tpu.obs.metrics import LoweringCounter, registry
    from mx_rcnn_tpu.ops.nms import set_nms_backend
    from mx_rcnn_tpu.ops.proposal import propose_batch
    from mx_rcnn_tpu.ops.roi_pool import roi_align_batched
    from mx_rcnn_tpu.ops.targets import anchor_target, proposal_target

    set_nms_backend(args.nms_backend)

    h, w = (int(v) for v in args.shape.split("x"))
    n = args.batch_images
    N = args.iters
    cfg = generate_config(args.network, args.dataset)
    cfg = cfg.replace_in("train", batch_images=n,
                         roi_align_backend=args.roi_backend,
                         roi_align_chunk=args.roi_chunk,
                         nms_batched=args.nms_mode == "batched")
    if args.prenms is not None:
        cfg = cfg.replace_in("train", rpn_pre_nms_top_n=args.prenms)
    if args.pad_stem:
        cfg = cfg.replace_in("network", stem_channel_pad=args.pad_stem)
    model = build_model(cfg)
    tr = cfg.train
    key = jax.random.PRNGKey(0)
    batch = make_batch(cfg, n, h, w)

    print(f"device: {jax.devices()[0].platform} "
          f"({jax.devices()[0].device_kind}); loop N={N}", file=sys.stderr)
    state, tx = setup_training(model, cfg, key, (n, h, w, 3),
                               steps_per_epoch=10_000)
    variables = {"params": state.params, "batch_stats": state.batch_stats}

    def fetch(x):
        return np.asarray(jax.tree_util.tree_leaves(x)[0]).ravel()[:1]

    # tunnel round-trip floor: min over a few trivial fetched programs
    # (min, not single-shot — jitter would over-subtract on fast paths)
    tiny = jax.jit(lambda c: c + 1.0)
    fetch(tiny(jnp.float32(0)))
    rtt = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        fetch(tiny(jnp.float32(0)))
        rtt = min(rtt, time.perf_counter() - t0)
    print(f"{'fetch round-trip (floor)':<34s} {rtt * 1e3:9.2f} ms",
          flush=True)

    def retry_compile(fn, *a, **kw):
        """The tunneled remote-compile endpoint is intermittently flaky
        — retry ONLY that failure; real errors surface immediately."""
        for attempt in range(5):
            try:
                return fn(*a, **kw)
            except Exception as e:
                transient = ("remote_compile" in str(e)
                             or "response body" in str(e))
                if attempt == 4 or not transient:
                    raise
                time.sleep(5.0)

    # stage table accounting: per-stage ms land in the process obs
    # registry (gauges under profile/stage_ms/* — the unified /metrics
    # view and runrec summaries pick them up) and in ``stage_ms`` for the
    # --check self-test; ``relowerings`` counts jit cache misses on the
    # TIMED pass of each stage (the warm pass must never retrace).
    stage_ms: dict = {}
    relowerings: dict = {}

    def record_stage(label, per_s, lowerings=0):
        ms = per_s * 1e3
        slug = "".join(ch if ch.isalnum() else "_" for ch in label.lower())
        slug = "_".join(filter(None, slug.split("_")))
        stage_ms[label] = ms
        relowerings[label] = lowerings
        registry().set_gauge(f"profile/stage_ms/{slug}", round(ms, 4))

    def timed_loop(stage, label, note=""):
        """stage: carry (f32 scalar) -> carry.  Runs N reps in one program,
        UNROLLED (no fori_loop — see module docstring); the carry chain is
        an unfoldable data dependence, so XLA cannot CSE the copies."""

        def chain(c):
            for _ in range(N):
                c = stage(c)
            return c

        looped = jax.jit(chain)
        retry_compile(lambda: fetch(looped(jnp.float32(0))))  # compile+warm
        with LoweringCounter() as lc:
            t0 = time.perf_counter()
            fetch(looped(jnp.float32(0)))
            per = (time.perf_counter() - t0 - rtt) / N
        print(f"{label:<34s} {per * 1e3:9.2f} ms  {note}", flush=True)
        record_stage(label, per, lc.n)
        return per

    def carry_of(x):
        return jax.tree_util.tree_leaves(x)[0].ravel()[0].astype(jnp.float32)

    eps = jnp.float32(1e-30)

    # --- stages ------------------------------------------------------------
    def feat_of(images):
        return model.apply(variables, images, method=model.features)

    t_feat = timed_loop(
        lambda c: carry_of(feat_of(batch.images + c * eps)),
        "backbone fwd")

    feat = retry_compile(jax.jit(feat_of), batch.images)
    _, fh, fw, fc = feat.shape
    anchors = jnp.asarray(model.anchors_for(fh, fw))

    def feat_bwd(c):
        def f(p):
            y = model.apply({**variables, "params": p},
                            batch.images + c * eps, method=model.features)
            return (y.astype(jnp.float32) ** 2).mean()
        g = jax.grad(f)(variables["params"])
        return carry_of(g)

    t_feat_bwd = timed_loop(feat_bwd, "backbone fwd+bwd (dummy loss)")

    rpn_cls, rpn_box = retry_compile(jax.jit(
        lambda v, f: model.apply(v, f, method=model.rpn_raw)), variables, feat)
    fg = jax.nn.softmax(rpn_cls.astype(jnp.float32), axis=-1)[..., 1]
    box32 = rpn_box.astype(jnp.float32)

    prop_fn = functools.partial(
        propose_batch, batched_nms=tr.nms_batched,
        pre_nms_top_n=tr.rpn_pre_nms_top_n,
        post_nms_top_n=tr.rpn_post_nms_top_n,
        nms_thresh=tr.rpn_nms_thresh, min_size=tr.rpn_min_size)

    def prop_stage(c):
        rois, _, _ = prop_fn(fg + c * eps, box32, anchors, batch.im_info)
        return carry_of(rois)

    t_prop = timed_loop(prop_stage, "proposal (decode+topk+NMS)",
                        f"pre={tr.rpn_pre_nms_top_n} "
                        f"post={tr.rpn_post_nms_top_n} "
                        f"nms={args.nms_mode}/{args.nms_backend}")

    rois, _, rois_valid = retry_compile(
        jax.jit(lambda s, d, i: prop_fn(s, d, anchors, i)),
        fg, box32, batch.im_info)

    at_one = functools.partial(
        anchor_target, rpn_batch_size=tr.rpn_batch_size,
        rpn_fg_fraction=tr.rpn_fg_fraction,
        positive_overlap=tr.rpn_positive_overlap,
        negative_overlap=tr.rpn_negative_overlap,
        clobber_positives=tr.rpn_clobber_positives,
        allowed_border=tr.rpn_allowed_border,
        bbox_weights=tr.rpn_bbox_weights)
    keys = jax.random.split(key, n)

    def at_stage(c):
        at = jax.vmap(at_one, in_axes=(None, 0, 0, 0, 0))(
            anchors, batch.gt_boxes + c * eps, batch.gt_valid,
            batch.im_info, keys)
        return carry_of(at.bbox_targets)

    t_at = timed_loop(at_stage, "anchor_target",
                      f"anchors={anchors.shape[0]}")

    pt_one = functools.partial(
        proposal_target, num_classes=model.num_classes,
        batch_rois=tr.batch_rois, fg_fraction=tr.fg_fraction,
        fg_thresh=tr.fg_thresh, bg_thresh_hi=tr.bg_thresh_hi,
        bg_thresh_lo=tr.bg_thresh_lo, bbox_means=tr.bbox_means,
        bbox_stds=tr.bbox_stds, gt_append=tr.gt_append)

    def pt_stage(c):
        pt = jax.vmap(pt_one)(rois + c * eps, rois_valid, batch.gt_boxes,
                              batch.gt_classes, batch.gt_valid, keys)
        return carry_of(pt.rois)

    t_pt = timed_loop(pt_stage, "proposal_target")

    pt = retry_compile(jax.jit(jax.vmap(pt_one)), rois, rois_valid,
                       batch.gt_boxes, batch.gt_classes, batch.gt_valid,
                       keys)

    # the stage runs whatever backend the full step will run
    # (cfg.train.roi_align_backend — 'auto' resolves like core/train)
    ra_backend = None if tr.roi_align_backend == "auto" \
        else tr.roi_align_backend
    ra_fn = functools.partial(
        roi_align_batched, output_size=model.pooled_size,
        spatial_scale=1.0 / model.feat_stride, backend=ra_backend,
        chunk=tr.roi_align_chunk)

    def ra_stage(c):
        pooled = ra_fn(feat + c * eps.astype(feat.dtype), pt.rois)
        return carry_of(pooled)

    t_ra = timed_loop(ra_stage, "roi_align",
                      f"rois={pt.rois.shape[0] * pt.rois.shape[1]} "
                      f"backend={tr.roi_align_backend}")

    pooled = retry_compile(jax.jit(ra_fn), feat, pt.rois)
    flat = pooled.reshape((-1,) + pooled.shape[2:])

    def head_stage(c):
        def f(p):
            cl, b = model.apply(
                {**variables, "params": p},
                flat + c * eps.astype(flat.dtype), True,
                method=model.roi_head, rngs={"dropout": jax.random.PRNGKey(0)})
            return (cl.astype(jnp.float32) ** 2).mean() + \
                   (b.astype(jnp.float32) ** 2).mean()
        return carry_of(jax.grad(f)(variables["params"]))

    t_head = timed_loop(head_stage, "roi head fwd+bwd (dummy loss)",
                        f"rois={flat.shape[0]}")

    # --- aggregate ablations ----------------------------------------------
    from mx_rcnn_tpu.core.train import Batch, loss_and_metrics

    def loss_fwd_stage(c):
        b = Batch(batch.images + c * eps, batch.im_info, batch.gt_boxes,
                  batch.gt_classes, batch.gt_valid)
        total, _ = loss_and_metrics(model, variables["params"],
                                    variables["batch_stats"], b, key, cfg)
        return total

    t_loss_fwd = timed_loop(loss_fwd_stage, "full loss fwd (no bwd)")

    def loss_bwd_stage(c):
        b = Batch(batch.images + c * eps, batch.im_info, batch.gt_boxes,
                  batch.gt_classes, batch.gt_valid)

        def f(p):
            total, _ = loss_and_metrics(model, p, variables["batch_stats"],
                                        b, key, cfg)
            return total

        return carry_of(jax.grad(f)(variables["params"]))

    t_loss_bwd = timed_loop(loss_bwd_stage, "full loss fwd+bwd (no update)")

    grads = retry_compile(jax.jit(lambda: jax.grad(
        lambda p: loss_and_metrics(model, p, variables["batch_stats"],
                                   batch, key, cfg)[0]
    )(variables["params"])))

    def opt_stage(c):
        g = jax.tree_util.tree_map(lambda x: x + c * eps.astype(x.dtype),
                                   grads)
        updates, _ = tx.update(g, state.opt_state, variables["params"])
        return carry_of(updates)

    t_opt = timed_loop(opt_stage, "optimizer update")

    # --- full step (natural chaining through the state) --------------------
    step = jax.jit(make_train_step(model, cfg, tx), donate_argnums=(0,))
    # the step donates its state: give each retry attempt a FRESH copy, or
    # a failed first attempt leaves deleted buffers for every retry
    s = retry_compile(
        lambda: step(jax.tree.map(jnp.copy, state), batch, key))[0]
    s, metrics = step(s, batch, key)
    fetch(metrics["loss"])
    with LoweringCounter() as lc_full:
        t0 = time.perf_counter()
        for _ in range(N):
            s, metrics = step(s, batch, key)
        fetch(metrics["loss"])
        t_full = (time.perf_counter() - t0 - rtt) / N
    print(f"{'FULL train step (donated)':<34s} {t_full * 1e3:9.2f} ms  "
          f"imgs/s/chip={n / t_full:.1f}", flush=True)
    record_stage("FULL train step (donated)", t_full, lc_full.n)

    acct = t_feat_bwd + t_prop + t_at + t_pt + t_ra + t_head
    print(f"{'sum of pieces (approx)':<34s} {acct * 1e3:9.2f} ms", flush=True)
    record_stage("sum of pieces (approx)", acct)
    registry().set_gauge("profile/self_check_ratio",
                         round(acct / t_full, 4) if t_full > 0 else -1.0)

    # --- quantized inference A/B (--quant; docs/PERF.md "Quantized
    # inference"): the full TEST-MODE forward — the program serving and
    # eval run — fp vs quantized, same chain methodology.  Calibration
    # sweeps the synthetic batch (deterministic), so the arm is
    # self-contained; accuracy is gated elsewhere (gauntlet/quant-smoke),
    # this measures pure step time.
    if args.quant:
        from mx_rcnn_tpu.core.tester import calibrate_quant

        test_images = jnp.asarray(np.asarray(batch.images, np.float32))
        test_info = jnp.asarray(batch.im_info)

        def fp_fwd_stage(c):
            out = model.apply(variables, test_images + c * eps, test_info)
            return carry_of(out[2])

        timed_loop(fp_fwd_stage, "inference fwd (fp)",
                   f"batch={n} post={model.test_post_nms_top_n}")

        qcfg = cfg.replace_in("quant", enabled=True,
                              dtype=args.quant_dtype, mode=args.quant_mode)
        quant_col = calibrate_quant(
            qcfg, variables["params"], variables["batch_stats"],
            batches=[(np.asarray(test_images), np.asarray(batch.im_info))])
        qmodel = build_model(qcfg)
        qvars = {**variables, "quant": quant_col}

        def q_fwd_stage(c):
            out = qmodel.apply(qvars, test_images + c * eps, test_info)
            return carry_of(out[2])

        timed_loop(q_fwd_stage,
                   f"inference fwd ({args.quant_dtype}/{args.quant_mode})",
                   f"batch={n}")

    if args.check:
        _run_check(stage_ms, relowerings, acct, t_full)

    if args.trace_dir:
        import jax.profiler

        with jax.profiler.trace(args.trace_dir):
            for _ in range(3):
                s, metrics = step(s, batch, key)
            fetch(metrics["loss"])
        print(f"trace written to {args.trace_dir}", file=sys.stderr)
        if args.trace_summary:
            summarize_trace(args.trace_dir)


def _run_check(stage_ms: dict, relowerings: dict, acct: float,
               t_full: float) -> None:
    """`--check` (make perf-smoke): assert the profiler's own invariants —
    every stage measured finite, NO stage retraced on its timed pass, the
    chain self-check holds (sum of the six component stages lands in the
    same ballpark as the full step; wide band because a contended CPU box
    adds multiplicative noise and XLA overlaps stages in-program), and the
    per-stage gauges landed in the obs registry.  Raises SystemExit(1) on
    the first violation so `make test-gate` fails loudly."""
    import math

    from mx_rcnn_tpu.obs.metrics import registry

    failures = []
    for label, ms in stage_ms.items():
        if not math.isfinite(ms):
            failures.append(f"stage {label!r} not finite: {ms}")
    for label, lows in relowerings.items():
        if lows:
            failures.append(
                f"stage {label!r} lowered {lows} new program(s) on its "
                f"timed pass (jit cache miss — the chain retraced)")
    if t_full <= 0:
        failures.append(f"full step non-positive: {t_full * 1e3:.3f} ms")
    elif not 0.1 <= acct / t_full <= 10.0:
        # an order of magnitude each way: the check catches structural
        # breakage (a stage timing garbage, RTT subtraction gone wrong),
        # not noise — a contended 1-core box was measured swinging the
        # ratio 0.28–0.42 run to run on the tiny model
        failures.append(
            f"chain self-check failed: sum of stages {acct * 1e3:.2f} ms "
            f"vs full step {t_full * 1e3:.2f} ms (ratio "
            f"{acct / t_full:.2f} outside [0.1, 10])")
    snap = registry().snapshot()
    gauges = snap.get("gauges", {})
    missing = [k for k in ("profile/stage_ms/full_train_step_donated",
                           "profile/self_check_ratio") if k not in gauges]
    if missing:
        failures.append(f"obs registry gauges missing: {missing}")
    if failures:
        for f in failures:
            print(f"CHECK FAIL: {f}", file=sys.stderr)
        raise SystemExit(1)
    print(f"CHECK OK: {len(stage_ms)} stages, zero timed-pass recompiles, "
          f"self-check ratio {acct / t_full:.2f}", flush=True)


def summarize_trace(trace_dir: str, top: int = 15) -> None:
    """Parse the newest xplane.pb under ``trace_dir`` and print device
    time grouped by named scope AND by HLO op category — the loop-free
    attribution path (works wherever the profiler captures a device
    timeline; no TensorFlow dependency)."""
    import glob
    import os

    from mx_rcnn_tpu.utils.xplane import (category_of, parse_xspace,
                                          summarize_device_time)

    pbs = sorted(glob.glob(os.path.join(trace_dir, "plugins", "profile",
                                        "*", "*.xplane.pb")),
                 key=os.path.getmtime)
    if not pbs:
        print("no xplane.pb found under trace dir", file=sys.stderr)
        return
    # parse once: the pure-Python protobuf walk dominates, and both
    # groupings read the same planes
    planes = parse_xspace(pbs[-1])
    for title, key in (("named scope", None), ("HLO op class", category_of)):
        summary = summarize_device_time(planes, key=key)
        for plane, groups in summary.items():
            total = sum(groups.values())
            if not groups or not total:
                continue
            if title == "named scope" and set(groups) == {"(unscoped)"}:
                # XLA:CPU events carry no op_name/tf_op metadata — scope
                # attribution is a device-plane (TPU) feature; the op-class
                # table below always works
                print(f"-- {plane}: no scope metadata in this trace "
                      f"(XLA:CPU); see the op-class table")
                continue
            print(f"-- {plane} by {title} (total {total:.2f} ms over the "
                  f"traced steps)")
            for g, ms in list(groups.items())[:top]:
                print(f"   {g:<42s} {ms:9.3f} ms  {100 * ms / total:5.1f}%")


if __name__ == "__main__":
    main()
