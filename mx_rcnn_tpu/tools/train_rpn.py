"""RPN-only training entry point (alternate-training stages 1/3).

Reference: ``rcnn/tools/train_rpn.py`` — the standalone stage tool; the
4-stage driver (``tools/train_alternate.py``) invokes the same machinery
programmatically.  ``--frozen_shared`` freezes FIXED_PARAMS_SHARED (stage
3: retrain RPN with the shared convs pinned); ``--init_from`` chains from a
previous stage checkpoint.
"""

from __future__ import annotations

import argparse
import logging

from mx_rcnn_tpu.tools.train import (add_set_arg, config_from_args,
                                     train_net)

logger = logging.getLogger("mx_rcnn_tpu")


def _stage_args(p: argparse.ArgumentParser, default_prefix: str) -> None:
    """CLI surface shared by the RPN/RCNN stage tools."""
    p.add_argument("--network", default="resnet101",
                   choices=["vgg", "resnet50", "resnet101", "tiny"])
    p.add_argument("--dataset", default="PascalVOC",
                   choices=["PascalVOC", "coco", "synthetic", "synthetic_hard"])
    p.add_argument("--image_set", default=None)
    p.add_argument("--root_path", default=None)
    p.add_argument("--dataset_path", default=None)
    p.add_argument("--prefix", default=default_prefix)
    add_set_arg(p)
    p.add_argument("--pretrained", default=None)
    p.add_argument("--pretrained_epoch", type=int, default=0)
    p.add_argument("--init_from", default=None,
                   help="checkpoint prefix to initialize params from "
                        "(stage chaining)")
    p.add_argument("--init_from_epoch", type=int, default=0)
    p.add_argument("--frozen_shared", action="store_true",
                   help="freeze FIXED_PARAMS_SHARED (stages 3/4)")
    p.add_argument("--end_epoch", type=int, default=None)
    p.add_argument("--lr", type=float, default=None)
    p.add_argument("--lr_step", default=None)
    p.add_argument("--frequent", type=int, default=None)
    p.add_argument("--batch_images", type=int, default=None)
    p.add_argument("--num_devices", type=int, default=1)
    p.add_argument("--no_flip", action="store_true")
    p.add_argument("--seed", type=int, default=0)


# shared CLI→config mapping (tolerates tools that omit train-only flags)
stage_config = config_from_args


def run_stage(args, mode: str, proposals=None) -> None:
    cfg = stage_config(args)
    d = cfg.default
    end_epoch = args.end_epoch
    if end_epoch is None:
        end_epoch = d.rpn_epoch if mode == "rpn" else d.rcnn_epoch
    lr = args.lr if args.lr is not None else (
        d.rpn_lr if mode == "rpn" else d.rcnn_lr)
    lr_step = args.lr_step if args.lr_step is not None else (
        d.rpn_lr_step if mode == "rpn" else d.rcnn_lr_step)
    train_net(
        cfg, mode=mode, prefix=args.prefix, end_epoch=end_epoch, lr=lr,
        lr_step=lr_step, num_devices=args.num_devices,
        frequent=args.frequent, seed=args.seed,
        pretrained=args.pretrained, pretrained_epoch=args.pretrained_epoch,
        init_from=((args.init_from, args.init_from_epoch)
                   if args.init_from else None),
        frozen_prefixes=(cfg.network.fixed_params_shared
                         if args.frozen_shared else None),
        proposals=proposals)


def main(argv=None):
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(message)s")
    p = argparse.ArgumentParser(
        description="Train the RPN stage (ref rcnn/tools/train_rpn.py)")
    _stage_args(p, default_prefix="model/rpn")
    run_stage(p.parse_args(argv), mode="rpn")


if __name__ == "__main__":
    main()
