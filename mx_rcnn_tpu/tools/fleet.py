"""Fleet serving CLI: AOT export store + N-replica HTTP service.

No reference equivalent.  Three subcommands (docs/SERVING.md "Fleet
tier"):

* ``export`` — trace + ``jax.export``-serialize every per-bucket serving
  program (and the eval forward) into an export store, verify each
  program BIT-EQUAL to the live trace, and populate the store's bundled
  persistent XLA cache — the artifact a cold replica joins from in
  seconds::

      python -m mx_rcnn_tpu.tools.fleet export --network resnet101 \\
          --prefix model/e2e --epoch 10 --out model/export

* ``serve`` — replica manager + join-shortest-queue router behind the
  same stdlib HTTP front end as ``tools/serve.py`` (``POST /detect``,
  ``GET /healthz`` now reporting per-replica state, ``GET /metrics``
  with fleet-level accounting)::

      python -m mx_rcnn_tpu.tools.fleet serve --replicas 4 \\
          --export_dir model/export --prefix model/e2e --epoch 10

* ``join_bench`` — measure ONE replica's cold-join in a fresh process
  (``--mode trace``: today's trace+compile path, persistent cache off;
  ``--mode export``: AOT store + bundled cache) and print the timing
  JSON.  ``tools/loadgen.py --fleet_bench`` drives both modes and
  records the ratio in ``FLEET_r08.json``.
"""

from __future__ import annotations

import argparse
import json
import logging
import time

logger = logging.getLogger("mx_rcnn_tpu")


def _add_model_args(p: argparse.ArgumentParser) -> None:
    from mx_rcnn_tpu.tools.train import add_set_arg

    p.add_argument("--network", default="tiny",
                   choices=["vgg", "resnet50", "resnet101", "tiny"])
    p.add_argument("--dataset", default="synthetic",
                   choices=["PascalVOC", "coco", "synthetic",
                            "synthetic_hard", "synthetic_stream"])
    p.add_argument("--prefix", default=None,
                   help="checkpoint prefix (default: random init)")
    p.add_argument("--epoch", type=int, default=0)
    p.add_argument("--seed", type=int, default=0)
    add_set_arg(p)


def _config(args):
    from mx_rcnn_tpu.config import generate_config
    from mx_rcnn_tpu.tools.train import parse_set_overrides

    return generate_config(args.network, args.dataset,
                           **parse_set_overrides(args))


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        description="Fleet serving: AOT export + replica manager + "
                    "router (docs/SERVING.md 'Fleet tier')")
    sub = p.add_subparsers(dest="cmd", required=True)

    pe = sub.add_parser("export", help="write + verify an export store")
    _add_model_args(pe)
    pe.add_argument("--out", required=True, help="export store directory")
    pe.add_argument("--eval_batch", type=int, default=None,
                    help="also export the eval Predictor forward at this "
                         "batch size")
    pe.add_argument("--no_verify", action="store_true",
                    help="skip the bit-equality pin (also skips "
                         "populating the bundled XLA cache — joins then "
                         "pay the compile once)")

    ps = sub.add_parser("serve", help="N-replica fleet HTTP service")
    _add_model_args(ps)
    ps.add_argument("--replicas", type=int, default=None,
                    help="replica count (default cfg.fleet.replicas)")
    ps.add_argument("--export_dir", default=None,
                    help="warm replicas from this export store "
                         "(default cfg.fleet.export_dir; empty = "
                         "trace-warm)")
    ps.add_argument("--host", default="127.0.0.1")
    ps.add_argument("--port", type=int, default=8080)
    ps.add_argument("--class_names", default=None)
    ps.add_argument("--no_warmup", action="store_true",
                    help=argparse.SUPPRESS)  # parity with tools/serve.py

    pj = sub.add_parser("join_bench",
                        help="time one replica's cold join (fresh "
                             "process) and print JSON")
    _add_model_args(pj)
    pj.add_argument("--mode", required=True, choices=["trace", "export"])
    pj.add_argument("--export_dir", default=None,
                    help="store for --mode export")
    return p.parse_args(argv)


def _init_predictor(cfg, args):
    from mx_rcnn_tpu.tools.loadgen import init_predictor

    return init_predictor(cfg, args.prefix, args.epoch, args.seed)


def cmd_export(args) -> int:
    from mx_rcnn_tpu.serve.export import (CACHE_SUBDIR,
                                          enable_compile_cache,
                                          export_serve_programs)

    cfg = _config(args)
    import os

    from mx_rcnn_tpu.obs.runrec import cli_obs

    # the verify pass compiles every exported program — pointing the
    # persistent cache INTO the store makes those compiles the cache
    # entries a joining replica will read
    enable_compile_cache(os.path.join(args.out, CACHE_SUBDIR))
    obs_sess = cli_obs(cfg, "fleet_export")
    report = None
    try:
        predictor = _init_predictor(cfg, args)
        t0 = time.perf_counter()
        report = export_serve_programs(predictor, cfg, args.out,
                                       eval_batch=args.eval_batch,
                                       verify=not args.no_verify)
        report["export_s"] = round(time.perf_counter() - t0, 2)
    finally:
        if obs_sess is not None:
            obs_sess.close(metric="fleet_export_s",
                           value=(report or {}).get("export_s"),
                           unit="s", store=args.out)
    print(json.dumps(report))
    return 0


def cmd_serve(args) -> int:
    cfg = _config(args)
    if args.replicas:
        cfg = cfg.replace_in("fleet", replicas=args.replicas)
    export_dir = (cfg.fleet.export_dir if args.export_dir is None
                  else args.export_dir)
    if export_dir:
        from mx_rcnn_tpu.serve.export import (CACHE_SUBDIR,
                                              enable_compile_cache)
        import os

        enable_compile_cache(os.path.join(export_dir, CACHE_SUBDIR))
    elif cfg.ft.compile_cache_dir:
        from mx_rcnn_tpu.serve.export import enable_compile_cache

        enable_compile_cache(cfg.ft.compile_cache_dir)

    from mx_rcnn_tpu.obs.runrec import cli_obs

    obs_sess = cli_obs(cfg, "fleet")
    from mx_rcnn_tpu.models import build_model
    from mx_rcnn_tpu.serve.fleet import build_fleet
    from mx_rcnn_tpu.serve.server import make_server

    model = build_model(cfg)
    if args.prefix:
        from mx_rcnn_tpu.utils.checkpoint import load_param

        params, batch_stats = load_param(args.prefix, args.epoch)
    else:
        import jax

        from mx_rcnn_tpu.core.train import init_variables

        params, batch_stats = init_variables(
            model, jax.random.PRNGKey(args.seed),
            (1,) + tuple(cfg.bucket.shapes[0]) + (3,))
    variables = {"params": params, "batch_stats": batch_stats}
    if cfg.quant.enabled:
        # quantized fleet (docs/PERF.md "Quantized inference"): every
        # replica's Predictor is built from these shared variables, so
        # the calibration sweep runs ONCE here; the export-store
        # admission check (serve/export.py) refuses a store whose quant
        # knobs/fingerprint disagree with what this derives
        from mx_rcnn_tpu.core.tester import calibrate_quant

        variables["quant"] = calibrate_quant(cfg, params, batch_stats)
        logger.info("quant fleet: %s/%s calibrated", cfg.quant.dtype,
                    cfg.quant.mode)
    logger.info("launching %d replica(s), %s ...", cfg.fleet.replicas,
                f"export-warm from {export_dir}" if export_dir
                else "trace-warm")
    router = build_fleet(cfg, model, variables,
                         export_root=export_dir or None,
                         record=obs_sess.record if obs_sess else None)
    if obs_sess is not None and obs_sess.flight is not None:
        # a flight record from this process should carry the fleet's
        # shape at dump time, not just its metrics
        obs_sess.flight.add_context("fleet", router.healthz)
    names = args.class_names.split(",") if args.class_names else None
    srv = make_server(router, args.host, args.port, class_names=names,
                      max_body_mb=cfg.serve.max_body_mb)
    host, port = srv.server_address[:2]
    logger.info("fleet serving on http://%s:%d  (%d replicas ready; "
                "POST /detect, GET /healthz, GET /metrics)", host, port,
                router.healthz()["ready"])
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        logger.info("shutting down")
    finally:
        srv.server_close()
        router.close()
        if obs_sess is not None:
            snap = router.metrics.snapshot()
            obs_sess.close(metric="fleet_requests_served",
                           value=snap["counters"]["served"],
                           unit="requests")
    return 0


def cmd_join_bench(args) -> int:
    """One replica's cold join, timed in THIS (fresh) process: build the
    predictor, then warm it — trace mode re-traces and re-compiles with
    the persistent cache OFF (today's path); export mode loads the AOT
    store through its bundled cache.  Prints one JSON line."""
    import jax

    cfg = _config(args)
    if args.mode == "export":
        if not args.export_dir:
            raise SystemExit("--mode export requires --export_dir")
        from mx_rcnn_tpu.serve.export import (CACHE_SUBDIR,
                                              enable_compile_cache)
        import os

        enable_compile_cache(os.path.join(args.export_dir, CACHE_SUBDIR))
    else:
        # the trace-warm baseline must not read a cache some earlier run
        # populated (tests export JAX_COMPILATION_CACHE_DIR process-wide)
        try:
            jax.config.update("jax_compilation_cache_dir", None)
        except Exception:
            pass

    from mx_rcnn_tpu.obs.runrec import cli_obs
    from mx_rcnn_tpu.serve.engine import ServingEngine

    obs_sess = cli_obs(cfg, "join_bench")
    t_start = time.perf_counter()
    predictor = _init_predictor(cfg, args)
    t_build = time.perf_counter() - t_start
    engine = ServingEngine(predictor, cfg, start=False)
    t0 = time.perf_counter()
    if args.mode == "export":
        from mx_rcnn_tpu.serve.export import ExportStore

        join = engine.warm_from_export(ExportStore(args.export_dir))
    else:
        engine.warmup()
        join = {}
    warm_s = time.perf_counter() - t0
    first = list(engine.last_warmup_run_s)
    # second warmup pass: every program is resident, so each bucket's
    # run times the pure MODEL EXECUTION of its dummy batch — identical
    # in both modes and huge on a CPU backbone (a TPU executes it in
    # ms).  Pairing each bucket's first call with its ADJACENT second
    # call splits join overhead (trace+compile, resp.
    # deserialize+cache-read — the stage the AOT store addresses) from
    # execution without cross-minute load drift.
    engine.warmup()
    second = engine.last_warmup_run_s
    exec_s = sum(second)
    overhead_s = sum(max(a - b, 0.0) for a, b in zip(first, second)) \
        + join.get("load_s", 0.0)
    doc = {
        "mode": args.mode,
        "build_s": round(t_build, 3),
        "warm_s": round(warm_s, 3),
        "exec_s": round(exec_s, 3),
        "overhead_s": round(max(overhead_s, 0.001), 3),
        "total_s": round(time.perf_counter() - t_start, 3),
        "programs": engine.program_count(),
        **{k: v for k, v in join.items() if k in ("load_s",)},
    }
    if obs_sess is not None:
        obs_sess.close(metric="join_total_s", value=doc["total_s"],
                       unit="s", mode=args.mode)
    print(json.dumps(doc))
    return 0


def main(argv=None) -> int:
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(message)s")
    # opt-in lock sanitizer (MXRCNN_THREAD_SANITIZER; docs/ANALYSIS.md)
    from mx_rcnn_tpu.analysis import sanitizer

    sanitizer.maybe_install_from_env()
    args = parse_args(argv)
    return {"export": cmd_export, "serve": cmd_serve,
            "join_bench": cmd_join_bench}[args.cmd](args)


if __name__ == "__main__":
    raise SystemExit(main())
