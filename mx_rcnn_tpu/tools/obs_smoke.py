"""``make obs-smoke``: prove the unified observability layer end to end.

Runs a 2-epoch tiny-network train on synthetic data with ``cfg.obs``
fully enabled, then a short serve burst over the trained weights with the
serving metrics published into the SAME process registry, and asserts
the ISSUE-4 acceptance shape:

* ONE ``/metrics`` scrape (over the stdlib exporter, real HTTP) shows
  step (``train.*``), loader (``loader.*``), snapshot (``snapshot.*``)
  and request (``serve.*``) metrics together;
* ``runs/<id>/events.jsonl`` exists, every line parses, every line has
  the ``{ts, event}`` schema, and the expected event kinds are present;
* the config-triggered profiler window (``obs.profile_at_step``)
  produced a parseable, NON-EMPTY xplane rollup;
* the final epoch performed ZERO new lowerings (steady-state recompile
  guard via the existing ``LoweringCounter``);
* host spans + the device trace merge into one chrome-trace file.

``--check`` turns the assertions into the exit code (the ``make
test-gate`` wiring).  ``--overhead_out`` additionally measures the
obs-enabled vs obs-disabled steady-state step time (two extra 1-epoch
runs, per-step wall via ``step_callback``, compile steps excluded) and
writes the BENCH-style record ``docs/obs_overhead.json`` ships.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import shutil
import tempfile
import time
import urllib.request

from mx_rcnn_tpu.netio import read_limited

logger = logging.getLogger("mx_rcnn_tpu")

# the quick-tier miniature recipe (tests/conftest.py — shrink_tiny_cfg /
# ft/supervisor.py), plus serving shrunk like tools/loadgen.py --smoke
_TINY = {
    "train__rpn_pre_nms_top_n": 1024, "train__rpn_post_nms_top_n": 300,
    "train__max_gt_boxes": 8, "train__flip": False,
    "test__rpn_pre_nms_top_n": 512, "test__rpn_post_nms_top_n": 64,
    "bucket__scale": 128, "bucket__max_size": 160,
    "bucket__shapes": ((128, 160), (160, 128)),
    "serve__batch_size": 2, "serve__max_delay_ms": 20.0,
    "default__frequent": 2,
}


def _cfg(workdir: str, **obs_kw):
    from mx_rcnn_tpu.config import generate_config

    over = dict(_TINY)
    over.update({
        "dataset__root_path": os.path.join(workdir, "data"),
        "dataset__dataset_path": os.path.join(workdir, "data", "synthetic"),
    })
    over.update({f"obs__{k}": v for k, v in obs_kw.items()})
    return generate_config("tiny", "synthetic", **over)


def _scrape(port: int) -> dict:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10) as resp:
        return json.loads(read_limited(resp))


def run_smoke(workdir: str, num_images: int, epochs: int) -> dict:
    """The main observed run + serve burst; returns the evidence dict the
    checks (and the emitted JSON record) read."""
    import jax

    from mx_rcnn_tpu.core.tester import Predictor
    from mx_rcnn_tpu.models import build_model
    from mx_rcnn_tpu.obs import timeseries as obs_ts
    from mx_rcnn_tpu.obs import trace as obs_trace
    from mx_rcnn_tpu.obs.metrics import (LoweringCounter, ServeMetrics,
                                         registry, start_metrics_server)
    from mx_rcnn_tpu.obs.runrec import RunRecord
    from mx_rcnn_tpu.serve.engine import ServingEngine
    from mx_rcnn_tpu.tools.loadgen import synthetic_images
    from mx_rcnn_tpu.tools.train import train_net

    cfg = _cfg(workdir, enabled=True, trace=True, profile_at_step=3,
               profile_steps=2, run_dir=os.path.join(workdir, "runs"),
               timeseries=True, sample_interval_s=0.25)
    registry().reset()
    obs_trace.enable(cfg.obs.trace_cap)
    obs_trace.reset()
    run_rec = RunRecord("train", base_dir=cfg.obs.run_dir)
    srv = start_metrics_server(port=0)
    port = srv.server_address[1]
    # time-series plane (ISSUE 14): the sampler rings the registry for
    # the whole observed run, so the SAME scrape must carry the
    # "timeseries" section — and (check()) adds ZERO lowerings, since
    # sampling is pure host work
    store = obs_ts.TimeSeriesStore(cfg.obs.ts_capacity)
    obs_ts.set_active(store)
    sampler = obs_ts.Sampler(store,
                             interval_s=cfg.obs.sample_interval_s)
    sampler.start()
    lc = LoweringCounter()
    lc.__enter__()
    lowerings_at_epoch = []

    try:
        state = train_net(
            cfg, prefix=os.path.join(workdir, "model", "e2e"),
            end_epoch=epochs, seed=0,
            dataset_kw={"num_images": num_images}, run_record=run_rec,
            epoch_end_callback=lambda e, s: lowerings_at_epoch.append(lc.n))

        # serve burst over the trained weights, metrics into the SAME
        # registry — the unified-scrape half of the acceptance criterion
        predictor = Predictor(
            build_model(cfg),
            {"params": state.params, "batch_stats": state.batch_stats}, cfg)
        engine = ServingEngine(predictor, cfg,
                               metrics=ServeMetrics(registry=registry()))
        engine.warmup()
        for img in synthetic_images(cfg, 4):
            engine.detect(img, timeout_ms=0)
        engine.close()

        scrape = _scrape(port)
        profile_dir = os.path.join(run_rec.dir, "profile")
        trace_path = obs_trace.merge_device_trace(
            os.path.join(run_rec.dir, "trace.json"), profile_dir)
        run_rec.finish(metric="obs_smoke_steps",
                       value=registry().counter("train.steps"),
                       unit="steps")
    finally:
        sampler.stop(final_sample=True)
        obs_ts.set_active(None)
        run_rec.close()
        srv.shutdown()
        srv.server_close()
        obs_trace.disable()

    rollup_path = os.path.join(profile_dir, "rollup.json")
    rollup = {}
    if os.path.exists(rollup_path):
        with open(rollup_path) as f:
            rollup = json.load(f)
    events = []
    with open(run_rec.events_path) as f:
        events = [json.loads(line) for line in f if line.strip()]
    with open(trace_path) as f:
        trace = json.load(f)
    return {
        "scrape": scrape,
        "events": events,
        "events_path": run_rec.events_path,
        "rollup": rollup,
        "trace_events": trace.get("traceEvents", []),
        "lowerings_per_epoch": [lowerings_at_epoch[0]] + [
            b - a for a, b in zip(lowerings_at_epoch,
                                  lowerings_at_epoch[1:])],
        "run_dir": run_rec.dir,
    }


def check(ev: dict) -> list:
    """The acceptance assertions; returns a list of problem strings."""
    problems = []
    snap = ev["scrape"]
    have = (set(snap.get("counters", {})) | set(snap.get("gauges", {}))
            | set(snap.get("hists", {})))
    for name in ("train.steps", "train.step_ms", "train.data_wait_ms",
                 "train.samples_per_sec", "loader.decode_ms",
                 "loader.assemble_ms", "loader.queue_depth",
                 "snapshot.commits", "snapshot.stall_ms",
                 "snapshot.commit_ms", "serve.submitted", "serve.served",
                 "serve.model_ms"):
        if name not in have:
            problems.append(f"/metrics scrape missing {name}")
    if snap.get("counters", {}).get("serve.served", 0) < 1:
        problems.append("serve burst served nothing")

    # time-series plane (ISSUE 14): the same scrape carries the ring
    # store's windowed section while a store is active — and the steady
    # -state zero-lowering check below runs with the sampler LIVE, so a
    # sampler that lowered anything would fail that assertion
    ts = snap.get("timeseries")
    if not isinstance(ts, dict):
        problems.append("/metrics scrape has no timeseries section "
                        "while a store is active")
    elif ts.get("samples", 0) < 2:
        problems.append(f"timeseries store sampled {ts.get('samples')} "
                        "times over the whole observed run")

    if not ev["events"]:
        problems.append("events.jsonl empty")
    for i, e in enumerate(ev["events"]):
        if not (isinstance(e, dict) and isinstance(e.get("ts"), float)
                and isinstance(e.get("event"), str)):
            problems.append(f"events.jsonl line {i + 1} breaks the "
                            f"{{ts, event}} schema: {e}")
            break
    kinds = {e.get("event") for e in ev["events"]}
    for want in ("run_start", "epoch_start", "log", "epoch_end",
                 "snapshot", "run_finish"):
        if want not in kinds:
            problems.append(f"events.jsonl has no {want!r} event")

    by_class = ev["rollup"].get("by_op_class", {})
    if not any(groups for groups in by_class.values()):
        problems.append("profiler rollup empty (no device-time groups)")

    phases = {e.get("ph") for e in ev["trace_events"]}
    names = {e.get("name") for e in ev["trace_events"]}
    if "train.dispatch" not in names:
        problems.append("chrome trace has no train.dispatch host span")
    if not any(str(e.get("pid", "")).startswith("device:")
               for e in ev["trace_events"]):
        problems.append("chrome trace has no merged device events")
    if "X" not in phases:
        problems.append("chrome trace has no duration events")

    steady = ev["lowerings_per_epoch"][-1] if ev["lowerings_per_epoch"] \
        else None
    if steady != 0:
        problems.append(f"final epoch lowered {steady} new programs "
                        "(steady state must be recompile-free)")
    return problems


def measure_overhead(workdir: str, num_images: int) -> dict:
    """Enabled-vs-disabled steady-state step time (the <2% acceptance
    number recorded in docs/obs_overhead.json).  The enabled arm runs
    the FULL plane as ISSUE 14 wires it — spans + registry + the
    time-series sampler ticking at ``sample_interval_s`` + the health
    engine judging every sample — against a nothing-enabled arm.
    Per-step wall clocks via ``step_callback``; the first 4 steps
    (compiles, one per shape bucket plus warm-up jitter) are excluded;
    median over the rest."""
    import numpy as np

    from mx_rcnn_tpu.obs import health as obs_health
    from mx_rcnn_tpu.obs import timeseries as obs_ts
    from mx_rcnn_tpu.obs import trace as obs_trace
    from mx_rcnn_tpu.obs.metrics import registry
    from mx_rcnn_tpu.tools.train import train_net

    def arm(enabled: bool, tag: str) -> float:
        cfg = _cfg(workdir, enabled=enabled, trace=enabled,
                   timeseries=enabled, sample_interval_s=0.25,
                   health=enabled,
                   run_dir=os.path.join(workdir, "runs"))
        sampler = None
        if enabled:
            obs_trace.enable(cfg.obs.trace_cap)
            obs_trace.reset()
            registry().reset()
            store = obs_ts.TimeSeriesStore(cfg.obs.ts_capacity)
            obs_ts.set_active(store)
            engine = obs_health.HealthEngine(
                obs_health.default_rules(cfg), store,
                registry=registry())
            sampler = obs_ts.Sampler(
                store, interval_s=cfg.obs.sample_interval_s,
                after_sample=engine.evaluate_sample)
            sampler.start()
        ticks = []
        train_net(cfg, prefix=os.path.join(workdir, f"model-{tag}", "e2e"),
                  end_epoch=1, seed=0,
                  dataset_kw={"num_images": num_images},
                  step_callback=lambda step: ticks.append(
                      time.perf_counter()))
        if enabled:
            sampler.stop(final_sample=False)
            obs_ts.set_active(None)
            obs_trace.disable()
        deltas = np.diff(ticks)[4:]
        return float(np.median(deltas) * 1e3)

    disabled_ms = arm(False, "off")
    enabled_ms = arm(True, "on")
    return {
        "metric": "obs_enabled_step_overhead_pct",
        "value": round((enabled_ms - disabled_ms) / disabled_ms * 100, 2),
        "unit": "%",
        "measured": True,
        "disabled_step_ms_p50": round(disabled_ms, 3),
        "enabled_step_ms_p50": round(enabled_ms, 3),
        "network": "tiny",
        "canvas": "128x160",
        "steps_per_arm": num_images - 4,
        "sampling": "timeseries @ 0.25s + health rules (enabled arm)",
        "note": "median per-step wall over 1 epoch per arm, first 4 "
                "steps (compiles) excluded; single contended CPU core — "
                "treat small percentages as noise-bounded",
    }


def main(argv=None) -> int:
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(message)s")
    p = argparse.ArgumentParser(
        description="Observability smoke: 1 observed tiny train + serve "
                    "burst -> unified /metrics + events.jsonl + profiler "
                    "rollup (docs/OBSERVABILITY.md)")
    p.add_argument("--epochs", type=int, default=2,
                   help="2 = one compile epoch + one steady-state epoch "
                        "(the zero-recompile check needs the second)")
    p.add_argument("--num_images", type=int, default=16)
    p.add_argument("--workdir", default=None,
                   help="default: a temp dir, deleted on success")
    p.add_argument("--check", action="store_true",
                   help="exit 1 unless every acceptance assertion holds")
    p.add_argument("--overhead_out", default=None,
                   help="also measure enabled-vs-disabled step overhead "
                        "(two extra 1-epoch runs) and write the record "
                        "here (docs/obs_overhead.json)")
    args = p.parse_args(argv)

    workdir = args.workdir or tempfile.mkdtemp(prefix="obs_smoke_")
    keep = args.workdir is not None
    try:
        ev = run_smoke(workdir, args.num_images, args.epochs)
        problems = check(ev)
        rec = {
            "metric": "obs_smoke",
            "value": 0 if problems else 1,
            "measured": True,
            "metrics_scraped": sorted(
                set(ev["scrape"].get("counters", {}))
                | set(ev["scrape"].get("gauges", {}))
                | set(ev["scrape"].get("hists", {}))),
            "events": len(ev["events"]),
            "lowerings_per_epoch": ev["lowerings_per_epoch"],
            "trace_events": len(ev["trace_events"]),
            "problems": problems,
        }
        if args.overhead_out:
            overhead = measure_overhead(workdir, max(args.num_images, 32))
            with open(args.overhead_out, "w") as f:
                json.dump(overhead, f, indent=1)
            rec["overhead"] = overhead
            logger.info("obs overhead record -> %s", args.overhead_out)
        print(json.dumps(rec))
        for msg in problems:
            logger.error("CHECK FAILED: %s", msg)
        if args.check:
            return 1 if problems else 0
        return 0
    finally:
        if not keep:
            shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    raise SystemExit(main())
