"""Per-host replica agent CLI: the cross-host fleet's host-side entry
point (docs/SERVING.md "Cross-host tier").

Runs one :class:`~mx_rcnn_tpu.serve.agent.ReplicaAgent` — pull the
export store (when ``--store_url`` / ``crosshost.store_url`` points at
a head's store server), build ``crosshost.agent_replicas`` local
replicas, and serve the agent HTTP surface the head consumes
(``/healthz``, ``/metrics``, binary ``/prepared``, ``/detect``,
``POST /replicas``)::

    python -m mx_rcnn_tpu.tools.agent --port 9201 \\
        --store_url http://head:9200 --export_dir /tmp/store \\
        --replicas 2

``--stub_ms`` / ``--stub content`` swap the model path for the loadgen
stubs — the multi-process bench rig (``tools/loadgen.py
--crosshost_bench``) launches its "hosts" this way so router/wire/
scheduler behavior measures without N copies of model compute fighting
for one CPU core.  One JSON ready-line goes to stdout once the server
is bound (the rig's subprocess handshake); logs go to stderr.
"""

from __future__ import annotations

import argparse
import json
import logging
import sys

logger = logging.getLogger("mx_rcnn_tpu")


def parse_args(argv=None) -> argparse.Namespace:
    from mx_rcnn_tpu.tools.fleet import _add_model_args

    p = argparse.ArgumentParser(
        description="Per-host replica agent (docs/SERVING.md "
                    "'Cross-host tier')")
    _add_model_args(p)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0,
                   help="0 binds a free port (reported in the ready "
                        "line)")
    p.add_argument("--replicas", type=int, default=None,
                   help="local replica count (default "
                        "cfg.crosshost.agent_replicas)")
    p.add_argument("--store_url", default=None,
                   help="head store server to pull the export store "
                        "from (default cfg.crosshost.store_url; empty "
                        "= no pull)")
    p.add_argument("--export_dir", default=None,
                   help="local export-store path: pull target and/or "
                        "warm source (default cfg.fleet.export_dir)")
    p.add_argument("--class_names", default=None)
    p.add_argument("--stub_ms", type=float, default=None,
                   help="replace the model with a GIL-releasing sleep "
                        "stub of this many ms per batch (bench rig)")
    p.add_argument("--stub", default="plain",
                   choices=["plain", "content"],
                   help="stub flavor for --stub_ms: 'content' is the "
                        "deterministic content-dependent stub the bulk "
                        "byte-identity leg needs")
    return p.parse_args(argv)


def main(argv=None) -> int:
    logging.basicConfig(level=logging.INFO, stream=sys.stderr,
                        format="%(asctime)s %(name)s %(message)s")
    from mx_rcnn_tpu.analysis import sanitizer

    sanitizer.maybe_install_from_env()
    args = parse_args(argv)

    from mx_rcnn_tpu.tools.fleet import _config

    cfg = _config(args)
    if args.replicas:
        cfg = cfg.replace_in("crosshost", agent_replicas=args.replicas)
    if args.store_url is not None:
        cfg = cfg.replace_in("crosshost", store_url=args.store_url)
    if args.export_dir is not None:
        cfg = cfg.replace_in("fleet", export_dir=args.export_dir)
    if cfg.fleet.export_dir and args.stub_ms is None:
        import os

        from mx_rcnn_tpu.serve.export import (CACHE_SUBDIR,
                                              enable_compile_cache)

        # warm through the store's bundled XLA cache — the pulled store
        # carries it, so the join pays deserialize + cache read, not a
        # compile (the 0-post-warm-recompiles acceptance)
        enable_compile_cache(os.path.join(cfg.fleet.export_dir,
                                          CACHE_SUBDIR))

    run_fn_factory = None
    if args.stub_ms is not None:
        from mx_rcnn_tpu.tools.loadgen import (make_content_stub_run_fn,
                                               make_stub_run_fn)

        if args.stub == "content":
            run_fn_factory = (lambda rid:
                              make_content_stub_run_fn(cfg, args.stub_ms))
        else:
            run_fn_factory = (lambda rid:
                              make_stub_run_fn(cfg, args.stub_ms,
                                               seed=rid))

    from mx_rcnn_tpu.obs.runrec import cli_obs
    from mx_rcnn_tpu.serve.agent import ReplicaAgent, make_agent_server
    from mx_rcnn_tpu.tools.loadgen import init_predictor

    obs_sess = cli_obs(cfg, "agent")
    if run_fn_factory is not None:
        # stub agents skip the model build entirely (the bench launches
        # several per box; Predictor(None, {}) is the test-rig idiom)
        model, variables = None, {}
    else:
        predictor = init_predictor(cfg, args.prefix, args.epoch,
                                   args.seed)
        model, variables = predictor.model, predictor.variables
    agent = ReplicaAgent(
        cfg, model, variables,
        run_fn_factory=run_fn_factory,
        record=obs_sess.record if obs_sess else None,
        class_names=(args.class_names.split(",")
                     if args.class_names else None))
    srv = make_agent_server(agent, args.host, args.port)
    host, port = srv.server_address[:2]
    h = agent.healthz()
    ready = {"ready": bool(h.get("ok")), "host": host, "port": port,
             "replicas": h.get("ready"), "warm_s": h.get("warm_s"),
             "store_pull": h.get("store_pull")}
    print(json.dumps(ready), flush=True)
    logger.info("agent serving on http://%s:%d (%s replicas ready)",
                host, port, h.get("ready"))
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        logger.info("shutting down")
    finally:
        srv.server_close()
        agent.close()
        if obs_sess is not None:
            obs_sess.close(metric="agent_warm_s", value=agent.warm_s,
                           unit="s")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
