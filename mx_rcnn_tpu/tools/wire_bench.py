"""Wire data-plane bench: the WIRE_r20 measurement protocol.

Driven through ``tools/loadgen.py --wire_bench`` (full battery →
``docs/WIRE_r20.json``) and ``--wire_smoke`` (`make wire-smoke`, ~1 min
gate scale).  Same rig posture as ``tools/crosshost.py``: every "host"
is a real ``tools/agent.py`` subprocess on a loopback port, so all four
arms cross a true process boundary; every process shares this box's
core(s), so ratios validate the DATA PLANE (codec, syscalls, pipeline),
not silicon.

Arms — identical u8 burst, identical content-stub agent, the arms
differ ONLY head-side:

1. **v1-fp32** — the PR-15 wire: the head runs pad+normalize
   (``data/image.py::pad_normalize``) and ships full fp32 canvases
   (4 B/px) via ``submit_prepared``.  The head-side pad runs PER
   REQUEST inside the measured window because that is the deployed v1
   data path — the head owned preprocess.
2. **v2-u8** — ``submit_source`` ships source u8 pixels (1 B/px, no
   pad bytes); the agent runs the SAME ``pad_normalize`` before
   enqueue, so canvases — and content-stub detections — stay
   bit-equal.
3. **v2-u8 + coalesce** — ``crosshost.frames_per_send`` packs queued
   frames into count-prefixed multi-frame envelopes shipped with
   vectored ``sendmsg`` (one syscall, one HTTP round trip per
   envelope).
4. **+ adaptive** — ``crosshost.pipeline_depth_max`` lets the
   per-connection pipeline depth self-tune from windowed wire RTT
   (starts shallow, must grow under the closed loop).

Checks (``--check``): v2-u8 ≤ ``--max_wire_bytes_ratio`` (0.30×)
bytes/image vs v1-fp32 — measured from the engine's ``wire_tx_bytes``
counters AND from pure codec arithmetic at the production bucket;
coalesced arm ≥ ``--min_wire_speedup`` (1.8×) the v1 arm's throughput;
detections bit-equal across all four arms; 0 lost requests every leg;
0 post-warm recompiles; and the SIGKILL-mid-envelope leg accounts
every frame exactly once (reroute inside the original deadline).
"""

from __future__ import annotations

import json
import logging
import os
import tempfile
import threading
import time
from typing import Dict, List, Tuple

import numpy as np

from mx_rcnn_tpu.config import Config, generate_config
from mx_rcnn_tpu.data.image import pad_normalize
from mx_rcnn_tpu.serve.queue import (DeadlineExceeded, RequestFailed,
                                     ShedError)
from mx_rcnn_tpu.serve.remote import (_REQ_HEAD, _REQ_HEAD2,
                                      RemoteEngine,
                                      build_crosshost_router)
from mx_rcnn_tpu.tools.crosshost import AgentProc, _free_ports, _scrape
from mx_rcnn_tpu.tools.loadgen import _drain, _smoke_overrides

logger = logging.getLogger("mx_rcnn_tpu")

# wire counters RemoteEngine maintains beyond the pinned serve set —
# read straight off the registry (ServeMetrics.snapshot keeps the
# pre-registry counter list bit-for-bit, so these never appear there)
_WIRE_KEYS = ("wire_tx_bytes", "wire_rx_bytes", "wire_frames",
              "wire_sends", "envelopes")


def _wire_counters(eng: RemoteEngine) -> Dict[str, int]:
    return {k: eng.metrics.registry.counter("serve." + k)
            for k in _WIRE_KEYS}


def _source_set(cfg: Config, n: int, seed: int = 0) -> List[Tuple]:
    """n (u8 image, im_info, bucket) triples alternating over the shape
    buckets; every third image is SMALLER than its bucket so the v2
    pad-on-agent path (h<bh, w<bw) is measured, not just full
    canvases."""
    rng = np.random.RandomState(seed)
    buckets = [tuple(b) for b in cfg.bucket.shapes]
    out = []
    for i in range(n):
        bh, bw = buckets[i % len(buckets)]
        h, w = (bh, bw) if i % 3 else (max(bh - 16, 8), max(bw - 24, 8))
        img = rng.randint(0, 256, size=(h, w, 3), dtype=np.uint8)
        out.append((img, np.array([h, w, 1.0], np.float32), (bh, bw)))
    return out


def _det_key(dets) -> bytes:
    """Canonical byte key over a detections dict — bit-equality across
    arms is the whole claim (same idiom as the fleet bench)."""
    return b"".join(np.ascontiguousarray(dets[c]).tobytes()
                    for c in sorted(dets))


def _submit(target, item, mode: str, means, timeout_ms: float):
    img, im_info, bucket = item
    if mode == "v1":
        # deployed v1 path: the head materializes the fp32 canvas
        return target.submit_prepared(pad_normalize(img, means, bucket),
                                      im_info, bucket,
                                      timeout_ms=timeout_ms)
    return target.submit_source(img, im_info, bucket,
                                timeout_ms=timeout_ms)


def _equality_pass(target, items, mode: str, means,
                   timeout_ms: float) -> List[bytes]:
    """One sequential pass over the corpus → per-image detection keys
    (sequential so shed/backpressure can never skew the comparison)."""
    keys = []
    for item in items:
        dets = _submit(target, item, mode, means,
                       timeout_ms).wait(timeout_ms / 1000.0 + 30.0)
        keys.append(_det_key(dets) if dets else b"<empty>")
    return keys


def _run_wire_closed(target, items, mode: str, means,
                     duration_s: float, concurrency: int,
                     timeout_ms: float) -> dict:
    """Closed loop over the arm's submit path — ``target`` is a bare
    RemoteEngine or the cross-host router."""
    stop = time.monotonic() + duration_s
    outcomes = {"ok": 0, "shed": 0, "expired": 0, "failed": 0}
    lock = threading.Lock()

    def worker(wid: int):
        i = wid
        while time.monotonic() < stop:
            item = items[i % len(items)]
            i += concurrency
            try:
                req = _submit(target, item, mode, means, timeout_ms)
                req.wait(timeout=timeout_ms / 1000.0 + 30.0)
                key = "ok"
            except ShedError:
                key = "shed"
                time.sleep(0.005)  # real clients back off; a tight
                # resubmit spin would just burn the shared core
            except DeadlineExceeded:
                key = "expired"
            except (RequestFailed, TimeoutError):
                key = "failed"
            with lock:
                outcomes[key] += 1

    threads = [threading.Thread(target=worker, args=(w,), daemon=True)
               for w in range(concurrency)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return {"wall_s": time.perf_counter() - t0, "client": outcomes}


def _run_arm(name: str, url: str, acfg: Config, items, mode: str,
             means, dur: float, concurrency: int, timeout_ms: float,
             problems: List[str]) -> dict:
    eng = RemoteEngine(f"wire-{name}", url, acfg, wire="binary")
    try:
        keys = _equality_pass(eng, items, mode, means, timeout_ms)
        _drain(eng)
        # warm the whole path (connections, codec, agent lanes) before
        # the measured window, then zero the serve counters AND take a
        # wire-counter baseline (the registry keeps wire_* across
        # ServeMetrics.reset — deltas keep warm traffic out)
        _run_wire_closed(eng, items, mode, means, 0.5, concurrency,
                         timeout_ms)
        _drain(eng)
        eng.metrics.reset()
        base = _wire_counters(eng)
        run = _run_wire_closed(eng, items, mode, means, dur,
                               concurrency, timeout_ms)
        _drain(eng)
        snap = eng.metrics.snapshot()
        wire = {k: v - base[k] for k, v in _wire_counters(eng).items()}
        frames = max(wire["wire_frames"], 1)
        leg = {
            "mode": mode,
            "imgs_per_sec": round(run["client"]["ok"] / run["wall_s"],
                                  2),
            "p50_ms": snap["total_ms"]["p50"],
            "p99_ms": snap["total_ms"]["p99"],
            "client": run["client"],
            "lost": snap["counters"]["submitted"] - snap["terminated"],
            "wire": wire,
            "tx_bytes_per_image": round(wire["wire_tx_bytes"] / frames,
                                        1),
            "frames_per_send": round(frames
                                     / max(wire["wire_sends"], 1), 2),
        }
        pipe = getattr(eng, "_pipe", None)
        if pipe is not None:
            leg["pipeline_depth_initial"] = acfg.crosshost.pipeline_depth
            leg["pipeline_depth_final"] = pipe.current()
            leg["pipeline_depth_peak"] = pipe.depth_peak
            leg["pipeline_retunes"] = pipe.retunes
        if leg["lost"]:
            problems.append(f"arm {name} lost {leg['lost']} requests")
        if run["client"]["ok"] == 0:
            problems.append(f"arm {name} served nothing")
        if run["client"]["failed"] or run["client"]["expired"]:
            problems.append(f"arm {name} had client failures/expiries: "
                            f"{run['client']}")
        return leg, keys
    finally:
        eng.close()


def _codec_math(network: str, dataset: str) -> dict:
    """Pure codec arithmetic at the PRODUCTION bucket (no overrides):
    header + payload bytes per image for a full-canvas frame on each
    wire version.  The rig measures tiny buckets on a shared core; this
    is the bytes-per-image claim at deployment scale, where payload
    dwarfs every fixed cost."""
    pcfg = generate_config(network, dataset)
    bh, bw = max((tuple(b) for b in pcfg.bucket.shapes),
                 key=lambda b: b[0] * b[1])
    v1 = _REQ_HEAD.size + bh * bw * 3 * 4
    v2 = _REQ_HEAD2.size + bh * bw * 3
    return {
        "bucket": [bh, bw],
        "v1_fp32_bytes_per_image": v1,
        "v2_u8_bytes_per_image": v2,
        "ratio": round(v2 / v1, 4),
    }


def _kill_leg(cfg: Config, agent_overrides: Dict, args, workdir: str,
              ports: List[int], items, means, timeout_ms: float,
              concurrency: int, problems: List[str]) -> dict:
    """SIGKILL one of two agents mid-burst while the head is shipping
    coalesced v2 envelopes: every admitted frame must reach EXACTLY ONE
    terminal, and every non-shed frame must serve within its ORIGINAL
    deadline (reroute never extends it)."""
    kcfg = cfg.replace_in("crosshost", dead_after_failures=2,
                          for_samples=2, cooldown_s=1.0,
                          interval_s=0.2, window_s=5.0)
    kcfg = kcfg.replace_in("fleet", reroute_retries=2,
                           health_interval_s=0.2)
    agents = [AgentProc(workdir, f"wirekill-{i}", ports[i],
                        agent_overrides, network=args.network,
                        dataset=args.dataset, replicas=1, stub_ms=0.0,
                        stub="content")
              for i in range(2)]
    try:
        for a in agents:
            a.wait_ready()
        router, feed = build_crosshost_router(kcfg,
                                              [a.url for a in agents])
        try:
            kdur = max(min(args.duration, 6.0)
                       if args.wire_smoke else args.duration, 6.0)
            box = {}

            def burst():
                box["run"] = _run_wire_closed(router, items, "v2",
                                              means, kdur, concurrency,
                                              timeout_ms)

            bt = threading.Thread(target=burst, daemon=True)
            bt.start()
            time.sleep(kdur / 3.0)
            served_before = router.metrics.snapshot()["counters"][
                "served"]
            agents[1].sigkill()
            bt.join()
            _drain(router)
            run = box["run"]
            snap = router.metrics.snapshot()
            c = snap["counters"]
            envelopes = sum(
                r.engine.metrics.registry.counter("serve.envelopes")
                for r in router.manager.replicas
                if r.engine is not None)
            leg = {
                "submitted": c["submitted"], "served": c["served"],
                "shed": c["shed"], "expired": c["expired"],
                "failed": c["failed"],
                "lost": c["submitted"] - snap["terminated"],
                "served_after_kill": c["served"] - served_before,
                "rerouted": router.rerouted(),
                "envelopes": envelopes,
                "client": run["client"],
            }
            if leg["lost"]:
                problems.append(f"kill leg lost {leg['lost']} frames — "
                                "exactly-once accounting broken")
            if run["client"]["failed"] or run["client"]["expired"]:
                problems.append(
                    "kill leg had client failures/expiries — reroute "
                    "did not complete within the original deadline: "
                    f"{run['client']}")
            if leg["served_after_kill"] <= 0:
                problems.append("nothing served after the agent kill")
            if leg["rerouted"] <= 0:
                problems.append("kill leg recorded no reroutes")
            if envelopes <= 0:
                problems.append("kill leg shipped no envelopes — the "
                                "coalescing path was not exercised")
            return leg
        finally:
            feed.close()
            router.close()
    finally:
        for a in agents:
            a.kill()


def run_wire_bench(args) -> int:
    from mx_rcnn_tpu.analysis import sanitizer
    from mx_rcnn_tpu.tools.train import parse_set_overrides

    smoke = args.wire_smoke
    overrides = dict(_smoke_overrides())  # both tiers use the tiny
    # rig: every "host" shares one box, so the production canvas would
    # only measure core contention; --check's bytes claim at production
    # scale comes from the codec-math block
    overrides.update(parse_set_overrides(args))
    cfg = generate_config(args.network, args.dataset, **overrides)
    agent_overrides = dict(overrides, serve__max_delay_ms=2.0)
    workdir = args.workdir or tempfile.mkdtemp(prefix="wire_bench_")
    os.makedirs(workdir, exist_ok=True)
    timeout_ms = (20_000.0 if args.timeout_ms is None
                  else args.timeout_ms)
    dur = min(args.duration, 2.5) if smoke else max(args.duration / 2,
                                                    4.0)
    batch = cfg.serve.batch_size
    # per-engine capacity (connections x pipeline depth) must cover the
    # closed-loop concurrency or the head's own gate sheds the burst
    concurrency = 4 * batch
    ch_over = {"connections": 2, "pipeline_depth": 2 * batch,
               "scrape_interval_s": 0.2, "io_timeout_s": 30.0}
    items = _source_set(cfg, max(args.images, 6), args.seed)
    means = cfg.network.pixel_means
    rec: dict = {
        "metric": "wire_tx_bytes_per_image_v2_over_v1",
        "unit": "x",
        "measured": True,
        "smoke": smoke,
        "network": args.network,
        "bucket_shapes": [list(b) for b in cfg.bucket.shapes],
        "batch_size": batch,
        "host": {"physical_cores": os.cpu_count()},
        "note": "all four arms share one box and one content-stub "
                "agent process; the arms differ ONLY head-side, so "
                "ratios isolate the wire codec + send path.  The v1 "
                "arm pays head-side pad+normalize per request — that "
                "IS the deployed v1 data path (the head owned "
                "preprocess); v2 moves it behind the wire.",
    }
    problems: List[str] = []
    ports = _free_ports(3)

    # -- 1. four-arm A/B against one content-stub agent -----------------
    logger.info("[wire] arm agent boot ...")
    aw = AgentProc(workdir, "wire-arms", ports[0], agent_overrides,
                   network=args.network, dataset=args.dataset,
                   replicas=1, stub_ms=0.0, stub="content")
    arms: dict = {}
    keys: Dict[str, List[bytes]] = {}
    try:
        aw.wait_ready()
        base = cfg.replace_in("crosshost", **ch_over)
        plans = [
            ("v1-fp32", "v1", base),
            ("v2-u8", "v2", base),
            ("v2-u8-coalesce", "v2",
             base.replace_in("crosshost", frames_per_send=4)),
            ("v2-u8-adaptive", "v2",
             base.replace_in("crosshost", frames_per_send=4,
                             pipeline_depth_max=4 * batch)),
        ]
        for name, mode, acfg in plans:
            logger.info("[wire] arm %s ...", name)
            arms[name], keys[name] = _run_arm(
                name, aw.url, acfg, items, mode, means, dur,
                concurrency, timeout_ms, problems)
        snap = _scrape(aw.url)
        lowered = snap.get("gauges", {}).get("agent.lowered_after_warm")
        rec["recompiles_after_warm"] = lowered
        if lowered:
            problems.append(f"agent recompiled {lowered} time(s) "
                            "after warm")
    finally:
        aw.kill()
    rec["arms"] = arms

    # detections must be bit-equal across every arm — the v2 claim is
    # "same canvas, fewer bytes", not "close enough"
    ref = keys["v1-fp32"]
    for name, ks in keys.items():
        if ks != ref:
            diff = sum(1 for a, b in zip(ref, ks) if a != b)
            problems.append(f"arm {name} detections differ from "
                            f"v1-fp32 on {diff}/{len(ref)} images — "
                            "wire v2 changed results")
    rec["bit_equal_arms"] = all(ks == ref for ks in keys.values())

    bytes_ratio = (arms["v2-u8"]["tx_bytes_per_image"]
                   / max(arms["v1-fp32"]["tx_bytes_per_image"], 1e-9))
    rec["measured_bytes_ratio"] = round(bytes_ratio, 4)
    rec["value"] = rec["measured_bytes_ratio"]
    rec["codec_math_production"] = _codec_math(args.network,
                                               args.dataset)
    speed = (arms["v2-u8-coalesce"]["imgs_per_sec"]
             / max(arms["v1-fp32"]["imgs_per_sec"], 1e-9))
    rec["coalesce_speedup_over_v1"] = round(speed, 3)
    if bytes_ratio > args.max_wire_bytes_ratio:
        problems.append(f"measured v2/v1 bytes ratio {bytes_ratio:.3f}"
                        f" > {args.max_wire_bytes_ratio}")
    if rec["codec_math_production"]["ratio"] > args.max_wire_bytes_ratio:
        problems.append("production-bucket codec ratio "
                        f"{rec['codec_math_production']['ratio']} > "
                        f"{args.max_wire_bytes_ratio}")
    if speed < args.min_wire_speedup:
        problems.append(f"coalesced arm {speed:.3f}x v1-fp32 < "
                        f"{args.min_wire_speedup}")
    # the GROWTH/DECREASE directions are pinned deterministically with
    # synthetic RTTs in tests/test_wire_v2.py; on this shared-core rig
    # the converged depth is load-dependent, so the bench asserts the
    # controller ran and actually moved the depth, not where it landed
    ad = arms["v2-u8-adaptive"]
    if ad.get("pipeline_retunes", 0) <= 0:
        problems.append("adaptive arm's controller never retuned")
    if (ad.get("pipeline_depth_final") == ad.get("pipeline_depth_initial")
            and ad.get("pipeline_depth_peak")
            == ad.get("pipeline_depth_initial")):
        problems.append("adaptive arm's depth never moved off "
                        f"{ad.get('pipeline_depth_initial')} under a "
                        "saturating closed loop")

    # -- 2. SIGKILL mid-envelope over 2 hosts ----------------------------
    logger.info("[wire] kill-mid-envelope leg ...")
    kill_cfg = cfg.replace_in("crosshost", **dict(ch_over,
                                                  frames_per_send=4))
    rec["kill_mid_envelope"] = _kill_leg(
        kill_cfg, agent_overrides, args, workdir, ports[1:3], items,
        means, timeout_ms, concurrency=2 * concurrency,
        problems=problems)

    print(json.dumps(rec))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(rec, f, indent=1)
    if args.check:
        problems += sanitizer.check_problems()
        for msg in problems:
            logger.error("CHECK FAILED: %s", msg)
        return 1 if problems else 0
    return 0
