"""Single-image inference demo with box visualization.

Reference: ``demo.py`` + ``rcnn/core/tester.py — vis_all_detection``
(SURVEY.md §3.4): load an image, run the test-mode forward, draw the
per-class detections above a score threshold.

Matplotlib-free: boxes and labels are drawn with PIL so the tool runs
headless; output is a written PNG/JPEG.
"""

from __future__ import annotations

import argparse
import logging
import os
from typing import Dict, List

import numpy as np

from mx_rcnn_tpu.config import Config, generate_config
from mx_rcnn_tpu.core.tester import Predictor
from mx_rcnn_tpu.data.image import imread_rgb, resize_to_bucket
from mx_rcnn_tpu.models import build_model
from mx_rcnn_tpu.utils.checkpoint import load_param

logger = logging.getLogger("mx_rcnn_tpu")


def detect_image(predictor: Predictor, img: np.ndarray, cfg: Config,
                 vis_thresh: float = 0.5) -> Dict[int, np.ndarray]:
    """Run detection on one RGB uint8 image; returns
    {class_id: (k, 5) [x1 y1 x2 y2 score]} in raw image coordinates.

    Reuses the eval path's jitted ``_postprocess_batch`` (decode + clip +
    unscale + per-class masked NMS) so demo and eval can never disagree on
    postprocess semantics."""
    import jax.numpy as jnp

    from mx_rcnn_tpu.core.tester import (_postprocess_batch,
                                         detections_from_keep,
                                         tiled_bbox_stats)

    data, im_scale, bucket = resize_to_bucket(
        img, cfg.network.pixel_means, cfg.bucket.scale, cfg.bucket.max_size,
        tuple(tuple(s) for s in cfg.bucket.shapes))
    h, w = img.shape[:2]
    im_info = np.array([[round(h * im_scale), round(w * im_scale),
                         im_scale]], np.float32)
    rois, roi_valid, cls_prob, deltas = predictor.raw(data[None], im_info)
    num_classes = cls_prob.shape[-1]
    stds, means = tiled_bbox_stats(cfg, num_classes)
    boxes_b, scores_b, keep_b = map(np.asarray, _postprocess_batch(
        rois, roi_valid, cls_prob, deltas, jnp.asarray(im_info),
        jnp.asarray([im_scale], dtype=jnp.float32), stds, means,
        nms_thresh=cfg.test.nms, score_thresh=vis_thresh))
    return detections_from_keep(boxes_b, scores_b, keep_b, 0)


_COLORS = [(230, 60, 60), (60, 200, 80), (70, 110, 240), (240, 200, 50),
           (200, 70, 220), (70, 210, 210), (250, 140, 50), (150, 150, 150)]


def draw_detections(img: np.ndarray, dets: Dict[int, np.ndarray],
                    class_names: List[str] = None) -> np.ndarray:
    """Draw labelled boxes (ref ``vis_all_detection``, PIL instead of
    matplotlib); returns an annotated RGB uint8 array."""
    from PIL import Image, ImageDraw

    im = Image.fromarray(img.astype(np.uint8))
    draw = ImageDraw.Draw(im)
    for c, arr in sorted(dets.items()):
        color = _COLORS[c % len(_COLORS)]
        name = class_names[c] if class_names and c < len(class_names) \
            else f"cls{c}"
        for x1, y1, x2, y2, score in arr:
            draw.rectangle([float(x1), float(y1), float(x2), float(y2)],
                           outline=color, width=2)
            draw.text((float(x1) + 2, float(y1) + 2),
                      f"{name} {score:.2f}", fill=color)
    return np.asarray(im)


def demo(cfg: Config, *, prefix: str, epoch: int, image: str,
         out_path: str, vis_thresh: float = 0.5,
         class_names: List[str] = None) -> Dict[int, np.ndarray]:
    model = build_model(cfg)
    params, batch_stats = load_param(prefix, epoch)
    predictor = Predictor(
        model, {"params": params, "batch_stats": batch_stats}, cfg)
    img = imread_rgb(image)
    dets = detect_image(predictor, img, cfg, vis_thresh)
    n = sum(len(v) for v in dets.values())
    logger.info("%d detections over %.2f in %s", n, vis_thresh, image)
    annotated = draw_detections(img, dets, class_names)
    from PIL import Image

    Image.fromarray(annotated).save(out_path)
    logger.info('wrote annotated image to "%s"', out_path)
    return dets


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        description="Single-image detection demo (ref demo.py)")
    p.add_argument("--network", default="resnet101",
                   choices=["vgg", "resnet50", "resnet101", "tiny"])
    p.add_argument("--dataset", default="PascalVOC",
                   choices=["PascalVOC", "coco", "synthetic", "synthetic_hard"])
    p.add_argument("--prefix", default="model/e2e")
    p.add_argument("--epoch", type=int, required=True)
    p.add_argument("--image", required=True)
    p.add_argument("--out", default=None,
                   help="output path (default: <image>_det.png)")
    p.add_argument("--vis_thresh", type=float, default=0.5)
    return p.parse_args(argv)


def main(argv=None):
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(message)s")
    args = parse_args(argv)
    cfg = generate_config(args.network, args.dataset)
    out = args.out or (os.path.splitext(args.image)[0] + "_det.png")
    demo(cfg, prefix=args.prefix, epoch=args.epoch, image=args.image,
         out_path=out, vis_thresh=args.vis_thresh)


if __name__ == "__main__":
    main()
