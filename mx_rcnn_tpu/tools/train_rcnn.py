"""Fast R-CNN-only training from precomputed proposals (stages 2/4).

Reference: ``rcnn/tools/train_rcnn.py`` — trains the detection head on
proposals dumped by ``test_rpn.py`` (here ``tools/test_rpn.py`` writes the
same pkl this tool reads).
"""

from __future__ import annotations

import argparse
import logging
import pickle

from mx_rcnn_tpu.tools.train_rpn import _stage_args, run_stage

logger = logging.getLogger("mx_rcnn_tpu")


def main(argv=None):
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(message)s")
    p = argparse.ArgumentParser(
        description="Train the Fast R-CNN stage on precomputed proposals "
                    "(ref rcnn/tools/train_rcnn.py)")
    _stage_args(p, default_prefix="model/rcnn")
    p.add_argument("--proposals", required=True,
                   help="proposal pkl written by tools/test_rpn.py "
                        "(roidb order, (k, 5) arrays)")
    args = p.parse_args(argv)
    with open(args.proposals, "rb") as f:
        proposals = pickle.load(f)
    logger.info("loaded proposals for %d images from %s", len(proposals),
                args.proposals)
    run_stage(args, mode="rcnn", proposals=proposals)


if __name__ == "__main__":
    main()
